"""Manager daemon — the module host
(src/mgr/Mgr.cc + src/pybind/mgr/mgr_module.py).

The reference mgr embeds CPython to run python modules against
cluster state it mirrors from the monitors.  Here the host IS python:
``Manager`` keeps a live OSDMap via a MonClient subscription, hosts
``MgrModule`` subclasses on a shared tick, and gives them the
mgr_module surface that matters:

- ``self.get("osd_map") / get("pg_summary") / get("df")`` — cluster
  state snapshots
- ``self.mon_command(cmd)`` — the command path back to the quorum
- per-module config via ``set_module_option``

Built-in modules (the pybind/mgr counterparts):

- ``balancer`` — runs the upmap balancer library
  (ceph_tpu/osd/balancer.py calc_pg_upmaps) on a COPY of the map and
  commits the new pg_upmap_items through "osd pg-upmap-items", the
  reference balancer module's active mode.
- ``prometheus`` — an HTTP /metrics endpoint in the Prometheus text
  exposition format (ceph_osd_up, ceph_osd_in, ceph_pool_*,
  ceph_pg_total ...), the src/pybind/mgr/prometheus role.
- ``status`` — health/df rollups for the CLI surface.
- ``tracing`` — cross-daemon span assembly: drains span batches
  piggybacked on MMgrReport and serves one logical op's spans from
  client + primary + replicas as a single tree (the collection half
  of the blkin/ZTracer role).
"""

from __future__ import annotations

import copy
import http.server
import json
import re
import threading
import time
from collections import OrderedDict, deque

from ..common import crash as crash_util
from ..common import tracing
from ..common.log_client import LogClient
from ..mon.monitor import MonClient
from ..msg import Messenger
from ..msg.message import (
    MMgrReport,
    MMonCommand,
    MMonCommandReply,
    MPGStats,
)
from ..msg.messenger import Dispatcher

__all__ = ["Manager", "MgrModule"]


def histogram_exposition_lines(
    name: str, help_: str, series: list
) -> list[str]:
    """Render ONE prometheus-native histogram family: a single
    HELP/TYPE header, then per-labelset cumulative ``_bucket`` rows
    (monotone, closing with the mandatory ``le="+Inf"``) plus the
    ``_sum``/``_count`` pair.  ``series`` is [(labels dict, histogram
    snapshot)].  Module-level so tools/check_metrics.py lints the
    exact text the exporter serves."""
    from ..common.histogram import cumulative_buckets, snapshot_counts

    name = PrometheusModule.sanitize_name(name)
    out = [f"# HELP {name} {help_}", f"# TYPE {name} histogram"]

    def lbl(labels: dict) -> str:
        return ",".join(
            f"{PrometheusModule.sanitize_name(k)}="
            f'"{PrometheusModule.escape_label(v)}"'
            for k, v in labels.items()
        )

    for labels, snap in series:
        base = lbl(labels)
        for le, cum in cumulative_buckets(snap):
            sep = "," if base else ""
            out.append(
                f'{name}_bucket{{{base}{sep}le="{le}"}} {cum}'
            )
        total = sum(snapshot_counts(snap))
        braces = f"{{{base}}}" if base else ""
        out.append(f"{name}_sum{braces} {float(snap.get('sum', 0.0))}")
        out.append(f"{name}_count{braces} {total}")
    return out


class MgrModule:
    """Base class for manager modules (mgr_module.MgrModule)."""

    NAME = "module"
    TICK_EVERY = 1.0  # seconds between serve() calls

    def __init__(self, mgr: "Manager"):
        self.mgr = mgr
        self._last_tick = 0.0

    # -- the mgr_module surface -------------------------------------------
    def get(self, what: str):
        return self.mgr.get(what)

    def mon_command(self, cmd: dict, timeout: float = 15.0):
        return self.mgr.monc.command(cmd, timeout=timeout)

    def get_module_option(self, key: str, default=None):
        return self.mgr.module_options.get(self.NAME, {}).get(
            key, default
        )

    def serve(self) -> None:  # pragma: no cover — interface hook
        """Called on the host tick, at most every TICK_EVERY s."""

    def shutdown(self) -> None:
        pass


class Manager(Dispatcher):
    """The mgr daemon: mon session + module host (Mgr.cc) + the
    daemon-stats plane (DaemonServer.cc role): daemons discover the
    mgr through the monitor ("mgr beacon"/"mgr stat") and push
    MMgrReport perf dumps to its messenger; modules and the
    prometheus exporter read them via get("daemon_perf")."""

    def __init__(
        self,
        modules: list[type[MgrModule]] | None = None,
        name: str = "x",
        shared_services: bool | None = None,
    ):
        self.name = name
        # shared-services: the tick loop rides a shared-stack timer
        # and mgr commands drain through a serial strand instead of a
        # thread per command — zero dedicated mgr threads (the PR 14
        # OSD treatment)
        self.shared_services = bool(shared_services)
        self._tick_handle = None
        self._cmd_strand = None
        self._last_beacon = 0.0
        self.messenger = Messenger("mgr")
        self.monc = MonClient(self.messenger, whoami=-2)
        self.module_options: dict[str, dict] = {}
        self._module_types = list(
            modules
            if modules is not None
            else [
                BalancerModule,
                PrometheusModule,
                StatusModule,
                PgAutoscalerModule,
                TelemetryModule,
                DashboardModule,
                TracingModule,
                CrashModule,
                SLOModule,
                PgMapModule,
                ProgressModule,
            ]
        )
        self.modules: dict[str, MgrModule] = {}
        self._ticker: threading.Thread | None = None
        self._stop = threading.Event()
        # DaemonServer role: inbound perf reports, daemon -> (ts, dump)
        self.daemon_perf: dict[str, tuple[float, dict]] = {}
        self._perf_lock = threading.Lock()
        # span inbox: (daemon, span dicts) batches from MMgrReport,
        # drained by the tracing module's tick; bounded so a span
        # firehose with no tracing module cannot grow without limit
        self._span_inbox: deque[tuple[str, list]] = deque(maxlen=4096)
        # crash inbox: reports piggybacked on MMgrReport, drained by
        # the crash module's tick (bounded the same way)
        self._crash_inbox: deque[dict] = deque(maxlen=256)
        # PG-stats plane (MPGStats ingestion): osd id -> (ts, epoch,
        # [pg stat dicts]); the pgmap module folds the freshest
        # primary reports into the digest
        self.pg_stats: dict[int, tuple[float, int, list]] = {}
        self._pg_stats_lock = threading.Lock()
        # progress events piggybacked on MPGStats (scrub/repair),
        # drained by the progress module's tick
        self._progress_inbox: deque[dict] = deque(maxlen=512)
        # the mgr's own cluster-log channel (flushed on the tick)
        self._log_client = LogClient(f"mgr.{name}")
        self.clog = self._log_client.channel()
        self.messenger.add_dispatcher(self)
        self.addr: str | None = None

    # -- MMgrReport ingestion (DaemonServer::handle_report) ----------------
    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MMonCommand):
            # mgr-targeted commands (`ceph crash ...`): the reference
            # CLI routes MgrCommands to the active mgr the same way.
            # Handled OFF the messenger loop — a handler that talks
            # back to the mon (crash archive → "crash report") would
            # deadlock the loop thread on its own blocking call
            def run(msg=msg, conn=conn):
                reply = self.handle_command(msg.cmd)
                reply.tid = msg.tid
                try:
                    conn.send(reply)
                except Exception:  # noqa: BLE001 — caller gone
                    pass

            strand = self._cmd_strand
            if strand is not None:
                strand.submit(run)
            else:
                threading.Thread(
                    target=run, name="mgr.command", daemon=True
                ).start()
            return True
        if isinstance(msg, MPGStats):
            try:
                stats = json.loads(msg.stats)
                events = json.loads(msg.events)
            except ValueError:
                return True
            if isinstance(stats, list):
                with self._pg_stats_lock:
                    self.pg_stats[msg.osd] = (
                        time.time(),
                        msg.epoch,
                        [s for s in stats if isinstance(s, dict)],
                    )
            if isinstance(events, list):
                self._progress_inbox.extend(
                    e for e in events if isinstance(e, dict)
                )
            return True
        if not isinstance(msg, MMgrReport):
            return False
        try:
            spans = json.loads(msg.spans)
        except ValueError:
            spans = []
        if spans:
            self._span_inbox.append((msg.daemon, spans))
        try:
            crashes = json.loads(msg.crashes)
        except ValueError:
            crashes = []
        if isinstance(crashes, list):
            self._crash_inbox.extend(
                c for c in crashes if isinstance(c, dict)
            )
        try:
            dump = json.loads(msg.perf)
        except ValueError:
            return True
        if dump:
            with self._perf_lock:
                self.daemon_perf[msg.daemon] = (time.time(), dump)
        return True

    # -- mgr command surface (MgrCommands dispatch) ------------------------
    def handle_command(self, cmd_json: str) -> MMonCommandReply:
        """Route a command to the owning module (prefix word 1 names
        it: "crash ls" → modules["crash"]); always reply."""
        try:
            cmd = json.loads(cmd_json)
            prefix = cmd.get("prefix", "")
            mod = self.modules.get(prefix.split(" ")[0])
            handler = getattr(mod, "handle_command", None)
            if handler is None:
                return MMonCommandReply(
                    rc=-22, outs=f"unknown mgr command {prefix!r}"
                )
            return handler(cmd)
        except Exception as e:  # noqa: BLE001 — the RPC contract
            return MMonCommandReply(
                rc=-22, outs=f"{type(e).__name__}: {e}"
            )

    def ms_handle_reset(self, conn) -> None:
        pass

    def set_module_option(self, module: str, key: str, value) -> None:
        self.module_options.setdefault(module, {})[key] = value

    def start(self, mon_addrs) -> None:
        if isinstance(mon_addrs, tuple):
            mon_addrs = [mon_addrs]
        host, port = self.messenger.bind()
        self.addr = f"{host}:{port}"
        self.monc.connect_any(mon_addrs)
        self._beacon()
        for mtype in self._module_types:
            mod = mtype(self)
            self.modules[mod.NAME] = mod
        if self.shared_services:
            stack = self.messenger._stack
            self._cmd_strand = stack.offload.strand()
            self._tick_handle = stack.timers.every(
                0.2, self._tick_once
            )
        else:
            self._ticker = threading.Thread(
                target=self._tick_loop, name="mgr.tick", daemon=True
            )
            self._ticker.start()

    def _beacon(self) -> None:
        try:
            self.monc.command(
                {
                    "prefix": "mgr beacon",
                    "name": self.name,
                    "addr": self.addr,
                }
            )
        except Exception:  # noqa: BLE001 — beacons retry on the tick
            pass

    def shutdown(self) -> None:
        self._stop.set()
        if self._tick_handle is not None:
            self._tick_handle.cancel()
        if self._ticker is not None:
            self._ticker.join(timeout=5)
        for mod in self.modules.values():
            try:
                mod.shutdown()
            except Exception:  # noqa: BLE001
                pass
        self.messenger.shutdown()

    def _tick_loop(self) -> None:
        while not self._stop.wait(0.2):
            self._tick_once()

    def _tick_once(self) -> None:
        if self._stop.is_set():
            return
        now = time.monotonic()
        if now - self._last_beacon > 2.0:
            self._last_beacon = now
            self._beacon()
        for mod in self.modules.values():
            if now - mod._last_tick < mod.TICK_EVERY:
                continue
            mod._last_tick = now
            try:
                mod.serve()
            except Exception as e:  # noqa: BLE001 — a module must
                # not kill the host (mgr module crash containment);
                # the contained crash still files a report
                import traceback

                traceback.print_exc()
                crash_util.capture(
                    f"mgr.{self.name}",
                    e,
                    clog=self.clog,
                    extra_meta={"module": mod.NAME},
                )
        self._log_client.flush(self.monc)

    # -- cluster state snapshots (MgrModule.get) ---------------------------
    def get(self, what: str):
        m = self.monc.osdmap
        if m is None:
            return None
        if what == "osd_map":
            return m
        if what == "osd_stats":
            return {
                "epoch": m.epoch,
                "num_osds": m.max_osd,
                "num_up": sum(
                    1 for o in range(m.max_osd) if m.is_up(o)
                ),
                "num_in": sum(
                    1
                    for o in range(m.max_osd)
                    if m.exists(o) and m.osd_weight[o] > 0
                ),
            }
        if what == "pg_summary":
            total = sum(p.pg_num for p in m.pools.values())
            return {
                "num_pools": len(m.pools),
                "num_pgs": total,
                "by_pool": {
                    pid: p.pg_num for pid, p in m.pools.items()
                },
            }
        if what == "daemon_perf":
            cutoff = time.time() - 30.0
            with self._perf_lock:
                for d in [
                    d
                    for d, (ts, _dump) in self.daemon_perf.items()
                    if ts < cutoff
                ]:
                    del self.daemon_perf[d]  # dead daemon: stop
                    # exporting a frozen, live-looking series
                return {
                    d: dump for d, (_ts, dump) in self.daemon_perf.items()
                }
        if what == "pg_stats":
            # merged primary view: pgid -> freshest stat dict across
            # reporting OSDs (freshest by (reported_epoch, recv ts));
            # silence past the grace drops an OSD's contribution, so
            # a dead primary's stale rows age out like daemon_perf
            cutoff = time.time() - 30.0
            merged: dict[str, tuple[tuple, dict]] = {}
            with self._pg_stats_lock:
                for osd in [
                    o for o, (ts, _e, _s) in self.pg_stats.items()
                    if ts < cutoff
                ]:
                    del self.pg_stats[osd]
                for _osd, (ts, _epoch, stats) in self.pg_stats.items():
                    for st in stats:
                        pgid = st.get("pgid")
                        if not isinstance(pgid, str):
                            continue
                        rank = (st.get("reported_epoch", 0), ts)
                        cur = merged.get(pgid)
                        if cur is None or rank > cur[0]:
                            merged[pgid] = (rank, st)
            return {pgid: st for pgid, (_r, st) in merged.items()}
        if what == "df":
            return {
                "pools": [
                    {
                        "name": m.pool_names.get(pid, str(pid)),
                        "id": pid,
                        "type": p.type,
                        "size": p.size,
                        "pg_num": p.pg_num,
                    }
                    for pid, p in m.pools.items()
                ],
            }
        raise KeyError(f"unknown mgr state {what!r}")


class StatusModule(MgrModule):
    """Health rollup (the mgr status/health surface).  The tick polls
    the mon's authoritative rollup (`health`, with mute-aware
    checks_detail) and the cluster-log counters (`log stat`) so the
    prometheus exporter and dashboard serve them without a mon
    round-trip per scrape."""

    NAME = "status"
    TICK_EVERY = 2.0  # two mon round-trips per tick: keep it off the
    # hot path (scrapes read the cache)

    def __init__(self, mgr: "Manager"):
        super().__init__(mgr)
        self.last_health: dict = {}
        self.last_log_stat: dict = {}

    def serve(self) -> None:
        # SHORT timeout: these are cache refreshes on the shared mgr
        # tick thread — during a mon outage the default 15s failover
        # retry would stall every other module's tick
        try:
            reply = self.mon_command({"prefix": "health"}, timeout=2.0)
            if reply.rc == 0 and reply.outb:
                self.last_health = json.loads(reply.outb)
            reply = self.mon_command(
                {"prefix": "log stat"}, timeout=2.0
            )
            if reply.rc == 0 and reply.outb:
                self.last_log_stat = json.loads(reply.outb)
        except Exception:  # noqa: BLE001 — mon away: keep last known
            pass

    def health(self) -> dict:
        stats = self.get("osd_stats")
        if stats is None:
            return {"status": "HEALTH_WARN", "checks": ["no map"]}
        if self.last_health:
            return {**self.last_health, **stats}
        # no mon rollup yet: degrade to the local map view
        checks = []
        if stats["num_up"] < stats["num_in"]:
            checks.append(
                f"{stats['num_in'] - stats['num_up']} osds down"
            )
        return {
            "status": "HEALTH_OK" if not checks else "HEALTH_WARN",
            "checks": checks,
            **stats,
        }


class BalancerModule(MgrModule):
    """Active upmap balancing (src/pybind/mgr/balancer, mode=upmap):
    plan on a map copy, commit the delta via pg-upmap-items."""

    NAME = "balancer"
    TICK_EVERY = 1.0

    def __init__(self, mgr: "Manager"):
        super().__init__(mgr)
        self.last_plan: dict = {}
        self.plans_applied = 0

    def serve(self) -> None:
        if not self.get_module_option("active", False):
            return
        m = self.get("osd_map")
        if m is None:
            return
        from ..osd.balancer import calc_pg_upmaps

        plan_map = copy.deepcopy(m)
        changed = calc_pg_upmaps(
            plan_map,
            max_deviation=int(
                self.get_module_option("upmap_max_deviation", 1)
            ),
            max_changes=int(
                self.get_module_option("max_optimizations", 10)
            ),
        )
        if not changed:
            return
        delta = {
            pg: items
            for pg, items in plan_map.pg_upmap_items.items()
            if m.pg_upmap_items.get(pg) != items
        }
        self.last_plan = {
            f"{pid}.{ps}": items for (pid, ps), items in delta.items()
        }
        for (pid, ps), items in delta.items():
            reply = self.mon_command(
                {
                    "prefix": "osd pg-upmap-items",
                    "pgid": f"{pid}.{ps}",
                    "mappings": [list(i) for i in items],
                }
            )
            if reply.rc == 0:
                self.plans_applied += 1


class PrometheusModule(MgrModule):
    """/metrics exporter in the Prometheus text format
    (src/pybind/mgr/prometheus)."""

    NAME = "prometheus"

    def __init__(self, mgr: "Manager"):
        super().__init__(mgr)
        self.port = int(self.get_module_option("port", 0))
        module = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = module.render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self.server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", self.port), Handler
        )
        self.port = self.server.server_address[1]
        threading.Thread(
            target=self.server.serve_forever,
            name="mgr.prometheus",
            daemon=True,
        ).start()

    def shutdown(self) -> None:
        self.server.shutdown()

    # exposition-format hygiene (the prometheus module's
    # promethize()): metric names allow [a-zA-Z0-9_:], label values
    # need \ and " escaped
    _BAD_NAME = re.compile(r"[^a-zA-Z0-9_:]")

    @classmethod
    def sanitize_name(cls, name: str) -> str:
        name = cls._BAD_NAME.sub("_", name)
        if name and name[0].isdigit():
            name = "_" + name
        return name

    @staticmethod
    def escape_label(value: str) -> str:
        return (
            str(value)
            .replace("\\", r"\\")
            .replace('"', r"\"")
            .replace("\n", r"\n")
        )

    def render(self) -> str:
        out = []
        # one HELP/TYPE header per metric FAMILY: prometheus parsers
        # reject (or silently mis-type) a family whose header arrived
        # under a different family's name
        headered: set[str] = set()

        def metric(name, value, help_=None, labels=None, kind="gauge"):
            name = self.sanitize_name(name)
            if help_ and name not in headered:
                headered.add(name)
                out.append(f"# HELP {name} {help_}")
                out.append(f"# TYPE {name} {kind}")
            lbl = ""
            if labels:
                inner = ",".join(
                    f'{self.sanitize_name(k)}="{self.escape_label(v)}"'
                    for k, v in labels.items()
                )
                lbl = "{" + inner + "}"
            out.append(f"{name}{lbl} {value}")

        stats = self.get("osd_stats")
        if stats is None:
            return "# mgr has no map yet\n"
        metric(
            "ceph_osdmap_epoch", stats["epoch"], "OSDMap epoch"
        )
        metric("ceph_num_osds", stats["num_osds"], "total osds")
        metric("ceph_num_up_osds", stats["num_up"], "up osds")
        metric("ceph_num_in_osds", stats["num_in"], "in osds")
        m = self.get("osd_map")
        for o in range(m.max_osd):
            metric(
                "ceph_osd_up",
                1 if m.is_up(o) else 0,
                "per-osd up state",
                labels={"ceph_daemon": f"osd.{o}"},
            )
        pg = self.get("pg_summary")
        metric("ceph_pg_total", pg["num_pgs"], "total pgs")
        # per-daemon series from MMgrReport perf dumps (the
        # DaemonServer -> exporter plane): plain counters become
        # gauges, avgcount/sum pairs become _count/_sum pairs —
        # every family gets ITS OWN header, once
        for daemon, dump in sorted(
            (self.get("daemon_perf") or {}).items()
        ):
            for cname, val in sorted(dump.items()):
                base = "ceph_daemon_" + cname.replace(".", "_")
                labels = {"ceph_daemon": daemon}
                help_ = f"per-daemon perf counter {cname}"
                if isinstance(val, dict) and "avgcount" in val:
                    metric(
                        base + "_count", val["avgcount"],
                        help_, labels=labels,
                    )
                    metric(
                        base + "_sum", val["sum"],
                        help_, labels=labels,
                    )
                elif isinstance(val, (int, float)):
                    metric(base, val, help_, labels=labels)
        # scrub plane (the data-integrity families): errors/progress/
        # last-scrubbed age per daemon, lifted out of the generic
        # per-daemon dump under their own stable names
        scrub_families = (
            ("scrub_errors", "ceph_osd_scrub_errors",
             "open scrub inconsistencies per osd", "gauge"),
            ("scrubs_active", "ceph_osd_scrubs_active",
             "scrubs in flight per osd", "gauge"),
            ("scrub_chunks", "ceph_osd_scrub_chunks_total",
             "scrub chunks processed (progress)", "counter"),
            ("scrub_last_age", "ceph_osd_scrub_last_age_seconds",
             "seconds since the stalest primary pg was scrubbed",
             "gauge"),
        )
        for daemon, dump in sorted(
            (self.get("daemon_perf") or {}).items()
        ):
            for key, fam, help_, kind in scrub_families:
                if key in dump and isinstance(
                    dump[key], (int, float)
                ):
                    metric(
                        fam, dump[key], help_,
                        labels={"ceph_daemon": daemon}, kind=kind,
                    )
        # latency histograms → NATIVE prometheus histogram families
        # (cumulative le buckets ending +Inf, _sum/_count): the
        # op_hist.<qos>.<type> entries become one labeled family,
        # everything else histogram-shaped gets its own
        from ..common.histogram import is_histogram_snapshot

        hist_families: dict[str, dict] = {}
        for daemon, dump in sorted(
            (self.get("daemon_perf") or {}).items()
        ):
            for cname, val in sorted(dump.items()):
                if not is_histogram_snapshot(val):
                    continue
                if cname.startswith("op_hist."):
                    parts = cname.split(".")
                    fam = "ceph_osd_op_latency_seconds"
                    help_ = (
                        "op completion latency by qos class and "
                        "op type (log2 buckets)"
                    )
                    labels = {
                        "ceph_daemon": daemon,
                        "qos_class": parts[1] if len(parts) > 1 else "",
                        "op_type": parts[2] if len(parts) > 2 else "",
                    }
                else:
                    fam = (
                        "ceph_daemon_"
                        + cname.replace(".", "_")
                        + "_seconds"
                    )
                    help_ = f"per-daemon latency histogram {cname}"
                    labels = {"ceph_daemon": daemon}
                hist_families.setdefault(
                    fam, {"help": help_, "series": []}
                )["series"].append((labels, val))
        for fam, ent in sorted(hist_families.items()):
            if fam in headered:
                continue
            headered.add(fam)
            out.extend(
                histogram_exposition_lines(
                    fam, ent["help"], ent["series"]
                )
            )
        # SLO plane rollups: burn rates + windowed percentiles per
        # class from the slo module's last evaluation
        slo_mod = self.mgr.modules.get("slo")
        status = getattr(slo_mod, "last_status", None) or {}
        for tgt in status.get("targets", []):
            for window in ("fast", "slow"):
                metric(
                    "ceph_slo_burn_rate",
                    tgt.get(f"{window}_burn", 0.0),
                    "error-budget burn rate per slo target and window",
                    labels={
                        "qos_class": tgt.get("qos_class", ""),
                        "percentile": f"{tgt.get('percentile', 0):g}",
                        "window": window,
                    },
                )
        for klass, row in sorted(
            (status.get("classes") or {}).items()
        ):
            for q in (50, 95, 99):
                metric(
                    "ceph_slo_latency_ms",
                    row.get(f"p{q}_ms", 0.0),
                    "windowed latency percentile per qos class",
                    labels={
                        "qos_class": klass, "quantile": f"0.{q}"
                    },
                )
        for entry in self.get("df")["pools"]:
            metric(
                "ceph_pool_pg_num",
                entry["pg_num"],
                "per-pool pg count",
                labels={"pool": entry["name"]},
            )
        # -- event plane: health detail, crash reports, cluster log --------
        status_mod = self.mgr.modules.get("status")
        health = getattr(status_mod, "last_health", None) or {}
        sev = {"HEALTH_OK": 0, "HEALTH_WARN": 1, "HEALTH_ERR": 2}
        metric(
            "ceph_health_status",
            sev.get(health.get("status"), 0),
            "cluster health (0=OK 1=WARN 2=ERR), mutes applied",
        )
        for code, det in sorted(
            (health.get("checks_detail") or {}).items()
        ):
            metric(
                "ceph_health_detail",
                1,
                "active health checks incl. muted ones",
                labels={
                    "name": code,
                    "severity": det.get("severity", "HEALTH_WARN"),
                    "muted": "true" if det.get("muted") else "false",
                },
            )
        crash_mod = self.mgr.modules.get("crash")
        if crash_mod is not None:
            metric(
                "ceph_crash_reports_total",
                crash_mod.total_ingested,
                "crash reports ingested by the mgr crash module",
                kind="counter",  # *_total + monotonic: OpenMetrics
                # parsers reject a gauge under this name
            )
            metric(
                "ceph_crash_reports_recent",
                len(crash_mod.recent()),
                "un-archived recent crashes (the RECENT_CRASH count)",
            )
        log_stat = getattr(status_mod, "last_log_stat", None) or {}
        for key, count in sorted(
            (log_stat.get("by_channel_prio") or {}).items()
        ):
            channel, _, prio = key.partition("/")
            metric(
                "ceph_cluster_log_messages_total",
                count,
                "cluster log entries by channel and priority",
                labels={"channel": channel, "prio": prio},
                kind="counter",
            )
        # -- PG-stats plane: pgmap digest families + progress events -------
        from .pgmap import pgmap_exposition_lines

        pgmap_mod = self.mgr.modules.get("pgmap")
        digest = getattr(pgmap_mod, "digest", None)
        if digest:
            out.extend(pgmap_exposition_lines(digest))
        progress_mod = self.mgr.modules.get("progress")
        if progress_mod is not None:
            events = progress_mod.active_events()
            metric(
                "ceph_progress_events",
                sum(1 for e in events if not e["done"]),
                "open (not yet completed) mgr progress events",
            )
        return "\n".join(out) + "\n"


class TelemetryModule(MgrModule):
    """Cluster telemetry report (src/pybind/mgr/telemetry reduced):
    the same anonymized "basic channel" shape — cluster geometry,
    pool shapes, daemon versions/perf rollups — generated on tick
    and kept as the last report.  Deviation: nothing phones home;
    the report is served locally (module.report() / the dashboard)."""

    NAME = "telemetry"
    TICK_EVERY = 5.0

    def __init__(self, mgr: "Manager"):
        super().__init__(mgr)
        self.last_report: dict = {}
        self.reports_generated = 0

    def report(self) -> dict:
        from ..version import FRAMEWORK_VERSION

        stats = self.get("osd_stats") or {}
        pg = self.get("pg_summary") or {}
        df = self.get("df") or {"pools": []}
        perf = self.get("daemon_perf") or {}
        rep = {
            "report_version": 1,
            "version": FRAMEWORK_VERSION,
            "created": time.time(),
            "cluster": stats,
            "pg": pg,
            "pools": [
                # anonymized shape, not names (telemetry's
                # basic-channel redaction)
                {"id": p["id"], "type": p["type"],
                 "size": p["size"], "pg_num": p["pg_num"]}
                for p in df["pools"]
            ],
            "daemons": {
                "count": len(perf),
                "kinds": sorted(
                    {d.split(".")[0] for d in perf}
                ),
                "total_client_ops": sum(
                    (dump.get("op") or {}).get("value", 0)
                    if isinstance(dump.get("op"), dict)
                    else dump.get("op", 0)
                    for dump in perf.values()
                ),
            },
        }
        return rep

    def serve(self) -> None:
        self.last_report = self.report()
        self.reports_generated += 1


class DashboardModule(MgrModule):
    """Minimal dashboard (src/pybind/mgr/dashboard reduced to the
    read-only status surface): an HTTP endpoint serving a live HTML
    cluster overview plus JSON APIs (/api/health, /api/osds,
    /api/pools, /api/daemons, /api/telemetry)."""

    NAME = "dashboard"

    def __init__(self, mgr: "Manager"):
        super().__init__(mgr)
        module = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _reply(self, body: bytes, ctype: str):
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    if self.path in ("/", "/index.html"):
                        self._reply(
                            module.render_html().encode(),
                            "text/html",
                        )
                    elif self.path.startswith("/api/"):
                        payload = module.api(self.path[5:])
                        self._reply(
                            json.dumps(payload).encode(),
                            "application/json",
                        )
                    else:
                        self.send_response(404)
                        self.end_headers()
                except Exception:  # noqa: BLE001 — a half-up mgr
                    # must answer 500, not kill the handler thread
                    self.send_response(500)
                    self.end_headers()

            def log_message(self, *a):  # quiet
                pass

        self.server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", int(self.get_module_option("port", 0))),
            Handler,
        )
        self.port = self.server.server_address[1]
        threading.Thread(
            target=self.server.serve_forever,
            name="mgr.dashboard",
            daemon=True,
        ).start()

    def shutdown(self) -> None:
        self.server.shutdown()

    def api(self, what: str):
        if what == "health":
            mod = self.mgr.modules.get("status")
            if isinstance(mod, StatusModule):
                return mod.health()
            return self.get("osd_stats")
        if what == "osds":
            m = self.get("osd_map")
            return [
                {
                    "osd": o,
                    "up": m.is_up(o),
                    "in": m.exists(o) and m.osd_weight[o] > 0,
                    "addr": m.osd_addrs.get(o, ""),
                }
                for o in range(m.max_osd)
            ] if m is not None else []
        if what == "pools":
            return (self.get("df") or {}).get("pools", [])
        if what == "daemons":
            return self.get("daemon_perf") or {}
        if what == "telemetry":
            mod = self.mgr.modules.get("telemetry")
            if isinstance(mod, TelemetryModule):
                return mod.report()
            return {}
        if what == "crashes":
            mod = self.mgr.modules.get("crash")
            if isinstance(mod, CrashModule):
                mod.ingest_pending()
                return mod.ls()
            return []
        if what == "log":
            try:
                # short timeout: this runs per HTTP request — a dead
                # mon must not hang page loads for the 15s failover
                reply = self.mgr.monc.command(
                    {"prefix": "log last", "num": 20}, timeout=2.0
                )
                if reply.rc == 0 and reply.outb:
                    return json.loads(reply.outb)
            except Exception:  # noqa: BLE001 — mon away
                pass
            return []
        raise KeyError(what)

    def render_html(self) -> str:
        health = self.api("health") or {}
        osds = self.api("osds")
        pools = self.api("pools")
        rows = "".join(
            f"<tr><td>osd.{o['osd']}</td>"
            f"<td>{'up' if o['up'] else 'down'}</td>"
            f"<td>{'in' if o['in'] else 'out'}</td>"
            f"<td>{o['addr']}</td></tr>"
            for o in osds
        )
        prows = "".join(
            f"<tr><td>{p['name']}</td><td>{p['pg_num']}</td>"
            f"<td>{'ec' if p['type'] == 3 else 'rep'}</td>"
            f"<td>{p['size']}</td></tr>"
            for p in pools
        )
        import html as _html

        crashes = self.api("crashes")
        recent_log = self.api("log")
        # clog messages are remotely-injectable free text (`ceph log
        # <anything>`): escape EVERY field or the dashboard is stored
        # XSS for whoever can reach the mon
        lrows = "".join(
            "<tr>"
            + "".join(
                f"<td>{_html.escape(str(e.get(k, '')))}</td>"
                for k in ("name", "channel", "prio", "message")
            )
            + "</tr>"
            for e in recent_log[-10:]
        )
        muted = _html.escape(
            ", ".join(health.get("muted", [])) or "none"
        )
        # health summaries carry wire-injectable text too (SLOW_OPS
        # embeds reporter daemon names): escape like the log rows
        status = _html.escape(str(health.get("status", "?")))
        checks = _html.escape(
            ", ".join(health.get("checks", [])) or "no checks"
        )
        return (
            "<html><head><title>ceph-tpu</title></head><body>"
            f"<h1>cluster: {status}</h1>"
            f"<p>{checks}"
            f"</p><p>muted checks: {muted} &middot; crash reports: "
            f"{len(crashes)}</p>"
            "<h2>osds</h2><table border=1><tr><th>osd</th>"
            f"<th>state</th><th>in/out</th><th>addr</th></tr>{rows}"
            "</table><h2>pools</h2><table border=1><tr><th>name</th>"
            f"<th>pg_num</th><th>type</th><th>size</th></tr>{prows}"
            "</table><h2>cluster log</h2><table border=1>"
            "<tr><th>from</th><th>channel</th><th>prio</th>"
            f"<th>message</th></tr>{lrows}</table></body></html>"
        )


class TracingModule(MgrModule):
    """Cross-daemon trace assembly (the collection half of the
    blkin/ZTracer seat; op_tracker.py's docstring promised the
    correlation, this module delivers it).

    Daemons piggyback drained spans on their MMgrReport pushes; this
    module drains the manager's span inbox on its tick, indexes spans
    by trace id, and serves one logical op's spans — from the client,
    the primary, and every replica/shard — as a single tree
    (``get_trace``).  Traces are bounded LRU-by-insertion
    (``max_traces``); a trace stops accepting spans ``trace_ttl``
    after its first span arrived, so an id reused much later starts a
    fresh entry instead of gluing two ops together."""

    NAME = "tracing"
    TICK_EVERY = 0.2

    def __init__(self, mgr: "Manager"):
        super().__init__(mgr)
        self.max_traces = int(self.get_module_option("max_traces", 512))
        self.trace_ttl = float(self.get_module_option("trace_ttl", 600.0))
        # trace id -> {"first_seen": ts, "spans": [span dicts]}
        self._traces: OrderedDict[str, dict] = OrderedDict()
        self._lock = threading.Lock()
        self.spans_ingested = 0

    def serve(self) -> None:
        self.ingest_pending()

    def ingest_pending(self) -> None:
        """Drain the manager's span inbox (callable directly so tests
        and admin surfaces need not wait a tick)."""
        while True:
            try:
                daemon, spans = self.mgr._span_inbox.popleft()
            except IndexError:
                return
            self._ingest(daemon, spans)

    def _ingest(self, daemon: str, spans: list) -> None:
        now = time.time()
        with self._lock:
            for span in spans:
                if not isinstance(span, dict) or not span.get("trace_id"):
                    continue
                span.setdefault("daemon", daemon)
                entry = self._traces.get(span["trace_id"])
                if entry is None:
                    entry = {"first_seen": now, "spans": []}
                    self._traces[span["trace_id"]] = entry
                    while len(self._traces) > self.max_traces:
                        self._traces.popitem(last=False)
                elif now - entry["first_seen"] > self.trace_ttl:
                    entry = {"first_seen": now, "spans": []}
                    self._traces[span["trace_id"]] = entry
                entry["spans"].append(span)
                self.spans_ingested += 1

    # -- query surface -----------------------------------------------------
    def traces(self) -> list[str]:
        with self._lock:
            return list(self._traces)

    def get_trace(self, trace_id: str) -> dict:
        """One logical op as a span TREE across daemons: explicit
        parent ids when the spans carry them, role-rank attachment
        (client < primary < replica/shard) for the cross-daemon hops
        the wire does not encode."""
        with self._lock:
            entry = self._traces.get(trace_id)
            spans = list(entry["spans"]) if entry else []
        return {
            "trace_id": trace_id,
            "num_spans": len(spans),
            "daemons": sorted({s.get("daemon", "") for s in spans}),
            "roots": tracing.assemble_tree(spans),
        }

    def dump(self, qos_class: str = "") -> dict:
        """Summary of every held trace (the dump_traces rollup).
        ``qos_class`` keeps only traces whose spans carry that class
        tag (the objecter stamps it on every root span, the primary
        on every osd_op span — PR 1 left class invisible here)."""
        with self._lock:
            entries = {
                tid: e
                for tid, e in self._traces.items()
                if not qos_class
                or any(
                    s.get("tags", {}).get("qos_class") == qos_class
                    for s in e["spans"]
                )
            }
            return {
                "num_traces": len(entries),
                "spans_ingested": self.spans_ingested,
                "qos_class": qos_class,
                "traces": {
                    tid: {
                        "num_spans": len(e["spans"]),
                        "daemons": sorted(
                            {
                                s.get("daemon", "")
                                for s in e["spans"]
                            }
                        ),
                    }
                    for tid, e in entries.items()
                },
            }

    def handle_command(self, cmd: dict) -> MMonCommandReply:
        """`ceph tracing dump [qos_class=X]` / `ceph tracing
        summary` — the per-class filter/aggregation surface (routed
        to the active mgr like crash/slo commands)."""
        self.ingest_pending()  # fresh spans show up now
        prefix = cmd.get("prefix", "")
        if prefix == "tracing dump":
            return MMonCommandReply(
                outb=json.dumps(
                    self.dump(str(cmd.get("qos_class", "")))
                )
            )
        if prefix == "tracing summary":
            return MMonCommandReply(
                outb=json.dumps(self.class_summary())
            )
        return MMonCommandReply(
            rc=-22, outs=f"unknown tracing command {prefix!r}"
        )

    def class_summary(self) -> dict:
        """Span counts + mean duration per qos_class across every
        held trace — the per-class aggregation seat."""
        agg: dict[str, dict] = {}
        with self._lock:
            spans = [
                s
                for e in self._traces.values()
                for s in e["spans"]
            ]
        for s in spans:
            klass = str(
                (s.get("tags") or {}).get("qos_class") or "untagged"
            )
            row = agg.setdefault(
                klass, {"spans": 0, "total_duration": 0.0}
            )
            row["spans"] += 1
            row["total_duration"] += float(s.get("duration", 0.0))
        for row in agg.values():
            row["mean_duration"] = (
                row["total_duration"] / row["spans"]
                if row["spans"]
                else 0.0
            )
        return agg


class CrashModule(MgrModule):
    """Crash-report collection (src/pybind/mgr/crash reduced): drains
    reports piggybacked on MMgrReport plus the process-global pending
    queue (co-hosted daemons), dedupes by crash_id, serves
    ``ceph crash ls / info <id> / stat / archive [<id>|all]``, and
    keeps the mon's RECENT_CRASH count current via the "crash report"
    command — archiving pushes the cleared count, which clears the
    health warning."""

    NAME = "crash"
    TICK_EVERY = 0.5
    # un-archived crashes younger than this raise RECENT_CRASH
    # (mgr/crash/warn_recent_interval; the reference defaults to two
    # weeks)
    DEFAULT_WARN_RECENT_INTERVAL = 14 * 24 * 3600.0

    def __init__(self, mgr: "Manager"):
        super().__init__(mgr)
        self.max_reports = int(self.get_module_option("max_reports", 128))
        self.crashes: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()
        self.total_ingested = 0
        self._last_reported: int | None = None
        self._last_report_time = 0.0

    def serve(self) -> None:
        self.ingest_pending()
        self._report_health()

    # -- ingest ------------------------------------------------------------
    def ingest_pending(self) -> None:
        """Drain both delivery paths (callable directly so tests need
        not wait a tick)."""
        while True:
            try:
                report = self.mgr._crash_inbox.popleft()
            except IndexError:
                break
            self._ingest(report)
        for report in crash_util.drain_pending():
            self._ingest(report)

    def _ingest(self, report: dict) -> None:
        cid = report.get("crash_id")
        if not cid or not isinstance(cid, str):
            return
        with self._lock:
            if cid in self.crashes:
                return  # double delivery (wire + global queue)
            report.setdefault("archived", False)
            self.crashes[cid] = report
            self.total_ingested += 1
            while len(self.crashes) > self.max_reports:
                self.crashes.popitem(last=False)

    # -- health ------------------------------------------------------------
    def _is_recent(self, report: dict, cutoff: float) -> bool:
        """The ONE recency predicate (health count and `crash stat`
        must never disagree)."""
        return (
            not report.get("archived")
            and float(report.get("timestamp", 0)) >= cutoff
        )

    def _recent_cutoff(self) -> float:
        interval = float(
            self.get_module_option(
                "warn_recent_interval",
                self.DEFAULT_WARN_RECENT_INTERVAL,
            )
        )
        return time.time() - interval

    def recent(self) -> list[dict]:
        cutoff = self._recent_cutoff()
        with self._lock:
            return [
                r
                for r in self.crashes.values()
                if self._is_recent(r, cutoff)
            ]

    def _report_health(self) -> None:
        n = len(self.recent())
        now = time.monotonic()
        # re-push an UNCHANGED count every few seconds anyway: the
        # mon holds it in memory only, so a restarted mon would
        # otherwise show HEALTH_OK over un-archived crashes forever
        # (the SLOW_OPS re-report idiom)
        if n == self._last_reported and now - self._last_report_time < 5.0:
            return
        try:
            reply = self.mon_command(
                {"prefix": "crash report", "num_recent": n},
                timeout=2.0,  # tick thread: never stall other modules
            )
            if reply.rc == 0:
                self._last_reported = n
                self._last_report_time = now
        except Exception:  # noqa: BLE001 — retried next tick
            pass

    # -- query/command surface ---------------------------------------------
    def ls(self) -> list[dict]:
        with self._lock:
            return sorted(
                (
                    {
                        "crash_id": r["crash_id"],
                        "entity_name": r.get("entity_name", ""),
                        "timestamp_iso": r.get("timestamp_iso", ""),
                        "exception": r.get("exception", ""),
                        "archived": bool(r.get("archived")),
                    }
                    for r in self.crashes.values()
                ),
                key=lambda r: r["crash_id"],
            )

    def info(self, crash_id: str) -> dict | None:
        with self._lock:
            return self.crashes.get(crash_id)

    def stat(self) -> dict:
        cutoff = self._recent_cutoff()
        with self._lock:
            archived = sum(
                1 for r in self.crashes.values() if r.get("archived")
            )
            return {
                "total_ingested": self.total_ingested,
                "held": len(self.crashes),
                "archived": archived,
                "recent": sum(
                    1
                    for r in self.crashes.values()
                    if self._is_recent(r, cutoff)
                ),
            }

    def archive(self, crash_id: str) -> bool:
        with self._lock:
            report = self.crashes.get(crash_id)
            if report is None:
                return False
            report["archived"] = True
        self._report_health()
        return True

    def archive_all(self) -> int:
        with self._lock:
            n = 0
            for r in self.crashes.values():
                if not r.get("archived"):
                    r["archived"] = True
                    n += 1
        self._report_health()
        return n

    def handle_command(self, cmd: dict) -> MMonCommandReply:
        prefix = cmd.get("prefix", "")
        self.ingest_pending()  # a just-crashed daemon shows up now
        if prefix == "crash ls":
            rows = self.ls()
            return MMonCommandReply(
                outs="\n".join(
                    f"{r['crash_id']}  {r['entity_name']}"
                    + ("  (archived)" if r["archived"] else "")
                    for r in rows
                ),
                outb=json.dumps(rows),
            )
        if prefix == "crash info":
            report = self.info(str(cmd.get("id", "")))
            if report is None:
                return MMonCommandReply(
                    rc=-2, outs="no such crash (-ENOENT)"
                )
            return MMonCommandReply(outb=json.dumps(report))
        if prefix == "crash stat":
            return MMonCommandReply(outb=json.dumps(self.stat()))
        if prefix == "crash archive":
            target = str(cmd.get("id", ""))
            if target == "all":
                n = self.archive_all()
                return MMonCommandReply(
                    outs=f"archived {n} crash report(s)"
                )
            if not self.archive(target):
                return MMonCommandReply(
                    rc=-2, outs="no such crash (-ENOENT)"
                )
            return MMonCommandReply(outs=f"archived {target}")
        return MMonCommandReply(
            rc=-22, outs=f"unknown crash command {prefix!r}"
        )


class PgAutoscalerModule(MgrModule):
    """pg_num autoscaling (src/pybind/mgr/pg_autoscaler/module.py
    reduced): per replicated pool, the ideal pg count is the power of
    two nearest target_pgs_per_osd * in-osds / (pools * size); an
    undersized pool gets a recommendation, and in mode "on" the
    module commits the increase through "osd pool set pg_num"
    (primaries split by stable_mod re-homing when they observe the
    map).  Erasure pools split like any other: the pool-type-agnostic
    re-homing path decodes whole objects and re-writes them through
    the child primary's EC write (the reference's split machinery is
    pool-type-agnostic too, src/osd/OSDMap.cc)."""

    NAME = "pg_autoscaler"
    TICK_EVERY = 1.0

    def __init__(self, mgr: "Manager"):
        super().__init__(mgr)
        self.recommendations: dict[str, dict] = {}
        self.applied = 0

    def _ideal(self, m, pool) -> int:
        target_per_osd = int(
            self.get_module_option("target_pgs_per_osd", 32)
        )
        num_in = max(
            1,
            sum(
                1
                for o in range(m.max_osd)
                if m.exists(o) and m.osd_weight[o] > 0
            ),
        )
        npools = max(1, len(m.pools))
        raw = target_per_osd * num_in / (npools * max(pool.size, 1))
        ideal = 1
        while ideal * 2 <= raw:
            ideal *= 2
        return max(ideal, pool.pg_num)

    def serve(self) -> None:
        m = self.get("osd_map")
        if m is None:
            return
        for pid, pool in list(m.pools.items()):
            ideal = self._ideal(m, pool)
            name = m.pool_names.get(pid, str(pid))
            if ideal > pool.pg_num:
                self.recommendations[name] = {
                    "current": pool.pg_num,
                    "ideal": ideal,
                }
                if self.get_module_option("mode", "warn") == "on":
                    # one doubling per tick: bounded splitting churn,
                    # the reference's max_misplaced throttling role
                    step = min(ideal, pool.pg_num * 2)
                    reply = self.mon_command(
                        {
                            "prefix": "osd pool set",
                            "pool": name,
                            "var": "pg_num",
                            "val": str(step),
                        }
                    )
                    if reply.rc == 0:
                        self.applied += 1
            else:
                self.recommendations.pop(name, None)


# imported last: slo.py subclasses MgrModule from this module (the
# bottom import breaks the would-be cycle)
from .slo import SLOModule  # noqa: E402
from .pgmap import PgMapModule  # noqa: E402
from .progress import ProgressModule  # noqa: E402

__all__.extend(["SLOModule", "PgMapModule", "ProgressModule"])
