"""Manager daemon — the module host
(src/mgr/Mgr.cc + src/pybind/mgr/mgr_module.py).

The reference mgr embeds CPython to run python modules against
cluster state it mirrors from the monitors.  Here the host IS python:
``Manager`` keeps a live OSDMap via a MonClient subscription, hosts
``MgrModule`` subclasses on a shared tick, and gives them the
mgr_module surface that matters:

- ``self.get("osd_map") / get("pg_summary") / get("df")`` — cluster
  state snapshots
- ``self.mon_command(cmd)`` — the command path back to the quorum
- per-module config via ``set_module_option``

Built-in modules (the pybind/mgr counterparts):

- ``balancer`` — runs the upmap balancer library
  (ceph_tpu/osd/balancer.py calc_pg_upmaps) on a COPY of the map and
  commits the new pg_upmap_items through "osd pg-upmap-items", the
  reference balancer module's active mode.
- ``prometheus`` — an HTTP /metrics endpoint in the Prometheus text
  exposition format (ceph_osd_up, ceph_osd_in, ceph_pool_*,
  ceph_pg_total ...), the src/pybind/mgr/prometheus role.
- ``status`` — health/df rollups for the CLI surface.
"""

from __future__ import annotations

import copy
import http.server
import json
import threading
import time

from ..mon.monitor import MonClient
from ..msg import Messenger

__all__ = ["Manager", "MgrModule"]


class MgrModule:
    """Base class for manager modules (mgr_module.MgrModule)."""

    NAME = "module"
    TICK_EVERY = 1.0  # seconds between serve() calls

    def __init__(self, mgr: "Manager"):
        self.mgr = mgr
        self._last_tick = 0.0

    # -- the mgr_module surface -------------------------------------------
    def get(self, what: str):
        return self.mgr.get(what)

    def mon_command(self, cmd: dict):
        return self.mgr.monc.command(cmd)

    def get_module_option(self, key: str, default=None):
        return self.mgr.module_options.get(self.NAME, {}).get(
            key, default
        )

    def serve(self) -> None:  # pragma: no cover — interface hook
        """Called on the host tick, at most every TICK_EVERY s."""

    def shutdown(self) -> None:
        pass


class Manager:
    """The mgr daemon: mon session + module host (Mgr.cc)."""

    def __init__(self, modules: list[type[MgrModule]] | None = None):
        self.messenger = Messenger("mgr")
        self.monc = MonClient(self.messenger, whoami=-2)
        self.module_options: dict[str, dict] = {}
        self._module_types = list(
            modules
            if modules is not None
            else [BalancerModule, PrometheusModule, StatusModule]
        )
        self.modules: dict[str, MgrModule] = {}
        self._ticker: threading.Thread | None = None
        self._stop = threading.Event()

    def set_module_option(self, module: str, key: str, value) -> None:
        self.module_options.setdefault(module, {})[key] = value

    def start(self, mon_addrs) -> None:
        if isinstance(mon_addrs, tuple):
            mon_addrs = [mon_addrs]
        self.monc.connect_any(mon_addrs)
        for mtype in self._module_types:
            mod = mtype(self)
            self.modules[mod.NAME] = mod
        self._ticker = threading.Thread(
            target=self._tick_loop, name="mgr.tick", daemon=True
        )
        self._ticker.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5)
        for mod in self.modules.values():
            try:
                mod.shutdown()
            except Exception:  # noqa: BLE001
                pass
        self.messenger.shutdown()

    def _tick_loop(self) -> None:
        while not self._stop.wait(0.2):
            now = time.monotonic()
            for mod in self.modules.values():
                if now - mod._last_tick < mod.TICK_EVERY:
                    continue
                mod._last_tick = now
                try:
                    mod.serve()
                except Exception:  # noqa: BLE001 — a module must not
                    # kill the host (mgr module crash containment)
                    import traceback

                    traceback.print_exc()

    # -- cluster state snapshots (MgrModule.get) ---------------------------
    def get(self, what: str):
        m = self.monc.osdmap
        if m is None:
            return None
        if what == "osd_map":
            return m
        if what == "osd_stats":
            return {
                "epoch": m.epoch,
                "num_osds": m.max_osd,
                "num_up": sum(
                    1 for o in range(m.max_osd) if m.is_up(o)
                ),
                "num_in": sum(
                    1
                    for o in range(m.max_osd)
                    if m.exists(o) and m.osd_weight[o] > 0
                ),
            }
        if what == "pg_summary":
            total = sum(p.pg_num for p in m.pools.values())
            return {
                "num_pools": len(m.pools),
                "num_pgs": total,
                "by_pool": {
                    pid: p.pg_num for pid, p in m.pools.items()
                },
            }
        if what == "df":
            return {
                "pools": [
                    {
                        "name": m.pool_names.get(pid, str(pid)),
                        "id": pid,
                        "type": p.type,
                        "size": p.size,
                        "pg_num": p.pg_num,
                    }
                    for pid, p in m.pools.items()
                ],
            }
        raise KeyError(f"unknown mgr state {what!r}")


class StatusModule(MgrModule):
    """Health rollup (the mgr status/health surface)."""

    NAME = "status"

    def health(self) -> dict:
        stats = self.get("osd_stats")
        if stats is None:
            return {"status": "HEALTH_WARN", "checks": ["no map"]}
        checks = []
        if stats["num_up"] < stats["num_in"]:
            checks.append(
                f"{stats['num_in'] - stats['num_up']} osds down"
            )
        return {
            "status": "HEALTH_OK" if not checks else "HEALTH_WARN",
            "checks": checks,
            **stats,
        }


class BalancerModule(MgrModule):
    """Active upmap balancing (src/pybind/mgr/balancer, mode=upmap):
    plan on a map copy, commit the delta via pg-upmap-items."""

    NAME = "balancer"
    TICK_EVERY = 1.0

    def __init__(self, mgr: "Manager"):
        super().__init__(mgr)
        self.last_plan: dict = {}
        self.plans_applied = 0

    def serve(self) -> None:
        if not self.get_module_option("active", False):
            return
        m = self.get("osd_map")
        if m is None:
            return
        from ..osd.balancer import calc_pg_upmaps

        plan_map = copy.deepcopy(m)
        changed = calc_pg_upmaps(
            plan_map,
            max_deviation=int(
                self.get_module_option("upmap_max_deviation", 1)
            ),
            max_changes=int(
                self.get_module_option("max_optimizations", 10)
            ),
        )
        if not changed:
            return
        delta = {
            pg: items
            for pg, items in plan_map.pg_upmap_items.items()
            if m.pg_upmap_items.get(pg) != items
        }
        self.last_plan = {
            f"{pid}.{ps}": items for (pid, ps), items in delta.items()
        }
        for (pid, ps), items in delta.items():
            reply = self.mon_command(
                {
                    "prefix": "osd pg-upmap-items",
                    "pgid": f"{pid}.{ps}",
                    "mappings": [list(i) for i in items],
                }
            )
            if reply.rc == 0:
                self.plans_applied += 1


class PrometheusModule(MgrModule):
    """/metrics exporter in the Prometheus text format
    (src/pybind/mgr/prometheus)."""

    NAME = "prometheus"

    def __init__(self, mgr: "Manager"):
        super().__init__(mgr)
        self.port = int(self.get_module_option("port", 0))
        module = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = module.render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self.server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", self.port), Handler
        )
        self.port = self.server.server_address[1]
        threading.Thread(
            target=self.server.serve_forever,
            name="mgr.prometheus",
            daemon=True,
        ).start()

    def shutdown(self) -> None:
        self.server.shutdown()

    def render(self) -> str:
        out = []

        def metric(name, value, help_=None, labels=None):
            if help_:
                out.append(f"# HELP {name} {help_}")
                out.append(f"# TYPE {name} gauge")
            lbl = ""
            if labels:
                inner = ",".join(
                    f'{k}="{v}"' for k, v in labels.items()
                )
                lbl = "{" + inner + "}"
            out.append(f"{name}{lbl} {value}")

        stats = self.get("osd_stats")
        if stats is None:
            return "# mgr has no map yet\n"
        metric(
            "ceph_osdmap_epoch", stats["epoch"], "OSDMap epoch"
        )
        metric("ceph_num_osds", stats["num_osds"], "total osds")
        metric("ceph_num_up_osds", stats["num_up"], "up osds")
        metric("ceph_num_in_osds", stats["num_in"], "in osds")
        m = self.get("osd_map")
        for o in range(m.max_osd):
            metric(
                "ceph_osd_up",
                1 if m.is_up(o) else 0,
                "per-osd up state" if o == 0 else None,
                labels={"ceph_daemon": f"osd.{o}"},
            )
        pg = self.get("pg_summary")
        metric("ceph_pg_total", pg["num_pgs"], "total pgs")
        for entry in self.get("df")["pools"]:
            metric(
                "ceph_pool_pg_num",
                entry["pg_num"],
                "per-pool pg count"
                if entry is self.get("df")["pools"][0]
                else None,
                labels={"pool": entry["name"]},
            )
        return "\n".join(out) + "\n"
