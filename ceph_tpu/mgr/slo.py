"""mgr ``slo`` module — cluster-wide latency SLOs over the histogram
plane (the prometheus/alert-rule seat pulled into the mgr, shaped
like the SRE multi-window burn-rate recipe).

Daemons push cumulative ``op_hist.<qos_class>.<op_type>`` histogram
snapshots on MMgrReport (common/histogram.py layout).  This module:

- merges them cluster-wide per QoS class every tick (same-layout
  histograms add elementwise);
- keeps a ring of timestamped merges, so any sliding window is a
  snapshot SUBTRACTION (cumulative-counter semantics, the prometheus
  ``rate()`` trick without a TSDB);
- computes p50/p95/p99 per class over the fast window — the
  ``slo status`` surface and the curves the exporter serves;
- evaluates declarative targets (``slo_targets``, e.g.
  ``client_p99_ms=50@99.9``): the violation fraction over a window,
  divided by the error budget (1 − objective), is the BURN RATE;
- raises ``SLO_LATENCY`` through the mon ("slo report", the crash
  report push idiom): HEALTH_WARN when the fast window burns hot
  (a page-worthy spike), HEALTH_ERR when the slow window burns too
  (sustained — the budget is actually being spent), clearing on
  recovery since every push replaces the verdict set.

Target grammar: ``<class>_p<percentile>_ms=<target>[@<objective>]``,
whitespace- or comma-separated; objective defaults to 99.9 (%).  The
percentile names the INTENT ("p99 under 50 ms"); the evaluation is
exact over buckets: the fraction of ops slower than the target must
stay under 1 − objective.
"""

from __future__ import annotations

import json
import re
import threading
import time
from collections import deque

from ..common.histogram import (
    is_histogram_snapshot,
    percentile_from_counts,
    snapshot_counts,
)
from ..msg.message import MMonCommandReply
from . import MgrModule

_TARGET_RE = re.compile(
    r"^(?P<klass>[a-zA-Z][a-zA-Z0-9_]{0,31})"
    r"_p(?P<pct>\d{1,2}(?:\.\d+)?)"
    r"_ms=(?P<target>\d+(?:\.\d+)?)"
    r"(?:@(?P<objective>\d+(?:\.\d+)?)%?)?$"
)


def parse_slo_targets(spec: str) -> list[dict]:
    """``client_p99_ms=50@99.9 bulk_p95_ms=500`` → target dicts.
    Raises ValueError on any malformed token (a half-applied SLO
    config is worse than a rejected one)."""
    targets = []
    for token in re.split(r"[\s,]+", spec.strip()):
        if not token:
            continue
        m = _TARGET_RE.match(token)
        if m is None:
            raise ValueError(f"bad slo target {token!r}")
        objective = float(m.group("objective") or 99.9)
        if not 0.0 < objective < 100.0:
            raise ValueError(
                f"objective {objective} out of (0, 100) in {token!r}"
            )
        targets.append(
            {
                "qos_class": m.group("klass"),
                "percentile": float(m.group("pct")),
                "target_s": float(m.group("target")) / 1000.0,
                "objective": objective,
            }
        )
    return targets


def fraction_over(bounds, counts, threshold: float) -> float:
    """Fraction of samples ABOVE ``threshold`` seconds, interpolating
    inside the bucket the threshold splits."""
    total = sum(counts)
    if total == 0:
        return 0.0
    under = 0.0
    prev = 0.0
    for i, c in enumerate(counts):
        if i >= len(bounds):  # overflow bucket: entirely above any
            break  # finite threshold ≥ the last bound
        hi = bounds[i]
        if hi <= threshold:
            under += c
        else:
            if threshold > prev and c:
                under += c * (threshold - prev) / (hi - prev)
            break
        prev = hi
    return max(0.0, min(1.0, 1.0 - under / total))


def _merge_into(acc: dict, snap: dict) -> None:
    counts = snapshot_counts(snap)
    if "counts" not in acc:
        acc["counts"] = [0] * len(counts)
        acc["bounds"] = list(snap.get("bounds", []))
    if len(acc["counts"]) != len(counts):
        return  # foreign layout: drop rather than corrupt
    for i, c in enumerate(counts):
        acc["counts"][i] += c
    acc["sum"] = acc.get("sum", 0.0) + float(snap.get("sum", 0.0))
    acc["count"] = acc.get("count", 0) + sum(counts)


def _delta(cur: dict, old: dict | None) -> dict:
    """cur − old per class (cumulative counters → window counts);
    old=None means the window reaches back to the start."""
    out: dict[str, dict] = {}
    for klass, snap in cur.items():
        prev = (old or {}).get(klass)
        counts = list(snap["counts"])
        s = snap.get("sum", 0.0)
        if prev and len(prev.get("counts", ())) == len(counts):
            counts = [
                max(0, c - p) for c, p in zip(counts, prev["counts"])
            ]
            s = max(0.0, s - prev.get("sum", 0.0))
        out[klass] = {
            "bounds": snap["bounds"],
            "counts": counts,
            "sum": s,
            "count": sum(counts),
        }
    return out


class SLOModule(MgrModule):
    """The burn-rate evaluator (see module docstring)."""

    NAME = "slo"
    TICK_EVERY = 0.5
    # at least this many window ops before a verdict: a two-op window
    # with one slow op is noise, not a burning SLO
    MIN_WINDOW_OPS = 10

    def __init__(self, mgr):
        super().__init__(mgr)
        self._lock = threading.Lock()
        # ring of (wallclock, {class: merged cumulative snapshot})
        self._ring: deque[tuple[float, dict]] = deque(maxlen=4096)
        self._targets_raw: str | None = None
        self._targets: list[dict] = []
        self._target_error = ""
        self._config_cached: str | None = None
        self._config_checked = -1e9
        self.last_status: dict = {}
        # what the mon currently holds (for change-driven pushes)
        self._reported: dict | None = None
        self._last_push = 0.0

    # -- config ------------------------------------------------------------
    def _opt_float(self, key: str, default: float) -> float:
        try:
            return float(self.get_module_option(key, default))
        except (TypeError, ValueError):
            return default

    # how often to re-poll the mon config_db for slo_targets when no
    # module option overrides it (a config-set must take effect
    # without an mgr restart, but not cost a mon round-trip per tick)
    CONFIG_POLL_EVERY = 5.0

    def _config_targets(self) -> str | None:
        """`ceph config set mgr slo_targets ...` — the persistent
        path; polled at a slow cadence, cached between polls."""
        now = time.monotonic()
        if now - self._config_checked < self.CONFIG_POLL_EVERY:
            return self._config_cached
        self._config_checked = now
        try:
            reply = self.mon_command(
                {"prefix": "config get", "who": "mgr",
                 "key": "slo_targets"},
                timeout=2.0,
            )
            self._config_cached = (
                json.loads(reply.outb)
                if reply.rc == 0 and reply.outb
                else None
            )
        except Exception:  # noqa: BLE001 — mon away: keep last known
            pass
        return self._config_cached

    def _refresh_targets(self) -> None:
        """Precedence: runtime module option (`slo targets set`) >
        mon config_db (`ceph config set mgr slo_targets ...`) >
        schema default."""
        raw = str(self.get_module_option("targets", "") or "")
        if not raw:
            raw = str(self._config_targets() or "")
        if not raw:
            from ..common.config import SCHEMA

            raw = str(SCHEMA["slo_targets"].default)
        if raw == self._targets_raw:
            return
        self._targets_raw = raw
        try:
            self._targets = parse_slo_targets(raw)
            self._target_error = ""
        except ValueError as e:
            self._targets = []
            self._target_error = str(e)

    # -- ingestion ---------------------------------------------------------
    def _merged_now(self) -> dict:
        """Merge every daemon's op_hist.* snapshots per QoS class."""
        merged: dict[str, dict] = {}
        for _daemon, dump in (self.get("daemon_perf") or {}).items():
            if not isinstance(dump, dict):
                continue
            for key, val in dump.items():
                if not key.startswith("op_hist."):
                    continue
                if not is_histogram_snapshot(val):
                    continue
                parts = key.split(".")
                klass = parts[1] if len(parts) > 2 else "client"
                _merge_into(merged.setdefault(klass, {}), val)
        return {k: v for k, v in merged.items() if "counts" in v}

    def _window(self, seconds: float, now: float) -> dict:
        """Per-class counts over the trailing ``seconds`` (newest ring
        entry at or before the window start is the baseline)."""
        with self._lock:
            if not self._ring:
                return {}
            cur = self._ring[-1][1]
            baseline = None
            for ts, snap in reversed(self._ring):
                if ts <= now - seconds:
                    baseline = snap
                    break
        return _delta(cur, baseline)

    # -- evaluation --------------------------------------------------------
    def serve(self) -> None:
        self._refresh_targets()
        now = time.time()
        merged = self._merged_now()
        if merged:
            with self._lock:
                self._ring.append((now, merged))
        fast_s = self._opt_float("fast_window", 60.0)
        slow_s = self._opt_float("slow_window", 300.0)
        fast_burn_thresh = self._opt_float("fast_burn_threshold", 14.4)
        slow_burn_thresh = self._opt_float("slow_burn_threshold", 6.0)
        fast = self._window(fast_s, now)
        slow = self._window(slow_s, now)
        classes: dict[str, dict] = {}
        for klass, snap in fast.items():
            if snap["count"] <= 0:
                continue
            classes[klass] = {
                "count": snap["count"],
                **{
                    f"p{int(p)}_ms": round(
                        1000.0
                        * percentile_from_counts(
                            snap["bounds"], snap["counts"],
                            snap["sum"], p,
                        ),
                        3,
                    )
                    for p in (50, 95, 99)
                },
            }
        burning: list[dict] = []
        for tgt in self._targets:
            verdict = {
                **tgt,
                "target_ms": round(tgt["target_s"] * 1000.0, 3),
            }
            budget = 1.0 - tgt["objective"] / 100.0
            for label, win, thresh in (
                ("fast", fast, fast_burn_thresh),
                ("slow", slow, slow_burn_thresh),
            ):
                snap = win.get(tgt["qos_class"])
                if snap is None or snap["count"] < self.MIN_WINDOW_OPS:
                    verdict[f"{label}_burn"] = 0.0
                    verdict[f"{label}_burning"] = False
                    continue
                frac = fraction_over(
                    snap["bounds"], snap["counts"], tgt["target_s"]
                )
                burn = frac / budget if budget > 0 else 0.0
                verdict[f"{label}_burn"] = round(burn, 3)
                verdict[f"{label}_burning"] = burn >= thresh
            burning.append(verdict)
        checks = self._build_checks(burning)
        self.last_status = {
            "targets": burning,
            "targets_error": self._target_error,
            "classes": classes,
            "fast_window_s": fast_s,
            "slow_window_s": slow_s,
            "active_checks": checks,
        }
        self._push_report(checks, now)

    def _build_checks(self, verdicts: list[dict]) -> dict:
        """WARN on a fast burn, ERR when the slow window burns too
        (sustained budget spend); one rollup check for the plane."""
        warn, err = [], []
        for v in verdicts:
            who = (
                f"{v['qos_class']} p{v['percentile']:g}"
                f"<{v['target_ms']:g}ms"
            )
            if v.get("fast_burning") and v.get("slow_burning"):
                err.append(
                    f"{who} burn {v['slow_burn']:g}x sustained"
                )
            elif v.get("fast_burning"):
                warn.append(f"{who} burn {v['fast_burn']:g}x fast")
        if not warn and not err:
            return {}
        severity = "HEALTH_ERR" if err else "HEALTH_WARN"
        detail = "; ".join(err + warn)
        return {
            "SLO_LATENCY": {
                "severity": severity,
                "summary": (
                    f"{len(err) + len(warn)} latency SLO(s) burning "
                    f"error budget: {detail}"
                ),
            }
        }

    def _push_report(self, checks: dict, now: float) -> None:
        """Push on change immediately; refresh an unchanged NONEMPTY
        set every few seconds (the mon ages reports out, so silence
        means clear — exactly the crash/slow-ops re-report idiom)."""
        unchanged = checks == self._reported
        if unchanged and (not checks or now - self._last_push < 5.0):
            return
        try:
            reply = self.mon_command(
                {"prefix": "slo report", "checks": checks},
                timeout=2.0,  # tick thread: never stall other modules
            )
            if reply.rc == 0:
                self._reported = checks
                self._last_push = now
        except Exception:  # noqa: BLE001 — retried next tick
            pass

    # -- command surface ---------------------------------------------------
    def status(self) -> dict:
        return dict(self.last_status)

    def handle_command(self, cmd: dict) -> MMonCommandReply:
        prefix = cmd.get("prefix", "")
        if prefix == "slo status":
            return MMonCommandReply(outb=json.dumps(self.status()))
        if prefix == "slo targets":
            return MMonCommandReply(
                outb=json.dumps(
                    {
                        "raw": self._targets_raw,
                        "parsed": self._targets,
                        "error": self._target_error,
                    }
                )
            )
        if prefix == "slo targets set":
            raw = str(cmd.get("targets", ""))
            try:
                parse_slo_targets(raw)  # validate before adopting
            except ValueError as e:
                return MMonCommandReply(rc=-22, outs=str(e))
            self.mgr.set_module_option(self.NAME, "targets", raw)
            # persist through the mon config database so an mgr
            # restart keeps evaluating (module options are in-memory)
            try:
                self.mon_command(
                    {"prefix": "config set", "who": "mgr",
                     "key": "slo_targets", "value": raw},
                    timeout=2.0,
                )
            except Exception:  # noqa: BLE001 — runtime set still
                pass  # applies; persistence retried by the operator
            return MMonCommandReply(outs=f"slo targets set to {raw!r}")
        return MMonCommandReply(
            rc=-22, outs=f"unknown slo command {prefix!r}"
        )
