"""PGMap digest (src/mon/PGMap.{h,cc} + the DaemonServer stats fold).

The OSDs push per-PG ``pg_stat_t``-analog dicts on MPGStats; the
Manager parks them per-OSD (``Manager.pg_stats``); this module rolls
the freshest primary reports into the PGMap digest — per-pool and
cluster totals, a pg-state histogram, io/recovery rates from
daemon-perf counter deltas, and the full per-PG table — and pushes the
binary-encoded digest to the mon ("pgmap report"), where it feeds
``ceph status``/``ceph df``/``pg dump`` and the PG_DEGRADED /
PG_AVAILABILITY health checks.

The digest encoding is dencoder-pinned (corpus/dencoder/): maps
encode sorted, so the same digest always produces the same bytes.
"""

from __future__ import annotations

import base64
import json
import time
from collections import deque

from ..common.encoding import Decoder, Encoder
from ..msg.message import MMonCommandReply
from . import MgrModule, PrometheusModule

PGMAP_DIGEST_VERSION = 1

# io/recovery rates come from deltas between perf-counter snapshots;
# keep a short window so rates react within a few ticks
RATE_WINDOW_SAMPLES = 8

_RATE_KEYS = (
    "op", "op_r", "op_w", "recovery_pushes", "recovery_push_bytes",
)


def _enc_pool(e: Encoder, p: dict) -> None:
    e.string(p.get("name", ""))
    e.u32(p.get("num_pgs", 0)).u32(p.get("active_pgs", 0))
    e.u64(p.get("objects", 0)).u64(p.get("bytes", 0))
    e.u64(p.get("degraded", 0)).u64(p.get("misplaced", 0))
    e.u64(p.get("unfound", 0))


def _dec_pool(d: Decoder) -> dict:
    return {
        "name": d.string(),
        "num_pgs": d.u32(), "active_pgs": d.u32(),
        "objects": d.u64(), "bytes": d.u64(),
        "degraded": d.u64(), "misplaced": d.u64(),
        "unfound": d.u64(),
    }


def _enc_pg(e: Encoder, p: dict) -> None:
    e.string(p.get("state", ""))
    e.u64(p.get("objects", 0)).u64(p.get("bytes", 0))
    e.u64(p.get("degraded", 0)).u64(p.get("misplaced", 0))
    e.u64(p.get("unfound", 0))
    e.list(p.get("up", []), lambda en, v: en.s32(v))
    e.list(p.get("acting", []), lambda en, v: en.s32(v))
    e.u32(p.get("reported_epoch", 0))
    e.f64(p.get("recovery_progress", 0.0))


def _dec_pg(d: Decoder) -> dict:
    return {
        "state": d.string(),
        "objects": d.u64(), "bytes": d.u64(),
        "degraded": d.u64(), "misplaced": d.u64(),
        "unfound": d.u64(),
        "up": d.list(lambda de: de.s32()),
        "acting": d.list(lambda de: de.s32()),
        "reported_epoch": d.u32(),
        "recovery_progress": d.f64(),
    }


def encode_pgmap_digest(digest: dict) -> bytes:
    """Deterministic binary encoding of the digest (the dencoder pin:
    Encoder.map iterates sorted, so byte-for-byte stable)."""
    e = Encoder()
    e.u32(PGMAP_DIGEST_VERSION)
    e.u32(digest.get("num_pgs", 0)).u32(digest.get("num_pools", 0))
    e.map(
        digest.get("pg_states", {}),
        lambda en, k: en.string(k),
        lambda en, v: en.u64(v),
    )
    e.map(
        digest.get("pools", {}),
        lambda en, k: en.s64(int(k)),
        _enc_pool,
    )
    t = digest.get("totals", {})
    e.u64(t.get("objects", 0)).u64(t.get("bytes", 0))
    e.u64(t.get("degraded", 0)).u64(t.get("misplaced", 0))
    e.u64(t.get("unfound", 0))
    io = digest.get("io", {})
    e.f64(io.get("ops_sec", 0.0)).f64(io.get("read_ops_sec", 0.0))
    e.f64(io.get("write_ops_sec", 0.0))
    rec = digest.get("recovery", {})
    e.f64(rec.get("objects_sec", 0.0)).f64(rec.get("bytes_sec", 0.0))
    e.map(
        digest.get("pgs", {}),
        lambda en, k: en.string(k),
        _enc_pg,
    )
    return e.getvalue()


def decode_pgmap_digest(buf: bytes) -> dict:
    d = Decoder(buf)
    version = d.u32()
    if version != PGMAP_DIGEST_VERSION:
        raise ValueError(f"pgmap digest version {version}")
    out = {
        "version": version,
        "num_pgs": d.u32(),
        "num_pools": d.u32(),
        "pg_states": d.map(
            lambda de: de.string(), lambda de: de.u64()
        ),
        "pools": d.map(lambda de: de.s64(), _dec_pool),
        "totals": {
            "objects": d.u64(), "bytes": d.u64(),
            "degraded": d.u64(), "misplaced": d.u64(),
            "unfound": d.u64(),
        },
        "io": {
            "ops_sec": d.f64(), "read_ops_sec": d.f64(),
            "write_ops_sec": d.f64(),
        },
        "recovery": {
            "objects_sec": d.f64(), "bytes_sec": d.f64(),
        },
        "pgs": d.map(lambda de: de.string(), _dec_pg),
    }
    return out


def pgmap_exposition_lines(digest: dict) -> list[str]:
    """Prometheus text for the pgmap families — module-level so
    tools/check_metrics.py lints the exact text the exporter serves
    (the histogram_exposition_lines pattern).  ``ceph_pg_total`` is
    NOT emitted here: the exporter already serves it from
    pg_summary."""
    esc = PrometheusModule.escape_label
    out: list[str] = []

    def fam(name: str, help_: str) -> None:
        out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} gauge")

    t = digest.get("totals", {})
    fam("ceph_pg_degraded", "objects with missing replicas/shards")
    out.append(f"ceph_pg_degraded {t.get('degraded', 0)}")
    fam("ceph_pg_misplaced", "objects not on their CRUSH-up home")
    out.append(f"ceph_pg_misplaced {t.get('misplaced', 0)}")
    fam("ceph_pg_unfound", "objects with no known authoritative copy")
    out.append(f"ceph_pg_unfound {t.get('unfound', 0)}")
    fam("ceph_pg_state", "pg count by state string")
    for state, count in sorted(digest.get("pg_states", {}).items()):
        out.append(f'ceph_pg_state{{state="{esc(state)}"}} {count}')
    fam("ceph_pool_stored_bytes", "per-pool stored bytes")
    fam("ceph_pool_objects", "per-pool object count")
    pools = digest.get("pools", {})
    for pid in sorted(pools):
        p = pools[pid]
        lbl = f'pool="{esc(p.get("name", str(pid)))}"'
        out.append(
            f"ceph_pool_stored_bytes{{{lbl}}} {p.get('bytes', 0)}"
        )
        out.append(
            f"ceph_pool_objects{{{lbl}}} {p.get('objects', 0)}"
        )
    return out


class PgMapModule(MgrModule):
    """Builds the PGMap digest every tick and pushes it to the mon.

    The mon treats digest staleness like osd-stat staleness (silence
    past the grace drops the pgmap section), so the push is
    continuous rather than on-change — rates move every tick
    anyway."""

    NAME = "pgmap"
    TICK_EVERY = 1.0

    def __init__(self, mgr):
        super().__init__(mgr)
        self.digest: dict = {}
        self._samples: deque[tuple[float, dict]] = deque(
            maxlen=RATE_WINDOW_SAMPLES
        )
        self._last_push = 0.0

    # -- digest construction ----------------------------------------------
    def _rates(self) -> tuple[dict, dict]:
        """io + recovery rates from perf-counter deltas across the
        sample window; negative deltas (an OSD restarted and its
        counters reset) clamp to zero."""
        perf = self.get("daemon_perf") or {}
        total = {k: 0 for k in _RATE_KEYS}
        for daemon, dump in perf.items():
            if not daemon.startswith("osd."):
                continue
            for k in _RATE_KEYS:
                v = dump.get(k, 0)
                if isinstance(v, (int, float)):
                    total[k] += v
        self._samples.append((time.time(), total))
        if len(self._samples) < 2:
            return (
                {"ops_sec": 0.0, "read_ops_sec": 0.0,
                 "write_ops_sec": 0.0},
                {"objects_sec": 0.0, "bytes_sec": 0.0},
            )
        (t0, a), (t1, b) = self._samples[0], self._samples[-1]
        dt = max(t1 - t0, 1e-6)

        def rate(key: str) -> float:
            return round(max(b[key] - a[key], 0) / dt, 2)

        return (
            {
                "ops_sec": rate("op"),
                "read_ops_sec": rate("op_r"),
                "write_ops_sec": rate("op_w"),
            },
            {
                "objects_sec": rate("recovery_pushes"),
                "bytes_sec": rate("recovery_push_bytes"),
            },
        )

    def _build_digest(self) -> dict | None:
        m = self.get("osd_map")
        if m is None:
            return None
        try:
            pg_stats = self.get("pg_stats") or {}
        except KeyError:
            pg_stats = {}
        io, recovery = self._rates()
        pg_states: dict[str, int] = {}
        pools: dict[int, dict] = {}
        totals = {
            "objects": 0, "bytes": 0,
            "degraded": 0, "misplaced": 0, "unfound": 0,
        }
        pgs: dict[str, dict] = {}
        for pid, pool in m.pools.items():
            pools[pid] = {
                "name": m.pool_names.get(pid, str(pid)),
                "num_pgs": pool.pg_num,
                "active_pgs": 0,
                "objects": 0, "bytes": 0,
                "degraded": 0, "misplaced": 0, "unfound": 0,
            }
        for pgid, st in pg_stats.items():
            state = str(st.get("state", "unknown"))
            pg_states[state] = pg_states.get(state, 0) + 1
            rec = st.get("recovery") or {}
            planned = int(rec.get("planned", 0) or 0)
            pushed = int(rec.get("pushed", 0) or 0)
            progress = (
                pushed / planned if planned else
                (1.0 if state.startswith("active") else 0.0)
            )
            row = {
                "state": state,
                "objects": int(st.get("num_objects", 0)),
                "bytes": int(st.get("num_bytes", 0)),
                "degraded": int(st.get("num_objects_degraded", 0)),
                "misplaced": int(st.get("num_objects_misplaced", 0)),
                "unfound": int(st.get("num_objects_unfound", 0)),
                "up": list(st.get("up", [])),
                "acting": list(st.get("acting", [])),
                "reported_epoch": int(st.get("reported_epoch", 0)),
                "recovery_progress": round(progress, 4),
            }
            pgs[pgid] = row
            try:
                pid = int(pgid.split(".")[0])
            except (ValueError, IndexError):
                continue
            pool = pools.get(pid)
            if pool is None:
                continue
            if state.startswith("active"):
                pool["active_pgs"] += 1
            for src, dst in (
                ("objects", "objects"), ("bytes", "bytes"),
                ("degraded", "degraded"),
                ("misplaced", "misplaced"),
                ("unfound", "unfound"),
            ):
                pool[dst] += row[src]
                totals[dst] += row[src]
        return {
            "version": PGMAP_DIGEST_VERSION,
            "num_pgs": sum(p.pg_num for p in m.pools.values()),
            "num_pools": len(m.pools),
            "pg_states": pg_states,
            "pools": pools,
            "totals": totals,
            "io": io,
            "recovery": recovery,
            "pgs": pgs,
        }

    # -- serve/push ---------------------------------------------------------
    def serve(self) -> None:
        digest = self._build_digest()
        if digest is None:
            return
        self.digest = digest
        now = time.time()
        if now - self._last_push < 1.0:
            return
        try:
            reply = self.mon_command(
                {
                    "prefix": "pgmap report",
                    "digest": base64.b64encode(
                        encode_pgmap_digest(digest)
                    ).decode("ascii"),
                },
                timeout=2.0,  # tick thread: never stall other modules
            )
            if reply.rc == 0:
                self._last_push = now
        except Exception:  # noqa: BLE001 — retried next tick
            pass

    # -- command surface ----------------------------------------------------
    def handle_command(self, cmd: dict) -> MMonCommandReply:
        prefix = cmd.get("prefix", "")
        if prefix in ("pgmap dump", "pgmap"):
            return MMonCommandReply(outb=json.dumps(self.digest))
        return MMonCommandReply(
            rc=-22, outs=f"unknown pgmap command {prefix!r}"
        )
