"""Mgr progress module (src/pybind/mgr/progress reduced).

Global progress bars for long-running cluster operations.  Three
producers feed the same event table:

- **osdmap diffing** (the reference's OSD out/in handlers): an OSD
  marked out or back in opens a rebalance event whose fraction is
  degraded+misplaced objects remaining versus the start snapshot
  (from the pgmap digest).  The start total latches lazily — the
  storm needs a tick or two to surface in PG stats — and an event
  that never sees a nonzero remaining within the grace completes
  immediately (the remap was a no-op).
- **MPGStats piggyback**: OSDs ship scrub/repair run fractions in
  the MPGStats ``events`` field; the Manager parks them in
  ``_progress_inbox`` and this module folds them in.
- **the "progress event" command**: in-process subsystems (RGW
  reshard) and external tooling push {id, message, fraction, done}
  through the normal command path.

Completed events stay listed (done, fraction 1.0) until the TTL
retires them.  Event starts/completions clog, so they stream in
``ceph -w``.
"""

from __future__ import annotations

import json
import threading
import time

from ..msg.message import MMonCommandReply
from . import MgrModule

# a rebalance event that never shows a nonzero remaining within this
# many seconds was a no-op remap: complete it instead of leaking a
# forever-0% bar
NOOP_GRACE = 5.0

DEFAULT_TTL = 30.0

MAX_EVENTS = 256


class ProgressModule(MgrModule):
    NAME = "progress"
    TICK_EVERY = 1.0

    def __init__(self, mgr):
        super().__init__(mgr)
        # id -> {message, fraction, started, updated, done, done_at,
        #         start_total (rebalance events only)}
        self._events: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._prev_out: set[int] | None = None
        self._prev_up: set[int] | None = None

    # -- event API (the mgr_module remote interface) -----------------------
    def start_event(
        self, ev_id: str, message: str, fraction: float = 0.0
    ) -> None:
        with self._lock:
            if ev_id in self._events and not self._events[ev_id]["done"]:
                return
            if len(self._events) >= MAX_EVENTS:
                self._retire(force=True)
            now = time.time()
            self._events[ev_id] = {
                "message": message,
                "fraction": max(0.0, min(float(fraction), 1.0)),
                "started": now,
                "updated": now,
                "done": False,
                "done_at": 0.0,
                "start_total": None,
            }
        self.mgr.clog.info(f"Progress started: {message}")

    def update_event(
        self, ev_id: str, fraction: float, message: str | None = None
    ) -> None:
        with self._lock:
            ev = self._events.get(ev_id)
            if ev is None or ev["done"]:
                return
            # monotone: a bar that regresses reads as a bug, and the
            # chaos verdict asserts it never does
            ev["fraction"] = max(
                ev["fraction"], min(float(fraction), 1.0)
            )
            if message:
                ev["message"] = message
            ev["updated"] = time.time()

    def complete_event(self, ev_id: str) -> None:
        with self._lock:
            ev = self._events.get(ev_id)
            if ev is None or ev["done"]:
                return
            ev["fraction"] = 1.0
            ev["done"] = True
            ev["done_at"] = time.time()
            message = ev["message"]
        self.mgr.clog.info(f"Progress completed: {message}")

    def active_events(self) -> list[dict]:
        with self._lock:
            return [
                {"id": k, **{x: v[x] for x in (
                    "message", "fraction", "started", "updated",
                    "done", "done_at",
                )}}
                for k, v in sorted(self._events.items())
            ]

    # -- producers ----------------------------------------------------------
    def _drain_inbox(self) -> None:
        inbox = getattr(self.mgr, "_progress_inbox", None)
        if inbox is None:
            return
        while inbox:
            try:
                ev = inbox.popleft()
            except IndexError:
                break
            if not isinstance(ev, dict):
                continue
            ev_id = str(ev.get("id", ""))[:256]
            if not ev_id:
                continue
            if ev.get("done"):
                if ev_id in self._events:
                    self.complete_event(ev_id)
                continue
            try:
                fraction = float(ev.get("fraction", 0.0))
            except (TypeError, ValueError):
                fraction = 0.0
            message = str(ev.get("message", ev_id))[:512]
            if ev_id not in self._events:
                self.start_event(ev_id, message, fraction)
            else:
                self.update_event(ev_id, fraction, message)

    def _diff_osdmap(self) -> None:
        m = self.get("osd_map")
        if m is None:
            return
        out_set = {
            o for o in range(m.max_osd)
            if m.exists(o) and m.osd_weight[o] == 0
        }
        up_set = {o for o in range(m.max_osd) if m.is_up(o)}
        prev_out, prev_up = self._prev_out, self._prev_up
        self._prev_out, self._prev_up = out_set, up_set
        if prev_out is None:
            return  # first sight of the map: no transition to report
        for o in sorted(out_set - prev_out):
            self.start_event(
                f"rebalance:osd.{o}-out",
                f"Rebalancing after osd.{o} marked out",
            )
        for o in sorted(prev_out - out_set):
            self.start_event(
                f"rebalance:osd.{o}-in",
                f"Rebalancing after osd.{o} marked in",
            )

    def _advance_rebalance(self) -> None:
        """Drive every open rebalance event from the pgmap digest:
        remaining = degraded + misplaced, fraction = 1 - remaining /
        start_total (monotone-clamped)."""
        pgmap = self.mgr.modules.get("pgmap")
        digest = getattr(pgmap, "digest", None) or {}
        totals = digest.get("totals")
        if totals is None:
            return
        remaining = int(totals.get("degraded", 0)) + int(
            totals.get("misplaced", 0)
        )
        now = time.time()
        with self._lock:
            open_rebalance = [
                (k, v) for k, v in self._events.items()
                if k.startswith("rebalance:") and not v["done"]
            ]
        for ev_id, ev in open_rebalance:
            if ev["start_total"] is None:
                if remaining > 0:
                    with self._lock:
                        ev["start_total"] = remaining
                elif now - ev["started"] > NOOP_GRACE:
                    self.complete_event(ev_id)
                continue
            if remaining <= 0:
                self.complete_event(ev_id)
            else:
                total = max(ev["start_total"], remaining)
                self.update_event(ev_id, 1.0 - remaining / total)

    def _retire(self, force: bool = False) -> None:
        """Drop completed events past the TTL (caller may hold the
        lock only in the force path)."""
        ttl = float(self.get_module_option("ttl", DEFAULT_TTL))
        now = time.time()
        dead = [
            k for k, v in self._events.items()
            if v["done"] and (force or now - v["done_at"] > ttl)
        ]
        for k in dead:
            self._events.pop(k, None)

    # -- serve --------------------------------------------------------------
    def serve(self) -> None:
        self._drain_inbox()
        self._diff_osdmap()
        self._advance_rebalance()
        with self._lock:
            self._retire()

    # -- command surface -----------------------------------------------------
    def _render(self) -> str:
        rows = []
        for ev in self.active_events():
            width = 30
            filled = int(round(ev["fraction"] * width))
            bar = "=" * filled + ">" * (0 if ev["done"] else 1)
            rows.append(
                f"[{bar:<{width}}] {ev['fraction'] * 100:5.1f}%  "
                f"{ev['message']}"
                + ("  (done)" if ev["done"] else "")
            )
        return "\n".join(rows) if rows else "(no active events)"

    def handle_command(self, cmd: dict) -> MMonCommandReply:
        prefix = cmd.get("prefix", "")
        if prefix == "progress":
            return MMonCommandReply(outb=self._render())
        if prefix == "progress json":
            return MMonCommandReply(
                outb=json.dumps({"events": self.active_events()})
            )
        if prefix == "progress clear":
            with self._lock:
                n = len(self._events)
                self._events.clear()
            return MMonCommandReply(outb=f"cleared {n} event(s)")
        if prefix == "progress event":
            ev_id = str(cmd.get("id", ""))[:256]
            if not ev_id:
                return MMonCommandReply(rc=-22, outs="missing id")
            if cmd.get("done"):
                self.complete_event(ev_id)
                return MMonCommandReply(outb="ok")
            try:
                fraction = float(cmd.get("fraction", 0.0))
            except (TypeError, ValueError):
                return MMonCommandReply(rc=-22, outs="bad fraction")
            message = str(cmd.get("message", ev_id))[:512]
            if ev_id in self._events:
                self.update_event(ev_id, fraction, message)
            else:
                self.start_event(ev_id, message, fraction)
            return MMonCommandReply(outb="ok")
        return MMonCommandReply(
            rc=-22, outs=f"unknown progress command {prefix!r}"
        )
