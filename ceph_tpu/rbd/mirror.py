"""rbd-mirror — one-way asynchronous image replication by journal
replay (src/tools/rbd_mirror/Mirror.cc + src/librbd/mirror/,
reduced to the working core: journal-based mirroring only).

A ``MirrorDaemon`` watches a SOURCE ioctx for journaled images and
replays each image's journal into a TARGET ioctx (another pool or
another cluster entirely — the ioctx carries the cluster session):

- **bootstrap**: a missing target image is created with the source's
  geometry and full-copied at the current journal position (the
  reference's image-sync phase).
- **replay**: the daemon registers as a journal CLIENT on the source
  (trim never passes it — entries survive until consumed), tails
  entries from its recorded position, applies write/discard/resize
  to the target, and advances its position durably.  A restarted
  daemon resumes exactly where it stopped.

Deviations: one-way (no promotion/demotion handshake or split-brain
detection), snapshot-based mirroring absent (journal mode only),
and the target image is plain (no feature bits)."""

from __future__ import annotations

import threading
import time

from ..common.encoding import Decoder
from ..mds.journaler import Journaler
from ..osdc.objecter import ObjectNotFound, RadosError
from . import DIRECTORY, Image, RBD, _header_oid

CLIENT_ID = "rbd-mirror"


class MirrorDaemon:
    def __init__(self, src_ioctx, dst_ioctx, interval: float = 0.5):
        self.src = src_ioctx
        self.dst = dst_ioctx
        self.interval = interval
        self.images_synced = 0  # observability
        self.entries_replayed = 0
        self._stop = threading.Event()
        self._thread = None
        if interval > 0:
            # interval=0: no background thread — the caller drives
            # replay_once() itself (the CLI's --once mode; a thread
            # racing it would replay the same entries concurrently)
            self._thread = threading.Thread(
                target=self._loop, name="rbd-mirror", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    # -- discovery ---------------------------------------------------------
    def _journaled_images(self) -> list[str]:
        try:
            names = self.src.omap_get_vals(DIRECTORY)
        except (ObjectNotFound, RadosError):
            return []
        out = []
        for name in names:
            try:
                meta = self.src.omap_get_vals(_header_oid(name))
            except (ObjectNotFound, RadosError):
                continue
            feats = meta.get("features", b"").decode()
            if "journaling" in feats:
                out.append(name)
        return sorted(out)

    # -- replication -------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.replay_once()
            except Exception:  # noqa: BLE001 — the replayer survives
                pass

    def replay_once(self) -> int:
        """One pass over every journaled image; returns entries
        applied (callable directly for deterministic tests)."""
        applied = 0
        for name in self._journaled_images():
            try:
                applied += self._replay_image(name)
            except (ObjectNotFound, RadosError):
                continue  # next pass retries
        return applied

    def _ensure_target(self, name: str, journal: Journaler) -> None:
        """Bootstrap (image-sync): create + full-copy at the current
        replay position so journal entries from here converge."""
        try:
            self.dst.omap_get_vals(_header_oid(name))
            return
        except (ObjectNotFound, RadosError):
            pass
        meta = self.src.omap_get_vals(_header_oid(name))
        RBD().create(
            self.dst, name,
            int(meta["size"]),
            stripe_unit=int(meta["stripe_unit"]),
            stripe_count=int(meta["stripe_count"]),
            object_size=int(meta["object_size"]),
        )
        src_img = Image(self.src, name)
        dst_img = Image(self.dst, name)
        try:
            size = src_img.size()
            step = 4 << 20
            for off in range(0, size, step):
                chunk = src_img.read(off, min(step, size - off))
                if chunk.strip(b"\0"):
                    dst_img.write(off, chunk)
            self.images_synced += 1
        finally:
            src_img.close()
            dst_img.close()

    def _replay_image(self, name: str) -> int:
        journal = Journaler(
            self.src, prefix=f"rbd_journal.{name}"
        ).load()
        pos = journal.register_client(CLIENT_ID)
        self._ensure_target(name, journal)
        applied = 0
        dst_img = None
        try:
            for blob, end in journal.replay_from(pos):
                if dst_img is None:
                    dst_img = Image(self.dst, name)
                self._apply(dst_img, blob)
                journal.update_client(CLIENT_ID, end)
                applied += 1
                self.entries_replayed += 1
        finally:
            if dst_img is not None:
                dst_img.close()
        return applied

    @staticmethod
    def _apply(img: Image, blob: bytes) -> None:
        d = Decoder(blob)
        op, off, length = d.u8(), d.u64(), d.u64()
        data = d.bytes()
        if op == 1:
            if off + len(data) > img.size():
                img.resize(off + len(data))
            img.write(off, data)
        elif op == 2:
            img.discard(off, length)
        elif op == 3:
            img.resize(off)
