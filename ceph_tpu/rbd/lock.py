"""rbd exclusive lock — cooperative write arbitration on an image
(the ManagedLock/ExclusiveLock state machines,
src/librbd/ManagedLock.cc:1, src/librbd/exclusive_lock/ — redesigned
as one small client-side protocol object instead of a callback state
machine; the asyncio-era control flow those 854 LoC of continuations
encode is a plain method sequence here).

The lock itself is the cls_lock record on the image header object
(src/cls/lock/cls_lock.cc role); coordination rides watch/notify on
the same object:

- ``acquire`` tries ``lock.lock``; on -EBUSY it notifies
  ``request_lock`` and waits for the owner's cooperative release
  (the owner flushes its cache and unlocks; cls unlock broadcasts
  ``unlocked`` to every watcher).
- An owner that never answers is DEAD or partitioned: after
  ``break_timeout`` the waiter **fences** it — OSDMap-blocklists the
  owner's client id (every OSD then rejects its ops, including any
  in-flight writeback), force-unlocks the stale record, and takes
  the lock.  This is the reference's break-lock + blocklist flow
  (ManagedLock::break_lock, ExclusiveLock's
  blacklist-on-break) and is what makes two mounts of one image
  safe against a half-dead writer.

The lock cookie is ``<client_id>:<watch_cookie>`` so a breaker knows
exactly which client to fence and which watch to test for liveness.
"""

from __future__ import annotations

import json
import threading
import time

from ..osdc.objecter import RadosError

__all__ = ["ExclusiveLock", "LockBusy"]


class LockBusy(RadosError):
    """Another client holds the lock and is still alive."""


class ExclusiveLock:
    def __init__(
        self,
        ioctx,
        header_oid: str,
        *,
        request_timeout: float = 2.0,
        break_timeout: float = 5.0,
        on_release_request=None,
    ):
        """``on_release_request()`` is the owner-side hook: called
        (off the watch thread) when a peer asks for the lock; it must
        quiesce writes, flush, and call :meth:`release`."""
        self.ioctx = ioctx
        self.oid = header_oid
        self.request_timeout = request_timeout
        self.break_timeout = break_timeout
        self.on_release_request = on_release_request
        self._watch_cookie: int | None = None
        self._owned = False
        self._lock = threading.Lock()
        self._released = threading.Event()

    # -- identity ----------------------------------------------------------
    @property
    def cookie(self) -> str:
        return f"{self.ioctx.rados.client_id}:{self._watch_cookie}"

    @property
    def is_owner(self) -> bool:
        return self._owned

    # -- watch plumbing ----------------------------------------------------
    def _ensure_watch(self) -> None:
        if self._watch_cookie is not None:
            return
        self._watch_cookie = self.ioctx.watch(self.oid, self._on_notify)

    def _on_notify(self, payload: bytes):
        try:
            ev = json.loads(payload)
        except ValueError:
            return None
        if ev.get("event") == "request_lock":
            if self._owned and self.on_release_request is not None:
                # hand off OUTSIDE the notify ack path: the requester
                # is waiting on the 'unlocked' broadcast, not our ack
                threading.Thread(
                    target=self._cooperative_release, daemon=True
                ).start()
            return b"owner" if self._owned else b"idle"
        if ev.get("event") == "unlocked":
            self._released.set()
        return None

    def _cooperative_release(self) -> None:
        try:
            self.on_release_request()
        except Exception:
            pass

    # -- core protocol -----------------------------------------------------
    def _try_lock(self) -> bool:
        try:
            self.ioctx.execute(
                self.oid, "lock", "lock",
                json.dumps({"cookie": self.cookie,
                            "type": "exclusive"}).encode(),
            )
            return True
        except RadosError as e:
            if "EBUSY" in str(e):
                return False
            raise

    def _holder(self) -> str | None:
        info = json.loads(self.ioctx.execute(
            self.oid, "lock", "get_info", b""
        ))
        holders = list(info.get("holders", {}))
        return holders[0] if holders else None

    def acquire(self) -> None:
        """Block until this client owns the lock, requesting a
        cooperative handoff; a DEAD owner (its watch never acks the
        request) is fenced and its lock broken.  A live owner that
        acks but keeps the lock past ``break_timeout`` raises
        :class:`LockBusy` — liveness is the break criterion, not
        patience (ManagedLock breaks only an expired/dead locker)."""
        with self._lock:
            if self._owned:
                return
            self._ensure_watch()
            if self._try_lock():
                self._owned = True
                return
            deadline = time.monotonic() + self.break_timeout
            dead_owner: str | None = None
            while time.monotonic() < deadline:
                self._released.clear()
                acks = self.ioctx.notify(self.oid, json.dumps(
                    {"event": "request_lock", "from": self.cookie}
                ).encode())
                if self._try_lock():
                    self._owned = True
                    return
                owner = self._holder()
                if owner is None:
                    continue  # released; retry the lock op
                # is the owner's watch alive?  its watch cookie is in
                # the lock cookie; an owner that did not ack the
                # notify is gone (or partitioned) — fence it
                _oc, _, own_wc = owner.partition(":")
                if not any(
                    a["acked"] and str(a["cookie"]) == own_wc
                    for a in acks
                ):
                    dead_owner = owner
                    break
                self._released.wait(self.request_timeout)
            owner = self._holder()
            if owner is None and self._try_lock():
                self._owned = True
                return
            if owner is None or owner != dead_owner:
                # either we lost a race to another waiter, or the
                # holder CHANGED since the liveness test — the cookie
                # we proved dead is the ONLY one we may fence
                # (blocklisting whoever holds it now could fence a
                # live, healthy new owner)
                raise LockBusy(
                    f"image lock held by live owner {owner!r} (-EBUSY)"
                )
            self._break_lock(owner)
            if not self._try_lock():
                raise LockBusy("lost the break-lock race (-EBUSY)")
            self._owned = True

    def _break_lock(self, owner: str) -> None:
        """Fence-then-break (ManagedLock::break_lock): blocklist the
        dead owner FIRST so any write it still has in flight is
        rejected, then remove its stale lock record."""
        own_client, _, _wc = owner.partition(":")
        if own_client and own_client != self.ioctx.rados.client_id:
            self.ioctx.rados.blocklist_add(own_client)
        try:
            self.ioctx.execute(
                self.oid, "lock", "unlock",
                json.dumps({"cookie": owner}).encode(),
            )
        except RadosError as e:
            if "ENOENT" not in str(e):
                raise

    def release(self) -> None:
        with self._lock:
            if not self._owned:
                return
            self._owned = False
            try:
                self.ioctx.execute(
                    self.oid, "lock", "unlock",
                    json.dumps({"cookie": self.cookie}).encode(),
                )
            except RadosError as e:
                if "ENOENT" not in str(e):
                    raise

    def close(self) -> None:
        self.release()
        if self._watch_cookie is not None:
            try:
                self.ioctx.unwatch(self.oid, self._watch_cookie)
            except RadosError:
                pass
            self._watch_cookie = None
