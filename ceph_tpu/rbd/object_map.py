"""rbd object-map + fast-diff — per-object existence tracking
(src/librbd/ObjectMap.cc:1, src/cls/rbd object_map methods, and the
fast-diff feature of src/librbd/api/DiffIterate.cc — redesigned as a
numpy state vector persisted in one map object instead of a cls-side
2-bit BitVector; states are byte-wide here, a documented deviation
that trades 4x map size — one byte per 4MB object — for direct
numpy indexing of diff queries).

States per data object (the reference's OBJECT_* values):

- 0 ``NONEXISTENT`` — never written (reads fall through / zero-fill)
- 1 ``EXISTS`` — written, and DIRTY since the last snapshot
- 2 ``EXISTS_CLEAN`` — written before the last snapshot, untouched
  since (the fast-diff distinction: snapshots demote 1 → 2)

Update discipline mirrors the reference's crash-safety order: the
map marks an object EXISTS **before** the data write lands (a crash
leaves the map conservative — it may claim existence for an object
the write never reached, which costs one spurious read, never a
missed one), and marks NONEXISTENT **after** a whole-object remove.

``snap_create`` persists a copy of the map at the snap
(``<map_oid>@<snapid>``) and demotes head states to CLEAN, so
``diff`` between any snap and head is a vector compare — no data
object is ever scanned (the rbd diff --whole-object fast path).

The map is only trusted while this client holds the image's
exclusive lock (same invariant as the reference, ObjectMap.cc's
"requires exclusive lock" precondition): lockless writers would race
their read-modify-write of the map object.
"""

from __future__ import annotations

import threading

import numpy as np

from ..osdc.objecter import ObjectNotFound, RadosError

__all__ = [
    "ObjectMap",
    "OBJECT_NONEXISTENT",
    "OBJECT_EXISTS",
    "OBJECT_EXISTS_CLEAN",
]

OBJECT_NONEXISTENT = 0
OBJECT_EXISTS = 1
OBJECT_EXISTS_CLEAN = 2


class ObjectMap:
    def __init__(self, ioctx, map_oid: str, num_objects: int):
        self.ioctx = ioctx
        self.oid = map_oid
        self._states = np.zeros(num_objects, dtype=np.uint8)
        self._loaded = False
        # Image fans one striped write over a thread pool and admits
        # concurrent writers; every mutate-then-save must be atomic
        # or one thread's tobytes() snapshot can persist over (and
        # erase) another's just-set EXISTS bit (the reference
        # serializes via in-process aio_update queueing)
        self._mut = threading.Lock()

    # -- persistence -------------------------------------------------------
    def load(self) -> None:
        try:
            raw = self.ioctx.read(self.oid)
        except (ObjectNotFound, RadosError):
            raw = b""
        got = np.frombuffer(raw, dtype=np.uint8)
        with self._mut:
            n = len(self._states)
            self._states = np.zeros(n, dtype=np.uint8)
            self._states[: min(n, got.size)] = got[: min(n, got.size)]
            self._loaded = True

    def save(self) -> None:
        with self._mut:
            self._save_locked()

    def _save_locked(self) -> None:
        self.ioctx.write_full(self.oid, self._states.tobytes())

    def resize(self, num_objects: int) -> None:
        with self._mut:
            old = self._states
            self._states = np.zeros(num_objects, dtype=np.uint8)
            self._states[: min(num_objects, old.size)] = old[
                : min(num_objects, old.size)
            ]

    # -- state updates (persisted immediately; see module doc order) -------
    def pre_write(self, objectno: int) -> None:
        """Mark EXISTS (dirty) before the data write ships."""
        self.pre_write_many((objectno,))

    def pre_write_many(self, objectnos) -> None:
        """One persisted update covering every object a striped write
        touches (ObjectMap::aio_update batches the same way)."""
        with self._mut:
            objectnos = [
                o for o in objectnos
                if self._states[o] != OBJECT_EXISTS
            ]
            if objectnos:
                self._states[list(objectnos)] = OBJECT_EXISTS
                self._save_locked()

    def post_remove(self, objectno: int) -> None:
        """Mark NONEXISTENT after a whole-object remove commits."""
        with self._mut:
            if self._states[objectno] != OBJECT_NONEXISTENT:
                self._states[objectno] = OBJECT_NONEXISTENT
                self._save_locked()

    # -- queries (the point: no data-object scans) -------------------------
    def object_exists(self, objectno: int) -> bool:
        return self._states[objectno] != OBJECT_NONEXISTENT

    def existing_objects(self) -> list[int]:
        return np.nonzero(self._states)[0].tolist()

    def used_objects(self) -> int:
        """rbd du seat: object count without listing the pool."""
        return int(np.count_nonzero(self._states))

    # -- snapshots / fast-diff ---------------------------------------------
    def _snap_oid(self, snapid: int) -> str:
        return f"{self.oid}@{snapid}"

    def snap_create(self, snapid: int) -> None:
        """Freeze the map at the snap and demote head to CLEAN."""
        with self._mut:
            self.ioctx.write_full(
                self._snap_oid(snapid), self._states.tobytes()
            )
            self._states[self._states == OBJECT_EXISTS] = (
                OBJECT_EXISTS_CLEAN
            )
            self._save_locked()

    def snap_remove(self, snapid: int, next_snapid: int | None) -> None:
        """Retiring a snap must not lose its interval's dirty set:
        fold it into the NEXT snap's map (merging interval A→B into
        B→C yields A→C) or, with no later snap, back into the head as
        EXISTS.  Only objects still existing at the fold target take
        the dirty bit — a vanished object is covered by the
        existence compare.  Then the frozen map object is removed
        (it would otherwise leak forever)."""
        with self._mut:
            try:
                doomed = self._load_snap(snapid)
            except (ObjectNotFound, RadosError):
                doomed = None
            if doomed is not None:
                dirty = doomed == OBJECT_EXISTS
                if next_snapid is not None:
                    nxt = self._load_snap(next_snapid)
                    nxt[dirty & (nxt == OBJECT_EXISTS_CLEAN)] = (
                        OBJECT_EXISTS
                    )
                    self.ioctx.write_full(
                        self._snap_oid(next_snapid), nxt.tobytes()
                    )
                else:
                    self._states[
                        dirty & (self._states == OBJECT_EXISTS_CLEAN)
                    ] = OBJECT_EXISTS
                    self._save_locked()
            try:
                self.ioctx.remove(self._snap_oid(snapid))
            except (ObjectNotFound, RadosError):
                pass

    def _load_snap(self, snapid: int) -> np.ndarray:
        raw = self.ioctx.read(self._snap_oid(snapid))
        got = np.frombuffer(raw, dtype=np.uint8)
        out = np.zeros(len(self._states), dtype=np.uint8)
        out[: min(out.size, got.size)] = got[: min(out.size, got.size)]
        return out

    def diff(
        self,
        from_snapid: int | None = None,
        through_snapids: tuple[int, ...] = (),
    ) -> list[int]:
        """Object numbers that changed since ``from_snapid`` (None =
        everything that exists), straight from the state vectors —
        the fast-diff whole-object answer.

        ``through_snapids``: snaps taken AFTER ``from_snapid`` — a
        head-dirty bit only proves change since the *latest* snap, so
        each intermediate interval's dirty set (frozen in that snap's
        map) ORs in (DiffIterate's per-snap object-map walk)."""
        if from_snapid is None:
            return self.existing_objects()
        base = self._load_snap(from_snapid)
        head = self._states
        base_ex = base != OBJECT_NONEXISTENT
        head_ex = head != OBJECT_NONEXISTENT
        changed = (
            (head == OBJECT_EXISTS)  # dirtied since the latest snap
        ) | (base_ex != head_ex)  # appeared or vanished
        for sid in through_snapids:
            changed |= self._load_snap(sid) == OBJECT_EXISTS
        return np.nonzero(changed)[0].tolist()
