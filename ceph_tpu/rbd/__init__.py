"""librbd analog — block images striped over the object layer
(src/librbd/librbd.cc public surface; image metadata in the
cls_rbd/omap style: header object + rbd_directory index;
data objects laid out by the Striper, src/osdc/Striper.cc).

An image is:

- ``rbd_header.<name>`` — an object whose OMAP holds size, order and
  stripe layout (the cls_rbd header pattern: metadata as omap keys,
  not serialized blobs, so partial updates are single-key writes).
- ``rbd_directory`` — pool-wide omap index of image names (cls_rbd's
  directory object).
- ``rbd_data.<name>.<object_no:016x>`` — data objects, SPARSE: a
  never-written object simply doesn't exist and reads as zeros.

I/O maps logical extents through the Striper and fans per-object ops
out on a thread pool (the io dispatch/ObjectCacher parallelism role —
and on an erasure pool this is the batch feeder for the TPU encode
seam: ``stripe_count`` concurrent full-object writes per window).
Snapshots delegate to pool snapshots (``Image.set_snap`` routes reads
through the pool snap context) — a documented deviation from librbd's
per-image snap contexts.
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import json
import threading

from ..osdc.striper import StripeLayout, map_extent
from ..osdc.objecter import ObjectNotFound, RadosError
from .lock import ExclusiveLock, LockBusy
from .object_map import ObjectMap

__all__ = [
    "RBD", "Image", "RBDError", "StripeLayout", "ExclusiveLock",
    "LockBusy", "ObjectMap",
]

DIRECTORY = "rbd_directory"
_IO_WORKERS = 8


class RBDError(RadosError):
    pass


def _header_oid(name: str) -> str:
    return f"rbd_header.{name}"


def _data_oid(name: str, objectno: int) -> str:
    return f"rbd_data.{name}.{objectno:016x}"


class RBD:
    """Pool-level image management (the librbd::RBD surface)."""

    def create(
        self,
        ioctx,
        name: str,
        size: int,
        stripe_unit: int = 1 << 22,
        stripe_count: int = 1,
        object_size: int = 1 << 22,
        features: str = "",
    ) -> None:
        """``features``: comma list of "exclusive-lock" and
        "object-map" (the RBD_FEATURE_* bits; object-map implies
        exclusive-lock exactly as the reference enforces)."""
        if size < 0:
            raise RBDError("negative image size")
        feats = {f for f in features.split(",") if f}
        if not feats <= {"exclusive-lock", "object-map", "journaling"}:
            raise RBDError(f"unknown features {features!r} (-EINVAL)")
        if "object-map" in feats or "journaling" in feats:
            feats.add("exclusive-lock")
        layout = StripeLayout(stripe_unit, stripe_count, object_size)
        existing = ioctx.omap_get_vals(DIRECTORY) if self._dir_exists(
            ioctx
        ) else {}
        if name in existing:
            raise RBDError(f"image {name!r} exists (-EEXIST)")
        ioctx.write_full(_header_oid(name), b"")
        ioctx.omap_set(
            _header_oid(name),
            {
                "size": str(size).encode(),
                "stripe_unit": str(layout.stripe_unit).encode(),
                "stripe_count": str(layout.stripe_count).encode(),
                "object_size": str(layout.object_size).encode(),
                "features": ",".join(sorted(feats)).encode(),
            },
        )
        ioctx.omap_set(DIRECTORY, {name: b"1"})

    def clone(
        self,
        ioctx,
        parent_name: str,
        parent_snap: str,
        child_name: str,
    ) -> None:
        """COW clone of a parent image snapshot (librbd layering,
        librbd/Operations.cc clone): the child starts as pure
        metadata — reads fall through to the parent AT THE SNAP for
        objects the child has never written, writes copy-up the
        parent object first (object-granular COW, exactly the
        reference's granularity).  Deviations: no protect/unprotect
        gate and no children registry — removing a parent (or its
        snap) under live clones is the operator's misstep to avoid;
        flatten() severs the dependency."""
        snap_full = f"{parent_name}@{parent_snap}"
        snaps = {n: s for s, n in ioctx.snap_list().items()}
        if snap_full not in snaps:
            raise RBDError(
                f"parent snap {parent_snap!r} not found (-ENOENT)"
            )
        try:
            # the header AT THE SNAP: a parent resized after the
            # snapshot must not leak its head size into the child
            pmeta = ioctx.omap_get_vals(
                _header_oid(parent_name), snapid=snaps[snap_full]
            )
        except (ObjectNotFound, RadosError) as e:
            raise RBDError(f"parent {parent_name!r} not found: {e}")
        if "parent" in pmeta:
            # a clone of an unflattened clone would need recursive
            # read-through; flatten the middle image first
            raise RBDError(
                f"parent {parent_name!r} is itself a clone — "
                "flatten it before cloning (-EINVAL)"
            )
        existing = ioctx.omap_get_vals(DIRECTORY) if self._dir_exists(
            ioctx
        ) else {}
        if child_name in existing:
            raise RBDError(f"image {child_name!r} exists (-EEXIST)")
        psize = int(pmeta["size"])
        ioctx.write_full(_header_oid(child_name), b"")
        ioctx.omap_set(
            _header_oid(child_name),
            {
                "size": pmeta["size"],
                "stripe_unit": pmeta["stripe_unit"],
                "stripe_count": pmeta["stripe_count"],
                "object_size": pmeta["object_size"],
                "parent": json.dumps(
                    {
                        "name": parent_name,
                        "snap": parent_snap,
                        "snapid": snaps[snap_full],
                        "size": psize,
                    }
                ).encode(),
            },
        )
        ioctx.omap_set(DIRECTORY, {child_name: b"1"})

    @staticmethod
    def _dir_exists(ioctx) -> bool:
        try:
            ioctx.stat(DIRECTORY)
            return True
        except (ObjectNotFound, RadosError):
            return False

    def list(self, ioctx) -> list[str]:
        if not self._dir_exists(ioctx):
            return []
        return sorted(ioctx.omap_get_vals(DIRECTORY))

    def remove(self, ioctx, name: str) -> None:
        img = Image(ioctx, name)
        try:
            for objectno in range(img._max_objects()):
                try:
                    ioctx.remove(_data_oid(name, objectno))
                except (ObjectNotFound, RadosError):
                    pass
            map_oids = [f"rbd_object_map.{name}"] + [
                f"rbd_object_map.{name}@{sid}"
                for sid in img._image_snapids()
            ]
        finally:
            img.close()
        for moid in map_oids:
            try:
                ioctx.remove(moid)
            except (ObjectNotFound, RadosError):
                pass
        ioctx.remove(_header_oid(name))
        ioctx.omap_rm_keys(DIRECTORY, [name])


class Image:
    """One open image (librbd::Image): striped read/write/discard,
    resize, snapshot-routed reads."""

    def __init__(self, ioctx, name: str, cache: bool = False,
                 cache_opts: dict | None = None):
        """``cache=True`` opens the image behind an ObjectCacher
        (rbd_cache role): reads serve from cached extents, writes go
        write-back and flush on close()/flush() — single-writer
        semantics, like rbd_cache without an exclusive-lock
        arbiter (documented deviation)."""
        self.ioctx = ioctx
        self.name = name
        self._cache = None
        try:
            meta = ioctx.omap_get_vals(_header_oid(name))
        except (ObjectNotFound, RadosError) as e:
            raise RBDError(f"image {name!r} not found: {e}")
        if "size" not in meta:
            raise RBDError(f"image {name!r} has no header metadata")
        self._size = int(meta["size"])
        self.parent = (
            json.loads(meta["parent"]) if "parent" in meta else None
        )
        self._copyup_lock = threading.Lock()
        self._copyup_locks: dict[int, threading.Lock] = {}
        self.layout = StripeLayout(
            int(meta["stripe_unit"]),
            int(meta["stripe_count"]),
            int(meta["object_size"]),
        )
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=_IO_WORKERS,
            thread_name_prefix=f"rbd.{name}",
        )
        # feature plane: exclusive-lock + object-map (ExclusiveLock /
        # ObjectMap seats).  Mutations gate on lock ownership; a
        # cooperative handoff drains in-flight writes, flushes, and
        # releases (see _handoff_release)
        self.features = set(
            meta.get("features", b"").decode().split(",")
        ) - {""}
        self._xlock: ExclusiveLock | None = None
        self._objmap: ObjectMap | None = None
        self._wr_cond = threading.Condition()
        self._wr_inflight = 0
        self._releasing = False
        # acquire+map-load must complete ATOMICALLY before any other
        # local writer proceeds: is_owner flips true inside acquire()
        # BEFORE the map load, and a second writer racing past on
        # that flag could persist an EXISTS bit the stale load then
        # clobbers.  _ready flips only after the load.
        self._acquire_mu = threading.Lock()
        self._ready = False
        if "exclusive-lock" in self.features:
            self._xlock = ExclusiveLock(
                ioctx, _header_oid(name),
                on_release_request=self._handoff_release,
            )
        if "object-map" in self.features:
            self._objmap = ObjectMap(
                ioctx, f"rbd_object_map.{name}", self._max_objects()
            )
            self._objmap.load()
        # image journal (librbd/Journal.cc role): mutations append
        # to a per-image rados journal stream BEFORE the data ships;
        # the journal tail replays on lock acquisition (crash
        # consistency) and feeds rbd-mirror (see rbd/mirror.py)
        self._journal = None
        self._journal_uncommitted = 0
        # append+flush must be atomic across concurrent writers, and
        # replay suppression is THREAD-scoped: a replaying thread's
        # re-entrant writes skip journaling while other writers'
        # mutations journal normally
        self._journal_mu = threading.Lock()
        self._replay_tls = threading.local()
        if "journaling" in self.features:
            from ..mds.journaler import Journaler

            self._journal = Journaler(
                ioctx, prefix=f"rbd_journal.{name}"
            )
        if cache:
            if self.parent is not None:
                # the cacher cannot see parent read-through/copy-up;
                # silently uncached IO would betray cache=True
                raise RBDError(
                    "cache=True unsupported on an unflattened clone "
                    "(flatten first) (-EINVAL)"
                )
            # AFTER header validation: a failed open must not leak
            # the cacher's flusher thread
            from ..osdc.object_cacher import ObjectCacher

            self._cache = ObjectCacher(ioctx, **(cache_opts or {}))

    # -- exclusive-lock gating ---------------------------------------------
    def _ensure_owner_ready(self) -> None:
        """Lock held AND map loaded, atomically vs other local
        writers (see _acquire_mu/_ready above)."""
        if self._xlock.is_owner and self._ready:
            return
        with self._acquire_mu:
            if self._xlock.is_owner and self._ready:
                return
            self._xlock.acquire()
            if self._objmap is not None:
                # the map is only trusted under the lock: reload
                # what the previous owner persisted
                self._objmap.load()
            # _ready flips BEFORE journal replay: replay re-applies
            # entries through write()/discard(), which re-enter the
            # owner-ready fast path — entering the mutex again would
            # self-deadlock
            self._ready = True
            if self._journal is not None:
                self._journal_replay_tail()

    def _enter_write(self) -> None:
        """Every mutation passes here: wait out a handoff/barrier in
        progress, take (or confirm) the exclusive lock, count
        ourselves in-flight so a handoff can drain us."""
        if self._xlock is None:
            return
        with self._wr_cond:
            while self._releasing:
                self._wr_cond.wait()
            self._wr_inflight += 1
        try:
            self._ensure_owner_ready()
        except BaseException:
            with self._wr_cond:
                self._wr_inflight -= 1
                self._wr_cond.notify_all()
            raise

    def _exit_write(self) -> None:
        if self._xlock is None:
            return
        with self._wr_cond:
            self._wr_inflight -= 1
            self._wr_cond.notify_all()

    @contextlib.contextmanager
    def _write_barrier(self):
        """Exclude ALL writers (local in-flight drained, new ones
        held at the gate) for an operation that must see a frozen
        image — the snapshot+map-freeze pair.  A cooperative handoff
        queues behind the same flag, so the lock cannot leave this
        client mid-barrier."""
        if self._xlock is None:
            yield
            return
        with self._wr_cond:
            while self._releasing:
                self._wr_cond.wait()
            self._releasing = True
            while self._wr_inflight:
                self._wr_cond.wait()
        try:
            yield
        finally:
            with self._wr_cond:
                self._releasing = False
                self._wr_cond.notify_all()

    def _handoff_release(self) -> None:
        """Peer asked for the lock: drain in-flight writes, barrier
        the cache, hand it over (ExclusiveLock's release path)."""
        with self._wr_cond:
            while self._releasing:
                self._wr_cond.wait()
            self._releasing = True
            while self._wr_inflight:
                self._wr_cond.wait()
            try:
                if self._cache is not None:
                    self._cache.flush()
                self._ready = False
                self._xlock.release()
            finally:
                self._releasing = False
                self._wr_cond.notify_all()

    # -- image journal (librbd/Journal.cc reduced) -------------------------
    def _journal_append(self, op: int, off: int, length: int,
                        data: bytes = b"") -> None:
        """Journal-ahead: the entry is DURABLE before the data ships
        (a crash replays it on the next lock acquisition; rbd-mirror
        tails the same stream)."""
        if self._journal is None or getattr(
            self._replay_tls, "on", False
        ):
            return
        from ..common.encoding import Encoder

        e = Encoder()
        e.u8(op).u64(off).u64(length).bytes(data)
        with self._journal_mu:
            self._journal.append(e.getvalue())
            self._journal.flush()

    def _journal_commit(self) -> None:
        """Mark the applied prefix committed (trim honors mirror
        clients, so entries survive until every consumer saw them)."""
        if self._journal is None or getattr(
            self._replay_tls, "on", False
        ):
            # replay commits once, at its end — a mid-replay trim
            # would delete stream objects the generator still reads
            return
        self._journal_uncommitted += 1
        if self._journal_uncommitted >= 16:
            self._journal_uncommitted = 0
            with self._journal_mu:
                self._journal.trim()

    def _journal_replay_tail(self) -> None:
        """Re-apply the uncommitted journal tail (entries appended
        by a previous owner that crashed between journal and data;
        every entry is idempotent absolute-offset state)."""
        with self._journal_mu:
            self._journal.load()
        self._replay_tls.on = True
        try:
            for blob in self._journal.replay():
                self._journal_apply(blob)
        finally:
            self._replay_tls.on = False
        with self._journal_mu:
            self._journal.trim()

    def _journal_apply(self, blob: bytes) -> None:
        from ..common.encoding import Decoder

        d = Decoder(blob)
        op, off, length = d.u8(), d.u64(), d.u64()
        data = d.bytes()
        if op == 1:
            # the entry was in-bounds at append time; the image may
            # have SHRUNK since (a later resize entry restores it) —
            # grow transiently rather than wedging replay on the
            # size check
            if off + len(data) > self._size:
                self.resize(off + len(data))
            self.write(off, data)
        elif op == 2:
            self.discard(off, length)
        elif op == 3:
            self.resize(off)

    def lock_acquire(self) -> None:
        """Explicitly take the exclusive lock (rbd lock acquire)."""
        if self._xlock is None:
            raise RBDError("exclusive-lock feature not enabled")
        self._ensure_owner_ready()

    def lock_release(self) -> None:
        if self._xlock is not None:
            self._handoff_release()

    def is_lock_owner(self) -> bool:
        return self._xlock is not None and self._xlock.is_owner

    def lock_holder(self) -> str | None:
        """Current exclusive-lock holder cookie, or None (the rbd
        lock-status surface)."""
        if self._xlock is None:
            raise RBDError("exclusive-lock feature not enabled")
        return self._xlock._holder()

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        # drain in-flight aio FIRST: a queued aio_write must buffer
        # into a live cacher, not a closed one (its data would be
        # silently lost)
        self._pool.shutdown(wait=True)
        if self._cache is not None:
            self._cache.close()  # flush-on-close (rbd_cache contract)
        if self._xlock is not None:
            self._xlock.close()

    def flush(self) -> None:
        """Barrier all write-back state to the cluster."""
        if self._cache is not None:
            self._cache.flush()

    def __enter__(self) -> "Image":
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- metadata ----------------------------------------------------------
    def size(self) -> int:
        return self._size

    def stat(self) -> dict:
        return {
            "size": self._size,
            "obj_size": self.layout.object_size,
            "stripe_unit": self.layout.stripe_unit,
            "stripe_count": self.layout.stripe_count,
            "num_objs": self._max_objects(),
        }

    def _max_objects(self) -> int:
        if self._size == 0:
            return 0
        last = map_extent(self.layout, self._size - 1, 1)
        return last[-1][0] + 1

    def resize(self, new_size: int) -> None:
        """Grow is metadata-only (sparse); shrink trims the dropped
        range first — whole objects are removed and the boundary
        object's tail zeroed (librbd trim)."""
        if new_size < 0:
            raise RBDError("negative image size")
        old = self._size
        if self._journal is not None and not getattr(
            self._replay_tls, "on", False
        ):
            self._enter_write()
            try:
                self._journal_append(3, new_size, 0)
            finally:
                self._exit_write()
        was = getattr(self._replay_tls, "on", False)
        self._replay_tls.on = True  # the shrink's discard is covered
        try:                        # by the resize entry (this thread
            if new_size < old:      # only); don't double-journal
                self.discard(new_size, old - new_size)
        finally:
            self._replay_tls.on = was
        self._size = new_size
        self.ioctx.omap_set(
            _header_oid(self.name), {"size": str(new_size).encode()}
        )
        if self._objmap is not None:
            self._enter_write()
            try:
                self._objmap.resize(self._max_objects())
                self._objmap.save()
            finally:
                self._exit_write()

    # -- data path ---------------------------------------------------------
    def read(self, offset: int, length: int) -> bytes:
        """Striped read; holes (missing objects / short objects) read
        as zeros (sparse semantics)."""
        if offset < 0 or length < 0:
            raise RBDError("negative read extent")
        length = max(0, min(length, self._size - offset))
        if length == 0:
            return b""
        extents = map_extent(self.layout, offset, length)

        def read_one(ext):
            objectno, obj_off, n = ext
            oid = _data_oid(self.name, objectno)
            if self._cache is not None:
                return self._cache.read(oid, obj_off, n)
            try:
                data = self.ioctx.read(
                    oid, length=n, offset=obj_off
                )
            except (ObjectNotFound, RadosError):
                if self.parent is not None:
                    return self._parent_read(objectno, obj_off, n)
                data = b""
            return data + b"\0" * (n - len(data))

        parts = list(self._pool.map(read_one, extents))
        return b"".join(parts)

    def _parent_read(self, objectno: int, obj_off: int, n: int) -> bytes:
        """Read-through to the parent snapshot for an object the
        child never wrote (librbd's parent overlap read)."""
        p = self.parent
        # no explicit overlap bound: beyond-parent ranges simply have
        # no parent object bytes and zero-fill below (a computed
        # bound would need the inverse striper map for
        # stripe_count > 1 and gets it wrong otherwise)
        try:
            data = self.ioctx.read(
                _data_oid(p["name"], objectno), length=n,
                offset=obj_off, snapid=p["snapid"],
            )
        except (ObjectNotFound, RadosError):
            data = b""
        return data + b"\0" * (n - len(data))

    def _copy_up(self, objectno: int) -> None:
        """First write to an inherited object materializes the whole
        parent object in the child (librbd copy-up) so the child
        object fully shadows the parent from then on.  Serialized per
        object: concurrent stripes of one write (or parallel aio)
        must not let a late write_full of the parent base clobber a
        sibling's already-written chunk."""
        with self._copyup_lock:
            lock = self._copyup_locks.setdefault(
                objectno, threading.Lock()
            )
        with lock:
            oid = _data_oid(self.name, objectno)
            try:
                self.ioctx.stat(oid)
                return  # child already owns this object
            except (ObjectNotFound, RadosError):
                pass
            base = self._parent_read(
                objectno, 0, self.layout.object_size
            ).rstrip(b"\0")
            # write even when empty: the object's EXISTENCE is the
            # shadow
            self.ioctx.write_full(oid, base)

    def write(self, offset: int, data: bytes) -> int:
        if offset < 0:
            raise RBDError("negative write offset")
        data = bytes(data)
        if offset + len(data) > self._size:
            raise RBDError(
                f"write past image end ({offset + len(data)} > "
                f"{self._size}) (-EINVAL)"
            )
        extents = map_extent(self.layout, offset, len(data))
        cuts = []
        pos = 0
        for objectno, obj_off, n in extents:
            cuts.append((objectno, obj_off, data[pos : pos + n]))
            pos += n

        def write_one(cut):
            objectno, obj_off, chunk = cut
            oid = _data_oid(self.name, objectno)
            if self.parent is not None and not (
                obj_off == 0 and len(chunk) == self.layout.object_size
            ):
                # partial writes copy-up; a full-object write fully
                # shadows the parent by itself (librbd skips too)
                self._copy_up(objectno)
            if self._cache is not None:
                self._cache.write(oid, obj_off, chunk)
            else:
                self.ioctx.write(oid, chunk, offset=obj_off)

        self._enter_write()
        try:
            self._journal_append(1, offset, len(data), data)
            if self._objmap is not None:
                # EXISTS lands in the map BEFORE the data ships: a
                # crash between the two leaves the map conservative
                self._objmap.pre_write_many(
                    [c[0] for c in cuts]
                )
            list(self._pool.map(write_one, cuts))
            self._journal_commit()
        finally:
            self._exit_write()
        return len(data)

    def discard(self, offset: int, length: int) -> None:
        """Zero a range (librbd discard): whole objects drop, partial
        ranges overwrite with zeros."""
        if offset < 0 or length < 0:
            raise RBDError("negative discard extent")
        length = max(0, min(length, self._size - offset))
        if length == 0:
            return
        self._enter_write()
        try:
            self._journal_append(2, offset, length)
            self._discard_inner(offset, length)
            self._journal_commit()
        finally:
            self._exit_write()

    def _discard_inner(self, offset: int, length: int) -> None:
        for objectno, obj_off, n in map_extent(
            self.layout, offset, length
        ):
            oid = _data_oid(self.name, objectno)
            whole = obj_off == 0 and n == self.layout.object_size
            if self.parent is not None:
                # removing the child object would RESURRECT parent
                # data; a clone's discard writes zeros instead — and
                # a FAILED zeroing must surface (swallowing it would
                # be exactly the resurrection this path prevents)
                self._copy_up(objectno)
                self.ioctx.write(oid, b"\0" * n, offset=obj_off)
                continue
            if self._objmap is not None and not whole:
                self._objmap.pre_write(objectno)
            if self._cache is not None and whole:
                self._cache.discard(oid)
            elif self._cache is not None:
                # partial discard: zero through the cache so no
                # stale cached bytes survive it
                self._cache.write(oid, obj_off, b"\0" * n)
                continue
            if whole:
                try:
                    self.ioctx.remove(oid)
                except (ObjectNotFound, RadosError):
                    pass
                if self._objmap is not None:
                    # NONEXISTENT lands AFTER the remove commits (the
                    # inverse of the pre-write order, same reasoning)
                    self._objmap.post_remove(objectno)
            else:
                try:
                    self.ioctx.write(oid, b"\0" * n, offset=obj_off)
                except RadosError:
                    pass

    def flatten(self) -> None:
        """Copy every still-inherited object down from the parent and
        sever the dependency (librbd flatten): afterwards the child
        is a standalone image and the parent/snap may be retired."""
        if self.parent is None:
            return
        list(
            self._pool.map(self._copy_up, range(self._max_objects()))
        )
        self.ioctx.omap_rm_keys(_header_oid(self.name), ["parent"])
        self.parent = None

    # -- object-map queries (rbd diff/du fast path) ------------------------
    def _image_snapids(self) -> list[int]:
        """This image's snap ids, oldest first (ids are monotone)."""
        prefix = f"{self.name}@"
        return sorted(
            sid
            for sid, n in self.ioctx.snap_list().items()
            if n.startswith(prefix)
        )

    def diff_objects(self, from_snap: str | None = None) -> list[int]:
        """Object numbers changed since ``from_snap`` (None = all
        existing), answered ENTIRELY from the object map — no data
        object is read or listed (the fast-diff whole-object path,
        src/librbd/api/DiffIterate.cc).  Requires the object-map
        feature."""
        if self._objmap is None:
            raise RBDError(
                "diff_objects needs the object-map feature (-EINVAL)"
            )
        self._objmap.load()
        if from_snap is None:
            return self._objmap.existing_objects()
        from_id = self.ioctx.snap_lookup(f"{self.name}@{from_snap}")
        later = tuple(
            s for s in self._image_snapids() if s > from_id
        )
        return self._objmap.diff(from_id, later)

    def used_objects(self) -> int:
        """Allocated object count from the map (rbd du seat)."""
        if self._objmap is None:
            raise RBDError(
                "used_objects needs the object-map feature (-EINVAL)"
            )
        self._objmap.load()
        return self._objmap.used_objects()

    # -- aio (librbd completions) ------------------------------------------
    def aio_read(self, offset: int, length: int):
        return self._pool.submit(self.read, offset, length)

    def aio_write(self, offset: int, data: bytes):
        return self._pool.submit(self.write, offset, bytes(data))

    # -- snapshots (pool-snap delegation; documented deviation) ------------
    def snap_create(self, snap_name: str) -> int:
        # the snapshot and the map freeze must see a QUIESCED image:
        # a write racing between them would have its dirty bit
        # demoted to CLEAN even though its data lands after the snap,
        # hiding the object from every future fast-diff.  The barrier
        # drains in-flight writers and holds new ones (and any lock
        # handoff) until both land.
        with self._write_barrier():
            if self._xlock is not None:
                self._ensure_owner_ready()
            # completed writes must be IN the snapshot: barrier the
            # write-back cache before taking it (rbd_cache contract)
            if self._cache is not None:
                self._cache.flush()
            snapid = self.ioctx.snap_create(
                f"{self.name}@{snap_name}"
            )
            if self._objmap is not None:
                self._objmap.snap_create(snapid)
        return snapid

    def snap_remove(self, snap_name: str) -> None:
        if self._objmap is not None:
            snapid = self.ioctx.snap_lookup(
                f"{self.name}@{snap_name}"
            )
            later = [
                s for s in self._image_snapids() if s > snapid
            ]
            with self._write_barrier():
                self._ensure_owner_ready()
                self._objmap.snap_remove(
                    snapid, later[0] if later else None
                )
        self.ioctx.snap_remove(f"{self.name}@{snap_name}")

    def snap_list(self) -> list[str]:
        prefix = f"{self.name}@"
        return sorted(
            n[len(prefix):]
            for n in self.ioctx.snap_list().values()
            if n.startswith(prefix)
        )

    def set_snap(self, snap_name: str | None) -> None:
        """Route reads through a snapshot (librbd::Image::snap_set);
        None returns to the head.  The cache cannot distinguish head
        from snapshot bytes, so it flushes and invalidates on every
        routing change (librbd flushes+invalidates on snap_set for
        the same reason)."""
        if self._cache is not None:
            self._cache.invalidate_all()
        if snap_name is None:
            self.ioctx.snap_set_read(0)
        else:
            self.ioctx.snap_set_read(f"{self.name}@{snap_name}")
