"""lockdep — lock-ordering cycle detection
(src/common/lockdep.cc reduced; SURVEY §5.2's race-detection tier).

The reference registers every named mutex and records, at acquire
time, "B taken while holding A" edges; a new edge that closes a cycle
in the global order graph is a potential deadlock and aborts with the
two conflicting backtraces — catching ABBA inversions on the FIRST
run through the code path, not the unlucky interleaving years later.

Same machinery here:

- ``Mutex(name)`` / ``RMutex(name)`` wrap threading locks; when
  lockdep is enabled, each acquire records order edges against every
  lock the thread already holds.
- a cycle (B before A registered while A-before-B exists, possibly
  transitively) raises ``LockOrderError`` naming the full cycle and
  where each edge was first taken.
- disabled (the default) the wrappers are plain locks — zero
  overhead in production daemons; tests and the thrasher enable it.

Orders are keyed by lock NAME, so every instance of "pg-lock" shares
one vertex — exactly lockdep's design: instance-level cycles across
different objects of the same class are the bugs worth catching.
"""

from __future__ import annotations

import threading
import traceback

_enabled = False
_state_lock = threading.Lock()
# order[a][b] = first-stack-trace where b was taken while holding a
_order: dict[str, dict[str, str]] = {}
_held = threading.local()


class LockOrderError(RuntimeError):
    pass


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    with _state_lock:
        _order.clear()


def enabled() -> bool:
    return _enabled


def _holding() -> list[str]:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


def _path(src: str, dst: str) -> list[str] | None:
    """Existing order path src -> ... -> dst (DFS over the graph)."""
    seen = set()
    stack = [(src, [src])]
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        if node in seen:
            continue
        seen.add(node)
        for nxt in _order.get(node, {}):
            stack.append((nxt, path + [nxt]))
    return None


def _will_lock(name: str, recursive: bool) -> None:
    holding = _holding()
    if not holding:
        return
    with _state_lock:
        for prev in holding:
            if prev == name:
                if recursive:
                    continue  # RMutex: same-class re-take is legal
                # nested acquisition of a non-recursive class: either
                # self-deadlock (same instance) or the classic two-
                # instance ABBA (pg1->pg2 in one thread, pg2->pg1 in
                # another) — real lockdep flags it here, from ONE
                # thread's behavior
                raise LockOrderError(
                    "nested acquisition of non-recursive lock "
                    + f"class {name!r}:" + chr(10)
                    + "".join(traceback.format_stack(limit=8))
                )
            # does an order name -> ... -> prev already exist?  Then
            # prev -> name closes a cycle.
            cycle = _path(name, prev)
            if cycle is not None:
                first = _order[cycle[0]][cycle[1]]
                raise LockOrderError(
                    f"lock order inversion: taking {name!r} while "
                    f"holding {prev!r}, but the inverse order "
                    f"{' -> '.join(cycle)} was established here:\n"
                    f"{first}\n--- current acquisition:\n"
                    + "".join(traceback.format_stack(limit=8))
                )
            edges = _order.setdefault(prev, {})
            if name not in edges:
                edges[name] = "".join(
                    traceback.format_stack(limit=8)
                )


def _locked(name: str) -> None:
    _holding().append(name)


def _unlocked(name: str) -> None:
    holding = _holding()
    # remove the most recent entry (locks release innermost-first in
    # well-formed code; lockdep tolerates out-of-order releases)
    for i in range(len(holding) - 1, -1, -1):
        if holding[i] == name:
            del holding[i]
            return


class Mutex:
    """threading.Lock with lockdep registration."""

    _factory = staticmethod(threading.Lock)

    def __init__(self, name: str):
        self.name = name
        self._lock = self._factory()

    RECURSIVE = False

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _enabled:
            _will_lock(self.name, self.RECURSIVE)
        got = self._lock.acquire(blocking, timeout)
        if got and _enabled:
            _locked(self.name)
        return got

    def release(self) -> None:
        self._lock.release()
        # unconditional: an acquire tracked before disable() must not
        # strand a phantom entry in the per-thread held stack
        _unlocked(self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class RMutex(Mutex):
    """threading.RLock with lockdep registration."""

    RECURSIVE = True
    _factory = staticmethod(threading.RLock)
