"""Performance counters (src/common/perf_counters.{h,cc}).

Typed per-subsystem metrics with the reference's four shapes: u64
counters, gauges, long-run averages (avgcount+sum pairs, used for
latencies), and histograms — dumped as the nested JSON `perf dump`
emits over the admin socket.  A builder declares the schema up front
(PerfCountersBuilder), instances are cheap to update on hot paths.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

PERFCOUNTER_U64 = "u64"
PERFCOUNTER_GAUGE = "gauge"
PERFCOUNTER_LONGRUNAVG = "avg"
PERFCOUNTER_TIME = "time"
PERFCOUNTER_HISTOGRAM = "histogram"


@dataclass
class _Counter:
    name: str
    kind: str
    description: str = ""
    value: float = 0
    avgcount: int = 0
    buckets: list = field(default_factory=list)
    bucket_bounds: tuple = ()


class PerfCounters:
    """One subsystem's counter set (e.g. l_osd_*, OSD.cc:9681)."""

    def __init__(self, name: str):
        self.name = name
        self._counters: dict[str, _Counter] = {}
        self._lock = threading.Lock()

    # -- updates -----------------------------------------------------------
    def inc(self, name: str, amount: int = 1) -> None:
        c = self._counters[name]
        assert c.kind in (
            PERFCOUNTER_U64,
            PERFCOUNTER_GAUGE,
            PERFCOUNTER_LONGRUNAVG,
        ), f"inc on {c.kind} counter {name}; use tinc/hinc"
        with self._lock:
            if c.kind == PERFCOUNTER_LONGRUNAVG:
                c.value += amount
                c.avgcount += 1
            else:
                c.value += amount

    def dec(self, name: str, amount: int = 1) -> None:
        c = self._counters[name]
        assert c.kind == PERFCOUNTER_GAUGE, "dec is gauge-only"
        with self._lock:
            c.value -= amount

    def set(self, name: str, value: float) -> None:
        c = self._counters[name]
        assert c.kind in (PERFCOUNTER_U64, PERFCOUNTER_GAUGE), (
            f"set on {c.kind} counter {name}"
        )
        with self._lock:
            c.value = value

    def tinc(self, name: str, seconds: float) -> None:
        """Accumulate a latency sample (time + avgcount pair)."""
        c = self._counters[name]
        assert c.kind == PERFCOUNTER_TIME
        with self._lock:
            c.value += seconds
            c.avgcount += 1

    def hinc(self, name: str, value: float) -> None:
        c = self._counters[name]
        assert c.kind == PERFCOUNTER_HISTOGRAM
        with self._lock:
            # sum + count accumulate alongside the buckets so the
            # exporter can emit the prometheus-native _sum/_count pair
            c.value += value
            c.avgcount += 1
            for i, bound in enumerate(c.bucket_bounds):
                if value <= bound:
                    c.buckets[i] += 1
                    return
            c.buckets[-1] += 1

    def time_it(self, name: str) -> "_Timer":
        """Context manager: tinc the elapsed wall time."""
        return _Timer(self, name)

    # -- dump --------------------------------------------------------------
    def dump(self) -> dict:
        """The `perf dump` JSON shape: avg/time counters dump as
        {avgcount, sum}; histograms as bucket arrays."""
        out = {}
        with self._lock:
            for name, c in self._counters.items():
                if c.kind in (PERFCOUNTER_LONGRUNAVG, PERFCOUNTER_TIME):
                    out[name] = {
                        "avgcount": c.avgcount,
                        "sum": c.value,
                    }
                elif c.kind == PERFCOUNTER_HISTOGRAM:
                    out[name] = {
                        "bounds": list(c.bucket_bounds),
                        "buckets": list(c.buckets),
                        "sum": c.value,
                        "count": c.avgcount,
                    }
                else:
                    out[name] = c.value
        return out

    def reset(self) -> None:
        with self._lock:
            for c in self._counters.values():
                c.value = 0
                c.avgcount = 0
                c.buckets = [0] * len(c.buckets)


class _Timer:
    __slots__ = ("_pc", "_name", "_t0")

    def __init__(self, pc: PerfCounters, name: str):
        self._pc = pc
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._pc.tinc(self._name, time.perf_counter() - self._t0)
        return False


class PerfCountersBuilder:
    """Declare the counter schema, then create_perf_counters()
    (perf_counters.h builder pattern)."""

    def __init__(self, name: str):
        self._pc = PerfCounters(name)

    def _add(self, name, kind, description="", bounds=()):
        assert name not in self._pc._counters, name
        c = _Counter(name, kind, description, bucket_bounds=tuple(bounds))
        if kind == PERFCOUNTER_HISTOGRAM:
            c.buckets = [0] * (len(bounds) + 1)
        self._pc._counters[name] = c
        return self

    def add_u64_counter(self, name, description=""):
        return self._add(name, PERFCOUNTER_U64, description)

    def add_u64_gauge(self, name, description=""):
        return self._add(name, PERFCOUNTER_GAUGE, description)

    def add_u64_avg(self, name, description=""):
        return self._add(name, PERFCOUNTER_LONGRUNAVG, description)

    def add_time_avg(self, name, description=""):
        return self._add(name, PERFCOUNTER_TIME, description)

    def add_histogram(self, name, bounds, description=""):
        return self._add(name, PERFCOUNTER_HISTOGRAM, description, bounds)

    def create_perf_counters(self) -> PerfCounters:
        return self._pc


class PerfCountersCollection:
    """Registry of every subsystem's counters — the admin socket's
    `perf dump` aggregates across it (perf_counters.cc collection)."""

    def __init__(self):
        self._sets: dict[str, PerfCounters] = {}
        self._lock = threading.Lock()

    def add(self, pc: PerfCounters) -> None:
        with self._lock:
            self._sets[pc.name] = pc

    def remove(self, name: str) -> None:
        with self._lock:
            self._sets.pop(name, None)

    def dump(self) -> dict:
        with self._lock:
            return {name: pc.dump() for name, pc in self._sets.items()}

    def reset(self) -> None:
        """Zero every registered set (the `perf reset all` builtin)."""
        with self._lock:
            for pc in self._sets.values():
                pc.reset()
