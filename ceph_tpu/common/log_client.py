"""Cluster-log client (src/common/LogClient.{h,cc} + LogEntry.h).

Every daemon holds a ``LogClient``; code paths clog through a
``LogChannel`` (named channel, default "cluster"; operator actions go
to "audit").  Entries carry the daemon identity, a wall-clock stamp, a
priority, and a per-daemon sequence number, and queue into a bounded
buffer the daemon's tick drains into an ``MLog`` message to the
monitor — the LogClient → LogMonitor path that makes ``ceph log last``
the cluster's health timeline.

Entries also echo into the local dout ring (subsys "clog"), so a crash
report's dout tail shows what the daemon clogged before dying.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

from .log import dout

# priority ladder, least to most severe (LogEntry.h's clog levels)
CLOG_PRIOS = ("debug", "info", "warn", "error", "sec")

# clog prio -> dout level for the local ring mirror
_DOUT_LEVEL = {"debug": 20, "info": 5, "warn": 1, "error": 0, "sec": 0}

# schema bounds (tools/check_metrics.py lints these)
MAX_MESSAGE_LEN = 4096
MAX_CHANNEL_LEN = 64
MAX_NAME_LEN = 64


def prio_rank(prio: str) -> int:
    """Severity rank for level filtering; unknown prios sort lowest."""
    try:
        return CLOG_PRIOS.index(prio)
    except ValueError:
        return -1


class LogChannel:
    """One named channel of a daemon's LogClient (LogChannel role):
    the ``clog.error(...)`` surface."""

    def __init__(self, client: "LogClient", channel: str = "cluster"):
        self.client = client
        self.channel = channel

    def log(self, prio: str, message: str) -> None:
        self.client.queue(self.channel, prio, message)

    def debug(self, message: str) -> None:
        self.log("debug", message)

    def info(self, message: str) -> None:
        self.log("info", message)

    def warn(self, message: str) -> None:
        self.log("warn", message)

    def error(self, message: str) -> None:
        self.log("error", message)


class LogClient:
    """Per-daemon cluster-log queue: bounded, drained onto the wire by
    the daemon's tick (drop-oldest under flooding, counted)."""

    def __init__(self, name: str, max_pending: int = 256):
        self.name = name[:MAX_NAME_LEN]
        self._pending: deque[dict] = deque(maxlen=max_pending)
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        self._channels: dict[str, LogChannel] = {}
        self.entries_queued = 0
        self.entries_dropped = 0

    def channel(self, name: str = "cluster") -> LogChannel:
        with self._lock:
            ch = self._channels.get(name)
            if ch is None:
                ch = self._channels[name] = LogChannel(self, name)
            return ch

    def queue(self, channel: str, prio: str, message: str) -> dict:
        if prio not in CLOG_PRIOS:
            prio = "info"
        entry = {
            "name": self.name,
            "stamp": time.time(),
            "channel": channel[:MAX_CHANNEL_LEN],
            "prio": prio,
            "message": str(message)[:MAX_MESSAGE_LEN],
            "seq": next(self._seq),
        }
        with self._lock:
            if len(self._pending) == self._pending.maxlen:
                self.entries_dropped += 1
            self._pending.append(entry)
            self.entries_queued += 1
        dout("clog", _DOUT_LEVEL[prio], f"[{channel} {prio}] {message}")
        return entry

    def drain(self) -> list[dict]:
        """Take every pending entry (the MLog batch)."""
        with self._lock:
            out = list(self._pending)
            self._pending.clear()
            return out

    def requeue(self, entries: list[dict]) -> None:
        """Put a failed batch back at the FRONT (order preserved) so a
        transient mon outage loses nothing; overflow still drops the
        oldest."""
        with self._lock:
            for i, entry in enumerate(reversed(entries)):
                if len(self._pending) == self._pending.maxlen:
                    # count EVERY entry of the batch we discard, not
                    # just the first — the drop counter is the
                    # operator's signal for clog loss
                    self.entries_dropped += len(entries) - i
                    break
                self._pending.appendleft(entry)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def flush(self, monc) -> bool:
        """Drain onto the mon (MLog via ``monc.send_log``); a failed
        send requeues so a mon outage loses nothing.  The one flush
        contract every daemon shares — returns True when the batch
        (if any) went out."""
        entries = self.drain()
        if not entries:
            return True
        try:
            monc.send_log(entries, name=self.name)
            return True
        except Exception:  # noqa: BLE001 — transport-agnostic: any
            # failure means "mon didn't get it", so requeue
            self.requeue(entries)
            return False
