"""Common runtime: config schema/sources and performance counters.

The reference's CephContext carries md_config_t (src/common/config.cc
over the ~1,658 Option definitions in src/common/options.cc) and
PerfCounters (src/common/perf_counters.cc); this package provides the
same two services for the TPU framework's daemons and tools.
"""

from .admin_socket import AdminSocket, admin_command
from .config import Config, Option, OPT_INT, OPT_STR, OPT_BOOL, OPT_FLOAT
from .histogram import LogHistogram, PerfHistogram2D
from .log_client import LogChannel, LogClient
from .op_tracker import OpTracker, TrackedOp
from .perf_counters import (
    PerfCounters,
    PerfCountersBuilder,
    PerfCountersCollection,
)
from .tracing import Span, Tracer

__all__ = [
    "AdminSocket",
    "admin_command",
    "Config",
    "LogChannel",
    "LogClient",
    "LogHistogram",
    "OpTracker",
    "PerfHistogram2D",
    "Span",
    "TrackedOp",
    "Tracer",
    "Option",
    "OPT_BOOL",
    "OPT_FLOAT",
    "OPT_INT",
    "OPT_STR",
    "PerfCounters",
    "PerfCountersBuilder",
    "PerfCountersCollection",
]
