"""dout-style logging (src/log/Log.cc + common/subsys.h).

Per-subsystem debug levels gate cheaply at call time; accepted entries
go to an in-memory ring buffer whose recent tail can be dumped on
crash (the reference's async log keeps `log_max_recent` entries for
exactly this).  Gather levels control what also reaches the python
``logging`` stream.  Levels follow the reference's 0..30 convention
(0 = always, higher = chattier).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

DEFAULT_SUBSYS_LEVEL = 5

# subsys.h's table, covering every subsystem the daemons log under
# (a name missing here would silently gate at the default level)
SUBSYSTEMS = {
    "crush": 1,
    "ec": 1,
    "osd": 5,
    "store": 5,
    "config": 5,
    "balancer": 5,
    "mon": 5,
    "mgr": 5,
    "msg": 1,
    "mds": 5,
    "rgw": 5,
    "rbd": 5,
    "client": 5,
    # the cluster-log mirror (LogClient entries echo into the local
    # dout ring so a crash dump shows what the daemon clogged)
    "clog": 5,
}


class Log:
    def __init__(self, max_recent: int = 500, gather_level: int = 5):
        self._levels = dict(SUBSYSTEMS)
        self._recent: deque = deque(maxlen=max_recent)
        self._lock = threading.Lock()
        self.gather_level = gather_level
        self._py = logging.getLogger("ceph_tpu")

    # -- levels ------------------------------------------------------------
    def set_level(self, subsys: str, level: int) -> None:
        self._levels[subsys] = level

    def get_level(self, subsys: str) -> int:
        return self._levels.get(subsys, DEFAULT_SUBSYS_LEVEL)

    def should_log(self, subsys: str, level: int) -> bool:
        return level <= self.get_level(subsys)

    # -- entry points ------------------------------------------------------
    def dout(self, subsys: str, level: int, message: str) -> None:
        """The dout(n) macro role: cheap gate, ring append, optional
        python-logging passthrough."""
        if not self.should_log(subsys, level):
            return
        entry = (time.time(), subsys, level, message)
        with self._lock:
            self._recent.append(entry)
        if level <= self.gather_level:
            self._py.log(
                logging.DEBUG if level > 0 else logging.INFO,
                "%s %d: %s",
                subsys,
                level,
                message,
            )

    def derr(self, subsys: str, message: str) -> None:
        self.dout(subsys, 0, message)

    # -- crash dump --------------------------------------------------------
    def dump_recent(self, subsys: str | None = None) -> list[dict]:
        """The SIGSEGV-handler dump of the ring buffer, optionally
        filtered to one subsystem."""
        with self._lock:
            return [
                {
                    "stamp": stamp,
                    "subsys": s,
                    "level": level,
                    "message": message,
                }
                for stamp, s, level, message in self._recent
                if subsys is None or s == subsys
            ]

    def register_admin_commands(self, admin_socket) -> None:
        admin_socket.register_command(
            "log dump",
            lambda args: self.dump_recent(args.get("subsys")),
            "dump recent log entries (optional subsys filter)",
        )

        def _set(args):
            self.set_level(args["subsys"], int(args["level"]))
            return {"success": True}

        admin_socket.register_command(
            "log set-level", _set, "set a subsystem debug level"
        )


_global = Log()


def log() -> Log:
    return _global


def dout(subsys: str, level: int, message: str) -> None:
    _global.dout(subsys, level, message)
