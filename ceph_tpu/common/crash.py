"""Crash capture (the reference's SIGSEGV/assert handler dump +
src/pybind/mgr/crash's report shape).

Daemon loops call :func:`capture` from their catch-all handlers; the
report bundles the traceback, the tail of the process dout ring
(exactly what the reference's async log dumps on crash), and daemon
metadata under a ``crash_id`` shaped like the reference's
(``<ISO stamp>_<uuid>``).

Delivery is two-path, matching how this framework deploys:

- daemons with an mgr session (the OSD) keep a local sink and
  piggyback reports on their next MMgrReport push — the wire path;
- daemons without one (mon, mds, mgr modules) append to the
  process-global pending queue, which the mgr ``crash`` module drains
  directly (co-hosted daemons share the process — documented
  deviation from the reference's ceph-crash uploader).

The mgr module dedupes by ``crash_id``, so double delivery is
harmless.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
import uuid
from collections import deque
from datetime import datetime, timezone

from .log import log as _ring_log

# schema bounds (tools/check_metrics.py lints these)
MAX_BACKTRACE_LINES = 100
MAX_BACKTRACE_LINE_LEN = 2048
DOUT_TAIL_LINES = 50

_pending: deque[dict] = deque(maxlen=64)
_pending_lock = threading.Lock()

# per-signature throttle (the reference crash module dedupes by stack
# signature): a loop that dies identically every tick must not flood
# the crash store, the clog, and RECENT_CRASH with one fresh-uuid
# report per iteration
THROTTLE_WINDOW = 60.0
_MAX_SIGNATURES = 128
_recent_sigs: dict[tuple, float] = {}
_sig_lock = threading.Lock()
suppressed_total = 0


def _throttled(entity: str, exc: BaseException) -> bool:
    """True when an identical (entity, exception) crashed within the
    window — the new occurrence is counted, not reported."""
    global suppressed_total
    sig = (entity, type(exc).__name__, str(exc)[:120])
    now = time.monotonic()
    with _sig_lock:
        last = _recent_sigs.get(sig)
        if last is not None and now - last < THROTTLE_WINDOW:
            suppressed_total += 1
            return True
        if len(_recent_sigs) >= _MAX_SIGNATURES:
            _recent_sigs.clear()  # coarse reset beats unbounded growth
        _recent_sigs[sig] = now
        return False


def reset_throttle() -> None:
    """Forget signature history (test isolation)."""
    with _sig_lock:
        _recent_sigs.clear()


def build_report(
    entity: str, exc: BaseException, extra_meta: dict | None = None
) -> dict:
    """Traceback + dout-ring tail + daemon metadata, under a
    reference-shaped crash id."""
    from ..version import FRAMEWORK_VERSION

    now = time.time()
    stamp = (
        datetime.fromtimestamp(now, tz=timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%S.%fZ")
    )
    lines = traceback.format_exception(type(exc), exc, exc.__traceback__)
    backtrace = [
        ln[:MAX_BACKTRACE_LINE_LEN]
        for chunk in lines
        for ln in chunk.rstrip("\n").split("\n")
    ][:MAX_BACKTRACE_LINES]
    meta = {
        "framework_version": FRAMEWORK_VERSION,
        "python_version": sys.version.split()[0],
        "platform": sys.platform,
    }
    if extra_meta:
        meta.update(extra_meta)
    return {
        "crash_id": f"{stamp}_{uuid.uuid4()}",
        "entity_name": entity,
        "timestamp": now,
        "timestamp_iso": stamp,
        "exception": f"{type(exc).__name__}: {exc}",
        "backtrace": backtrace,
        "dout_tail": _ring_log().dump_recent()[-DOUT_TAIL_LINES:],
        "meta": meta,
    }


def build_process_report(
    entity: str,
    returncode: int,
    log_tail: list[str] | None = None,
    extra_meta: dict | None = None,
) -> dict:
    """A crash report for a REAL process death (the supervisor's
    ceph-crash role): same schema as :func:`build_report`, but the
    "exception" is the wait status (signal name for a killed child,
    exit code otherwise) and the backtrace is the tail of the child's
    captured log — the closest thing to a stack an external observer
    has."""
    import signal as _signal

    from ..version import FRAMEWORK_VERSION

    now = time.time()
    stamp = (
        datetime.fromtimestamp(now, tz=timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%S.%fZ")
    )
    if returncode < 0:
        try:
            signame = _signal.Signals(-returncode).name
        except ValueError:
            signame = f"signal {-returncode}"
        exception = f"ProcessDeath: killed by {signame}"
    else:
        exception = f"ProcessDeath: exited with status {returncode}"
    backtrace = [
        ln[:MAX_BACKTRACE_LINE_LEN] for ln in (log_tail or [])
    ][-MAX_BACKTRACE_LINES:]
    meta = {
        "framework_version": FRAMEWORK_VERSION,
        "python_version": sys.version.split()[0],
        "platform": sys.platform,
        "process_death": True,
        "returncode": returncode,
    }
    if extra_meta:
        meta.update(extra_meta)
    return {
        "crash_id": f"{stamp}_{uuid.uuid4()}",
        "entity_name": entity,
        "timestamp": now,
        "timestamp_iso": stamp,
        "exception": exception,
        "backtrace": backtrace,
        "dout_tail": [],
        "meta": meta,
    }


def capture(
    entity: str,
    exc: BaseException,
    sink=None,
    clog=None,
    extra_meta: dict | None = None,
) -> dict | None:
    """Build a report and queue it for the mgr crash module.

    ``sink`` is the daemon's local pending deque (wire delivery via
    MMgrReport); without one the report joins the process-global
    queue.  ``clog`` (a LogChannel) additionally announces the crash
    on the cluster log — the health timeline entry.

    Identical (entity, exception) faults within ``THROTTLE_WINDOW``
    return None without filing a report (counted in
    ``suppressed_total``)."""
    if _throttled(entity, exc):
        return None
    # derr the fault FIRST (the reference's handler does too), so the
    # ring tail in the report always carries at least the crash line
    subsys = entity.split(".", 1)[0]
    _ring_log().derr(
        subsys, f"{entity} crashed: {type(exc).__name__}: {exc}"
    )
    report = build_report(entity, exc, extra_meta=extra_meta)
    if sink is not None:
        sink.append(report)
    else:
        with _pending_lock:
            _pending.append(report)
    if clog is not None:
        try:
            clog.error(
                f"daemon {entity} crashed: {report['exception']} "
                f"(crash id {report['crash_id']})"
            )
        except Exception:  # noqa: BLE001 — capture must never raise
            pass
    return report


def drain_pending() -> list[dict]:
    """Take the process-global queue (the mgr crash module's direct
    ingest path)."""
    with _pending_lock:
        out = list(_pending)
        _pending.clear()
        return out
