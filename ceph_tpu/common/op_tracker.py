"""Op tracking — in-flight op registry + historic ring buffer
(src/common/TrackedOp.cc; dumped as dump_ops_in_flight /
dump_historic_ops over the admin socket).

A TrackedOp accumulates per-stage timestamped events ("queued",
"reached_pg", "commit_sent", ...); on completion it moves into a
bounded history keyed for the slowest-ops view.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque


class TrackedOp:
    def __init__(
        self, tracker: "OpTracker", description: str, trace: str = ""
    ):
        self._tracker = tracker
        self.seq = next(tracker._seq)
        self.description = description
        # the span/trace id (blkin/ZTracer role): the client's reqid,
        # carried by every sub-op, so dump_historic_ops on DIFFERENT
        # daemons correlates one logical op end-to-end
        self.trace = trace
        self.initiated_at = time.time()
        self.events: list[tuple[float, str]] = []
        self._done = False

    def mark_event(self, event: str) -> None:
        self.events.append((time.time(), event))

    def finish(self) -> None:
        if not self._done:
            self._done = True
            self.mark_event("done")
            self._tracker._complete(self)

    def __enter__(self):
        self.mark_event("start")
        return self

    def __exit__(self, exc_type, *exc):
        self.mark_event("exception" if exc_type else "finish")
        self.finish()
        return False

    @property
    def duration(self) -> float:
        end = self.events[-1][0] if self._done else time.time()
        return end - self.initiated_at

    def dump(self) -> dict:
        return {
            "seq": self.seq,
            "description": self.description,
            "trace": self.trace,
            "initiated_at": self.initiated_at,
            "duration": self.duration,
            "type_data": {
                "events": [
                    {"time": t, "event": e} for t, e in self.events
                ]
            },
        }


def _slowest_stage(op: TrackedOp) -> dict:
    """The single longest inter-event gap — the stage that made a
    slow op slow (the ``dump_historic_slow_ops`` view only states the
    total; the gap names the culprit).  The op's initiation counts as
    the zeroth event, so a long queue wait before the first mark is
    attributed too."""
    prev_t, prev_e = op.initiated_at, "initiated"
    best = {"event": prev_e, "gap": 0.0}
    for t, e in op.events:
        gap = t - prev_t
        if gap > best["gap"]:
            # the gap ENDS at this event: it is the wait between
            # prev_e and e, reported as "prev_e -> e"
            best = {"event": f"{prev_e} -> {e}", "gap": gap}
        prev_t, prev_e = t, e
    return best


class OpTracker:
    """history_size/history_duration mirror
    osd_op_history_size/duration's roles."""

    def __init__(self, history_size: int = 20, history_duration: float = 600.0):
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._inflight: dict[int, TrackedOp] = {}
        self._history: deque[TrackedOp] = deque()
        self.history_size = history_size
        self.history_duration = history_duration

    def create_op(self, description: str, trace: str = "") -> TrackedOp:
        op = TrackedOp(self, description, trace)
        with self._lock:
            self._inflight[op.seq] = op
        return op

    def _complete(self, op: TrackedOp) -> None:
        with self._lock:
            self._inflight.pop(op.seq, None)
            self._history.append(op)
            now = time.time()
            while len(self._history) > self.history_size or (
                self._history
                and now - self._history[0].initiated_at
                > self.history_duration
            ):
                self._history.popleft()

    # -- admin socket views ------------------------------------------------
    def dump_ops_in_flight(self) -> dict:
        with self._lock:
            ops = [op.dump() for op in self._inflight.values()]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_ops(self) -> dict:
        with self._lock:
            ops = [op.dump() for op in self._history]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_slow_ops(self, threshold: float = 0.0) -> dict:
        with self._lock:
            ops = sorted(
                (op for op in self._history if op.duration >= threshold),
                key=lambda o: o.duration,
                reverse=True,
            )
            dumps = []
            for op in ops:
                d = op.dump()
                d["slowest_stage"] = _slowest_stage(op)
                dumps.append(d)
            return {"num_ops": len(dumps), "ops": dumps}

    # -- SLOW_OPS watchdog views (OSD::check_ops_in_flight role) -----------
    def slow_ops(self, threshold: float) -> list[TrackedOp]:
        """In-flight ops older than ``threshold`` seconds — the
        osd_op_complaint_time check the health watchdog polls."""
        now = time.time()
        with self._lock:
            return [
                op
                for op in self._inflight.values()
                if now - op.initiated_at >= threshold
            ]

    def slow_op_summary(self, threshold: float) -> dict:
        """(count, oldest age) for the mon health report."""
        slow = self.slow_ops(threshold)
        now = time.time()
        oldest = max(
            (now - op.initiated_at for op in slow), default=0.0
        )
        return {"num_slow_ops": len(slow), "oldest_age": oldest}

    def register_admin_commands(self, admin_socket) -> None:
        admin_socket.register_command(
            "dump_ops_in_flight",
            lambda args: self.dump_ops_in_flight(),
            "show in-flight ops",
        )
        admin_socket.register_command(
            "dump_historic_ops",
            lambda args: self.dump_historic_ops(),
            "show recent completed ops",
        )
        admin_socket.register_command(
            "dump_historic_slow_ops",
            lambda args: self.dump_historic_slow_ops(
                float(args.get("threshold", 0.0))
            ),
            "show recent ops sorted by duration",
        )
