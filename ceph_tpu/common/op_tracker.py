"""Op tracking — in-flight op registry + historic ring buffer
(src/common/TrackedOp.cc; dumped as dump_ops_in_flight /
dump_historic_ops over the admin socket).

A TrackedOp accumulates per-stage timestamped events ("queued",
"reached_pg", "commit_sent", ...); on completion it moves into a
bounded history keyed for the slowest-ops view.
"""

from __future__ import annotations

import itertools
import re
import threading
import time
from collections import deque

from .histogram import LogHistogram

# qos classes / op types become perf-dump keys and prometheus label
# values: anything outside this alphabet collapses to "other" at the
# recording site, so one hostile/garbled class string cannot poison
# the exporter (the label-safety rule check_metrics lints)
_CLASS_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9_]{0,31}$")
# stage labels ("prev__cur" event pairs) run longer than class names
_STAGE_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9_]{0,79}$")
# distinct per-stage labels are client-influenced (event names embed
# peer osd ids): bound the map so the tracker cannot grow unbounded
MAX_STAGE_HISTOGRAMS = 64


def sanitize_class(name: str, default: str = "other") -> str:
    return name if _CLASS_RE.match(name or "") else default


class TrackedOp:
    def __init__(
        self,
        tracker: "OpTracker",
        description: str,
        trace: str = "",
        op_type: str = "",
        qos_class: str = "",
    ):
        self._tracker = tracker
        self.seq = next(tracker._seq)
        self.description = description
        # the span/trace id (blkin/ZTracer role): the client's reqid,
        # carried by every sub-op, so dump_historic_ops on DIFFERENT
        # daemons correlates one logical op end-to-end
        self.trace = trace
        # the latency-histogram keys: what kind of op, and which QoS
        # class the scheduler served it under
        self.op_type = sanitize_class(op_type)
        self.qos_class = sanitize_class(qos_class, default="client")
        self.initiated_at = time.time()
        self.events: list[tuple[float, str]] = []
        self._done = False

    def mark_event(self, event: str) -> None:
        self.events.append((time.time(), event))

    def finish(self) -> None:
        if not self._done:
            self._done = True
            self.mark_event("done")
            self._tracker._complete(self)

    def __enter__(self):
        self.mark_event("start")
        return self

    def __exit__(self, exc_type, *exc):
        self.mark_event("exception" if exc_type else "finish")
        self.finish()
        return False

    @property
    def duration(self) -> float:
        end = self.events[-1][0] if self._done else time.time()
        return end - self.initiated_at

    def dump(self) -> dict:
        return {
            "seq": self.seq,
            "description": self.description,
            "trace": self.trace,
            "op_type": self.op_type,
            "qos_class": self.qos_class,
            "initiated_at": self.initiated_at,
            "duration": self.duration,
            "type_data": {
                "events": [
                    {"time": t, "event": e} for t, e in self.events
                ]
            },
        }


def _slowest_stage(op: TrackedOp) -> dict:
    """The single longest inter-event gap — the stage that made a
    slow op slow (the ``dump_historic_slow_ops`` view only states the
    total; the gap names the culprit).  The op's initiation counts as
    the zeroth event, so a long queue wait before the first mark is
    attributed too."""
    prev_t, prev_e = op.initiated_at, "initiated"
    best = {"event": prev_e, "gap": 0.0}
    for t, e in op.events:
        gap = t - prev_t
        if gap > best["gap"]:
            # the gap ENDS at this event: it is the wait between
            # prev_e and e, reported as "prev_e -> e"
            best = {"event": f"{prev_e} -> {e}", "gap": gap}
        prev_t, prev_e = t, e
    return best


class OpTracker:
    """history_size/history_duration mirror
    osd_op_history_size/duration's roles."""

    def __init__(self, history_size: int = 20, history_duration: float = 600.0):
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._inflight: dict[int, TrackedOp] = {}
        self._history: deque[TrackedOp] = deque()
        self.history_size = history_size
        self.history_duration = history_duration
        # latency distributions (the PerfHistogram seat): completion
        # latency per (qos_class, op_type), plus the gap between
        # adjacent stage events per stage label — cumulative, so the
        # mgr windows them by snapshot subtraction
        self._hist: dict[tuple[str, str], LogHistogram] = {}
        self._stage_hist: dict[str, LogHistogram] = {}

    def create_op(
        self,
        description: str,
        trace: str = "",
        op_type: str = "",
        qos_class: str = "",
    ) -> TrackedOp:
        op = TrackedOp(self, description, trace, op_type, qos_class)
        with self._lock:
            self._inflight[op.seq] = op
        return op

    def _complete(self, op: TrackedOp) -> None:
        with self._lock:
            self._inflight.pop(op.seq, None)
            self._history.append(op)
            now = time.time()
            while len(self._history) > self.history_size or (
                self._history
                and now - self._history[0].initiated_at
                > self.history_duration
            ):
                self._history.popleft()
            key = (op.qos_class, op.op_type)
            hist = self._hist.get(key)
            if hist is None:
                hist = self._hist[key] = LogHistogram()
        # histogram adds take the histogram's own lock, not the
        # tracker's — completion must stay cheap under contention
        end = op.events[-1][0] if op.events else now
        hist.add(max(0.0, end - op.initiated_at))
        self._record_stage_gaps(op)

    def _record_stage_gaps(self, op: TrackedOp) -> None:
        """Per-stage latency: the gap between each adjacent event
        pair, recorded under "prev->cur" (the slowest_stage labels,
        as distributions instead of one winner per op).  ONE tracker
        lock acquisition resolves every label; the adds run after,
        under the histograms' own locks — completion stays cheap."""
        gaps: list[tuple[str, float]] = []
        prev_t, prev_e = op.initiated_at, "initiated"
        for t, e in op.events:
            raw = f"{prev_e}__{e}".replace(" ", "_").replace(".", "_")
            label = raw if _STAGE_RE.match(raw) else "other"
            gaps.append((label, max(0.0, t - prev_t)))
            prev_t, prev_e = t, e
        pending: list[tuple[LogHistogram, float]] = []
        with self._lock:
            for label, gap in gaps:
                hist = self._stage_hist.get(label)
                if hist is None:
                    if len(self._stage_hist) >= MAX_STAGE_HISTOGRAMS:
                        hist = self._stage_hist.setdefault(
                            "other", LogHistogram()
                        )
                    else:
                        hist = self._stage_hist[label] = LogHistogram()
                pending.append((hist, gap))
        for hist, gap in pending:
            hist.add(gap)

    # -- histogram views ---------------------------------------------------
    def dump_histograms(self) -> dict:
        """The `perf histogram dump` op block: completion latency per
        (qos_class, op_type) and per-stage gap distributions."""
        with self._lock:
            hists = dict(self._hist)
            stages = dict(self._stage_hist)
        return {
            "ops": {
                f"{qos}.{typ}": h.snapshot()
                for (qos, typ), h in sorted(hists.items())
            },
            "stages": {
                label: h.snapshot()
                for label, h in sorted(stages.items())
            },
        }

    def histogram_perf_entries(self) -> dict:
        """Flat entries for the MMgrReport perf dump: one
        ``op_hist.<qos_class>.<op_type>`` snapshot per pair — the mgr
        slo module merges these cluster-wide, the exporter renders
        them as native histogram families.  Stage-gap histograms stay
        local (admin/tell surface): their labels are unbounded-ish
        and per-daemon is where they are diagnostic."""
        with self._lock:
            hists = dict(self._hist)
        return {
            f"op_hist.{qos}.{typ}": h.snapshot()
            for (qos, typ), h in hists.items()
        }

    # -- admin socket views ------------------------------------------------
    def dump_ops_in_flight(self) -> dict:
        with self._lock:
            ops = [op.dump() for op in self._inflight.values()]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_ops(self) -> dict:
        with self._lock:
            ops = [op.dump() for op in self._history]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_slow_ops(
        self, threshold: float = 0.0, qos_class: str = ""
    ) -> dict:
        """``qos_class`` filters to one class (PR 1 left class
        invisible here; the span/tracker plumbing now carries it)."""
        with self._lock:
            ops = sorted(
                (
                    op
                    for op in self._history
                    if op.duration >= threshold
                    and (not qos_class or op.qos_class == qos_class)
                ),
                key=lambda o: o.duration,
                reverse=True,
            )
            dumps = []
            for op in ops:
                d = op.dump()
                d["slowest_stage"] = _slowest_stage(op)
                dumps.append(d)
            return {"num_ops": len(dumps), "ops": dumps}

    # -- SLOW_OPS watchdog views (OSD::check_ops_in_flight role) -----------
    def slow_ops(self, threshold: float) -> list[TrackedOp]:
        """In-flight ops older than ``threshold`` seconds — the
        osd_op_complaint_time check the health watchdog polls."""
        now = time.time()
        with self._lock:
            return [
                op
                for op in self._inflight.values()
                if now - op.initiated_at >= threshold
            ]

    def slow_op_summary(self, threshold: float) -> dict:
        """(count, oldest age) for the mon health report."""
        slow = self.slow_ops(threshold)
        now = time.time()
        oldest = max(
            (now - op.initiated_at for op in slow), default=0.0
        )
        return {"num_slow_ops": len(slow), "oldest_age": oldest}

    def register_admin_commands(
        self, admin_socket, extra_histograms=None
    ) -> None:
        """``extra_histograms`` (zero-arg callable → dict) lets the
        owning daemon merge its own grids (the OSD's 2D commit
        histogram) into the admin-socket `perf histogram dump`, so
        the socket serves the same view as the tell surface."""
        admin_socket.register_command(
            "dump_ops_in_flight",
            lambda args: self.dump_ops_in_flight(),
            "show in-flight ops",
        )
        admin_socket.register_command(
            "dump_historic_ops",
            lambda args: self.dump_historic_ops(),
            "show recent completed ops",
        )
        admin_socket.register_command(
            "dump_historic_slow_ops",
            lambda args: self.dump_historic_slow_ops(
                float(args.get("threshold", 0.0)),
                str(args.get("qos_class", "")),
            ),
            "show recent ops sorted by duration "
            "(optional args: threshold, qos_class)",
        )
        def _hist_dump(args):
            out = self.dump_histograms()
            if extra_histograms is not None:
                out.update(extra_histograms())
            return out

        admin_socket.register_command(
            "perf histogram dump",
            _hist_dump,
            "per-(qos, op-type) latency + per-stage gap histograms"
            " (+ the daemon's own grids)",
        )
