"""Throttle — bounded-resource admission control
(src/common/Throttle.cc:1-876 reduced to the load-bearing contract).

The reference gates memory/in-flight-op budgets with a counted
throttle whose waiters wake FIFO (no barging: a large request parked
at the head must not starve behind a stream of small ones).  Same
semantics here: ``get`` blocks in arrival order, ``get_or_fail``
never blocks, ``put`` returns budget and wakes the head waiter(s).
"""

from __future__ import annotations

import collections
import threading
from . import lockdep


class Throttle:
    """Counted budget with FIFO waiters."""

    def __init__(self, name: str, max_: int):
        self.name = name
        self._max = max_
        self._count = 0
        self._lock = lockdep.Mutex(f"throttle.{name}")
        # FIFO of (amount, Event) — head wakes first (Throttle.cc's
        # ordered cond list)
        self._waiters: collections.deque = collections.deque()

    @property
    def max(self) -> int:
        return self._max

    @property
    def current(self) -> int:
        return self._count

    def past_midpoint(self) -> bool:
        return self._count >= self._max / 2

    def set_max(self, m: int) -> None:
        with self._lock:
            self._max = m
            self._wake_locked()

    def _fits_locked(self, c: int) -> bool:
        # a request larger than max is admitted alone (the reference
        # lets oversized requests through when the throttle is empty,
        # rather than deadlocking them forever)
        if c >= self._max:
            return self._count == 0
        return self._count + c <= self._max

    def _wake_locked(self) -> None:
        while self._waiters:
            amount, ev = self._waiters[0]
            if not self._fits_locked(amount):
                break
            self._count += amount
            self._waiters.popleft()
            ev.set()

    def get(self, c: int = 1, timeout: float | None = None) -> bool:
        """Take ``c`` units, blocking FIFO; False on timeout (the
        budget is NOT taken then)."""
        with self._lock:
            if not self._waiters and self._fits_locked(c):
                self._count += c
                return True
            ev = threading.Event()
            entry = (c, ev)
            self._waiters.append(entry)
        if ev.wait(timeout):
            return True
        with self._lock:
            if ev.is_set():
                return True  # won the race with the timeout
            self._waiters.remove(entry)
            self._wake_locked()  # our slot may unblock smaller heads
            return False

    def get_or_fail(self, c: int = 1) -> bool:
        with self._lock:
            if self._waiters or not self._fits_locked(c):
                return False
            self._count += c
            return True

    def put(self, c: int = 1) -> int:
        with self._lock:
            self._count = max(0, self._count - c)
            self._wake_locked()
            return self._count
