"""Latency histograms — fixed-bucket log2 distributions and the
reference-shaped 2D latency×size grid (src/common/perf_histogram.h +
the HdrHistogram idea reduced to its storage-useful core).

PRs 1–2 gave every latency an avgcount+sum pair, which answers "what
is the mean" and nothing else; tail latency — the metric the paper's
TPU-offload story is judged on — needs distributions.  Two shapes:

- ``LogHistogram`` — one-dimensional latency distribution over
  log2-spaced buckets: bucket *i* covers
  ``(min_value·2^(i-1), min_value·2^i]``.  ``add`` is an integer
  log2 (``frexp``) plus one += under a lock — cheap enough for every
  op completion.  Histograms MERGE exactly (same bucket layout ⇒
  elementwise add), which is what lets the mgr aggregate per-daemon
  snapshots cluster-wide, and SUBTRACT (cumulative counters ⇒ a
  sliding window is snapshot(now) − snapshot(then)).  Percentiles
  interpolate linearly inside the winning bucket — bounded relative
  error of one bucket ratio (×2 by default), exactly HdrHistogram's
  contract.
- ``PerfHistogram2D`` — the reference's ``PerfHistogramCommon`` 2D
  grid (axis conventions from src/common/perf_histogram.h): by
  default latency × request size, each axis log2-scaled, dumped in
  the ``perf histogram dump`` shape (axes config + row-major counts)
  the `ceph tell osd.N perf histogram dump` surface serves.

Snapshots are plain dicts (JSON- and MMgrReport-safe) and have a
dencoder-stable binary encoding (``encode``/``decode``) pinned in the
corpus, so the wire/artifact shape cannot drift silently.
"""

from __future__ import annotations

import math
import threading

from .encoding import Decoder, Encoder

# the default latency axis: 10 µs lower bound, 28 log2 buckets →
# covers ~10 µs .. ~22 min with ≤2x relative error per bucket
LATENCY_MIN_S = 1e-5
LATENCY_BUCKETS = 28

# the default size axis: 512 B lower bound, 16 buckets → 512 B .. 16 MB
SIZE_MIN_B = 512.0
SIZE_BUCKETS = 16


def log2_bounds(min_value: float, buckets: int) -> tuple[float, ...]:
    """Upper bounds of every bucket except the +Inf overflow:
    ``min_value · 2^i`` for i in [0, buckets)."""
    return tuple(min_value * (2.0**i) for i in range(buckets))


def bucket_index(value: float, min_value: float, buckets: int) -> int:
    """value → bucket, 0..buckets (the last index is the overflow
    bucket).  Bucket i covers (min·2^(i-1), min·2^i]."""
    if value <= min_value:
        return 0
    # frexp is an exponent read, not a log: value = m·2^e, m ∈ [0.5,1);
    # an exact power of two (m == 0.5) belongs to the bucket it CLOSES
    # — (2^(e-2), 2^(e-1)] — because buckets are upper-inclusive
    m, e = math.frexp(value / min_value)
    idx = e - 1 if m == 0.5 else e
    return min(idx, buckets)


def percentile_from_counts(
    bounds, counts, sum_, p: float
) -> float:
    """The p-th percentile (0..100) from bucket counts, linearly
    interpolated inside the winning bucket.  The overflow bucket
    (beyond the last bound) has no upper edge: report the larger of
    the last bound and the overall mean — bounded below by the data,
    never inventing precision the layout cannot support."""
    total = sum(counts)
    if total == 0:
        return 0.0
    rank = max(0.0, min(100.0, p)) / 100.0 * total
    acc = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if acc + c >= rank:
            if i >= len(bounds):  # overflow bucket
                mean = sum_ / total if total else 0.0
                return max(bounds[-1] if bounds else 0.0, mean)
            lo = bounds[i - 1] if i > 0 else 0.0
            frac = (rank - acc) / c
            return lo + frac * (bounds[i] - lo)
        acc += c
    return bounds[-1] if bounds else 0.0


class LogHistogram:
    """Mergeable fixed-layout log2 histogram (cumulative counter
    semantics: counts only ever grow; windows are snapshot deltas)."""

    __slots__ = ("min_value", "buckets", "bounds", "counts", "sum",
                 "count", "_lock")

    def __init__(
        self,
        min_value: float = LATENCY_MIN_S,
        buckets: int = LATENCY_BUCKETS,
    ):
        assert min_value > 0 and buckets >= 1
        self.min_value = float(min_value)
        self.buckets = int(buckets)
        self.bounds = log2_bounds(self.min_value, self.buckets)
        # buckets+1 slots: the last is the +Inf overflow
        self.counts = [0] * (self.buckets + 1)
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    # -- hot path ----------------------------------------------------------
    def add(self, value: float) -> None:
        idx = bucket_index(value, self.min_value, self.buckets)
        with self._lock:
            self.counts[idx] += 1
            self.sum += value
            self.count += 1

    # -- aggregation -------------------------------------------------------
    def merge(self, other) -> None:
        """Elementwise add of another LogHistogram or snapshot dict
        with the SAME layout (mismatched layouts raise — silently
        rebinning would corrupt percentiles)."""
        snap = other.snapshot() if isinstance(other, LogHistogram) else other
        if (
            float(snap.get("min_value", -1)) != self.min_value
            or len(snap.get("counts", ())) != len(self.counts)
        ):
            raise ValueError(
                "histogram layout mismatch: "
                f"{snap.get('min_value')}x{len(snap.get('counts', ()))}"
                f" vs {self.min_value}x{len(self.counts)}"
            )
        with self._lock:
            for i, c in enumerate(snap["counts"]):
                self.counts[i] += int(c)
            self.sum += float(snap.get("sum", 0.0))
            self.count += int(snap.get("count", 0))

    def snapshot(self) -> dict:
        """Plain-dict snapshot (the MMgrReport / artifact shape)."""
        with self._lock:
            return {
                "min_value": self.min_value,
                "bounds": list(self.bounds),
                "counts": list(self.counts),
                "sum": self.sum,
                "count": self.count,
            }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "LogHistogram":
        h = cls(
            min_value=float(snap["min_value"]),
            buckets=len(snap["counts"]) - 1,
        )
        h.counts = [int(c) for c in snap["counts"]]
        h.sum = float(snap.get("sum", 0.0))
        h.count = int(snap.get("count", 0))
        return h

    def percentile(self, p: float) -> float:
        with self._lock:
            counts = list(self.counts)
            s = self.sum
        return percentile_from_counts(self.bounds, counts, s, p)

    # -- dencoder-stable binary form ---------------------------------------
    def encode(self) -> bytes:
        snap = self.snapshot()
        e = Encoder()
        e.u8(1)  # struct version
        e.f64(snap["min_value"]).u32(len(snap["counts"]))
        for c in snap["counts"]:
            e.u64(c)
        e.f64(snap["sum"]).u64(snap["count"])
        return e.getvalue()

    @classmethod
    def decode(cls, blob: bytes) -> "LogHistogram":
        d = Decoder(blob)
        v = d.u8()
        if v != 1:
            raise ValueError(f"unknown histogram version {v}")
        min_value = d.f64()
        n = d.u32()
        counts = [d.u64() for _ in range(n)]
        s = d.f64()
        count = d.u64()
        return cls.from_snapshot(
            {
                "min_value": min_value,
                "counts": counts,
                "sum": s,
                "count": count,
            }
        )


def is_histogram_snapshot(value) -> bool:
    """Duck-check for a histogram shape riding a flat perf dump —
    either a LogHistogram snapshot (``counts``) or a PerfCounters
    histogram dump (``buckets``); the exporter and the mgr slo
    module both key on this."""
    return (
        isinstance(value, dict)
        and "bounds" in value
        and ("counts" in value or "buckets" in value)
    )


def snapshot_counts(snap: dict) -> list[int]:
    """Per-bucket counts from either snapshot shape."""
    return [
        int(c) for c in (snap.get("counts") or snap.get("buckets") or [])
    ]


def cumulative_buckets(snap: dict) -> list[tuple[str, int]]:
    """Prometheus-native cumulative buckets: [(le_label, cum_count)],
    ending with the mandatory ("+Inf", total)."""
    out: list[tuple[str, int]] = []
    acc = 0
    bounds = snap.get("bounds", [])
    counts = snapshot_counts(snap)
    for i, bound in enumerate(bounds):
        acc += int(counts[i]) if i < len(counts) else 0
        out.append((repr(float(bound)), acc))
    total = sum(int(c) for c in counts)
    out.append(("+Inf", total))
    return out


class PerfHistogram2D:
    """The reference's 2D grid (PerfHistogramCommon): two log2 axes —
    by default latency (x) × size (y) — and a row-major count grid.
    ``dump()`` matches the `perf histogram dump` shape: axes config
    first, then values."""

    def __init__(
        self,
        name: str = "op_w_latency_in_bytes_histogram",
        x_min: float = LATENCY_MIN_S,
        x_buckets: int = LATENCY_BUCKETS,
        y_min: float = SIZE_MIN_B,
        y_buckets: int = SIZE_BUCKETS,
        x_name: str = "latency_s",
        y_name: str = "request_size_bytes",
    ):
        self.name = name
        self.x_min, self.x_buckets = float(x_min), int(x_buckets)
        self.y_min, self.y_buckets = float(y_min), int(y_buckets)
        self.x_name, self.y_name = x_name, y_name
        self._grid = [
            [0] * (self.x_buckets + 1) for _ in range(self.y_buckets + 1)
        ]
        self.count = 0
        self._lock = threading.Lock()

    def add(self, x_value: float, y_value: float) -> None:
        xi = bucket_index(x_value, self.x_min, self.x_buckets)
        yi = bucket_index(y_value, self.y_min, self.y_buckets)
        with self._lock:
            self._grid[yi][xi] += 1
            self.count += 1

    def merge(self, other) -> None:
        snap = (
            other.dump() if isinstance(other, PerfHistogram2D) else other
        )
        values = snap.get("values", [])
        if len(values) != len(self._grid) or (
            values and len(values[0]) != len(self._grid[0])
        ):
            raise ValueError("2D histogram layout mismatch")
        with self._lock:
            for yi, row in enumerate(values):
                for xi, c in enumerate(row):
                    self._grid[yi][xi] += int(c)
            self.count += int(snap.get("count", 0))

    def dump(self) -> dict:
        with self._lock:
            values = [list(row) for row in self._grid]
            count = self.count
        return {
            "name": self.name,
            "axes": [
                {
                    "name": self.x_name,
                    "min": self.x_min,
                    "buckets": self.x_buckets + 1,
                    "scale_type": "log2",
                },
                {
                    "name": self.y_name,
                    "min": self.y_min,
                    "buckets": self.y_buckets + 1,
                    "scale_type": "log2",
                },
            ],
            "count": count,
            "values": values,
        }

    # -- dencoder-stable binary form ---------------------------------------
    def encode(self) -> bytes:
        snap = self.dump()
        e = Encoder()
        e.u8(1)
        e.string(snap["name"])
        e.f64(self.x_min).u32(self.x_buckets)
        e.f64(self.y_min).u32(self.y_buckets)
        e.string(self.x_name).string(self.y_name)
        e.u64(snap["count"])
        e.u32(len(snap["values"]))
        for row in snap["values"]:
            e.list(row, lambda e2, c: e2.u64(c))
        return e.getvalue()

    @classmethod
    def decode(cls, blob: bytes) -> "PerfHistogram2D":
        d = Decoder(blob)
        v = d.u8()
        if v != 1:
            raise ValueError(f"unknown 2D histogram version {v}")
        name = d.string()
        x_min, x_buckets = d.f64(), d.u32()
        y_min, y_buckets = d.f64(), d.u32()
        x_name, y_name = d.string(), d.string()
        count = d.u64()
        nrows = d.u32()
        grid = cls(
            name=name, x_min=x_min, x_buckets=x_buckets,
            y_min=y_min, y_buckets=y_buckets,
            x_name=x_name, y_name=y_name,
        )
        values = [
            d.list(lambda d2: d2.u64()) for _ in range(nrows)
        ]
        if len(values) != y_buckets + 1 or any(
            len(r) != x_buckets + 1 for r in values
        ):
            raise ValueError("2D histogram grid shape mismatch")
        grid._grid = [[int(c) for c in row] for row in values]
        grid.count = count
        return grid
