"""Distributed tracing — spans with parent ids across daemons (the
blkin/ZTracer seat, src/common/zipkin_trace.h + blkin's span model).

The repo already carries trace ids on every sub-op message
(msg/message.py MOSDRepOp.trace / MECSubWrite.trace, stamped with the
client reqid) but nothing ever collected them: dump_historic_ops on
two daemons could be joined by hand and that was the whole story.
This module is the missing collection plane:

- ``Span`` — one timed stage on one daemon: (trace_id, span_id,
  parent_id, daemon, name, start/end, tags, events).  The trace id is
  the client reqid, exactly the id the wire already carries.
- ``Tracer`` — per-daemon span factory + bounded buffer of finished
  spans.  ``dump_traces`` serves the buffer over the admin socket
  (the `dump_historic_ops`-shaped local view); ``drain`` hands
  batches to the MMgrReport push so the mgr ``tracing`` module can
  assemble one logical op's spans from DIFFERENT daemons into a
  single tree.
- ambient context — a thread-local (tracer, span) stack so deep
  layers (stores, codecs) open child spans without threading a
  tracer parameter through every signature, the same trick
  store/remote.py's ``trace_context`` plays for sub-op trace ids.

Span buffers are bounded (drop-oldest) — tracing must never be the
thing that OOMs a daemon.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from collections import deque

# role ranks used by the mgr's cross-daemon tree assembly: a span
# with no resolvable parent attaches under the nearest earlier span
# of a lower rank (client root <- primary op <- replica/shard subop)
ROLE_CLIENT = "client"
ROLE_PRIMARY = "primary"
ROLE_REPLICA = "replica"
ROLE_SHARD = "shard"
ROLE_RANK = {ROLE_CLIENT: 0, ROLE_PRIMARY: 1, ROLE_REPLICA: 2, ROLE_SHARD: 2}

_ambient = threading.local()  # .stack: list[(Tracer, Span)]


def _new_id() -> str:
    return os.urandom(6).hex()


class Span:
    """One timed stage; finished spans become plain dicts in the
    tracer's buffer (the wire/admin-socket shape)."""

    __slots__ = (
        "_tracer", "trace_id", "span_id", "parent_id", "daemon",
        "name", "role", "start", "end", "tags", "events", "_done",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        trace_id: str,
        parent_id: str = "",
        role: str = "",
        tags: dict | None = None,
    ):
        self._tracer = tracer
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.daemon = tracer.daemon
        self.name = name
        self.role = role
        self.start = time.time()
        self.end = 0.0
        self.tags = dict(tags or {})
        self.events: list[tuple[float, str]] = []
        self._done = False

    def mark_event(self, event: str) -> None:
        self.events.append((time.time(), event))

    def set_tag(self, key: str, value) -> None:
        self.tags[key] = value

    def finish(self) -> None:
        if self._done:
            return
        self._done = True
        self.end = time.time()
        self._tracer._complete(self)

    def __enter__(self) -> "Span":
        _push(self._tracer, self)
        return self

    def __exit__(self, exc_type, *exc) -> bool:
        if exc_type is not None:
            self.mark_event(f"exception: {exc_type.__name__}")
        _pop(self)
        self.finish()
        return False

    def dump(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "daemon": self.daemon,
            "name": self.name,
            "role": self.role,
            "start": self.start,
            "end": self.end or time.time(),
            "duration": (self.end or time.time()) - self.start,
            "tags": dict(self.tags),
            "events": [
                {"time": t, "event": e} for t, e in self.events
            ],
        }


class _NullSpan:
    """No ambient tracer: ``span()`` still returns a context manager
    so instrumented code needs no conditionals."""

    __slots__ = ()

    def mark_event(self, event: str) -> None:
        pass

    def set_tag(self, key: str, value) -> None:
        pass

    def finish(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class Tracer:
    """Per-daemon span factory + bounded finished-span buffer."""

    def __init__(self, daemon: str, max_spans: int = 2048):
        self.daemon = daemon
        self._lock = threading.Lock()
        self._buffer: deque[dict] = deque(maxlen=max_spans)
        self._seq = itertools.count()
        self.spans_started = 0
        self.spans_dropped = 0  # buffer overwrites (drop-oldest)

    def start_span(
        self,
        name: str,
        trace_id: str = "",
        parent_id: str = "",
        role: str = "",
        tags: dict | None = None,
    ) -> Span:
        """New span; with no explicit trace/parent it continues the
        ambient span's trace (child) or starts a fresh trace (root)."""
        amb = current_span()
        if not trace_id:
            if isinstance(amb, Span):
                trace_id = amb.trace_id
            else:
                trace_id = ambient_trace_id() or _new_id()
        if not parent_id and isinstance(amb, Span) and (
            amb.trace_id == trace_id
        ):
            parent_id = amb.span_id
        with self._lock:
            self.spans_started += 1
        return Span(self, name, trace_id, parent_id, role, tags)

    def _complete(self, span: Span) -> None:
        with self._lock:
            if len(self._buffer) == self._buffer.maxlen:
                self.spans_dropped += 1
            self._buffer.append(span.dump())

    # -- consumers ---------------------------------------------------------
    def drain(self, limit: int = 512) -> list[dict]:
        """Pop up to ``limit`` finished spans for an MMgrReport batch."""
        out: list[dict] = []
        with self._lock:
            while self._buffer and len(out) < limit:
                out.append(self._buffer.popleft())
        return out

    def dump_traces(self, trace_id: str = "") -> dict:
        """Admin-socket view of the (undrained) local buffer."""
        with self._lock:
            spans = [
                s for s in self._buffer
                if not trace_id or s["trace_id"] == trace_id
            ]
        return {
            "num_spans": len(spans),
            "spans_started": self.spans_started,
            "spans_dropped": self.spans_dropped,
            "spans": spans,
        }

    def register_admin_commands(self, admin_socket) -> None:
        admin_socket.register_command(
            "dump_traces",
            lambda args: self.dump_traces(str(args.get("trace", ""))),
            "show buffered trace spans (optional arg: trace)",
        )


# -- ambient context --------------------------------------------------------


def _stack() -> list:
    s = getattr(_ambient, "stack", None)
    if s is None:
        s = _ambient.stack = []
    return s


def _push(tracer: Tracer, span: Span) -> None:
    _stack().append((tracer, span))


def _pop(span: Span) -> None:
    s = _stack()
    for i in range(len(s) - 1, -1, -1):
        if s[i][1] is span:
            del s[i]
            return


def current_span():
    """The innermost ambient span on this thread (or NULL_SPAN)."""
    s = _stack()
    return s[-1][1] if s else NULL_SPAN


def ambient_trace_id() -> str:
    """Trace id propagated by the transport (messenger dispatch) for
    handlers that run with no ambient span yet."""
    return getattr(_ambient, "trace_id", "")


@contextlib.contextmanager
def propagate(trace_id: str):
    """Install a wire-carried trace id as this thread's ambient —
    the msg/messenger.py dispatch hook: any span a handler opens
    without an explicit trace id joins the sender's trace."""
    prev = getattr(_ambient, "trace_id", "")
    _ambient.trace_id = trace_id
    try:
        yield
    finally:
        _ambient.trace_id = prev


def current_tracer() -> Tracer | None:
    s = _stack()
    return s[-1][0] if s else None


def span(name: str, tags: dict | None = None, role: str = ""):
    """Child span of the ambient span — a no-op without one.  The
    store layers use this so their per-stage spans ride whichever
    daemon op is executing above them, without API changes."""
    tracer = current_tracer()
    if tracer is None:
        return NULL_SPAN
    return tracer.start_span(name, role=role, tags=tags)


# -- cross-daemon tree assembly (shared by the mgr tracing module) ----------


def assemble_tree(spans: list[dict]) -> list[dict]:
    """Spans (from ANY number of daemons) of one trace → span tree.

    Parent resolution: an explicit parent_id wins when that span is
    present; otherwise the span attaches under the nearest
    earlier-starting span with a strictly lower role rank (client 0 <
    primary 1 < replica/shard 2) — the cross-daemon links the wire
    does not carry.  Unresolvable spans become roots."""
    by_id = {s["span_id"]: dict(s, children=[]) for s in spans}
    nodes = sorted(by_id.values(), key=lambda s: s["start"])
    roots: list[dict] = []
    for node in nodes:
        parent = by_id.get(node["parent_id"])
        if parent is None or parent is node:
            rank = ROLE_RANK.get(node["role"], 99)
            best = None
            for cand in nodes:
                if cand is node or cand["start"] > node["start"]:
                    continue
                crank = ROLE_RANK.get(cand["role"], 99)
                if crank < rank and (
                    best is None or cand["start"] >= best[0]
                ):
                    best = (cand["start"], cand)
            parent = best[1] if best else None
        if parent is None:
            roots.append(node)
        else:
            parent["children"].append(node)
    return roots
