"""Typed options + layered configuration (src/common/options.cc schema,
src/common/config.cc semantics).

One schema of typed ``Option`` definitions (level/desc/default/min-max/
enum/see_also, options.cc's shape) consumed by ``Config``, which
resolves values through the reference's precedence chain:

    compiled defaults < conf file < environment < runtime set < override

(config.cc: default/conf/env/mon/override).  Runtime ``set`` plays the
ConfigMonitor role (centralized `ceph config set`); observers are
notified when an option's effective value changes (config_obs.h).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable

OPT_INT = "int"
OPT_STR = "str"
OPT_BOOL = "bool"
OPT_FLOAT = "float"

LEVEL_BASIC = "basic"
LEVEL_ADVANCED = "advanced"
LEVEL_DEV = "dev"


class ConfigError(ValueError):
    pass


@dataclass
class Option:
    name: str
    type: str = OPT_STR
    default: Any = None
    description: str = ""
    level: str = LEVEL_ADVANCED
    min: Any = None
    max: Any = None
    enum_allowed: tuple = ()
    see_also: tuple = ()

    def validate(self, value: Any) -> Any:
        try:
            if self.type == OPT_INT:
                value = int(value)
            elif self.type == OPT_FLOAT:
                value = float(value)
            elif self.type == OPT_BOOL:
                if isinstance(value, str):
                    low = value.lower()
                    if low in ("yes", "true", "1", "on"):
                        value = True
                    elif low in ("no", "false", "0", "off"):
                        value = False
                    else:
                        # strict like strict_strtob's -EINVAL
                        raise ValueError(value)
                else:
                    value = bool(value)
            else:
                value = str(value)
        except (TypeError, ValueError):
            raise ConfigError(
                f"{self.name}: {value!r} is not a valid {self.type}"
            )
        if self.min is not None and value < self.min:
            raise ConfigError(
                f"{self.name}: {value} < min {self.min}"
            )
        if self.max is not None and value > self.max:
            raise ConfigError(
                f"{self.name}: {value} > max {self.max}"
            )
        if self.enum_allowed and value not in self.enum_allowed:
            raise ConfigError(
                f"{self.name}: {value!r} not one of {self.enum_allowed}"
            )
        return value


# The framework's option schema — the options.cc analog for the
# components built so far (EC-relevant entries mirror options.cc:565,
# :2717, :2723).
SCHEMA: dict[str, Option] = {
    opt.name: opt
    for opt in [
        Option(
            "erasure_code_backend",
            OPT_STR,
            "jax",
            "compute backend for erasure-code region math",
            enum_allowed=("numpy", "jax"),
        ),
        Option(
            "osd_erasure_code_plugins",
            OPT_STR,
            "jerasure isa lrc shec clay",
            "erasure code plugins to preload at daemon start",
        ),
        Option(
            "osd_pool_default_erasure_code_profile",
            OPT_STR,
            "plugin=jerasure technique=reed_sol_van k=2 m=1",
            "default erasure code profile for new erasure-coded pools",
        ),
        Option(
            "crush_backend",
            OPT_STR,
            "jax",
            "batched PG mapping backend (jax device kernel or the "
            "exact python oracle)",
            enum_allowed=("oracle", "jax"),
        ),
        Option(
            "crush_device_batch",
            OPT_INT,
            1 << 20,
            "maximum PGs mapped per device call",
            min=1,
        ),
        Option(
            "osd_pool_default_size",
            OPT_INT,
            3,
            "default replica count",
            min=1,
            level=LEVEL_BASIC,
        ),
        Option(
            "osd_pool_default_pg_num",
            OPT_INT,
            32,
            "default pg_num for new pools",
            min=1,
            level=LEVEL_BASIC,
        ),
        Option(
            "ec_stripe_batch",
            OPT_INT,
            64,
            "stripes folded into one device encode call",
            min=1,
        ),
        Option(
            "osd_tpu_batch_max",
            OPT_INT,
            16,
            "queued same-pool client writes the OSD worker drains "
            "into one coalesced device encode dispatch (1 disables "
            "write coalescing)",
            min=1,
            level=LEVEL_BASIC,
        ),
        Option(
            "osd_recovery_batch_max",
            OPT_INT,
            16,
            "queued same-peer recovery pushes the OSD worker drains "
            "into one coalesced decode-from-survivors dispatch (1 "
            "disables recovery batching)",
            min=1,
            level=LEVEL_BASIC,
        ),
        Option(
            "wal_prefer_deferred_size",
            OPT_INT,
            65536,
            "transactions whose write payload is below this ack at "
            "WAL append and defer the apply to the drain "
            "(bluestore_prefer_deferred_size, options.cc)",
            min=0,
            level=LEVEL_BASIC,
        ),
        Option(
            "wal_max_group_txc",
            OPT_INT,
            32,
            "commit records one group-commit barrier may absorb "
            "(bluestore_max_deferred_txc analog)",
            min=1,
            level=LEVEL_BASIC,
        ),
        Option(
            "wal_flush_interval_ms",
            OPT_FLOAT,
            0.5,
            "how long a group-commit barrier holds for in-flight "
            "stragglers before syncing; a solo writer never waits",
            min=0.0,
        ),
        Option(
            "wal_checkpoint_bytes",
            OPT_INT,
            8 << 20,
            "WAL size that triggers a checkpoint + truncation once "
            "every record is applied (durable inner stores only)",
            min=1 << 10,
        ),
        Option(
            "rgw_max_objs_per_shard",
            OPT_INT,
            100000,
            "bucket-index entries per shard before the bucket joins "
            "the dynamic-reshard queue (rgw_max_objs_per_shard, "
            "options.cc)",
            min=1,
            level=LEVEL_BASIC,
        ),
        Option(
            "osd_deep_scrub_large_omap_object_key_threshold",
            OPT_INT,
            200000,
            "omap keys on one object before deep scrub flags it "
            "LARGE_OMAP_OBJECTS "
            "(osd_deep_scrub_large_omap_object_key_threshold, "
            "options.cc)",
            min=1,
            level=LEVEL_BASIC,
        ),
        Option(
            "perf_enabled",
            OPT_BOOL,
            True,
            "collect performance counters",
        ),
        Option(
            "osd_op_complaint_time",
            OPT_FLOAT,
            30.0,
            "an op in flight longer than this is a SLOW_OPS health "
            "complaint (osd_op_complaint_time, options.cc)",
            min=0.0,
            level=LEVEL_BASIC,
        ),
        Option(
            "mon_slow_op_report_grace",
            OPT_FLOAT,
            60.0,
            "seconds before a daemon's last slow-op report goes "
            "stale and stops degrading health",
            min=1.0,
        ),
        Option(
            "osd_max_scrubs",
            OPT_INT,
            1,
            "concurrent scrubs an OSD runs or grants to primaries "
            "(the scrub reservation cap, options.cc osd_max_scrubs)",
            min=1,
            level=LEVEL_BASIC,
        ),
        Option(
            "osd_scrub_chunk_max",
            OPT_INT,
            25,
            "objects digested per scrub chunk — the preemption "
            "granularity (osd_scrub_chunk_max)",
            min=1,
        ),
        Option(
            "osd_scrub_auto_repair",
            OPT_BOOL,
            False,
            "repair inconsistencies found by deep scrub "
            "automatically (osd_scrub_auto_repair)",
            level=LEVEL_BASIC,
        ),
        Option(
            "osd_scrub_auto_repair_num_errors",
            OPT_INT,
            5,
            "auto-repair only when deep scrub found at most this "
            "many errors (osd_scrub_auto_repair_num_errors)",
            min=1,
        ),
        Option(
            "mon_osd_nearfull_ratio",
            OPT_FLOAT,
            0.85,
            "used/total ratio above which an OSD raises OSD_NEARFULL "
            "(mon_osd_nearfull_ratio, options.cc)",
            min=0.0,
            max=1.0,
            level=LEVEL_BASIC,
            see_also=("mon_osd_full_ratio",),
        ),
        Option(
            "mon_osd_full_ratio",
            OPT_FLOAT,
            0.95,
            "used/total ratio above which an OSD is FULL: writes "
            "without FULL_TRY park on backoff and the mon raises "
            "OSD_FULL at HEALTH_ERR (mon_osd_full_ratio)",
            min=0.0,
            max=1.0,
            level=LEVEL_BASIC,
            see_also=("mon_osd_nearfull_ratio",),
        ),
        Option(
            "mon_osd_min_down_reporters",
            OPT_INT,
            1,
            "distinct live reporters required before the mon accepts "
            "a failure report — the flap guard against one partitioned "
            "reporter re-downing a reachable OSD "
            "(mon_osd_min_down_reporters)",
            min=1,
            level=LEVEL_BASIC,
        ),
        Option(
            "slo_targets",
            OPT_STR,
            "",
            "latency SLO targets the mgr slo module evaluates: "
            "whitespace/comma-separated "
            "<class>_p<pct>_ms=<target>[@<objective>] tokens, e.g. "
            "'client_p99_ms=50@99.9 bulk_p95_ms=500' (empty = no "
            "SLO evaluation)",
            level=LEVEL_BASIC,
        ),
        Option(
            "tracing_enabled",
            OPT_BOOL,
            True,
            "collect distributed trace spans and push them to the "
            "mgr tracing module",
        ),
        Option(
            "tracing_max_spans",
            OPT_INT,
            2048,
            "per-daemon bound on buffered finished spans "
            "(drop-oldest)",
            min=16,
        ),
    ]
}

# precedence, lowest to highest (config.cc source ordering)
_SOURCES = ("default", "file", "env", "runtime", "override")

# harness env vars that share the prefix but are not config options
_RESERVED_ENV = frozenset(
    {"CEPH_TPU_TEST_PLATFORM", "CEPH_TPU_LOCKDEP"}
)


class Config:
    """Layered config over a schema; the md_config_t role."""

    def __init__(self, schema: dict[str, Option] | None = None):
        self.schema = dict(schema or SCHEMA)
        self._layers: dict[str, dict[str, Any]] = {
            s: {} for s in _SOURCES
        }
        self._observers: list[Callable[[str, Any], None]] = []

    # -- sources -----------------------------------------------------------
    def parse_file(self, path: str) -> None:
        """JSON conf file (the ceph.conf role).  Atomic: every key is
        validated before any is applied."""
        with open(path) as f:
            data = json.load(f)
        self._set_layer_many("file", data)

    def parse_env(self, environ: dict | None = None) -> None:
        """CEPH_TPU_<OPTION> environment overrides."""
        environ = os.environ if environ is None else environ
        updates = {}
        for key, value in environ.items():
            if not key.startswith("CEPH_TPU_") or key in _RESERVED_ENV:
                continue
            # the prefix is ours, so an unknown suffix is always a
            # user error — rejected like parse_file rejects it
            updates[key[len("CEPH_TPU_"):].lower()] = value
        self._set_layer_many("env", updates)

    def set(self, name: str, value: Any) -> None:
        """Runtime set — the `ceph config set` / ConfigMonitor path."""
        self._set_layer("runtime", name, value)

    def override(self, name: str, value: Any) -> None:
        self._set_layer("override", name, value)

    def rm(self, name: str, source: str = "runtime") -> None:
        old = self.get(name)
        self._layers[source].pop(name, None)
        new = self.get(name)
        if new != old:
            self._notify(name, new)

    def _set_layer_many(self, source: str, updates: dict) -> None:
        """Validate every key first, then apply — a bad entry must not
        leave the config half-updated with observers already fired."""
        validated = {}
        for name, value in updates.items():
            opt = self.schema.get(name)
            if opt is None:
                raise ConfigError(f"unknown option {name!r}")
            validated[name] = opt.validate(value)
        for name, value in validated.items():
            self._apply(source, name, value)

    def _set_layer(self, source: str, name: str, value: Any) -> None:
        opt = self.schema.get(name)
        if opt is None:
            raise ConfigError(f"unknown option {name!r}")
        self._apply(source, name, opt.validate(value))

    def _apply(self, source: str, name: str, value: Any) -> None:
        """Store an already-validated value and notify on change."""
        old = self.get(name)
        self._layers[source][name] = value
        if self.get(name) != old:
            self._notify(name, value)

    # -- queries -----------------------------------------------------------
    def get(self, name: str) -> Any:
        opt = self.schema.get(name)
        if opt is None:
            raise ConfigError(f"unknown option {name!r}")
        for source in reversed(_SOURCES):
            if name in self._layers[source]:
                return self._layers[source][name]
        return opt.default

    def get_source(self, name: str) -> str:
        for source in reversed(_SOURCES):
            if name in self._layers[source]:
                return source
        return "default"

    def show_config(self) -> dict[str, Any]:
        return {name: self.get(name) for name in sorted(self.schema)}

    def diff(self) -> dict[str, dict]:
        """Non-default values with their source (`ceph config diff`)."""
        out = {}
        for name, opt in self.schema.items():
            value = self.get(name)
            if value != opt.default:
                out[name] = {
                    "value": value,
                    "source": self.get_source(name),
                    "default": opt.default,
                }
        return out

    # -- observers ---------------------------------------------------------
    def add_observer(self, fn: Callable[[str, Any], None]) -> None:
        self._observers.append(fn)

    def _notify(self, name: str, value: Any) -> None:
        for fn in self._observers:
            fn(name, value)
