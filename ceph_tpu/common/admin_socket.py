"""Admin socket — per-daemon JSON command endpoint
(src/common/admin_socket.cc, 789 LoC).

A unix-domain socket served from a background thread; commands are
newline-terminated JSON (or bare command strings) answered with JSON,
exactly the `ceph daemon <name> <command>` interaction.  Built-in
commands mirror the reference: help, version, perf dump, perf reset,
config show, config diff, config set/get.  Subsystems register extra
hooks with ``register_command``.
"""

from __future__ import annotations

import json
import os
import socket
import threading

from ..version import FRAMEWORK_VERSION
from .config import Config, ConfigError
from .perf_counters import PerfCountersCollection


class AdminSocket:
    def __init__(
        self,
        path: str,
        config: Config | None = None,
        perf: PerfCountersCollection | None = None,
    ):
        self.path = path
        self.config = config or Config()
        self.perf = perf or PerfCountersCollection()
        self._hooks: dict[str, callable] = {}
        self._server: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._register_builtins()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if os.path.exists(self.path):
            os.unlink(self.path)
        self._server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._server.bind(self.path)
        os.chmod(self.path, 0o600)
        self._server.listen(8)
        self._server.settimeout(0.2)
        self._thread = threading.Thread(
            target=self._serve, name="admin_socket", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._server is not None:
            self._server.close()
        if os.path.exists(self.path):
            os.unlink(self.path)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- commands ----------------------------------------------------------
    def register_command(self, prefix: str, fn, help="") -> None:
        """fn(args: dict) -> jsonable (AdminSocketHook::call role)."""
        if prefix in self._hooks:
            raise ValueError(f"command {prefix!r} already registered")
        self._hooks[prefix] = (fn, help)

    def _register_builtins(self) -> None:
        self.register_command(
            "help",
            lambda args: {
                name: help for name, (_, help) in sorted(self._hooks.items())
            },
            "list available commands",
        )
        self.register_command(
            "version",
            lambda args: {"version": FRAMEWORK_VERSION},
            "framework version",
        )
        self.register_command(
            "perf dump", lambda args: self.perf.dump(),
            "dump perfcounters",
        )

        def _perf_reset(args):
            self.perf.reset()
            return {"success": True}

        self.register_command(
            "perf reset", _perf_reset, "zero all perfcounters"
        )
        self.register_command(
            "config show", lambda args: self.config.show_config(),
            "show effective config",
        )
        self.register_command(
            "config diff", lambda args: self.config.diff(),
            "show non-default config with sources",
        )
        self.register_command(
            "config get",
            lambda args: {args["var"]: self.config.get(args["var"])},
            "get one option",
        )

        def _set(args):
            self.config.set(args["var"], args["val"])
            return {"success": True}

        self.register_command("config set", _set, "set one option")

    def execute(self, command) -> dict:
        """Run a command (str prefix or {"prefix": ..., args...})."""
        if isinstance(command, str):
            request = {"prefix": command.strip()}
        else:
            request = dict(command)
        prefix = request.pop("prefix", "")
        hook = self._hooks.get(prefix)
        if hook is None:
            return {
                "error": f"unknown command {prefix!r}; try 'help'"
            }
        fn, _help = hook
        try:
            return {"ok": fn(request)}
        except Exception as e:  # noqa: BLE001 — a hook must never be
            # able to kill the serve thread; every failure becomes a
            # JSON error reply (the reference logs and answers too)
            return {"error": f"{type(e).__name__}: {e}"}

    # -- wire --------------------------------------------------------------
    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except (socket.timeout, OSError):
                continue
            with conn:
                try:
                    data = b""
                    conn.settimeout(2)
                    while not data.endswith(b"\n"):
                        chunk = conn.recv(65536)
                        if not chunk:
                            break
                        data += chunk
                    line = data.decode().strip()
                    try:
                        command = json.loads(line)
                    except json.JSONDecodeError:
                        command = line
                    response = self.execute(command)
                    conn.sendall(json.dumps(response).encode() + b"\n")
                except OSError:
                    pass


def admin_command(path: str, command) -> dict:
    """Client helper: the `ceph daemon` side."""
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(10)
        s.connect(path)
        payload = (
            json.dumps(command)
            if not isinstance(command, str)
            else command
        )
        s.sendall(payload.encode() + b"\n")
        data = b""
        while not data.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
    return json.loads(data.decode())
