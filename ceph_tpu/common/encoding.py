"""Binary encoding primitives — the bufferlist encode/decode role.

The reference serializes every wire/disk struct through its denc/
encode framework (src/include/encoding.h): little-endian fixed-width
integers, length-prefixed strings, counted containers, and versioned
struct envelopes (ENCODE_START/ENCODE_FINISH with a compat version and
a byte length so old decoders can skip unknown trailing fields).  This
module provides the same primitives for the framework's own structs
(OSDMap/Incremental, messenger frames, object-store records).

It is deliberately NOT the reference's exact wire format (that would
require feature-bit negotiation and a hundred legacy struct layouts);
it is a clean versioned format with the same design rules: LE, length-
prefixed, versioned envelopes, crc-checkable.  Where we decode the
reference's actual on-disk formats (binary crushmaps), the decoder
lives with that component.
"""

from __future__ import annotations

import struct
from io import BytesIO


class Encoder:
    """Append-only little-endian byte sink (bufferlist::encode role)."""

    def __init__(self):
        self._buf = BytesIO()

    # fixed-width ints
    def u8(self, v: int) -> "Encoder":
        self._buf.write(struct.pack("<B", v & 0xFF))
        return self

    def u16(self, v: int) -> "Encoder":
        self._buf.write(struct.pack("<H", v & 0xFFFF))
        return self

    def u32(self, v: int) -> "Encoder":
        self._buf.write(struct.pack("<I", v & 0xFFFFFFFF))
        return self

    def u64(self, v: int) -> "Encoder":
        self._buf.write(struct.pack("<Q", v & 0xFFFFFFFFFFFFFFFF))
        return self

    def s32(self, v: int) -> "Encoder":
        self._buf.write(struct.pack("<i", v))
        return self

    def s64(self, v: int) -> "Encoder":
        self._buf.write(struct.pack("<q", v))
        return self

    def f64(self, v: float) -> "Encoder":
        self._buf.write(struct.pack("<d", v))
        return self

    def bool(self, v: bool) -> "Encoder":
        return self.u8(1 if v else 0)

    # variable-size
    def bytes(self, v: bytes) -> "Encoder":
        self.u32(len(v))
        self._buf.write(v)
        return self

    def string(self, v: str) -> "Encoder":
        return self.bytes(v.encode("utf-8"))

    def raw(self, v: bytes) -> "Encoder":
        self._buf.write(v)
        return self

    # containers: u32 count then elements (encoding.h container encode)
    def list(self, items, item_fn) -> "Encoder":
        self.u32(len(items))
        for it in items:
            item_fn(self, it)
        return self

    def map(self, d: dict, key_fn, val_fn) -> "Encoder":
        self.u32(len(d))
        for k in sorted(d):
            key_fn(self, k)
            val_fn(self, d[k])
        return self

    def getvalue(self) -> bytes:
        return self._buf.getvalue()


class Decoder:
    """Cursor over an encoded buffer (bufferlist::const_iterator role).

    Raises ``DecodeError`` (never struct.error/IndexError) on truncated
    or malformed input.
    """

    def __init__(self, data: bytes, pos: int = 0):
        self._data = memoryview(data)
        self._pos = pos

    def _take(self, n: int) -> memoryview:
        if self._pos + n > len(self._data):
            raise DecodeError(
                f"buffer underrun: need {n} at {self._pos}, "
                f"have {len(self._data)}"
            )
        v = self._data[self._pos : self._pos + n]
        self._pos += n
        return v

    def u8(self) -> int:
        return self._take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def s32(self) -> int:
        return struct.unpack("<i", self._take(4))[0]

    def s64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def bool(self) -> bool:
        return self.u8() != 0

    def bytes(self) -> bytes:
        return bytes(self._take(self.u32()))

    def string(self) -> str:
        return self.bytes().decode("utf-8")

    def raw(self, n: int) -> bytes:
        return bytes(self._take(n))

    def list(self, item_fn) -> list:
        return [item_fn(self) for _ in range(self.u32())]

    def map(self, key_fn, val_fn) -> dict:
        return {key_fn(self): val_fn(self) for _ in range(self.u32())}

    @property
    def pos(self) -> int:
        return self._pos

    def remaining(self) -> int:
        return len(self._data) - self._pos

    def skip(self, n: int) -> None:
        self._take(n)


class DecodeError(Exception):
    pass


# -- versioned struct envelope (ENCODE_START/ENCODE_FINISH) ---------------


def encode_versioned(version: int, compat: int, body: bytes) -> bytes:
    """ENCODE_START(v, compat, bl) ... ENCODE_FINISH: u8 version,
    u8 compat, u32 length, payload (src/include/encoding.h:1312)."""
    e = Encoder()
    e.u8(version).u8(compat).u32(len(body)).raw(body)
    return e.getvalue()


def decode_versioned(
    d: Decoder, understand: int
) -> tuple[int, Decoder]:
    """DECODE_START: returns (struct version, body decoder).  Raises
    DecodeError if compat > understand (we cannot safely interpret);
    unknown trailing fields of newer-but-compatible versions are
    skipped by the caller advancing past the body."""
    version = d.u8()
    compat = d.u8()
    length = d.u32()
    if compat > understand:
        raise DecodeError(
            f"struct compat {compat} > understood {understand}"
        )
    body = Decoder(d.raw(length))
    return version, body
