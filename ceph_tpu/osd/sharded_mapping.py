"""Mesh-sharded batched CRUSH — the ParallelPGMapper analog at
pod scale.

``crush/jaxmap.py`` turned ``crush_do_rule`` into one vmapped device
call; this module splits that call's PG batch across every chip of a
``DeviceMesh`` (ops/mesh.py) the way the reference splits pgid ranges
across a thread pool (src/osd/OSDMapMapping.h:18-156).  The per-lane
kernel is untouched — the batch axis is simply sharded — so results
are byte-identical to the single-device path; the acting-set table
re-assembles host-side from the gathered shards (ragged PG counts pad
to a device multiple and slice back), and the same exact-oracle
fallback sweeps any speculation-overflow lanes afterwards.

``mesh_batch_do_rule`` is the product entry point: OSDMap full remaps
(osd/mapping.py, so the balancer's dry-runs and osdmaptool inherit it)
route through it and shard automatically whenever more than one device
exists; single-device hosts keep the exact existing dispatch.
"""

from __future__ import annotations

import time

import numpy as np

from ..crush import jaxmap
from ..ops import mesh as meshmod


def sharded_batch_do_rule(
    cm,
    ruleno: int,
    xs,
    result_max: int,
    weights=None,
    dmesh: meshmod.DeviceMesh | None = None,
):
    """``jaxmap.batch_do_rule`` with the PG batch sharded across
    ``dmesh`` (default: the process mesh).  Same signature, same
    (results, counts) numpy contract, byte-identical output."""
    if dmesh is None:
        dmesh = meshmod.default_mesh()
    if dmesh is None:
        return jaxmap.batch_do_rule(cm, ruleno, xs, result_max, weights)
    import jax
    import jax.numpy as jnp

    if weights is None:
        weights = np.full(max(cm.max_devices, 1), 0x10000, np.int32)
    xs_np = np.asarray(xs, dtype=np.int32)
    padded, n = meshmod.pad_to_devices(xs_np, dmesh.n)
    t0 = time.perf_counter()
    xs_dev = jax.device_put(jnp.asarray(padded), dmesh.batch_spec(1))
    wv = jnp.asarray(weights, dtype=jnp.int32)
    fn, tables = jaxmap.batched_rule_call(
        cm, ruleno, result_max, weights
    )
    res, counts, ok = fn(xs_dev, wv, *tables)
    # host-side re-assembly: gather every shard, drop the pad lanes
    res = np.asarray(res)[:n]
    counts = np.asarray(counts)[:n]
    ok = np.asarray(ok)[:n]
    meshmod.record_shard_dispatch(
        dmesh, "crush", padded.nbytes, time.perf_counter() - t0
    )
    return jaxmap.apply_oracle_fallback(
        cm, ruleno, xs_np, res, counts, ok, result_max, weights
    )


def mesh_batch_do_rule(cm, ruleno, xs, result_max, weights=None):
    """Product dispatch: shard across the default mesh when more than
    one device exists, else the single-device path unchanged."""
    dmesh = meshmod.default_mesh()
    if dmesh is None:
        return jaxmap.batch_do_rule(cm, ruleno, xs, result_max, weights)
    return sharded_batch_do_rule(
        cm, ruleno, xs, result_max, weights, dmesh
    )


class ShardedPGMapper:
    """Thin OO wrapper over one (map, mesh) pair — the shape bench.py
    and the dryrun drive: compile once, map many PG ranges."""

    def __init__(self, crush_map, dmesh: meshmod.DeviceMesh):
        self.cm = jaxmap.compile_map(crush_map)
        self.dmesh = dmesh

    def map_pgs(self, ruleno: int, xs, result_max: int, weights=None):
        return sharded_batch_do_rule(
            self.cm, ruleno, xs, result_max, weights, self.dmesh
        )
