"""Op scheduler — the OSD worker queue with QoS classes
(src/osd/scheduler/OpScheduler.cc + WeightedPriorityQueue.h reduced).

The reference feeds every shard worker from an OpScheduler: strict
items (peering/map events) preempt everything, and the remaining
classes (client ops, recovery, scrub/background) share the worker in
proportion to configured weights via a weighted round-robin over op
COST — so a burst of background work cannot starve client ops, and
vice versa.  Same machinery here, replacing the plain FIFO the
daemon's worker drained before:

- ``enqueue(klass, cost, item)`` / ``dequeue()`` — the OpScheduler
  surface; CLASS_STRICT dequeues first, always in FIFO order.
- weighted classes drain by deficit round-robin: each visit grants a
  class ``weight`` credits; items charge their cost against them —
  byte-sized client ops and chunky recovery pushes share accurately.
- ``put``/``get`` aliases keep the queue.Queue shape the daemon's
  producers already use (None = shutdown sentinel, delivered ahead
  of everything).
"""

from __future__ import annotations

import collections
import threading

CLASS_STRICT = "strict"  # peering/map/activation: never queued behind IO
CLASS_CLIENT = "client"
CLASS_RECOVERY = "recovery"
CLASS_BACKGROUND = "background"  # scrub, splits, trims

DEFAULT_WEIGHTS = {
    # osd_op_queue weights role: client IO dominates, recovery gets a
    # protected share, background trickles
    CLASS_CLIENT: 63,
    CLASS_RECOVERY: 10,
    CLASS_BACKGROUND: 5,
}


class WeightedPriorityQueue:
    """Strict + deficit-weighted-round-robin work queue."""

    def __init__(self, weights: dict[str, int] | None = None):
        self._draining = False
        self.weights = dict(weights or DEFAULT_WEIGHTS)
        self._strict: collections.deque = collections.deque()
        self._queues: dict[str, collections.deque] = {
            k: collections.deque() for k in self.weights
        }
        self._credit: dict[str, float] = {k: 0.0 for k in self.weights}
        self._rr = list(self.weights)  # round-robin order
        self._rr_pos = 0
        self._fresh = True  # current class not yet granted this visit
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._size = 0

    # -- OpScheduler surface ----------------------------------------------
    def enqueue(self, klass: str, cost: int, item) -> None:
        with self._cond:
            if klass == CLASS_STRICT or klass not in self._queues:
                self._strict.append(item)
            else:
                self._queues[klass].append((max(int(cost), 1), item))
            self._size += 1
            self._cond.notify()

    def dequeue(self, timeout: float | None = None):
        with self._cond:
            while self._size == 0:
                if self._draining:
                    return None  # shutdown AFTER the queue drained
                if not self._cond.wait(timeout):
                    raise TimeoutError("queue idle")
            self._size -= 1
            if self._strict:
                return self._strict.popleft()
            # deficit round-robin: the current class serves while its
            # credit lasts (a burst proportional to its weight), gets
            # ONE quantum grant per visit, then yields the worker —
            # an expensive head accumulates credit across laps
            # instead of being skipped forever
            n = len(self._rr)
            spins = 0
            while spins <= 2 * n:
                klass = self._rr[self._rr_pos]
                q = self._queues[klass]
                if not q:
                    self._credit[klass] = 0.0
                    self._rr_pos = (self._rr_pos + 1) % n
                    self._fresh = True
                    spins += 1
                    continue
                if self._fresh:
                    # the quantum grants on ARRIVAL at a class, once
                    # per visit — granting whenever credit ran short
                    # would let one class hold the worker forever
                    self._credit[klass] += self.weights[klass]
                    self._fresh = False
                cost, item = q[0]
                if cost <= self._credit[klass]:
                    q.popleft()
                    self._credit[klass] -= cost
                    if not q:
                        self._credit[klass] = 0.0
                    return item
                self._rr_pos = (self._rr_pos + 1) % n
                self._fresh = True
                spins += 1
            # every head exceeded a full lap of grants: serve the
            # cheapest head rather than stalling
            best = min(
                (q[0][0], k)
                for k, q in self._queues.items()
                if q
            )
            cost, item = self._queues[best[1]].popleft()
            self._credit[best[1]] = 0.0
            return item

    def qlen(self) -> int:
        with self._lock:
            return self._size

    # -- queue.Queue-shaped aliases (the daemon's producer surface) --------
    def put(self, item) -> None:
        """Untyped put: legacy tuples go strict; None marks the queue
        DRAINING — the consumer sees it only once everything already
        queued has been served (queue.Queue's FIFO sentinel
        semantics, which the daemon's shutdown relies on: queued ops
        still get replies and release their throttle budget)."""
        if item is None:
            with self._cond:
                self._draining = True
                self._cond.notify_all()
            return
        self.enqueue(CLASS_STRICT, 0, item)

    def get(self, timeout: float | None = None):
        return self.dequeue(timeout)
