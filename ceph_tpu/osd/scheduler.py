"""Op scheduler — the OSD worker queue with QoS classes
(src/osd/scheduler/OpScheduler.cc + WeightedPriorityQueue.h reduced).

The reference feeds every shard worker from an OpScheduler: strict
items (peering/map events) preempt everything, and the remaining
classes (client ops, recovery, scrub/background) share the worker in
proportion to configured weights via a weighted round-robin over op
COST — so a burst of background work cannot starve client ops, and
vice versa.  Same machinery here, replacing the plain FIFO the
daemon's worker drained before:

- ``enqueue(klass, cost, item)`` / ``dequeue()`` — the OpScheduler
  surface; CLASS_STRICT dequeues first, always in FIFO order.
- weighted classes drain by deficit round-robin: each visit grants a
  class ``weight`` credits; items charge their cost against them —
  byte-sized client ops and chunky recovery pushes share accurately.
- ``put``/``get`` aliases keep the queue.Queue shape the daemon's
  producers already use (None = shutdown sentinel, delivered ahead
  of everything).
"""

from __future__ import annotations

import collections
import threading

CLASS_STRICT = "strict"  # peering/map/activation: never queued behind IO
CLASS_CLIENT = "client"
CLASS_RECOVERY = "recovery"
CLASS_BACKGROUND = "background"  # scrub, splits, trims

DEFAULT_WEIGHTS = {
    # osd_op_queue weights role: client IO dominates, recovery gets a
    # protected share, background trickles
    CLASS_CLIENT: 63,
    CLASS_RECOVERY: 10,
    CLASS_BACKGROUND: 5,
}


class _SchedulerBase:
    """Shared scheduler chassis: the strict deque (peering/map events
    preempt all QoS), the drain-aware shutdown sentinel, and the
    queue.Queue-shaped put/get aliases — subclasses supply only the
    weighted enqueue and pick policy."""

    def __init__(self, classes):
        self._draining = False
        self._strict: collections.deque = collections.deque()
        self._queues: dict[str, collections.deque] = {
            k: collections.deque() for k in classes
        }
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._size = 0
        # event-driven consumers (the shared-services strand drain)
        # register here: called AFTER every enqueue/put, outside the
        # scheduler lock, so a drain can be kicked without a thread
        # parked in get()
        self.on_enqueue = None
        # recent dequeue classes (observability: tests prove client
        # ops interleave with a recovery storm from this trace)
        self.class_log: collections.deque = collections.deque(
            maxlen=512
        )

    def enqueue(self, klass: str, cost: int, item) -> None:
        with self._cond:
            if klass == CLASS_STRICT:
                self._strict.append(item)
            elif klass not in self._queues:
                # an unregistered QoS class must not ride the strict
                # lane (that would let any client BYPASS QoS by naming
                # a class): it degrades to the default client class,
                # or strict only when no client queue exists at all
                if CLASS_CLIENT in self._queues:
                    self._enqueue_weighted(
                        CLASS_CLIENT, max(int(cost), 1), item
                    )
                else:
                    self._strict.append(item)
            else:
                self._enqueue_weighted(klass, max(int(cost), 1), item)
            self._size += 1
            self._cond.notify()
        cb = self.on_enqueue
        if cb is not None:
            cb()

    def known_class(self, klass: str) -> bool:
        """True when this scheduler has a registered queue (weight or
        dmclock profile) for ``klass``."""
        return klass in self._queues

    def last_class(self) -> str | None:
        """The class the most recent dequeue served (single-consumer
        worker loops use this to coalesce follow-on work from the
        same class)."""
        return self.class_log[-1] if self.class_log else None

    def drain_class(self, klass: str, predicate, max_n: int) -> list:
        """Write-coalescing hook: pop up to ``max_n`` CONSECUTIVE
        head items of ``klass``'s queue that satisfy ``predicate``
        (first non-match stops the drain — skipping over it would
        reorder the class's stream, and per-object ordering is the
        invariant batching must keep).  The drained items ride the
        dispatch the caller is already committing, so their costs are
        still charged (subclass hook) — cross-class fairness is
        perturbed by at most one bounded burst, exactly like the
        reference's op-shard batching.  ``predicate`` runs under the
        scheduler lock: it must be cheap and lock-free."""
        out: list = []
        with self._cond:
            q = self._queues.get(klass)
            if not q:
                return out
            while q and len(out) < max_n:
                entry = q[0]
                item = entry[-1]
                if not predicate(item):
                    break
                q.popleft()
                self._size -= 1
                self._drained(klass, entry)
                self.class_log.append(klass)
                out.append(item)
        return out

    def _drained(self, klass: str, entry) -> None:
        """Cost accounting for an item drained outside dequeue()
        (default: none — dmclock tags advanced at enqueue)."""

    def qlen(self) -> int:
        with self._lock:
            return self._size

    def put(self, item) -> None:
        """None marks the queue DRAINING — the consumer sees it only
        once everything already queued has been served (queue.Queue's
        FIFO sentinel semantics the daemon's shutdown relies on);
        legacy tuples go strict."""
        if item is None:
            with self._cond:
                self._draining = True
                self._cond.notify_all()
            cb = self.on_enqueue
            if cb is not None:
                cb()  # wake an event-driven drain to observe draining
            return
        self.enqueue(CLASS_STRICT, 0, item)

    def get(self, timeout: float | None = None):
        return self.dequeue(timeout)


class WeightedPriorityQueue(_SchedulerBase):
    """Strict + deficit-weighted-round-robin work queue."""

    def __init__(self, weights: dict[str, int] | None = None):
        self.weights = dict(weights or DEFAULT_WEIGHTS)
        super().__init__(self.weights)
        self._credit: dict[str, float] = {k: 0.0 for k in self.weights}
        self._rr = list(self.weights)  # round-robin order
        self._rr_pos = 0
        self._fresh = True  # current class not yet granted this visit

    def set_weight(self, klass: str, weight: int) -> None:
        """Register (or retune) a weighted class at runtime — the
        osd_op_queue per-class weight knob."""
        with self._cond:
            self.weights[klass] = int(weight)
            if klass not in self._queues:
                self._queues[klass] = collections.deque()
                self._credit[klass] = 0.0
                self._rr.append(klass)

    def _enqueue_weighted(self, klass: str, cost: int, item) -> None:
        self._queues[klass].append((cost, item))

    def _drained(self, klass: str, entry) -> None:
        # charge the drained item's cost; credit may go negative, so
        # the class yields the worker longer afterwards — fairness
        # holds over time even though the burst ran now
        if klass in self._credit:
            self._credit[klass] -= entry[0]

    def dequeue(self, timeout: float | None = None):
        with self._cond:
            while self._size == 0:
                if self._draining:
                    return None  # shutdown AFTER the queue drained
                if not self._cond.wait(timeout):
                    raise TimeoutError("queue idle")
            self._size -= 1
            if self._strict:
                self.class_log.append(CLASS_STRICT)
                return self._strict.popleft()
            # deficit round-robin: the current class serves while its
            # credit lasts (a burst proportional to its weight), gets
            # ONE quantum grant per visit, then yields the worker —
            # an expensive head accumulates credit across laps
            # instead of being skipped forever
            n = len(self._rr)
            spins = 0
            while spins <= 2 * n:
                klass = self._rr[self._rr_pos]
                q = self._queues[klass]
                if not q:
                    # clear UNUSED positive credit, but keep drain
                    # DEBT (negative, from coalesced bursts): a class
                    # that repeatedly empties its queue between
                    # bursts must still pay for them
                    self._credit[klass] = min(self._credit[klass], 0.0)
                    self._rr_pos = (self._rr_pos + 1) % n
                    self._fresh = True
                    spins += 1
                    continue
                if self._fresh:
                    # the quantum grants on ARRIVAL at a class, once
                    # per visit — granting whenever credit ran short
                    # would let one class hold the worker forever
                    self._credit[klass] += self.weights[klass]
                    self._fresh = False
                cost, item = q[0]
                if cost <= self._credit[klass]:
                    q.popleft()
                    self._credit[klass] -= cost
                    if not q:
                        self._credit[klass] = min(
                            self._credit[klass], 0.0
                        )
                    self.class_log.append(klass)
                    return item
                self._rr_pos = (self._rr_pos + 1) % n
                self._fresh = True
                spins += 1
            # every head exceeded a full lap of grants: serve the
            # cheapest head rather than stalling
            best = min(
                (q[0][0], k)
                for k, q in self._queues.items()
                if q
            )
            cost, item = self._queues[best[1]].popleft()
            self._credit[best[1]] = min(self._credit[best[1]], 0.0)
            self.class_log.append(best[1])
            return item


class MClockQueue(_SchedulerBase):
    """dmClock-style QoS queue (the mclock_scheduler role,
    src/osd/scheduler/mClockScheduler.cc over the dmclock library) —
    the reference's DEFAULT osd_op_queue.

    Each class gets (reservation, weight, limit) in cost-units/sec:

    - reservation: guaranteed rate — requests whose reservation tag
      has come due are served FIRST, in tag order, regardless of
      weights (the qos floor);
    - limit: hard cap — a request whose limit tag lies in the future
      is ineligible even when the worker idles (anti-starvation for
      OTHER consumers of the device behind this queue);
    - weight: proportional share of whatever capacity remains.

    Tags advance by cost/rate per request (dmclock's RhoPhi tags with
    delta/rho collapsed for the single-server case).  The clock is
    injectable so QoS tests drive virtual time deterministically.
    Strict items (peering/map events) bypass QoS entirely, and the
    drain-aware ``put(None)`` sentinel matches WeightedPriorityQueue.
    """

    def __init__(
        self,
        profiles: dict[str, tuple[float, float, float]] | None = None,
        clock=None,
        cost_unit: float = 4096.0,
    ):
        import time as _time

        # (reservation, weight, limit) per class in COST-UNITS/sec;
        # limit 0 = none.  The daemon enqueues BYTE costs, so
        # cost_unit converts (default: one 4KB op = one unit).  The
        # defaults cap only background work — a default limit on
        # recovery would stall pulls outright when uncontended.
        self.profiles = dict(
            profiles
            or {
                CLASS_CLIENT: (100.0, 60.0, 0.0),
                CLASS_RECOVERY: (20.0, 20.0, 0.0),
                CLASS_BACKGROUND: (5.0, 10.0, 100.0),
            }
        )
        super().__init__(self.profiles)
        self.clock = clock or _time.monotonic
        self.cost_unit = cost_unit
        # next-tag state per class
        self._rtag: dict[str, float] = {}
        self._wtag: dict[str, float] = {}
        self._ltag: dict[str, float] = {}

    def set_profile(
        self, klass: str, profile: tuple[float, float, float]
    ) -> None:
        """Register (or retune) a dmclock class at runtime: the
        (reservation, weight, limit) triple in cost-units/sec — how
        per-tenant QoS classes (gold/bulk/...) come to exist."""
        res, wgt, lim = (float(x) for x in profile)
        with self._cond:
            self.profiles[klass] = (res, wgt, lim)
            if klass not in self._queues:
                self._queues[klass] = collections.deque()

    def _enqueue_weighted(self, klass: str, cost: int, item) -> None:
        now = self.clock()
        res, wgt, lim = self.profiles[klass]
        c = max(float(cost), 1.0) / self.cost_unit
        c = max(c, 1e-6)
        rtag = max(
            now, self._rtag.get(klass, 0.0)
        ) + (c / res if res > 0 else float("inf"))
        wtag = max(now, self._wtag.get(klass, 0.0)) + c / max(
            wgt, 1e-9
        )
        ltag = (
            max(now, self._ltag.get(klass, 0.0)) + c / lim
            if lim > 0
            else now
        )
        self._rtag[klass] = rtag
        self._wtag[klass] = wtag
        self._ltag[klass] = ltag
        self._queues[klass].append((rtag, wtag, ltag, item))

    def _pick_locked(self):
        now = self.clock()
        # 1) reservation phase: any head whose reservation tag is due
        due = [
            (q[0][0], k)
            for k, q in self._queues.items()
            if q and q[0][0] <= now
        ]
        if due:
            _tag, k = min(due)
            self.class_log.append(k)
            return self._queues[k].popleft()[3]
        # 2) weight phase among limit-eligible heads
        eligible = [
            (q[0][1], k)
            for k, q in self._queues.items()
            if q and q[0][2] <= now
        ]
        if eligible:
            _tag, k = min(eligible)
            self.class_log.append(k)
            return self._queues[k].popleft()[3]
        return None

    def dequeue(self, timeout: float | None = None):
        import time as _time

        # the timeout is wall-clock even under an injected (virtual)
        # QoS clock — a test clock that never advances must not turn
        # a bounded dequeue into an infinite loop
        deadline = (
            None if timeout is None else _time.monotonic() + timeout
        )
        with self._cond:
            while True:
                if self._strict:
                    self._size -= 1
                    self.class_log.append(CLASS_STRICT)
                    return self._strict.popleft()
                if self._size > 0:
                    item = self._pick_locked()
                    if item is not None:
                        self._size -= 1
                        return item
                    # queued work exists but every head is limited:
                    # sleep until the earliest tag comes due (or the
                    # caller's deadline, whichever is first)
                    next_due = min(
                        min(q[0][0], q[0][2])
                        for q in self._queues.values()
                        if q
                    )
                    wait = max(0.001, next_due - self.clock())
                    if deadline is not None:
                        remaining = deadline - _time.monotonic()
                        if remaining <= 0:
                            raise TimeoutError("queue idle")
                        wait = min(wait, remaining)
                    self._cond.wait(wait)
                    continue
                if self._draining:
                    return None
                remaining = (
                    None
                    if deadline is None
                    else deadline - _time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("queue idle")
                if not self._cond.wait(remaining):
                    raise TimeoutError("queue idle")
