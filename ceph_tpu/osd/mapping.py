"""Batched full-map PG→OSD computation (OSDMapMapping replacement).

The reference shards pgid ranges over a thread pool
(ParallelPGMapper, src/osd/OSDMapMapping.h:18-156).  Here one device
call per pool runs the CRUSH stage for every PG
(ceph_tpu.crush.jaxmap), and the cheap fix-up stages — nonexistent/down
filtering, upmap overrides, primary affinity, pg_temp — are vectorized
numpy on the host.  Falls back to the scalar oracle per-PG when the map
is outside the device kernel's scope (legacy bucket algs etc.).
"""

from __future__ import annotations

import numpy as np

from ..crush.hashing import crush_hash32_2
from ..crush.types import CRUSH_ITEM_NONE
from .osdmap import (
    CEPH_OSD_DEFAULT_PRIMARY_AFFINITY,
    CEPH_OSD_MAX_PRIMARY_AFFINITY,
    OSDMap,
    PgPool,
)

_NONE = CRUSH_ITEM_NONE


def _stable_mod_vec(x: np.ndarray, b: int, bmask: int) -> np.ndarray:
    lo = x & bmask
    return np.where(lo < b, lo, x & (bmask >> 1))


def pool_pps_vec(pool: PgPool, ps: np.ndarray) -> np.ndarray:
    """Vectorized pg_pool_t::raw_pg_to_pps."""
    m = _stable_mod_vec(ps, pool.pgp_num, pool.pgp_num_mask)
    if pool.hashpspool:
        return crush_hash32_2(
            m.astype(np.uint32),
            np.uint32(pool.pool_id & 0xFFFFFFFF),
        )
    return (m + pool.pool_id).astype(np.uint32)


def _compact_rows(osds: np.ndarray, valid: np.ndarray) -> np.ndarray:
    """Shift valid entries left per row (replicated-pool hole removal);
    invalid tail slots become CRUSH_ITEM_NONE."""
    order = np.argsort(~valid, axis=1, kind="stable")
    packed = np.take_along_axis(osds, order, axis=1)
    keep = np.take_along_axis(valid, order, axis=1)
    return np.where(keep, packed, _NONE)


def _build_perf():
    from ..common import PerfCountersBuilder

    return (
        PerfCountersBuilder("osdmap_mapping")
        .add_u64_counter("updates", "full-map recomputes")
        .add_u64_counter("pgs_mapped", "PGs mapped across updates")
        .add_time_avg("crush_stage", "device/oracle CRUSH stage time")
        .add_time_avg("fixup_stages", "host fix-up stage time")
        .create_perf_counters()
    )


class OSDMapMapping:
    """Caches up/acting/primaries for every PG of every pool
    (the consumer API of src/osd/OSDMapMapping.h:173-340); exposes
    reference-style perf counters (the l_osd_* analog) via
    ``self.perf.dump()``."""

    def __init__(self):
        self.up: dict[int, np.ndarray] = {}
        self.up_primary: dict[int, np.ndarray] = {}
        self.acting: dict[int, np.ndarray] = {}
        self.acting_primary: dict[int, np.ndarray] = {}
        self.epoch = 0
        self.perf = _build_perf()

    # -- batch pipeline ----------------------------------------------------
    def update(self, osdmap: OSDMap, use_device: bool = True) -> None:
        """Recompute every pool's full PG mapping."""
        self.epoch = osdmap.epoch
        self.perf.inc("updates")
        for pool_id, pool in osdmap.pools.items():
            self._update_pool(osdmap, pool, use_device)
            self.perf.inc("pgs_mapped", pool.pg_num)

    def _update_pool(
        self, osdmap: OSDMap, pool: PgPool, use_device: bool
    ) -> None:
        from ..ops.kernel_stats import kernel_stats

        n = pool.pg_num
        size = pool.size
        ps = np.arange(n, dtype=np.int64)
        pps = pool_pps_vec(pool, ps).astype(np.int64)

        ks = kernel_stats()
        pgs_counter = ks.counter(
            "crush", "pgs", desc="PGs mapped through the CRUSH kernel"
        )
        with self.perf.time_it("crush_stage"), ks.timed(
            "crush", bytes_in=pps.nbytes
        ) as kt:
            raw = self._crush_stage(osdmap, pool, pps, use_device)
            kt.bytes_out = raw.nbytes
        ks.perf.inc(pgs_counter, n)

        with self.perf.time_it("fixup_stages"):
            up, up_primary, acting, acting_primary = self._fixup(
                osdmap, pool, ps, pps, raw
            )
        self.up[pool.pool_id] = up
        self.up_primary[pool.pool_id] = up_primary
        self.acting[pool.pool_id] = acting
        self.acting_primary[pool.pool_id] = acting_primary

    def _fixup(self, osdmap, pool, ps, pps, raw):
        # _remove_nonexistent_osds + _raw_to_up_osds, fused: both drop
        # to NONE (EC) or compact (replicated)
        exists = np.zeros(osdmap.max_osd + 1, dtype=bool)
        up_ok = np.zeros(osdmap.max_osd + 1, dtype=bool)
        exists[:-1] = np.asarray(osdmap.osd_exists, dtype=bool)
        up_ok[:-1] = exists[:-1] & np.asarray(osdmap.osd_up, dtype=bool)
        idx = np.clip(raw, 0, osdmap.max_osd)
        in_range = (raw >= 0) & (raw < osdmap.max_osd)
        raw_exists = in_range & exists[idx]
        if pool.can_shift_osds():
            raw = _compact_rows(raw, raw_exists)
        else:
            raw = np.where(raw_exists | (raw == _NONE), raw, _NONE)

        raw = self._upmap_stage(osdmap, pool, ps, raw)

        idx = np.clip(raw, 0, osdmap.max_osd)
        in_range = (raw >= 0) & (raw < osdmap.max_osd)
        alive = in_range & up_ok[idx]
        if pool.can_shift_osds():
            up = _compact_rows(raw, alive)
        else:
            up = np.where(alive, raw, _NONE)

        up_primary = self._primary_vec(up)
        up, up_primary = self._affinity_stage(
            osdmap, pool, pps, up, up_primary
        )

        acting = up.copy()
        acting_primary = up_primary.copy()
        self._temp_stage(osdmap, pool, acting, acting_primary)

        return up, up_primary, acting, acting_primary

    def _crush_stage(
        self, osdmap: OSDMap, pool: PgPool, pps: np.ndarray, use_device: bool
    ) -> np.ndarray:
        """(npgs, size) raw mappings via the device kernel, oracle
        fallback outside its scope."""
        ruleno = osdmap.crush.find_rule(pool.crush_rule, pool.type, pool.size)
        n = len(pps)
        if ruleno < 0:
            return np.full((n, pool.size), _NONE, dtype=np.int64)
        if use_device:
            try:
                from ..crush import jaxmap
                from ..ops.profiler import dispatch_profiler
                from ..ops.residency import bucket_pow2, note_shape
                from .sharded_mapping import mesh_batch_do_rule

                cm = _compiled(osdmap.crush)
                # an UnsupportedMap raised anywhere in here discards
                # the flight-recorder entry (no commit on exception —
                # the oracle loop below records its own)
                with dispatch_profiler().dispatch(
                    "crush", backend="jax"
                ) as dp:
                    dp.set_ops(1)
                    dp.set_stripes(n)
                    dp.add_bytes_in(pps.nbytes)
                    dp.add_upload(pps.nbytes)
                    # bucket the PG batch to a power of two (pad with
                    # a repeat of lane 0 — a valid input — and slice
                    # the rows back) so pools with ragged pg_num and
                    # remap sweeps replay ONE compiled program per
                    # bucket; reuse lands in
                    # l_tpu_compile_cache_{hit,miss}
                    nb = bucket_pow2(n)
                    pps_in = pps
                    if nb != n:
                        pps_in = np.concatenate(
                            [pps, np.full(nb - n, pps[0], dtype=pps.dtype)]
                        )
                        dp.add_pad((nb - n) * pps.itemsize)
                    note_shape("crush_batch", nb, pool.size)
                    # shards across the device mesh when >1 device
                    # exists (ParallelPGMapper role); single-device
                    # unchanged
                    with dp.stage("compute"):
                        res, counts = mesh_batch_do_rule(
                            cm, ruleno, pps_in, pool.size,
                            osdmap.osd_weight,
                        )
                    with dp.stage("sync"):
                        raw = np.asarray(res, dtype=np.int64)[:n]
                        counts = np.asarray(counts)[:n]
                    # positions beyond the returned count are absent,
                    # not NONE
                    cols = np.arange(pool.size)
                    return np.where(
                        cols[None, :] < counts[:, None], raw, _NONE
                    )
            except jaxmap.UnsupportedMap:
                pass
        from ..ops.profiler import dispatch_profiler

        with dispatch_profiler().dispatch(
            "crush", backend="cpu"
        ) as dp:
            dp.set_ops(1)
            dp.set_stripes(n)
            dp.add_bytes_in(pps.nbytes)
            raw = np.full((n, pool.size), _NONE, dtype=np.int64)
            for i in range(n):
                row = osdmap.crush.do_rule(
                    ruleno, int(pps[i]), pool.size, osdmap.osd_weight
                )
                raw[i, : len(row)] = row
            return raw

    def _upmap_stage(self, osdmap, pool, ps, raw):
        """Sparse dict overrides — handled per-affected-row."""
        if not osdmap.pg_upmap and not osdmap.pg_upmap_items:
            return raw
        seeds = _stable_mod_vec(ps, pool.pg_num, pool.pg_num_mask)
        affected = {}
        for (pid, seed), v in osdmap.pg_upmap.items():
            if pid == pool.pool_id:
                affected[seed] = True
        for (pid, seed), v in osdmap.pg_upmap_items.items():
            if pid == pool.pool_id:
                affected[seed] = True
        if not affected:
            return raw
        seed_to_rows: dict[int, list[int]] = {}
        for row, s in enumerate(seeds):
            if int(s) in affected:
                seed_to_rows.setdefault(int(s), []).append(row)
        for seed, rows in seed_to_rows.items():
            for row in rows:
                fixed = osdmap._apply_upmap(
                    pool, int(ps[row]), [int(o) for o in raw[row] if o != _NONE]
                    if pool.can_shift_osds()
                    else [int(o) for o in raw[row]],
                )
                out = np.full(raw.shape[1], _NONE, dtype=np.int64)
                out[: len(fixed)] = fixed
                raw[row] = out
        return raw

    @staticmethod
    def _primary_vec(up: np.ndarray) -> np.ndarray:
        """First non-NONE per row, -1 if none (OSDMap::_pick_primary)."""
        valid = up != _NONE
        first = np.argmax(valid, axis=1)
        has = valid.any(axis=1)
        return np.where(has, up[np.arange(len(up)), first], -1)

    def _affinity_stage(self, osdmap, pool, pps, up, up_primary):
        """Vectorized _apply_primary_affinity (OSDMap.cc:2540-2590)."""
        aff = osdmap.osd_primary_affinity
        if aff is None:
            return up, up_primary
        affv = np.zeros(osdmap.max_osd + 1, dtype=np.int64)
        affv[:-1] = np.asarray(aff, dtype=np.int64)
        idx = np.clip(up, 0, osdmap.max_osd)
        valid = (up != _NONE) & (up >= 0) & (up < osdmap.max_osd)
        a = np.where(valid, affv[idx], CEPH_OSD_DEFAULT_PRIMARY_AFFINITY)
        rows_any = (
            valid & (a != CEPH_OSD_DEFAULT_PRIMARY_AFFINITY)
        ).any(axis=1)
        if not rows_any.any():
            return up, up_primary
        draws = (
            crush_hash32_2(
                np.broadcast_to(
                    pps[:, None].astype(np.uint32), up.shape
                ).copy(),
                np.where(valid, up, 0).astype(np.uint32),
            ).astype(np.int64)
            >> 16
        )
        rejected = (a < CEPH_OSD_MAX_PRIMARY_AFFINITY) & (draws >= a)
        # accepted slot: first valid & ~rejected; fallback: first valid
        accept = valid & ~rejected
        pos_acc = np.argmax(accept, axis=1)
        has_acc = accept.any(axis=1)
        pos_fb = np.argmax(valid, axis=1)
        has_fb = valid.any(axis=1)
        pos = np.where(has_acc, pos_acc, pos_fb)
        has = has_acc | has_fb
        apply = rows_any & has
        rowix = np.arange(len(up))
        new_primary = np.where(apply, up[rowix, pos], up_primary)
        if pool.can_shift_osds():
            # rotate the chosen primary to the front of each applied row
            up = up.copy()
            for row in np.nonzero(apply & (pos > 0))[0]:
                p = pos[row]
                up[row, 1 : p + 1] = up[row, :p]
                up[row, 0] = new_primary[row]
        return up, new_primary

    def _temp_stage(self, osdmap, pool, acting, acting_primary):
        """pg_temp / primary_temp sparse overrides (scalar per entry)."""
        for (pid, seed), temps in osdmap.pg_temp.items():
            if pid != pool.pool_id or seed >= pool.pg_num:
                continue
            t, tp = osdmap._get_temp_osds(pool, seed)
            if t:
                row = np.full(acting.shape[1], _NONE, dtype=np.int64)
                row[: len(t)] = t
                acting[seed] = row
                acting_primary[seed] = tp
        for (pid, seed), tp in osdmap.primary_temp.items():
            if pid != pool.pool_id or seed >= pool.pg_num:
                continue
            acting_primary[seed] = tp

    # -- queries (OSDMapMapping consumer API) ------------------------------
    def get(self, pool_id: int, ps: int):
        """(up, up_primary, acting, acting_primary) for one PG."""
        up = [int(o) for o in self.up[pool_id][ps]]
        acting = [int(o) for o in self.acting[pool_id][ps]]
        while up and up[-1] == _NONE:
            up.pop()
        while acting and acting[-1] == _NONE:
            acting.pop()
        return (
            up,
            int(self.up_primary[pool_id][ps]),
            acting,
            int(self.acting_primary[pool_id][ps]),
        )


def _compiled(crush_map):
    """Per-CrushMap compiled-array cache, invalidated on mutation.

    Keyed on ``CrushMap.mutation`` (bumped by every builder mutator /
    ``touch()``) so editing the map after a batched mapping pass
    recompiles the dense arrays instead of silently reusing stale
    topology/weights."""
    gen = getattr(crush_map, "mutation", 0)
    cached = getattr(crush_map, "_jax_compiled", None)
    if cached is None or cached[0] != gen:
        from ..crush import jaxmap

        cached = (gen, jaxmap.compile_map(crush_map))
        crush_map._jax_compiled = cached
    return cached[1]
