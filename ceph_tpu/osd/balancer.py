"""Upmap balancer — OSDMap::calc_pg_upmaps re-designed over the batched
mapper (src/osd/OSDMap.cc:4638-5000, the mgr balancer's upmap mode).

The reference walks every PG through the scalar mapping pipeline and
iteratively generates pg_upmap_items entries that move PGs from
overfull to underfull OSDs (try_pg_upmap/try_remap_rule re-run the
CRUSH rule per candidate).  Here the full PG→OSD table comes from one
batched device call per pool (OSDMapMapping), deviations are vectorized
numpy, and candidate remaps are validated against the exact oracle
before being committed — failure-domain separation is enforced by
requiring the replacement OSD's domain ancestor to differ from every
other shard's.
"""

from __future__ import annotations

import numpy as np

from ..crush.types import CRUSH_ITEM_NONE
from .mapping import OSDMapMapping
from .osdmap import OSDMap, PgPool


def _parent_map(crush) -> dict[int, int]:
    """item -> containing bucket id."""
    parents: dict[int, int] = {}
    for b in crush.buckets.values():
        for item in b.items:
            parents[item] = b.id
    return parents


def _domain_of(parents, crush, osd: int, domain_type: int) -> int:
    """Ancestor of ``osd`` at ``domain_type`` (osd itself for type 0)."""
    if domain_type == 0:
        return osd
    node = osd
    while node in parents:
        node = parents[node]
        b = crush.buckets.get(node)
        if b is not None and b.type == domain_type:
            return node
    return osd  # no ancestor of that type: degenerate flat map


def _rule_domain_type(crush, ruleno: int) -> int:
    """The failure-domain type of the rule's choose step (arg2 of the
    first CHOOSE/CHOOSELEAF step)."""
    from ..crush.types import (
        CRUSH_RULE_CHOOSELEAF_FIRSTN,
        CRUSH_RULE_CHOOSELEAF_INDEP,
        CRUSH_RULE_CHOOSE_FIRSTN,
        CRUSH_RULE_CHOOSE_INDEP,
    )

    rule = crush.rules[ruleno]
    for step in rule.steps:
        if step.op in (
            CRUSH_RULE_CHOOSELEAF_FIRSTN,
            CRUSH_RULE_CHOOSELEAF_INDEP,
            CRUSH_RULE_CHOOSE_FIRSTN,
            CRUSH_RULE_CHOOSE_INDEP,
        ):
            return step.arg2
    return 0


def _subtree_osd_weights(crush, root: int) -> dict[int, float]:
    """Leaf crush weights (float) under a bucket — the
    get_rule_weight_osd_map role."""
    out: dict[int, float] = {}

    def walk(item: int, weight_16_16: int):
        if item >= 0:
            out[item] = out.get(item, 0.0) + weight_16_16 / 0x10000
            return
        b = crush.buckets.get(item)
        if b is None:
            return
        for child, w in zip(b.items, b.item_weights):
            walk(child, w)

    walk(root, 0)
    return out


def _rule_root(crush, ruleno: int) -> int:
    from ..crush.types import CRUSH_RULE_TAKE

    for step in crush.rules[ruleno].steps:
        if step.op == CRUSH_RULE_TAKE:
            return step.arg1
    raise ValueError(f"rule {ruleno} has no TAKE step")


def calc_pg_upmaps(
    osdmap: OSDMap,
    max_deviation: int = 1,
    max_changes: int = 10,
    only_pools: set[int] | None = None,
) -> int:
    """Generate pg_upmap_items entries into ``osdmap``; returns the
    number of PG remaps applied (OSDMap::calc_pg_upmaps contract:
    max_deviation floors at 1; stops at ``max_changes`` or when every
    OSD is within max_deviation of its weight-proportional target)."""
    max_deviation = max(max_deviation, 1)
    pools = {
        pid: pool
        for pid, pool in osdmap.pools.items()
        if not only_pools or pid in only_pools
    }
    if not pools:
        return 0

    mapping = OSDMapMapping()
    mapping.update(osdmap)

    # per-OSD PG sets and weight-proportional targets
    pgs_by_osd: dict[int, set] = {}
    osd_weight: dict[int, float] = {}
    total_pgs = 0
    domain_type_by_pool: dict[int, int] = {}
    for pid, pool in pools.items():
        ruleno = osdmap.crush.find_rule(
            pool.crush_rule, pool.type, pool.size
        )
        if ruleno < 0:
            continue
        domain_type_by_pool[pid] = _rule_domain_type(osdmap.crush, ruleno)
        root = _rule_root(osdmap.crush, ruleno)
        for osd, w in _subtree_osd_weights(osdmap.crush, root).items():
            reweight = (
                osdmap.osd_weight[osd] / 0x10000
                if 0 <= osd < osdmap.max_osd
                else 0.0
            )
            if w * reweight > 0:
                osd_weight[osd] = osd_weight.get(osd, 0.0) + w * reweight
        up = mapping.up[pid]
        for ps in range(pool.pg_num):
            for osd in up[ps]:
                if osd != CRUSH_ITEM_NONE:
                    pgs_by_osd.setdefault(int(osd), set()).add((pid, ps))
        total_pgs += pool.size * pool.pg_num
    weight_total = sum(osd_weight.values())
    if weight_total == 0:
        return 0
    for osd in osd_weight:
        pgs_by_osd.setdefault(osd, set())

    parents = _parent_map(osdmap.crush)

    def deviation(osd: int) -> float:
        target = total_pgs * osd_weight.get(osd, 0.0) / weight_total
        return len(pgs_by_osd.get(osd, ())) - target

    num_changed = 0
    for _ in range(max_changes * 4):  # bounded retry budget
        if num_changed >= max_changes:
            break
        plateau = False
        overfull = sorted(
            (o for o in pgs_by_osd if deviation(o) > max_deviation),
            key=deviation,
            reverse=True,
        )
        if not overfull:
            # plateau break (the role of the reference's randomized
            # retries): integer counts cannot hit fractional targets,
            # so an OSD can strand below -max_deviation while every
            # donor sits at dev <= max_deviation.  In plateau mode
            # ONLY stranded, reachable OSDs receive (otherwise moves
            # churn between healthy OSDs forever) and any
            # above-target OSD donates — a donor at dev > 0 lands at
            # dev - 1 > -max_deviation, so it can never itself become
            # stranded (no ping-pong, guaranteed progress).
            stranded = [
                o
                for o in osd_weight
                if deviation(o) < -max_deviation
                and osdmap.is_up(o)
                and 0 <= o < osdmap.max_osd
                and osdmap.osd_weight[o] > 0
            ]
            if stranded:
                plateau = True
                overfull = sorted(
                    (o for o in pgs_by_osd if deviation(o) > 0.0001),
                    key=deviation,
                    reverse=True,
                )
        if not overfull:
            break
        moved = False
        for src in overfull:
            underfull = sorted(
                (
                    o
                    for o in osd_weight
                    if deviation(o)
                    < (-max_deviation if plateau else -0.0001)
                ),
                key=deviation,
            )
            if not underfull:
                break
            for pid, ps in sorted(pgs_by_osd[src]):
                dtype = domain_type_by_pool.get(pid, 0)
                up = [int(o) for o in mapping.up[pid][ps] if o != CRUSH_ITEM_NONE]
                other_domains = {
                    _domain_of(parents, osdmap.crush, o, dtype)
                    for o in up
                    if o != src
                }
                dst = next(
                    (
                        c
                        for c in underfull
                        if osdmap.is_up(c)
                        and osdmap.osd_weight[c] > 0
                        and _domain_of(parents, osdmap.crush, c, dtype)
                        not in other_domains
                    ),
                    None,
                )
                if dst is None:
                    continue
                pg = (pid, ps)
                items = list(osdmap.pg_upmap_items.get(pg, []))
                items.append((src, dst))
                osdmap.pg_upmap_items[pg] = items
                # validate against the exact pipeline; roll back if the
                # remap didn't take effect as intended
                new_up, _, _, _ = osdmap.pg_to_up_acting_osds(pid, ps)
                if src in new_up or dst not in new_up:
                    if len(items) == 1:
                        del osdmap.pg_upmap_items[pg]
                    else:
                        osdmap.pg_upmap_items[pg] = items[:-1]
                    continue
                # commit: adjust the cached table + counts
                row = mapping.up[pid][ps]
                row[row == src] = dst
                pgs_by_osd[src].discard(pg)
                pgs_by_osd.setdefault(dst, set()).add(pg)
                num_changed += 1
                moved = True
                break
            if moved:
                break
        if not moved:
            break
    return num_changed
