"""Erasure-coded PGs under the OSD daemon — the backend half of
build_pg_backend's ERASURE branch (src/osd/PGBackend.cc:571-607,
src/osd/ECBackend.cc).

The daemon mounts the ECStore machinery (store/ec_store.py) as a
per-PG *view*: position p of the acting set maps to

- the daemon's own ObjectStore when this OSD holds position p,
- a RemoteStore proxy (MECSubRead/MECSubWrite over the messenger)
  when a live peer holds it — so gather/decode/minimum-repair reads,
  including CLAY fractional-chunk recovery reads, travel as real
  sub-op messages exactly like MOSDECSubOpRead
  (ECBackend.cc:1010 handle_sub_read), and
- an UnreachableStore when the position is a CRUSH_ITEM_NONE hole or
  the peer is down — every access raises StoreError, which is
  precisely how ECStore's degraded-read/reconstruct paths expect a
  missing shard to present.

Writes do NOT go through this view: the primary encodes the object,
builds one per-position transaction (shard bytes + HashInfo + pg_log
entry + pg_info riding atomically) and fans them out as MOSDRepOp —
the same logged-replication path replicated pools use, which is what
keeps ONE peering/recovery machinery for both pool types
(ECBackend::submit_transaction under PrimaryLogPG, ECBackend.cc:1502).
"""

from __future__ import annotations

import json

import numpy as np

from ..ec import ErasureCodeProfile, registry_instance
from ..ec.stripe import (
    HashInfo,
    StripeInfo,
    rmw_encode,
)
from ..store.objectstore import ObjectStore, StoreError, Transaction
from ..store.ec_store import HINFO_KEY

DEFAULT_STRIPE_UNIT = 4096  # osd_pool_erasure_code_stripe_unit role


class UnreachableStore(ObjectStore):
    """A shard position with nobody behind it (down OSD or
    CRUSH_ITEM_NONE hole): every access fails like a dead peer."""

    residency_local = False

    def _fail(self, *_a, **_kw):
        raise StoreError("shard unreachable (down or hole)")

    queue_transaction = _fail
    read = _fail
    getattr = _fail
    stat = _fail
    exists = _fail
    list_objects = _fail
    list_collections = _fail
    list_attrs = _fail
    omap_get = _fail
    omap_get_vals = _fail


class ECCodec:
    """One pool profile's codec + stripe geometry, cached per profile
    by the daemon (the ErasureCodePluginRegistry::factory product the
    reference hangs off the pool, PGBackend.cc:588)."""

    def __init__(self, profile: dict[str, str]):
        plugin = profile.get("plugin", "jerasure")
        prof = ErasureCodeProfile(
            {k: v for k, v in profile.items() if k != "plugin"}
        )
        self.ec = registry_instance().factory(plugin, prof)
        self.k = self.ec.get_data_chunk_count()
        self.n = self.ec.get_chunk_count()
        chunk = self.ec.get_chunk_size(self.k * DEFAULT_STRIPE_UNIT)
        self.sinfo = StripeInfo(self.k, self.k * chunk)

    def encode_object(
        self, data: bytes
    ) -> tuple[dict[int, bytes], dict]:
        """Full-object encode: pad to stripe multiples, run the stripe
        seam, compute per-shard HashInfo.  Returns ({pos: shard_bytes},
        meta) with meta in the shard-xattr JSON shape ECStore reads.
        ONE implementation serves both paths: this is the
        single-element case of the batch (encode_batch runs a
        1-element batch through the same per-buffer encode)."""
        return self.encode_object_batch([data])[0]

    def encode_object_batch(
        self, datas
    ) -> list[tuple[dict[int, bytes], dict]]:
        """Batched :meth:`encode_object`: every queued payload's
        stripes ride ONE pipelined device pass (the write-coalescing
        seam — ec/stripe.encode_batch with async double-buffered
        transfers underneath), byte-identical to per-object encodes.
        Returns one ({pos: shard_bytes}, meta) per payload, in
        order."""
        from ..ec.stripe import encode_batch

        padded = []
        for data in datas:
            logical = len(data)
            plen = self.sinfo.logical_to_next_stripe_offset(logical)
            padded.append(bytes(data) + b"\0" * (plen - logical))
        shard_sets = encode_batch(self.sinfo, self.ec, padded)
        out: list[tuple[dict[int, bytes], dict]] = []
        for data, shards in zip(datas, shard_sets):
            if not shards:  # zero-length object: n empty shards
                shards = {
                    i: np.zeros(0, dtype=np.uint8)
                    for i in range(self.n)
                }
            hinfo = HashInfo(self.n)
            hinfo.append(0, shards)
            meta = {
                "size": len(data),
                "hashes": hinfo.cumulative_shard_hashes,
            }
            out.append(
                ({i: bytes(shards[i]) for i in range(self.n)}, meta)
            )
        return out

    def decode_object_batch(self, shard_sets, want) -> list[dict]:
        """Batched decode-from-survivors (the repair-side twin of
        :meth:`encode_object_batch`, ROADMAP open item 2): rebuild
        the SAME missing positions for many objects in one coalesced
        device dispatch.  ``shard_sets`` holds one survivor dict per
        object ({position: bytes | DeviceBuf}); returns one
        {position: payload} per object, device-born DeviceBufs when
        the device path ran.  Byte-identical to per-object decode and
        degrades to it on any batched-path failure
        (ec/stripe.decode_batch)."""
        from ..ec.stripe import decode_batch

        return decode_batch(self.sinfo, self.ec, shard_sets, want)


def rmw_write_txns(
    codec: ECCodec,
    ecs,
    cid: str,
    oid: str,
    offset: int,
    data: bytes,
    positions,
    old_size: int,
) -> dict[int, "Transaction"]:
    """Stripe-granular partial overwrite for the daemon's EC write
    path (start_rmw, src/osd/ECBackend.cc:1858): read ONLY the
    partially-covered head/tail stripes that hold pre-existing bytes
    (through ``ecs`` — the per-PG store view, so degraded stripes
    reconstruct over real sub-op reads), re-encode just the covered
    stripe range, and return one RANGE transaction per position (shard
    bytes at the range's chunk offset + updated HashInfo) to ride the
    MOSDRepOp logged-replication path.

    Only ``(end-first)`` stripes' worth of shard bytes travel to each
    replica — a 4KB overwrite of a multi-MB object ships ~one chunk
    per shard, not the whole re-encoded object.  Matching the
    reference's ec_overwrites semantics, the cumulative HashInfo is
    invalidated (no "hashes" key): scrub falls back to the re-encode
    consistency check."""
    data = bytes(data)
    sinfo = codec.sinfo
    cs = sinfo.chunk_size
    first, _end, _buf, shards = rmw_encode(
        sinfo, codec.ec, offset, data, old_size,
        lambda stripes: ecs.read_stripes(oid, stripes),
    )
    meta = {"size": max(old_size, offset + len(data))}
    blob = json.dumps(meta).encode()
    txns: dict[int, Transaction] = {}
    for pos in positions:
        txn = Transaction()
        # touch first: the txn must apply unconditionally on a lagging
        # replica that does not hold the object yet
        txn.touch(cid, oid)
        txn.write(cid, oid, first * cs, bytes(shards[pos]))
        txn.setattr(cid, oid, HINFO_KEY, blob)
        txns[pos] = txn
    return txns


def shard_write_txn(
    cid: str,
    oid: str,
    shard: bytes,
    meta: dict,
    attrs: dict[str, bytes] | None = None,
) -> Transaction:
    """One position's full-shard write as an unconditional transaction
    (touch+truncate replaces remove-if-exists so the SAME op list
    applies on a replica that may not hold the object yet)."""
    txn = Transaction()
    txn.touch(cid, oid)
    txn.truncate(cid, oid, 0)
    if shard:
        txn.write(cid, oid, 0, shard)
    txn.setattr(cid, oid, HINFO_KEY, json.dumps(meta).encode())
    for name, value in (attrs or {}).items():
        txn.setattr(cid, oid, name, value)
    return txn
