"""OSD daemon — boot, map subscription, per-PG peering, replicated
I/O, log-based recovery, heartbeats (src/osd/OSD.cc, PeeringState.cc,
PrimaryLogPG.cc — the daemon core VERDICT §2.4 called out).

Shape vs the reference:

- Boot: bind the messenger, connect the MonClient, announce with
  MOSDBoot; the monitor marks the OSD up and a new map epoch arrives
  by subscription (OSD::start_boot → _send_boot).
- Dispatch: the messenger read loop enqueues ops onto a worker queue
  (the op_shardedwq role, OSD.cc:9612 enqueue_op) — nested sub-op
  RPC must never run on the loop thread.  Pure-answer messages
  (MPGQuery/MPGLogReq/MPGPull/MOSDRepOp) are served inline.
- PGs: every map epoch, the worker walks pool PGs, instantiates the
  ones this OSD serves, and runs the peering sequence on primaries:
  GetInfo (MPGQuery → MPGNotify), choose the authoritative log
  (find_best_info), GetLog (MPGLogReq), pull objects the primary
  itself is missing (MPGPull), push each peer's missing objects
  (MPGPush), then activate (MPGActivate carrying the log suffix) —
  the Initial→GetInfo→GetLog→GetMissing→Active walk of
  PeeringState.cc collapsed to one deterministic worker pass.
- I/O: client MOSDOp on the primary appends a pg_log entry and
  applies ONE transaction locally carrying data + log entry + info,
  then fans the same transaction out as MOSDRepOp (sub_op_modify:
  data and log ride one atomic apply).  Reads serve locally.
- Persistence: log entries and pg info live in the PG's collection
  (entries as ``_log/`` objects, info as an xattr on ``_pgmeta_``),
  so a restarted OSD reloads its PGs from the store and rejoins with
  honest history (load_pgs).
- Failure detection: a tick thread pings peers (MOSDPing role) and
  files mon failure reports after the grace window; the monitor's
  distinct-reporter threshold marks OSDs down, the epoch bumps, and
  primaries re-peer (OSD.cc:5235 handle_osd_ping / :5889
  send_failures).

Both pool types run through this one daemon — ONE peering/pg_log/
failover/recovery machinery with two backends, the reference's
build_pg_backend split (src/osd/PGBackend.cc:571-607):

- Replicated pools ship the SAME transaction to every acting OSD.
- Erasure pools (osd/ec_pg.py) encode the object and ship a DIFFERENT
  per-position transaction (shard bytes + HashInfo + log entry + info)
  down the same MOSDRepOp path (ECBackend::submit_transaction under
  PrimaryLogPG, ECBackend.cc:1502).  Reads and recovery mount the
  ECStore machinery over RemoteStore proxies so reconstruction and
  minimum-repair (CLAY fractional) reads travel as MECSubRead sub-ops
  (handle_sub_read, ECBackend.cc:1010); recovery pushes carry
  reconstructed shard bytes (objects_read_and_reconstruct,
  ECBackend.cc:2364).
"""

from __future__ import annotations

import concurrent.futures
import itertools
import json
import threading
import time
import types
from collections import deque

from ..common.encoding import Decoder, Encoder
from ..crush.types import CRUSH_ITEM_NONE
from ..ec.interface import ErasureCodeError
from ..msg import (
    MECSubRead,
    MECSubWrite,
    Message,
    MessageError,
    Messenger,
    MOSDOp,
    MOSDOpReply,
    MOSDRepOp,
    MOSDRepOpReply,
    MPGActivate,
    MPGLogReply,
    MPGLogReq,
    MPGNotify,
    MPGPull,
    MPGPush,
    MPGPushReply,
    MPGQuery,
    MPing,
    MRepScrub,
    MScrubCommand,
    MScrubMap,
)
from dataclasses import dataclass, field as dc_field

from ..common import tracing
from ..common.histogram import LogHistogram, PerfHistogram2D
from ..common.op_tracker import sanitize_class
from ..common.perf_counters import PerfCountersBuilder
from ..common.throttle import Throttle
from .scheduler import (
    CLASS_BACKGROUND,
    CLASS_CLIENT,
    CLASS_RECOVERY,
    CLASS_STRICT,
    MClockQueue,
    WeightedPriorityQueue,
)
from ..msg.message import (
    BACKOFF_OP_BLOCK,
    BACKOFF_OP_UNBLOCK,
    MCommand,
    MOSDBackoff,
    MRecoveryReserve,
    MMgrReport,
    MPGStats,
    OSD_FLAG_FULL_TRY,
    OSD_OP_APPEND,
    OSD_OP_CALL,
    OSD_OP_DELETE,
    OSD_OP_GETXATTR,
    OSD_OP_LIST,
    OSD_OP_NOTIFY,
    OSD_OP_OMAPCLEAR,
    OSD_OP_OMAPGET,
    OSD_OP_OMAPRM,
    OSD_OP_OMAPSET,
    OSD_OP_READ,
    OSD_OP_SETXATTR,
    OSD_OP_STAT,
    OSD_OP_UNWATCH,
    OSD_OP_WATCH,
    OSD_OP_WRITE,
    OSD_OP_WRITEFULL,
    MWatchNotify,
    MWatchNotifyAck,
)
from ..msg.messenger import Connection, Dispatcher
from ..cls import RD as CLS_RD, WR as CLS_WR, ClassError, MethodContext, default_handler
from ..common import crash as crash_util
from ..common.log import dout
from ..common.log_client import LogClient
from ..common import lockdep
from ..mon.monitor import MonClient
from ..store.ec_store import ECStore, HINFO_KEY
from ..store.objectstore import MemStore, ObjectStore, StoreError, Transaction
from ..store.remote import RemoteStore, ShardServer
from .ec_pg import (
    ECCodec,
    UnreachableStore,
    rmw_write_txns,
    shard_write_txn,
)
from .failure import HeartbeatTracker
from .scrub import ScrubStore, Scrubber, build_scrub_map
from .pg_log import (
    DELETE,
    EV_ZERO,
    MODIFY,
    LogEntry,
    PGInfo,
    PGLog,
    find_best_info,
    needs_backfill,
)

PG_META = "_pgmeta_"
LOG_PREFIX = "_log/"
OBJ_PREFIX = "o_"
# cache-tier object state attr (object_info_t dirty flag role): set
# by every client mutation on a writeback cache pool, cleared (value
# b"0") after the agent flushes the object to the base pool
TIER_DIRTY = "t_dirty"
INFO_ATTR = "pginfo"
# snapshots: clones are stored as "<OBJ_PREFIX><oid>@<snapid>" (the
# clone-object naming of hobject_t snaps); "@" is reserved in oids.
# "sn_born" records the pool snap_seq at object creation so reads at
# snaps older than the object's birth resolve to -ENOENT.
BORN_ATTR = "sn_born"


def _log_oid(version: tuple[int, int]) -> str:
    return f"{LOG_PREFIX}{version[0]:010d}.{version[1]:020d}"


def _interval_json(interval: tuple) -> list:
    """The (acting, primary) interval in its JSON round-trip shape
    (the watermark comparison must survive tuple→list decoding)."""
    return [list(interval[0]), interval[1]]


def _encode_entry(entry: LogEntry) -> bytes:
    e = Encoder()
    entry.encode(e)
    return e.getvalue()


def _decode_entry(blob: bytes) -> LogEntry:
    return LogEntry.decode(Decoder(blob))


def _encode_info(info: PGInfo) -> bytes:
    e = Encoder()
    info.encode(e)
    return e.getvalue()


def _decode_info(blob: bytes) -> PGInfo:
    return PGInfo.decode(Decoder(blob))


class PG:
    """One placement group's local state (PG/PeeringState role)."""

    def __init__(self, pgid: str, pool_id: int):
        self.pgid = pgid
        self.pool_id = pool_id
        self.cid = f"pg_{pgid}"
        self.log = PGLog()
        self.info = PGInfo(pgid=pgid)
        self.state = "initial"  # initial|peering|active|replica|stray
        self.acting: list[int] = []
        self.primary: int = -1
        self.seq = 0  # op counter feeding eversions
        # epoch of the last MPGActivate applied here (0 = never in
        # this incarnation); replicas refuse rep-ops until activated
        self.activated_epoch = 0
        # the (acting, primary) interval last peered, so unrelated
        # epoch bumps don't trigger a re-peering RPC storm
        self.peered_interval: tuple | None = None
        # the interval last OBSERVED by the map walk (set whether or
        # not peering succeeded): interval-death detection compares
        # against this — comparing against peered_interval would
        # read every unpeered pass as a "change" and abort the very
        # RecoveryOp the previous pass just started
        self.current_interval: tuple | None = None
        # recently applied client reqids → (version, outdata) (the
        # pg log dups role): outlives trimmed entries so a late retry
        # still dedups AND replays its original result
        self.reqid_cache: dict[str, tuple] = {}
        # objects THIS osd (as primary) adopted log entries for but
        # could not pull yet (the primary's own missing set,
        # PeeringState::needs_recovery role): the stale local copy is
        # dropped on the failed pull, and the peering pass retries
        # until the hole closes — the interval stays unpeered
        self.self_missing: dict[str, tuple] = {}
        # erasure pools: cached (key, ECStore, conns) view over the
        # acting set; rebuilt when the interval/up-set/conns change
        self.ec_view: tuple | None = None
        # True while every repop since the last successful peering
        # committed on every live replica: the EC stripe-range RMW
        # path requires it (a range write applied over a stale shard
        # would corrupt it silently; the full-shard txn it replaces
        # converged lagging replicas by construction).  Any
        # primary-visible replica failure clears it until re-peering
        # pushes the divergent objects.
        self.repop_clean = False
        # scrub scheduling state (PG::ScrubberPasskey stamps,
        # src/osd/PG.h:231-240): last completed stamps + findings
        # (the findings also persist in the ScrubStore omap)
        self.last_scrub = 0.0
        self.last_deep_scrub = 0.0
        self.scrub_errors: list[dict] = []
        # deep-scrub omap-cardinality findings (LARGE_OMAP_OBJECTS):
        # object names whose omap key count crossed the threshold at
        # the last deep scrub; only a deep scrub re-judges them
        self.large_omap: list[str] = []


@dataclass
class _RecoveryOp:
    """One peer's in-flight async recovery (RecoveryOp,
    src/osd/ECBackend.h:249 reduced): push items drain through the
    scheduler; the last one activates the peer and releases both
    reservations.

    ``interval`` pins the (acting, primary) this op was planned
    against — the generation check every push re-validates, so an
    interval death mid-recovery aborts the remaining pushes instead
    of landing stale shards on a peer whose position moved.
    ``versions`` records the exact version each push carries and
    ``pushed`` the completed ones — the persisted backfill watermark,
    so an interrupted recovery resumes without re-pushing."""

    pg: "PG"
    epoch: int
    osd: int
    since: tuple
    conn: Connection
    remaining: set
    interval: tuple = ()
    versions: dict = dc_field(default_factory=dict)
    pushed: dict = dc_field(default_factory=dict)
    failed: bool = False


def build_osd_perf(whoami: int):
    """The OSD's counter schema (the l_osd_* declaration block,
    OSD.cc:9681) — module-level so tools/check_metrics.py can lint
    it without constructing a daemon."""
    return (
        PerfCountersBuilder(f"osd.{whoami}")
        .add_u64_counter("op", "client ops")
        .add_u64_counter("op_r", "client reads")
        .add_u64_counter("op_w", "client mutations")
        .add_time_avg("op_latency", "client op latency")
        .add_u64_gauge("numpg", "hosted pgs")
        .add_u64_gauge("recovery_active", "in-flight recovery pushes")
        # recovery-storm plane (the l_osd_recovery_* block,
        # ROADMAP open item 2): push/byte totals, coalesced
        # decode-from-survivors batches, and the survivor-read
        # fan-in the LRC locality claim is measured from
        .add_u64_counter("recovery_pushes", "recovery pushes completed")
        .add_u64_counter(
            "recovery_push_bytes", "object bytes pushed by recovery"
        )
        .add_u64_counter(
            "recovery_batches",
            "coalesced decode-from-survivors rebuild dispatches",
        )
        .add_u64_counter(
            "recovery_batch_ops",
            "recovery pushes served from coalesced rebuilds",
        )
        .add_u64_counter(
            "recovery_survivor_shards",
            "helper shards consulted to rebuild pushed objects "
            "(the recovery-read fan-in)",
        )
        .add_u64_counter(
            "recovery_helper_bytes",
            "helper shard bytes read to rebuild pushed objects",
        )
        .add_u64_counter("tier_flush", "cache-tier agent flushes")
        .add_u64_counter("tier_evict", "cache-tier agent evictions")
        .add_u64_gauge(
            "slow_ops", "in-flight ops past the complaint time"
        )
        # scrub plane (the l_osd_scrub* block): errors is the live
        # inconsistency count across this OSD's primary PGs, chunks/
        # deep_bytes are progress counters, last_age the staleness of
        # the oldest primary PG's scrub stamp
        .add_u64_gauge("scrub_errors", "open scrub inconsistencies")
        .add_u64_gauge("scrubs_active", "scrubs in flight")
        .add_u64_counter("scrub_chunks", "scrub chunks processed")
        .add_u64_counter(
            "scrub_deep_bytes", "object bytes deep-scrubbed"
        )
        .add_u64_gauge(
            "scrub_last_age",
            "seconds since the stalest primary pg was scrubbed",
        )
        # fullness plane (the l_osd stat_bytes family): the same
        # numbers the stat reports carry to the mon
        .add_u64_gauge("stat_bytes", "store capacity bytes")
        .add_u64_gauge("stat_bytes_used", "store bytes used")
        .add_u64_gauge("stat_bytes_avail", "store bytes available")
        .add_u64_gauge(
            "backoffs_active", "client backoffs currently blocked"
        )
        .create_perf_counters()
    )


class OSD(Dispatcher):
    def __init__(
        self,
        whoami: int,
        store: ObjectStore | None = None,
        tick_interval: float = 0.5,
        heartbeat_grace: float = 2.0,
        scrub_interval: float = 0.0,
        deep_scrub_interval: float | None = None,
        osd_max_scrubs: int | None = None,
        scrub_auto_repair: bool | None = None,
        max_backfills: int = 2,
        admin_socket_path: str | None = None,
        client_message_cap: int = 256 << 20,
        op_queue: str = "wpq",
        qos_profiles: dict | None = None,
        shared_services: bool | None = None,
        wal_dir: str | None = None,
    ):
        """``scrub_interval`` > 0 arms tick-driven scrub scheduling
        (osd_scrub_min_interval); ``deep_scrub_interval`` spaces the
        payload-checksum passes (osd_deep_scrub_interval — None makes
        every scheduled scrub deep); ``osd_max_scrubs`` caps
        concurrent scrubs on BOTH sides of the scrub reservation
        handshake; ``scrub_auto_repair`` overrides the
        osd_scrub_auto_repair config; ``max_backfills`` caps
        concurrent per-(pg, peer) recoveries on BOTH sides of the
        reservation protocol (osd_max_backfills) — individual pushes
        serialize through the op scheduler's RECOVERY class.

        ``shared_services`` (default CEPH_TPU_SHARED_SERVICES, off)
        moves this daemon's worker/tick/mgr-report threads onto the
        shared NetworkStack (a serial strand for the op queue, stack
        timers for the periodic loops): per-daemon thread cost drops
        to ZERO, which is what lets tests/scale.py run 100 OSDs in
        one process with a thread count independent of daemon
        count."""
        import os as _os

        self.whoami = whoami
        if shared_services is None:
            shared_services = (
                _os.environ.get("CEPH_TPU_SHARED_SERVICES", "0")
                == "1"
            )
        self.shared_services = bool(shared_services)
        self._service_timers: list = []
        self._op_strand = None
        self._workq_kicked = False
        self._workq_kick_lock = threading.Lock()
        self.store = store or MemStore()
        self.messenger = Messenger(f"osd.{whoami}")
        self.messenger.add_dispatcher(self)
        self.monc = MonClient(
            self.messenger, on_map=self._on_map, whoami=whoami
        )
        self.pgs: dict[str, PG] = {}
        self._pg_lock = lockdep.RMutex("osd.pg")
        # the op worker drains a QoS-classed scheduler, not a FIFO:
        # peering/map events are strict, client ops and background
        # work (scrub, splits) share by weight or by dmclock QoS
        # (osd_op_queue: wpq | mclock_scheduler)
        if op_queue in ("mclock", "mclock_scheduler"):
            self._workq = MClockQueue()
            # per-tenant QoS classes (the mclock client profiles):
            # {class: (reservation, weight, limit)} in cost-units/sec
            # — client ops naming a registered class schedule under
            # its triple; unknown classes fall back to CLASS_CLIENT
            for klass, triple in (qos_profiles or {}).items():
                self._workq.set_profile(klass, triple)
        elif op_queue == "wpq":
            self._workq = WeightedPriorityQueue()
            for klass, triple in (qos_profiles or {}).items():
                # wpq has no reservations: the profile's weight seat
                # (middle of the triple, or a bare number) applies
                w = triple[1] if isinstance(triple, (tuple, list)) else triple
                self._workq.set_weight(klass, int(w))
        else:
            raise ValueError(
                f"unknown op_queue {op_queue!r} (wpq | mclock)"
            )
        # client-message admission control (osd_client_message_size_
        # cap role): over-budget ops are bounced with -EAGAIN (the
        # objecter retries), so one firehose client cannot queue the
        # daemon into the ground
        self.client_throttle = Throttle(
            f"osd.{whoami}.client-bytes", client_message_cap
        )
        self._worker: threading.Thread | None = None
        self._ticker: threading.Thread | None = None
        self._stop = threading.Event()
        # osd id → (addr, lossless-peer SessionConnection)
        self._conns: dict[int, tuple] = {}
        self._conn_lock = lockdep.Mutex("osd.conn")
        self.hb = HeartbeatTracker(whoami, grace=heartbeat_grace)
        self.tick_interval = tick_interval
        # EC pool support: cached codecs per profile + a shard-serving
        # delegate answering MECSubRead/MECSubWrite from our store
        # (the handle_sub_read/handle_sub_write role)
        self._ec_codecs: dict[tuple, ECCodec] = {}
        # op tracking with span ids (TrackedOp/OpTracker + the
        # blkin/ZTracer seat): every client op registers under its
        # reqid; every sub-op carries that reqid as its trace, so
        # dump_historic_ops on two daemons correlates one op
        from ..common import AdminSocket, Config, OpTracker
        from ..common.config import ConfigError

        self.config = Config()
        try:
            self.config.parse_env()
        except ConfigError as e:
            # a stray CEPH_TPU_* env var must not kill the daemon
            dout("osd", 0, f"osd.{whoami}: ignoring bad env config: {e}")
        # WAL front (ROADMAP item 5): wrap the concrete store so
        # small writes ack at WAL append and adjacent commits share
        # one group barrier; commit_latency_ms then measures the new
        # ack point because _commit_and_replicate times
        # queue_transaction end-to-end
        self._own_wal = False
        if wal_dir is not None:
            from ..store.wal_store import WALStore

            self.store = WALStore(
                self.store,
                wal_dir,
                prefer_deferred_size=int(
                    self.config.get("wal_prefer_deferred_size")
                ),
                max_group_txc=int(
                    self.config.get("wal_max_group_txc")
                ),
                flush_interval_ms=float(
                    self.config.get("wal_flush_interval_ms")
                ),
                checkpoint_bytes=int(
                    self.config.get("wal_checkpoint_bytes")
                ),
            )
            self._own_wal = True
        self.op_tracker = OpTracker()
        # write coalescing (ROADMAP item 1): the worker drains up to
        # this many queued same-pool full-object writes per dispatch
        # and encodes them as ONE batched device call (1 disables)
        self.osd_tpu_batch_max = int(
            self.config.get("osd_tpu_batch_max")
        )
        # recovery coalescing (ROADMAP item 2): the worker drains up
        # to this many queued same-peer recovery pushes per dispatch
        # and rebuilds them as ONE batched decode-from-survivors
        # device call (1 disables)
        self.osd_recovery_batch_max = int(
            self.config.get("osd_recovery_batch_max")
        )
        # distributed tracing (common/tracing.py): per-stage spans
        # under the client reqid, drained onto the MMgrReport push
        self.tracer = tracing.Tracer(
            f"osd.{whoami}",
            max_spans=int(self.config.get("tracing_max_spans")),
        )
        self.admin = None
        if admin_socket_path:
            self.admin = AdminSocket(
                str(admin_socket_path), config=self.config
            )
            # the OSD's own grids merge into the admin-socket `perf
            # histogram dump` (deferred: the commit grid is built a
            # few lines below; the hook only runs at command time)
            self.op_tracker.register_admin_commands(
                self.admin,
                extra_histograms=lambda: {
                    "osd": self.whoami,
                    "commit_latency_histogram": (
                        self._commit_grid.dump()
                    ),
                },
            )
            self.tracer.register_admin_commands(self.admin)
            # fault plane: `ceph daemon osd.N fault set/clear/list`
            self.messenger.faults.register_admin_commands(self.admin)
            self.admin.register_command(
                "dump_backoffs",
                lambda args: self.dump_backoffs(),
                "dump client backoffs this OSD holds",
            )
            # device-dispatch flight recorder (ops/profiler.py): the
            # raw ring and the per-kind rollup — process-global, like
            # the kernel counters above
            self.admin.register_command(
                "dispatch history",
                lambda args: self._dispatch_history(args),
                "raw device-dispatch flight-recorder ring "
                "(kind=<k> limit=<n> filter)",
            )
            self.admin.register_command(
                "dispatch summary",
                lambda args: self._dispatch_summary(args),
                "per-kind device-dispatch rollup "
                "(time split, occupancy, residency)",
            )
            self.admin.start()
        self._shard_server = ShardServer(
            self.store, whoami,
            tracker=self.op_tracker, tracer=self.tracer,
        )
        # watch/notify (PrimaryLogPG watchers + Notify machinery):
        # watchers are in-memory per primary — clients re-register via
        # Objecter linger on every new interval (documented deviation
        # from the reference's object_info-persisted watch records)
        self._watchers: dict[tuple[str, str], dict[int, Connection]] = {}
        self._watch_lock = lockdep.Mutex("osd.watch")
        self._notify_seq = itertools.count(1)
        self._notify_pending: dict[int, dict] = {}
        # scrub + recovery throttling
        self.scrub_interval = scrub_interval
        self.deep_scrub_interval = deep_scrub_interval
        # None = follow the osd_max_scrubs config option
        self.osd_max_scrubs = osd_max_scrubs
        self.scrub_auto_repair = scrub_auto_repair
        self.max_backfills = max(1, max_backfills)
        self._recovery_active = 0
        self.recovery_active_peak = 0  # high-water mark (perf gauge)
        # daemon perf counters (l_osd_* role): pushed to the mgr as
        # MMgrReport on the tick (the DaemonServer stats plane)
        self.perf = build_osd_perf(whoami)
        # ObjectStore commit latency: the reference-shaped 2D
        # latency×size grid (src/common/perf_histogram.h, served by
        # `ceph tell osd.N perf histogram dump`) plus a 1D histogram
        # whose windowed mean feeds `ceph osd perf` commit_latency_ms
        self._commit_grid = PerfHistogram2D(
            name="op_w_latency_in_bytes_histogram"
        )
        self._commit_hist = LogHistogram()
        # (sum, count) at the last stat report — the delta gives the
        # mean commit latency over the report interval
        self._commit_last = (0.0, 0)
        if self.admin is not None:
            # `perf dump` over the admin socket serves the daemon's
            # counters AND the process-global device-kernel plane
            from ..ops.kernel_stats import kernel_stats

            self.admin.perf.add(self.perf)
            self.admin.perf.add(kernel_stats().perf)
            self.admin.perf.add(self.messenger.faults.perf)
        # SLOW_OPS watchdog state (osd_op_complaint_time): last count
        # reported to the mon + report throttle stamp
        self._slow_ops_last_report = 0.0
        self._slow_ops_reported = 0
        # cluster log (LogClient role): queued here, drained to the
        # mon as MLog on the tick
        self._log_client = LogClient(f"osd.{whoami}")
        self.clog = self._log_client.channel()
        # crash reports pending delivery to the mgr (piggybacked on
        # the next MMgrReport push).  Sends are fire-and-forget, so
        # one "successful" send proves nothing: each report rides
        # several pushes (the mgr dedupes by crash_id) before we let
        # go of our only copy
        self._pending_crashes: deque = deque(maxlen=16)
        self._crash_sends: dict[str, int] = {}
        self.CRASH_RESEND_COUNT = 3
        # how often to re-ask the mon who the active mgr is while
        # none is known (scale harnesses stretch it: it is O(n) mon
        # commands per interval across a big cluster)
        self.mgr_discovery_interval = 5.0
        self._mgr_addr: str | None = None
        self._mgr_conn = None
        self._mgr_addr_checked = 0.0
        self._splitting: set[str] = set()
        self._recovery_lock = lockdep.Mutex("osd.recovery")
        self._scrubbing: set[str] = set()
        self._tier_running: set[str] = set()
        # async recovery through the scheduler (VERDICT r4 ask #7):
        # in-flight per-(pg, peer) recovery ops, gated by a TWO-SIDED
        # reservation — the local reserver caps how many recoveries
        # this primary runs, the remote one caps how many push INTO
        # this OSD (osd_max_backfills both sides,
        # doc/dev/osd_internals/backfill_reservation.rst)
        self._recovering: dict[tuple[str, int], "_RecoveryOp"] = {}
        self._local_reservations: set[tuple[str, int]] = set()
        # remote slots are LEASES: key -> (granted_at, conn) — a
        # crashed/remapped primary that never releases must not leak
        # its slot forever (expired leases purge on the next request;
        # a reset connection drops its leases immediately)
        self._remote_reservations: dict[tuple[str, int], tuple] = {}
        self.reservation_timeout = 60.0
        self.log_keep = 128  # pg_log length bound (osd_min_pg_log_entries role)
        self.class_handler = default_handler  # ClassHandler role
        self.addr: tuple[str, int] | None = None
        # repop sub-op timeout (tests shrink it so chaos partitions
        # fail fast instead of wedging the worker for 10s per write)
        self.repop_timeout = 10.0
        # recovery push call timeout (same role: a chaos-dropped push
        # must fail the RecoveryOp fast, not wedge the worker)
        self.recovery_push_timeout = 10.0
        # RADOS backoff protocol state (the Backoff registry of
        # src/osd/osd_types.h, session-scoped in the reference;
        # keyed by id here): id -> {pgid, reason, conn, since}
        self._backoffs: dict[int, dict] = {}
        self._backoff_seq = itertools.count(1)
        self._backoff_lock = threading.Lock()
        # store statfs is a walk — cache it at ~tick rate
        self._statfs_cache: tuple[float, dict] | None = None
        # ~1 Hz stat reports by default; 100-daemon clusters stretch
        # this (tests/scale.py) so the mon isn't saturated by O(n)
        # commands per second on one core
        self.stat_report_interval = 1.0
        self._stat_report_last = 0.0
        self._stat_report_inflight = False
        # the mon's EFFECTIVE full ratio, learned from the stat-report
        # reply (runtime `ceph config set mon mon_osd_full_ratio`);
        # None until the first report lands — local config gates then
        self._mon_full_ratio: float | None = None
        # peers this OSD has filed failure reports for (to withdraw
        # with failed_for=-1 when they speak again — send_still_alive)
        self._reported: set[int] = set()
        self._cur_op = None  # worker-thread-current TrackedOp
        # last seen up/down per peer, to reset heartbeat stamps on a
        # down→up transition (a stale stamp would re-report instantly)
        self._last_up: dict[int, bool] = {}
        # the scrub engine (osd/scrub.py): scheduling, reservations,
        # chunked runs, the ScrubStore, and repair
        self.scrubber = Scrubber(self)
        # scrub/repair runs already reported as progress events, so
        # the final done=True record goes out exactly once when a
        # run leaves the scrubber (MPGStats events field)
        self._progress_seen: set[str] = set()
        self._boot_stamp = time.monotonic()

    # -- lifecycle ---------------------------------------------------------
    def boot(
        self,
        mon_host: str | None = None,
        mon_port: int | None = None,
        mon_addrs=None,
    ) -> None:
        """bind → load PGs from disk → mon session → announce
        (OSD::init + start_boot).  ``mon_addrs`` (a list of
        (host, port)) enables failover across a monitor quorum."""
        self.addr = self.messenger.bind()
        self._load_pgs()
        if self.shared_services:
            # zero per-daemon threads: the op queue drains through a
            # serial strand on the stack's offload pool (kicked by
            # the scheduler's enqueue hook), tick + mgr-report ride
            # stack timers with overlap guards
            stack = self._stack()
            self._op_strand = stack.offload.strand()
            self._workq.on_enqueue = self._kick_workq
        else:
            self._worker = threading.Thread(
                target=self._work_loop, name=f"osd.{self.whoami}.wq",
                daemon=True,
            )
            self._worker.start()
        if mon_addrs is not None:
            self.monc.connect_any(mon_addrs)
        else:
            self.monc.connect(mon_host, mon_port)
        self.monc.boot(self.whoami, addr=f"{self.addr[0]}:{self.addr[1]}")
        if self.shared_services:
            stack = self._stack()
            self._service_timers.append(
                stack.timers.every(self.tick_interval, self._tick_safe)
            )
            self._service_timers.append(
                stack.timers.every(1.0, self._mgr_report_safe)
            )
        else:
            self._ticker = threading.Thread(
                target=self._tick_loop, name=f"osd.{self.whoami}.tick",
                daemon=True,
            )
            self._ticker.start()
            self._mgr_reporter = threading.Thread(
                target=self._mgr_report_loop,
                name=f"osd.{self.whoami}.mgrreport",
                daemon=True,
            )
            self._mgr_reporter.start()

    def _stack(self):
        from ..msg.stack import NetworkStack

        return NetworkStack.instance()

    def shutdown(self) -> None:
        self._stop.set()
        for handle in self._service_timers:
            handle.cancel()
        self._service_timers = []
        self._workq.put(None)
        if self._worker is not None:
            self._worker.join(timeout=5)
        if self._op_strand is not None:
            # let an in-flight drained item finish, then stop feeding
            deadline = time.monotonic() + 5.0
            while (
                not self._op_strand.idle
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            self._workq.on_enqueue = None
        if self.admin is not None:
            self.admin.stop()
        self.messenger.shutdown()
        if self._own_wal:
            # flush + stop the WAL threads; the inner store stays
            # open — restart-with-same-store rewraps it and replays
            self.store.close(close_inner=False)

    # -- map / PG walk -----------------------------------------------------
    def _on_map(self, epoch: int) -> None:
        self._workq.put(("map", epoch))

    def _peer_conn(self, osd: int) -> Connection:
        """OSD↔OSD links are LOSSLESS PEERS (src/msg/Policy.h): the
        session survives TCP drops and replays unacked messages on
        reconnect, so a mid-repop connection loss commits exactly
        once without a client-visible retry."""
        osdmap = self.monc.osdmap
        addr = osdmap.osd_addrs.get(osd, "")
        with self._conn_lock:
            cached = self._conns.get(osd)
            if cached is not None:
                c_addr, conn = cached
                if c_addr == addr and not conn._closed:
                    return conn
                # peer re-registered at a new address: the old session
                # is for a dead incarnation
                conn.close()
        host, _, port = addr.partition(":")
        if not port:
            # peer already marked down (mark_down drops the addr): the
            # caller treats it like any unreachable peer
            raise MessageError(f"osd.{osd} has no address")
        conn = self.messenger.connect_session(
            host, int(port), f"osd.{self.whoami}-{osd}"
        )
        with self._conn_lock:
            self._conns[osd] = (addr, conn)
        return conn

    def _load_pgs(self) -> None:
        """Rebuild PG state from the store (OSD::load_pgs)."""
        for cid in self.store.list_collections():
            if not cid.startswith("pg_"):
                continue
            pgid = cid[3:]
            pool_id = int(pgid.split(".")[0])
            pg = PG(pgid, pool_id)
            try:
                pg.info = _decode_info(
                    self.store.getattr(cid, PG_META, INFO_ATTR)
                )
            except StoreError:
                continue
            entries = sorted(
                o for o in self.store.list_objects(cid)
                if o.startswith(LOG_PREFIX)
            )
            pg.log.log_tail = pg.info.log_tail
            for oid in entries:
                pg.log.append(_decode_entry(self.store.read(cid, oid)))
            pg.seq = pg.info.last_update[1]
            self.pgs[pgid] = pg

    def _walk_pgs(self, epoch: int) -> None:
        osdmap = self.monc.osdmap
        if osdmap is None:
            return
        # a peer that came back up gets a fresh heartbeat slate
        for osd in range(osdmap.max_osd):
            up = osdmap.is_up(osd)
            if up and not self._last_up.get(osd, False):
                self.hb.remove_peer(osd)
                self._reported.discard(osd)
            self._last_up[osd] = up
        # snapshot: the MonClient applies incrementals on the loop
        # thread while this walk runs on the worker
        for pool_id, pool in list(osdmap.pools.items()):
            for ps in range(pool.pg_num):
                up, _upp, acting, primary = osdmap.pg_to_up_acting_osds(
                    pool_id, ps
                )
                pgid = f"{pool_id}.{ps}"
                if self.whoami not in acting:
                    pg = self.pgs.get(pgid)
                    if pg is not None:
                        pg.state = "stray"
                        # no longer a member at all: any in-flight
                        # recovery this (ex-)primary was driving is
                        # for a dead interval
                        self._abort_pg_recovery(pgid)
                    continue
                pg = self._get_or_create_pg(pgid)
                interval = (tuple(acting), primary)
                with self._pg_lock:
                    changed = pg.peered_interval != interval
                    interval_died = (
                        pg.current_interval is not None
                        and pg.current_interval != interval
                    )
                    pg.current_interval = interval
                    pg.acting = acting
                    pg.primary = primary
                if interval_died:
                    # interval death (a REAL transition, not just an
                    # unpeered re-walk): in-flight RecoveryOps were
                    # planned against the old acting set — abort them
                    # (queued pushes drain without landing stale
                    # shards; reservations release on the drain)
                    self._abort_pg_recovery(pgid)
                if primary == self.whoami:
                    # re-peer only on interval change (the reference's
                    # new-interval test) — an unrelated epoch bump must
                    # not trigger a cluster-wide RPC storm.  A pass
                    # with failed recovery pushes leaves the interval
                    # unpeered so the tick loop retries it.
                    if changed or pg.state != "active":
                        if self._peer(pg, epoch):
                            pg.peered_interval = interval
                            pg.repop_clean = True
                        else:
                            pg.peered_interval = None
                            pg.repop_clean = False
                    if (
                        pg.state == "active"
                        and self._pg_num_grew(pg)
                    ):
                        # pg_num grew: re-home objects whose
                        # stable_mod slot moved (PG splitting)
                        self._workq.enqueue(
                            CLASS_BACKGROUND, 1,
                            ("split", pg.pgid, epoch),
                        )
                else:
                    if changed:
                        # new interval: wait for the primary's
                        # activation before accepting rep-ops
                        pg.activated_epoch = 0
                    pg.state = "replica"
                    pg.peered_interval = interval
        # snap trimming: clones stranded by removed pool snaps go
        # through the same logged-delete path as client removals
        with self._pg_lock:
            primaries = [
                pg for pg in self.pgs.values()
                if pg.primary == self.whoami and pg.state == "active"
            ]
        for pg in primaries:
            try:
                self._trim_snaps(pg)
            except StoreError:
                pass

    def _ensure_coll(self, pg: PG) -> None:
        try:
            self.store.queue_transaction(
                Transaction().create_collection(pg.cid)
            )
        except StoreError:
            pass

    # -- erasure-pool backend (osd/ec_pg.py) --------------------------------
    def _pool_of(self, pg: PG):
        return self.monc.osdmap.pools.get(pg.pool_id)

    def _is_ec(self, pg: PG) -> bool:
        pool = self._pool_of(pg)
        return pool is not None and not pool.can_shift_osds()

    def _ec_codec(self, pg: PG) -> ECCodec:
        """The pool's codec, cached per profile contents
        (the registry factory hop of PGBackend.cc:588)."""
        pool = self._pool_of(pg)
        profile = self.monc.osdmap.erasure_code_profiles.get(
            pool.erasure_code_profile
        )
        if profile is None:
            raise StoreError(
                f"pool {pg.pool_id}: erasure profile "
                f"{pool.erasure_code_profile!r} missing (-EINVAL)"
            )
        key = tuple(sorted(profile.items()))
        codec = self._ec_codecs.get(key)
        if codec is None:
            codec = self._ec_codecs[key] = ECCodec(profile)
        return codec

    def _ec_store_for(self, pg: PG) -> ECStore:
        """Mount the EC machinery over the acting set: my position is
        my own store, live peers are RemoteStore proxies (MECSubRead
        sub-op reads), holes/down peers raise like dead shards."""
        codec = self._ec_codec(pg)
        if len(pg.acting) != codec.n:
            raise StoreError(
                f"pg {pg.pgid}: acting size {len(pg.acting)} != "
                f"k+m={codec.n} (-EAGAIN)"
            )
        osdmap = self.monc.osdmap
        key = (
            tuple(pg.acting),
            tuple(
                o != CRUSH_ITEM_NONE and osdmap.is_up(o)
                for o in pg.acting
            ),
        )
        cached = pg.ec_view
        if (
            cached is not None
            and cached[0] == key
            and all(not c._closed for c in cached[2])
        ):
            return cached[1]
        stores: list[ObjectStore] = []
        conns: list[Connection] = []
        for osd in pg.acting:
            if osd == self.whoami:
                stores.append(self.store)
            elif osd == CRUSH_ITEM_NONE or not osdmap.is_up(osd):
                stores.append(UnreachableStore())
            else:
                try:
                    conn = self._peer_conn(osd)
                except (MessageError, OSError):
                    stores.append(UnreachableStore())
                    continue
                conns.append(conn)
                # sub-op reads share the repop SLA: a freshly-dead
                # peer's session conn BLOCKS (it queues for replay
                # rather than refusing), so the timeout bounds how
                # long one dead shard can wedge the worker
                stores.append(
                    RemoteStore(
                        conn, timeout=max(self.repop_timeout, 5.0)
                    )
                )
        ecs = ECStore(
            ec=codec.ec,
            stores=stores,
            cid=pg.cid,
            stripe_width=codec.sinfo.stripe_width,
            ensure_collections=False,
        )
        pg.ec_view = (key, ecs, conns)
        return ecs

    # -- peering (primary) -------------------------------------------------
    def _peer(self, pg: PG, epoch: int) -> bool:
        """GetInfo → GetLog → GetMissing → Active in one worker pass.
        Returns False when some peer's recovery could not complete —
        the caller must leave the interval unpeered so the tick loop
        retries (a skipped push would otherwise become a permanent
        shard hole once activation advances the peer's log)."""
        pg.state = "peering"
        peers = [
            o for o in pg.acting
            if o != self.whoami and o != CRUSH_ITEM_NONE
        ]
        infos: dict[int, PGInfo] = {self.whoami: pg.info}
        peer_logs: dict[int, list[LogEntry]] = {}
        reachable: list[int] = []
        for osd in peers:
            try:
                # bounded like every sub-op: a chaos-dropped query
                # (or a freshly-dead peer's queue-for-replay session
                # conn) must not wedge the worker for the default
                # call timeout per peer per pass
                reply = self._peer_conn(osd).call(
                    MPGQuery(pgid=pg.pgid, epoch=epoch),
                    timeout=self.repop_timeout,
                )
            except (MessageError, OSError):
                continue
            if isinstance(reply, MPGNotify) and reply.info_blob:
                infos[osd] = _decode_info(reply.info_blob)
                peer_logs[osd] = [
                    _decode_entry(b) for b in reply.entry_blobs
                ]
            elif isinstance(reply, MPGNotify):
                infos[osd] = PGInfo(pgid=pg.pgid)
                peer_logs[osd] = []
            reachable.append(osd)

        best = find_best_info(infos)
        if best is not None and best != self.whoami:
            self._get_log(pg, epoch, best, infos[best])
        # close our OWN holes (failed pulls from this or an earlier
        # pass — e.g. a half-recovered OSD promoted to primary by a
        # failover) before recovering peers: a primary serving reads
        # must not sit on adopted-but-unpulled objects
        all_ok = self._recover_self_missing(pg, epoch, reachable)

        # primary consistent: rewind+push what each reachable peer
        # misses, then activate everyone
        for osd in reachable:
            peer_info = infos.get(osd, PGInfo(pgid=pg.pgid))
            rewind = self._divergence_point(
                pg, peer_info, peer_logs.get(osd, [])
            )
            if not self._recover_peer(pg, epoch, osd, peer_info, rewind):
                all_ok = False
        pg.state = "active"
        pg.activated_epoch = epoch
        pg.info.last_epoch_started = epoch
        self._persist_info(pg)
        return all_ok

    def _divergence_point(
        self, pg: PG, peer_info: PGInfo, peer_entries: list[LogEntry]
    ) -> tuple[int, int]:
        """Newest version the peer's log shares with the authoritative
        log (proc_replica_log): the peer must rewind everything after
        it.  With no divergence this is the peer's last_update."""
        if not peer_entries:
            return min(peer_info.last_update, pg.log.head)
        own = {
            e.version: (e.oid, e.op) for e in pg.log.entries
        }
        common = pg.log.log_tail
        for entry in sorted(peer_entries, key=lambda e: e.version):
            if own.get(entry.version) == (entry.oid, entry.op):
                common = max(common, entry.version)
            elif entry.version > pg.log.head or (
                entry.version in own
                and own[entry.version] != (entry.oid, entry.op)
            ) or entry.version > common:
                break  # first divergent entry ends the shared prefix
        return common

    def _get_log(self, pg: PG, epoch: int, best: int, best_info: PGInfo):
        """Adopt the authoritative log and pull missing objects."""
        since = pg.info.last_update
        if needs_backfill(best_info, pg.info):
            since = best_info.log_tail
        try:
            reply = self._peer_conn(best).call(
                MPGLogReq(pgid=pg.pgid, epoch=epoch, since=since),
                timeout=self.repop_timeout,
            )
        except (MessageError, OSError):
            return
        if not isinstance(reply, MPGLogReply):
            return
        entries = [_decode_entry(b) for b in reply.entry_blobs]
        missing: dict[str, LogEntry] = {}
        for entry in entries:
            if entry.version <= pg.log.head:
                continue
            pg.log.append(entry)
            self._persist_entry(pg, entry)
            missing[entry.oid] = entry
        for oid, entry in missing.items():
            if self._pull_object(pg, epoch, best, oid, entry):
                pg.self_missing.pop(oid, None)
            else:
                # a failed pull must not become a SILENT hole while
                # the log/info advance past it: record it so the
                # peering pass retries until the object lands (the
                # stale divergent copy was already dropped)
                pg.self_missing[oid] = entry.version
        pg.info.last_update = pg.log.head
        pg.seq = max(pg.seq, pg.info.last_update[1])
        # adopting an authoritative log must not leave this pg over
        # its bound (the donor may keep a longer log than ours)
        self._maybe_trim(pg)
        self._persist_info(pg)

    def _recover_self_missing(
        self, pg: PG, epoch: int, peers: list[int]
    ) -> bool:
        """Close the primary's OWN holes (objects whose authoritative
        log entries were adopted but whose pull failed — e.g. the
        serving peer's store view still pointed at a freshly-dead
        OSD): retry from ANY reachable peer.  Returns True when no
        hole remains; False keeps the interval unpeered so the tick
        retries."""
        for oid in list(pg.self_missing):
            entry = pg.log.object_op(oid)
            if (
                entry is not None
                and entry.version != pg.self_missing[oid]
            ):
                # superseded by a newer write this primary itself
                # applied: no longer our hole to pull
                pg.self_missing.pop(oid, None)
                continue
            if entry is None:
                # the entry TRIMMED out of the log — but the object
                # is still missing locally; dropping the hole here
                # would permanently serve -ENOENT for bytes every
                # replica still holds.  Pull by the recorded version
                # (the entry only gates the DELETE shortcut).
                entry = LogEntry(
                    op=MODIFY, oid=oid,
                    version=pg.self_missing[oid],
                )
            pulled = False
            for osd in peers:
                if self._pull_object(pg, epoch, osd, oid, entry):
                    pg.self_missing.pop(oid, None)
                    pulled = True
                    break
            if not pulled:
                # NO peer could serve this object right now: later
                # ones will almost surely fail the same way, and
                # each failed pull holds the worker for a timeout —
                # stop the sweep; the tick re-peers and retries
                return False
        return not pg.self_missing

    def _pull_object(self, pg, epoch, source, oid, entry) -> bool:
        """Pull one object this OSD's log says it misses; returns
        True when the object's authoritative state landed locally.
        On a FAILED pull the stale local copy is dropped — the
        authoritative log says the object changed past our head, so
        serving the old bytes would be a read-after-ack violation —
        and the object becomes honestly missing for the retry."""
        if entry.op == DELETE:
            try:
                self.store.queue_transaction(
                    Transaction().remove(pg.cid, OBJ_PREFIX + oid)
                )
            except StoreError:
                pass
            return True
        shard = -1
        if self._is_ec(pg):
            if self.whoami not in pg.acting:
                return True  # stray: nothing to hold here
            shard = pg.acting.index(self.whoami)
        try:
            reply = self._peer_conn(source).call(
                MPGPull(
                    pgid=pg.pgid, epoch=epoch, oid=oid, shard=shard
                ),
                timeout=self.repop_timeout,
            )
        except (MessageError, OSError):
            try:
                self.store.queue_transaction(
                    Transaction().remove(pg.cid, OBJ_PREFIX + oid)
                )
            except StoreError:
                pass
            return False
        if isinstance(reply, MPGPush):
            # exists=False is an AUTHORITATIVE answer ("the object is
            # gone everywhere", e.g. a logged CALL removal) — apply
            # it as the removal it is; treating it as a failed pull
            # would loop the oid in self_missing forever
            self._apply_push(pg, reply)
            return True
        return False

    def _apply_push(self, pg: PG, push: MPGPush) -> None:
        txn = Transaction()
        store_oid = OBJ_PREFIX + push.oid
        if self.store.exists(pg.cid, store_oid):
            txn.remove(pg.cid, store_oid)
        if push.exists:
            txn.touch(pg.cid, store_oid)
            if push.data:
                txn.write(pg.cid, store_oid, 0, push.data)
            for k, v in push.attrs.items():
                txn.setattr(pg.cid, store_oid, k, v)
            if push.omap:
                txn.omap_setkeys(pg.cid, store_oid, push.omap)
        if txn.ops:
            self.store.queue_transaction(txn)

    def _recover_peer(
        self, pg, epoch, osd, peer_info: PGInfo,
        rewind: tuple[int, int],
    ) -> bool:
        """Recover one peer (the RecoveryOp state machine seat,
        ECBackend.h:249): a peer with NOTHING missing activates
        immediately; a peer with missing objects starts an ASYNC
        recovery — reservation-gated (two-sided, see max_backfills)
        push work items flow through the op scheduler's RECOVERY
        class, interleaving with client ops by QoS weight, and the
        activation ships when the last push lands.  Returns False
        while recovery is pending/deferred so the tick re-peers and
        confirms completion."""
        since = rewind
        if needs_backfill(pg.info, peer_info) or since < pg.log.log_tail:
            since = pg.log.log_tail
        missing = pg.log.missing_since(since)
        try:
            conn = self._peer_conn(osd)
        except (MessageError, OSError):
            return False

        interval = (tuple(pg.acting), pg.primary)
        prior_pushed: dict[str, tuple] = {}
        if not missing:
            # recovery confirmed complete for this interval: any
            # watermark left behind by an interrupted run is done
            self._clear_watermark(pg, osd)
        else:
            # persisted backfill watermark: pushes a PRIOR interrupted
            # run of this same (interval, since) completed carry their
            # exact version — skip re-pushing an object whose current
            # version already landed (a newer write re-pushes)
            wm = self._load_watermark(pg, osd)
            if wm is not None:
                if (
                    wm.get("interval") == _interval_json(interval)
                    and tuple(wm.get("since", ())) == tuple(since)
                ):
                    prior_pushed = {
                        oid: tuple(v)
                        for oid, v in wm.get("pushed", {}).items()
                    }
                    missing = {
                        oid: v
                        for oid, v in missing.items()
                        if prior_pushed.get(oid) != tuple(v)
                    }
                else:
                    # interval (or rewind point) died with the run
                    # that wrote it: the watermark is meaningless now
                    self._clear_watermark(pg, osd)

        if missing:
            key = (pg.pgid, osd)
            with self._recovery_lock:
                if key in self._recovering:
                    return False  # already in flight; confirm later
                # local reservation (AsyncReserver, primary side)
                if (
                    key not in self._local_reservations
                    and len(self._local_reservations)
                    >= self.max_backfills
                ):
                    return False  # local slots busy; tick retries
                self._local_reservations.add(key)
            # remote reservation (the replica's osd_max_backfills)
            granted = False
            try:
                reply = conn.call(
                    MRecoveryReserve(
                        tid=self.messenger.new_tid(), op="request",
                        pgid=pg.pgid, epoch=epoch,
                        from_osd=self.whoami,
                    ),
                    timeout=5.0,
                )
                granted = (
                    isinstance(reply, MRecoveryReserve)
                    and reply.op == "grant"
                )
            except (MessageError, OSError):
                pass
            if not granted:
                with self._recovery_lock:
                    self._local_reservations.discard(key)
                return False  # peer busy/unreachable; tick retries
            state = _RecoveryOp(
                pg=pg, epoch=epoch, osd=osd, since=since,
                conn=conn, remaining=set(missing),
                interval=interval, versions=dict(missing),
                pushed=dict(prior_pushed),
            )
            with self._recovery_lock:
                self._recovering[key] = state
            for oid in missing:
                try:
                    cost = self.store.stat(pg.cid, OBJ_PREFIX + oid)
                except StoreError:
                    cost = 4096
                self._workq.enqueue(
                    CLASS_RECOVERY, max(cost, 4096),
                    ("recover_push", key, oid),
                )
            return False  # activation follows the last push

        self._activate_peer(pg, epoch, conn, since)
        return True

    def _activate_peer(self, pg, epoch, conn, since) -> None:
        suffix = [
            _encode_entry(e) for e in pg.log.entries_after(since)
        ]
        try:
            # fire-and-forget: blocking here can cross-deadlock two
            # primaries whose workers are each peering a PG the other
            # replicates (activation acks are async in the reference
            # too); an unactivated replica simply NAKs rep-ops until
            # its queued activation lands
            conn.send(
                MPGActivate(
                    tid=self.messenger.new_tid(),
                    pgid=pg.pgid, epoch=epoch,
                    info_blob=_encode_info(pg.info),
                    rewind_to=since,
                    entry_blobs=suffix,
                )
            )
        except (MessageError, OSError):
            pass

    def _recovery_interval_ok(self, state: "_RecoveryOp") -> bool:
        """The generation check every push re-validates: the interval
        this RecoveryOp was planned against must still be current
        (same acting set, same primary, and that primary is us) —
        otherwise a push would land a shard computed for a position
        assignment that no longer exists (a stale shard the next
        peering would silently trust)."""
        pg = state.pg
        return (
            pg.primary == self.whoami
            and (tuple(pg.acting), pg.primary) == state.interval
        )

    def _abort_pg_recovery(self, pgid: str) -> None:
        """Interval death: fail every in-flight RecoveryOp for this
        PG so the queued pushes drain WITHOUT touching peers and
        _finish_recovery releases both reservations promptly."""
        with self._recovery_lock:
            for (pid, _osd), state in self._recovering.items():
                if pid == pgid:
                    state.failed = True

    def _coalesce_recovery_items(self, item) -> list:
        """After dequeuing a recovery push, drain up to
        ``osd_recovery_batch_max - 1`` more CONSECUTIVE pushes for
        the SAME (pg, peer) RecoveryOp: they ride one coalesced
        decode-from-survivors dispatch while every push still sends,
        completes, and watermarks individually, in queue order —
        the repair-side twin of _coalesce_op_items."""
        if self.osd_recovery_batch_max <= 1:
            return []
        key = item[1]

        def matches(it) -> bool:
            # cheap + lock-free: runs under the scheduler lock
            return (
                isinstance(it, tuple)
                and len(it) == 3
                and it[0] == "recover_push"
                and it[1] == key
            )

        return self._workq.drain_class(
            CLASS_RECOVERY, matches, self.osd_recovery_batch_max - 1
        )

    def _do_recover_push_batch(self, items: list) -> None:
        """Serve a coalesced recovery batch: ONE batched
        decode-from-survivors dispatch rebuilds every drained
        object's shard (ECStore.reconstruct_shards_batch through the
        per-PG store view — survivor shards upload once, outputs
        device-born), then each push runs its normal per-op path with
        its MPGPush precomputed — send/reply/watermark/completion
        semantics unchanged, and a batch failure degrades every push
        to its own per-op rebuild."""
        key = items[0][1]
        with self._recovery_lock:
            state = self._recovering.get(key)
        pre: dict[str, MPGPush] = {}
        if (
            state is not None
            and not state.failed
            and self._recovery_interval_ok(state)
            and self._is_ec(state.pg)
            and len(items) > 1
        ):
            try:
                pos = state.pg.acting.index(state.osd)
                pre = self._ec_push_batch(
                    state.pg, state.epoch,
                    [it[2] for it in items], pos,
                )
            except Exception:  # noqa: BLE001 — coalescing is an
                # optimization: a batch failure degrades every push
                # to the per-op rebuild, never drops one
                pre = {}
        for it in items:
            self._do_recover_push(key, it[2], pre_push=pre.get(it[2]))

    def _do_recover_push(
        self, key: tuple[str, int], oid: str, pre_push=None
    ) -> None:
        """One scheduler-drained recovery push; the LAST one (or a
        failure) completes the RecoveryOp.  ``pre_push`` carries the
        MPGPush a coalesced batch dispatch already rebuilt."""
        with self._recovery_lock:
            state = self._recovering.get(key)
        if state is None:
            return
        pg, epoch, osd = state.pg, state.epoch, state.osd
        with self._recovery_lock:
            self._recovery_active += 1
            self.recovery_active_peak = max(
                self.recovery_active_peak, self._recovery_active
            )
        try:
            if not state.failed and not self._recovery_interval_ok(
                state
            ):
                # the interval died under this op (second failure,
                # remap, primary change): abort — a push computed for
                # the dead interval must never land
                state.failed = True
            if not state.failed:
                # once one push failed the rest of the queue DRAINS
                # without touching the peer: each blocking call
                # would otherwise hold the worker for a full timeout
                # per remaining item
                if pre_push is not None:
                    push = pre_push
                elif self._is_ec(pg):
                    pos = pg.acting.index(osd)
                    push = self._ec_push_for(pg, epoch, oid, pos)
                else:
                    push = self._push_for(pg, epoch, oid)
                state.conn.call(
                    push, timeout=self.recovery_push_timeout
                )
                self.perf.inc("recovery_pushes")
                self.perf.inc("recovery_push_bytes", len(push.data))
                version = state.versions.get(oid)
                if version is not None:
                    with self._recovery_lock:
                        state.pushed[oid] = tuple(version)
                        # amortized: the blob rewrites the whole
                        # pushed map, so persisting EVERY push would
                        # be O(n^2) bytes over a big storm — and the
                        # watermark is an optimization (a subset is
                        # still a valid resume point).  Small ops
                        # persist per push (the blob is tiny and the
                        # resume granularity matters most there);
                        # big ones stride
                        persist = (
                            len(state.versions) <= 32
                            or len(state.pushed) % 8 == 0
                            or len(state.remaining) <= 1
                        )
                    if persist:
                        self._persist_watermark(pg, osd, state)
        except Exception:  # noqa: BLE001 — ANY failure (unreachable
            # peer, missing shards, an epoch change yanking the osd
            # from pg.acting) must fail the op: completing anyway
            # would activate the peer past an object it never got,
            # an invisible permanent hole.  The tick re-peers.
            state.failed = True
        finally:
            with self._recovery_lock:
                self._recovery_active -= 1
                state.remaining.discard(oid)
                done = not state.remaining
                if done:
                    self._recovering.pop(key, None)
            if done:
                self._finish_recovery(key, state)

    def _finish_recovery(self, key, state: "_RecoveryOp") -> None:
        try:
            if not state.failed:
                self._activate_peer(
                    state.pg, state.epoch, state.conn, state.since
                )
        finally:
            with self._recovery_lock:
                self._local_reservations.discard(key)
            try:
                state.conn.send(
                    MRecoveryReserve(
                        tid=self.messenger.new_tid(), op="release",
                        pgid=state.pg.pgid, epoch=state.epoch,
                        from_osd=self.whoami,
                    )
                )
            except (MessageError, OSError):
                pass

    # -- backfill watermark (persisted recovery progress) ------------------
    @staticmethod
    def _wm_key(osd: int) -> str:
        return f"rwm_{osd}"

    def _load_watermark(self, pg: PG, osd: int) -> dict | None:
        """The persisted per-(pg, peer) push progress: {interval,
        since, pushed: {oid: version}} — valid only while both the
        interval and the rewind point it was computed for hold."""
        try:
            raw = self.store.omap_get(pg.cid, PG_META).get(
                self._wm_key(osd)
            )
        except StoreError:
            return None
        if not raw:
            return None
        try:
            wm = json.loads(raw)
        except ValueError:
            return None
        return wm if isinstance(wm, dict) else None

    def _persist_watermark(
        self, pg: PG, osd: int, state: "_RecoveryOp"
    ) -> None:
        """One omap row per completed push: a restarted or
        re-peered primary resumes instead of re-pushing objects the
        interrupted run already landed (version-exact, so a client
        write after the push re-pushes)."""
        blob = json.dumps(
            {
                "interval": _interval_json(state.interval),
                "since": list(state.since),
                "pushed": {
                    o: list(v) for o, v in state.pushed.items()
                },
            }
        ).encode()
        try:
            txn = Transaction()
            txn.touch(pg.cid, PG_META)
            txn.omap_setkeys(
                pg.cid, PG_META, {self._wm_key(osd): blob}
            )
            self.store.queue_transaction(txn)
        except StoreError:
            pass

    def _clear_watermark(self, pg: PG, osd: int) -> None:
        try:
            self.store.queue_transaction(
                Transaction().omap_rmkeys(
                    pg.cid, PG_META, [self._wm_key(osd)]
                )
            )
        except StoreError:
            pass

    def _push_for(self, pg: PG, epoch: int, oid: str) -> MPGPush:
        """One object's recovery push, attrs + omap included
        (prep_push)."""
        entry = pg.log.object_op(oid)
        exists = entry is None or entry.op != DELETE
        data = b""
        attrs: dict[str, bytes] = {}
        omap: dict[str, bytes] = {}
        if exists:
            try:
                data = self.store.read(pg.cid, OBJ_PREFIX + oid)
                attrs = self.store.list_attrs(pg.cid, OBJ_PREFIX + oid)
                omap = self.store.omap_get(pg.cid, OBJ_PREFIX + oid)
            except StoreError:
                exists = False
        return MPGPush(
            pgid=pg.pgid, epoch=epoch, oid=oid,
            exists=exists, data=data, attrs=attrs, omap=omap,
            entry_blob=_encode_entry(entry) if entry else b"",
        )

    def _ec_push_for(
        self, pg: PG, epoch: int, oid: str, pos: int
    ) -> MPGPush:
        """Recovery push for an erasure pool: RECONSTRUCT position
        ``pos``'s shard from the minimum helper set (CLAY profiles read
        fractional chunks) and ship it with its HashInfo + user/class
        attrs (ECBackend RecoveryOp READING→WRITING with
        minimum_to_decode reads, ECBackend.cc:1630)."""
        entry = pg.log.object_op(oid)
        store_oid = OBJ_PREFIX + oid
        push = MPGPush(
            pgid=pg.pgid, epoch=epoch, oid=oid, exists=False,
            entry_blob=_encode_entry(entry) if entry else b"",
        )
        if entry is not None and entry.op == DELETE:
            return push
        # pin the authoritative HashInfo from our own shard when we
        # hold it — a rewinding peer may still expose stale hinfo
        meta = None
        try:
            meta = json.loads(
                self.store.getattr(pg.cid, store_oid, HINFO_KEY)
            )
        except StoreError:
            pass
        ecs = self._ec_store_for(pg)
        try:
            data, reads, meta = ecs.reconstruct_shard(
                store_oid, pos, meta
            )
        except ErasureCodeError:
            if meta is None and not self.store.exists(pg.cid, store_oid):
                # object gone everywhere (e.g. a logged CALL removal)
                return push
            raise
        self.perf.inc("recovery_helper_bytes", reads)
        return self._ec_push_assemble(pg, push, data, meta, ecs, pos)

    def _ec_push_assemble(
        self, pg: PG, push: MPGPush, data: bytes, meta: dict,
        ecs: ECStore, pos: int,
    ) -> MPGPush:
        """Attach the rebuilt shard + its HashInfo + the replicated
        user/class attrs and omap to a push — the ONE assembly both
        the per-op and the coalesced rebuild paths share (byte
        identity between them rests on there being a single copy)."""
        store_oid = OBJ_PREFIX + push.oid
        attrs = {HINFO_KEY: json.dumps(meta).encode()}
        # user/class attrs and omap replicate on every shard — take
        # them from our copy, or any reachable shard when ours is gone
        src_attrs = None
        src_omap: dict[str, bytes] = {}
        if self.store.exists(pg.cid, store_oid):
            src_attrs = self.store.list_attrs(pg.cid, store_oid)
            src_omap = self._omap_of(pg, store_oid)
        else:
            for i, st in enumerate(ecs.stores):
                if i == pos:
                    continue
                try:
                    src_attrs = st.list_attrs(pg.cid, store_oid)
                    src_omap = st.omap_get(pg.cid, store_oid)
                    break
                except StoreError:
                    continue
        if src_attrs:
            attrs.update(
                {
                    k: v
                    for k, v in src_attrs.items()
                    if k.startswith(("u_", "c_"))
                }
            )
        push.exists = True
        push.data = data
        push.attrs = attrs
        push.omap = src_omap
        return push

    def _ec_push_batch(
        self, pg: PG, epoch: int, oids: list, pos: int
    ) -> dict[str, MPGPush]:
        """Rebuild position ``pos``'s shard for MANY objects in ONE
        coalesced decode-from-survivors dispatch
        (ECStore.reconstruct_shards_batch over the per-PG store view:
        survivor reads honor minimum_to_decode — LRC repairs touch
        k_local helpers — local survivors ride the residency cache,
        reconstructed shards come back device-born) and assemble each
        object's MPGPush exactly like the per-op path.  Objects the
        batch cannot serve are simply absent from the result — the
        caller's per-op path rebuilds them."""
        out: dict[str, MPGPush] = {}
        base: dict[str, MPGPush] = {}
        alive: list[str] = []
        metas: dict[str, dict] = {}
        for oid in oids:
            entry = pg.log.object_op(oid)
            push = MPGPush(
                pgid=pg.pgid, epoch=epoch, oid=oid, exists=False,
                entry_blob=_encode_entry(entry) if entry else b"",
            )
            if entry is not None and entry.op == DELETE:
                out[oid] = push
                continue
            base[oid] = push
            store_oid = OBJ_PREFIX + oid
            try:
                # pin the authoritative HashInfo from our own shard
                # when we hold it (a rewinding peer may expose stale
                # hinfo), like the per-op path
                metas[store_oid] = json.loads(
                    self.store.getattr(pg.cid, store_oid, HINFO_KEY)
                )
            except StoreError:
                pass
            alive.append(oid)
        if not alive:
            return out
        ecs = self._ec_store_for(pg)
        results, _fallback, stats = ecs.reconstruct_shards_batch(
            [OBJ_PREFIX + oid for oid in alive], pos, metas
        )
        self.perf.inc(
            "recovery_survivor_shards", stats["survivor_shards"]
        )
        self.perf.inc("recovery_helper_bytes", stats["read_bytes"])
        served = 0
        for oid in alive:
            got = results.get(OBJ_PREFIX + oid)
            if got is None:
                continue  # per-op fallback rebuilds (and verifies) it
            payload, meta = got
            data = (
                payload.host()
                if hasattr(payload, "host")
                else bytes(payload)
            )
            out[oid] = self._ec_push_assemble(
                pg, base[oid], data, meta, ecs, pos
            )
            served += 1
        if served > 1:
            self.perf.inc("recovery_batches")
            self.perf.inc("recovery_batch_ops", served)
        return out

    # -- persistence -------------------------------------------------------
    def _persist_entry(self, pg: PG, entry: LogEntry, txn=None) -> None:
        own = txn is None
        txn = txn or Transaction()
        txn.touch(pg.cid, _log_oid(entry.version))
        txn.write(pg.cid, _log_oid(entry.version), 0, _encode_entry(entry))
        if own:
            self.store.queue_transaction(txn)

    def _persist_info(self, pg: PG, txn=None) -> None:
        own = txn is None
        txn = txn or Transaction()
        # touch is idempotent and MUST be unconditional: the same
        # transaction ships verbatim to replicas whose store may not
        # have PG_META yet (a conditional guard against the PRIMARY's
        # store would abort the whole replicated txn there)
        txn.touch(pg.cid, PG_META)
        txn.setattr(pg.cid, PG_META, INFO_ATTR, _encode_info(pg.info))
        if own:
            self.store.queue_transaction(txn)

    # -- client op path (primary) ------------------------------------------
    # scheduler classes a CLIENT may never name: strict would bypass
    # QoS outright, and recovery/background would let a tenant ride
    # the recovery reservation while starving real recovery traffic
    _QOS_INTERNAL = frozenset(
        {CLASS_STRICT, CLASS_RECOVERY, CLASS_BACKGROUND}
    )

    def _qos_class_of(self, msg: MOSDOp) -> str:
        """The scheduler class this op rides: its named QoS class
        when a profile is registered AND the name is not an internal
        scheduler class, else the default client class (an unknown or
        reserved class must degrade, not bypass, QoS)."""
        qos = sanitize_class(msg.qos, default=CLASS_CLIENT)
        if qos in self._QOS_INTERNAL:
            return CLASS_CLIENT
        if qos != CLASS_CLIENT and not self._workq.known_class(qos):
            return CLASS_CLIENT
        return qos

    @staticmethod
    def _op_type_of(op: int) -> str:
        if op in (
            OSD_OP_READ, OSD_OP_STAT, OSD_OP_GETXATTR, OSD_OP_OMAPGET,
        ):
            return "read"
        if op == OSD_OP_LIST:
            return "list"
        return "write"

    def _handle_op(
        self, conn: Connection, msg: MOSDOp, pre_encoded=None
    ) -> None:
        t0 = time.perf_counter()
        qos_class = self._qos_class_of(msg)
        op_type = self._op_type_of(msg.op)
        top = self.op_tracker.create_op(
            f"osd_op({msg.reqid} {msg.pgid} {msg.oid} op={msg.op})",
            trace=msg.reqid,
            op_type=op_type,
            qos_class=qos_class,
        )
        top.mark_event("started")
        self._cur_op = top
        # primary-side span under the client's trace (= reqid): the
        # `with` installs it as this worker thread's ambient, so the
        # store layers' per-stage spans attach as children; qos_class
        # rides the tags so the mgr tracing module filters per class
        span = self.tracer.start_span(
            "osd_op",
            trace_id=msg.reqid or "",
            role=tracing.ROLE_PRIMARY,
            tags={
                "pgid": msg.pgid, "oid": msg.oid, "op": msg.op,
                "qos_class": qos_class,
            },
        )
        try:
            with span:
                self._handle_op_inner(conn, msg, pre_encoded)
        finally:
            self._cur_op = None
            top.finish()
            self.perf.inc("op")
            if msg.op in (
                OSD_OP_READ, OSD_OP_STAT, OSD_OP_GETXATTR,
                OSD_OP_OMAPGET, OSD_OP_LIST,
            ):
                self.perf.inc("op_r")
            else:
                self.perf.inc("op_w")
            self.perf.tinc("op_latency", time.perf_counter() - t0)

    def _client_blocklisted(self, reqid: str) -> bool:
        """The reqid's leading field is the objecter's client id —
        the entity-addr analog the blocklist keys on."""
        osdmap = self.monc.osdmap
        if osdmap is None or not osdmap.blocklist:
            return False
        return osdmap.is_blocklisted(reqid.rsplit(".", 1)[0])

    def _handle_op_inner(
        self, conn: Connection, msg: MOSDOp, pre_encoded=None
    ) -> None:
        epoch = self.monc.epoch
        pg = self.pgs.get(msg.pgid)
        reply = MOSDOpReply(tid=msg.tid, epoch=epoch)
        if msg.reqid and self._client_blocklisted(msg.reqid):
            # fencing (OSDMap::is_blocklisted, OSD.cc op admission):
            # a blocklisted client gets a hard reject on EVERY op —
            # this is what makes break-lock and MDS failover safe
            # against a partitioned-but-alive previous owner
            reply.ok = False
            reply.error = "client is blocklisted (-EBLOCKLISTED)"
            conn.send(reply)
            return
        if (
            pg is not None
            and pg.primary == self.whoami
            and pg.state == "peering"
        ):
            # the PG cannot take ops while peering (e.g. after an
            # injected partition changed the interval): send a block
            # backoff so the objecter PARKS the op instead of
            # hammering resends (MOSDBackoff, the reference's PG
            # backoff on a not-yet-active primary)
            self._send_block(conn, msg, pg.pgid, "peering")
            return
        if pg is None or pg.primary != self.whoami or pg.state not in (
            "active",
        ):
            reply.ok = False
            reply.error = f"not primary for pg {msg.pgid} (-EAGAIN)"
            conn.send(reply)
            return
        pool = self._pool_of(pg)
        if pool is not None and 0 < msg.epoch < pool.last_change:
            # the pool changed (e.g. pg_num split) after the client's
            # map: a misdirected write would land in a PG the rest of
            # the cluster no longer consults for this object
            # (OSD::handle_op's misdirected check)
            reply.ok = False
            reply.error = (
                f"client map epoch {msg.epoch} predates pool change "
                f"{pool.last_change}; refresh map (-EAGAIN)"
            )
            conn.send(reply)
            return
        if (
            self._op_is_write(msg)
            and not (msg.flags & OSD_FLAG_FULL_TRY)
            and self._check_full()
        ):
            # full-space degradation (the OSD_FULL write-blocking
            # path): reads keep serving, writes park on backoff until
            # space frees; FULL_TRY (repair/delete traffic) bypasses
            self._send_block(conn, msg, pg.pgid, "full")
            return
        store_oid = OBJ_PREFIX + msg.oid
        is_ec = self._is_ec(pg)
        tiered = (
            pool is not None
            and pool.tier_of >= 0
            and pool.cache_mode == "writeback"
            and not is_ec
        )
        try:
            if tiered and not msg.reqid.startswith("tier-"):
                self._tier_front(pg, pool, epoch, msg, store_oid)
            if msg.op in (
                OSD_OP_READ, OSD_OP_STAT, OSD_OP_GETXATTR,
                OSD_OP_OMAPGET,
            ) and msg.snapid:
                # reads at a snap serve from the covering clone
                store_oid = self._resolve_snap_read(
                    pg, msg.oid, msg.snapid
                )
            if msg.op == OSD_OP_READ:
                if is_ec:
                    whole = self._ec_store_for(pg).get(store_oid)
                    if msg.length < 0:
                        reply.data = whole[msg.offset :]
                    else:
                        reply.data = whole[
                            msg.offset : msg.offset + msg.length
                        ]
                else:
                    reply.data = self.store.read(
                        pg.cid, store_oid, msg.offset, msg.length
                    )
            elif msg.op == OSD_OP_STAT:
                if is_ec:
                    reply.size = self._ec_store_for(pg).size(store_oid)
                else:
                    reply.size = self.store.stat(pg.cid, store_oid)
            elif msg.op == OSD_OP_GETXATTR:
                reply.data = self.store.getattr(
                    pg.cid, store_oid, "u_" + msg.attr
                )
            elif msg.op in (OSD_OP_WATCH, OSD_OP_UNWATCH):
                self._handle_watch(pg, conn, msg)
            elif msg.op == OSD_OP_NOTIFY:
                acks = self._notify_watchers(pg, msg.oid, msg.data)
                reply.data = json.dumps(acks).encode()
            elif msg.op == OSD_OP_CALL:
                cls_name, _, method = msg.attr.partition(".")
                flags = self.class_handler.flags_of(cls_name, method)
                if flags & CLS_WR:
                    reply.data = self._mutate(pg, epoch, msg, store_oid)
                else:
                    ctx = self._cls_ctx(pg, store_oid)
                    reply.data = self._cls_call(
                        cls_name, method, ctx, msg.data
                    )
            elif msg.op == OSD_OP_OMAPGET:
                # omap replicates on every replica/shard: serve local
                kv = self.store.omap_get_vals(
                    pg.cid, store_oid,
                    start_after=msg.attr,
                    max_return=msg.length,
                )
                e = Encoder()
                e.map(
                    kv,
                    lambda e2, k: e2.string(k),
                    lambda e2, v: e2.bytes(v),
                )
                reply.data = e.getvalue()
            elif msg.op == OSD_OP_LIST:
                # heads only: snap clones ("@"-suffixed) stay hidden
                reply.names = sorted(
                    o[len(OBJ_PREFIX):]
                    for o in self.store.list_objects(pg.cid)
                    if o.startswith(OBJ_PREFIX) and "@" not in o
                )
            else:
                self._mutate(
                    pg, epoch, msg, store_oid, pre_encoded=pre_encoded
                )
                if (
                    tiered
                    and msg.op == OSD_OP_DELETE
                    and not msg.reqid.startswith("tier-")
                ):
                    # writeback deletes propagate to the base
                    # SYNCHRONOUSLY (deviation from the reference's
                    # whiteout objects — correctness over latency)
                    self._tier_base_op(
                        pool, msg.oid, OSD_OP_DELETE,
                        reqid=f"tier-del.{msg.reqid}",
                        ignore_enoent=True,
                    )
        except (StoreError, ClassError, ErasureCodeError) as e:
            reply.ok = False
            reply.error = str(e)
        conn.send(reply)

    def _cls_call(self, cls_name, method, ctx, indata) -> bytes:
        """Run a stored procedure, converting ANY method exception to
        ClassError — methods execute arbitrary code on
        client-controlled bytes and must never kill the op path or
        leave the client without a reply."""
        try:
            return self.class_handler.call(cls_name, method, ctx, indata)
        except ClassError:
            raise
        except Exception as e:  # noqa: BLE001
            raise ClassError(
                f"{cls_name}.{method} failed: {type(e).__name__}: {e}"
            )

    def _omap_of(self, pg: PG, store_oid: str) -> dict[str, bytes]:
        try:
            return self.store.omap_get(pg.cid, store_oid)
        except StoreError:
            return {}

    # -- snapshots (make_writeable / SnapSet resolution) -------------------
    def _born_at(self, pg: PG, store_oid: str) -> int:
        try:
            return int(
                self.store.getattr(pg.cid, store_oid, BORN_ATTR)
            )
        except (StoreError, ValueError):
            return 0

    def _commit_internal(
        self,
        pg: PG,
        epoch: int,
        oid: str,
        txn: Transaction,
        op=None,
        prior_version=(1, 0),
    ) -> None:
        """One internally-generated mutation through the SAME logged
        replication path client ops ride (clone preservation, snap
        trims, watch records)."""
        pg.seq += 1
        entry = LogEntry(
            op=MODIFY if op is None else op,
            oid=oid,
            version=(epoch, pg.seq),
            reqid="",
            prior_version=prior_version,
        )
        targets = {
            osd: txn
            for osd in pg.acting
            if osd != CRUSH_ITEM_NONE
            and (osd == self.whoami or self.monc.osdmap.is_up(osd))
        }
        self._commit_and_replicate(
            pg, epoch, types.SimpleNamespace(reqid=""), entry,
            targets, b"",
        )

    def _maybe_clone(
        self, pg: PG, epoch: int, oid: str, existed: bool,
        writer_seq: int = 0,
    ) -> None:
        """Clone-on-first-write-after-snap (PrimaryLogPG::
        make_writeable): before a mutation lands on an object that
        predates the pool's newest snap, preserve the head as
        "<oid>@<snap_seq>" — ONE store-local clone op riding a logged
        transaction of its own, so clones replicate, recover, and
        reconstruct exactly like any object on both backends."""
        pool = self._pool_of(pg)
        named = (
            max(
                (s for s, name in pool.snaps.items() if name),
                default=0,
            )
            if pool is not None
            else 0
        )
        # per-op writer SnapContext (make_writeable,
        # PrimaryLogPG.cc:1209): a writer's self-managed seq drives
        # its clones, so two images in one pool snapshot
        # independently; a NAMED pool snap newer than the writer's
        # context still wins (a stale writer must not overwrite a
        # snapshot the admin just took), and bystanders without a
        # context follow named snaps only
        snapc = max(writer_seq, named)
        if not existed or snapc <= 0:
            return
        head = OBJ_PREFIX + oid
        clone_store = OBJ_PREFIX + f"{oid}@{snapc}"
        if self.store.exists(pg.cid, clone_store):
            return  # already preserved for this snap context
        if self._born_at(pg, head) >= snapc:
            return  # object born after the newest snap: nothing owed
        txn = Transaction().clone(pg.cid, head, clone_store)
        self._commit_internal(
            pg, epoch, f"{oid}@{snapc}", txn,
            prior_version=EV_ZERO,
        )

    def _resolve_snap_read(self, pg: PG, oid: str, snapid: int) -> str:
        """Map (oid, snapid) to the store object serving that snap:
        the oldest clone whose id >= snapid, else the head — provided
        the serving object was born BEFORE the snap (SnapSet clone
        lookup, PrimaryLogPG::find_object_context)."""
        head = OBJ_PREFIX + oid
        if snapid <= 0:
            return head
        pool = self._pool_of(pg)
        live = sorted(s for s in (pool.snaps if pool else {}) if s >= snapid)
        for c in live:
            clone_store = OBJ_PREFIX + f"{oid}@{c}"
            if self.store.exists(pg.cid, clone_store):
                if self._born_at(pg, clone_store) >= snapid:
                    break  # born after the snap: didn't exist then
                return clone_store
        if (
            self.store.exists(pg.cid, head)
            and self._born_at(pg, head) < snapid
        ):
            return head
        raise StoreError(
            f"no object {oid} at snap {snapid} (-ENOENT)"
        )

    def _trim_snaps(self, pg: PG, limit: int = 32) -> None:
        """Remove clones stranded by deleted pool snaps (the snap
        trimmer role): a clone @c is removable once no live snap falls
        in the interval it covers, (next-lower clone or birth, c]."""
        if pg.primary != self.whoami or pg.state != "active":
            return
        pool = self._pool_of(pg)
        if pool is None:
            return
        live = set(pool.snaps)
        epoch = self.monc.epoch
        try:
            names = self.store.list_objects(pg.cid)
        except StoreError:
            return
        clones: dict[str, list[int]] = {}
        for n in names:
            if not n.startswith(OBJ_PREFIX) or "@" not in n:
                continue
            base, _, c = n[len(OBJ_PREFIX):].rpartition("@")
            try:
                clones.setdefault(base, []).append(int(c))
            except ValueError:
                continue
        done = 0
        for base, ids in clones.items():
            ids.sort()
            for i, c in enumerate(ids):
                if c in live:
                    continue
                clone_store = OBJ_PREFIX + f"{base}@{c}"
                lower = ids[i - 1] if i else self._born_at(
                    pg, clone_store
                )
                if any(lower < s <= c for s in live):
                    continue  # still serves a live snap
                txn = (
                    Transaction()
                    .touch(pg.cid, clone_store)
                    .remove(pg.cid, clone_store)
                )
                try:
                    self._commit_internal(
                        pg, epoch, f"{base}@{c}", txn, op=DELETE
                    )
                except StoreError:
                    return
                done += 1
                if done >= limit:
                    return

    # -- watch/notify (PrimaryLogPG watchers / Notify) ---------------------
    WATCH_ATTR = "w_"

    def _handle_watch(self, pg: PG, conn: Connection, msg: MOSDOp):
        key = (pg.pgid, msg.oid)
        store_oid = OBJ_PREFIX + msg.oid
        with self._watch_lock:
            if msg.op == OSD_OP_WATCH:
                self._watchers.setdefault(key, {})[msg.offset] = conn
            else:
                watchers = self._watchers.get(key, {})
                watchers.pop(msg.offset, None)
                if not watchers:
                    self._watchers.pop(key, None)
        # persist the watch record in object metadata (watch_info in
        # object_info_t, src/osd/osd_types.h) through the SAME logged
        # path as any mutation, so the record survives primary
        # failover and the NEW primary holds notifies for this
        # watcher until its linger re-attaches
        attr = self.WATCH_ATTR + str(msg.offset)
        try:
            have = attr in self.store.list_attrs(pg.cid, store_oid)
        except StoreError:
            # watch on a nonexistent object: reject like the
            # reference (-ENOENT) — a memory-only watch would lose
            # exactly the failover guarantee the record provides
            if msg.op == OSD_OP_WATCH:
                with self._watch_lock:
                    ws = self._watchers.get(key, {})
                    ws.pop(msg.offset, None)
                    if not ws:
                        self._watchers.pop(key, None)
                raise StoreError(
                    f"no object {msg.oid} to watch (-ENOENT)"
                )
            return
        epoch = self.monc.epoch
        if msg.op == OSD_OP_WATCH and not have:
            txn = Transaction().touch(pg.cid, store_oid)
            txn.setattr(pg.cid, store_oid, attr, b"1")
        elif msg.op == OSD_OP_UNWATCH and have:
            txn = Transaction().touch(pg.cid, store_oid)
            txn.rmattr(pg.cid, store_oid, attr)
        else:
            return  # re-register / already gone: record is current
        try:
            self._commit_internal(pg, epoch, msg.oid, txn)
        except StoreError:
            pass  # record update retries on the client's next linger

    def _persisted_watchers(self, pg: PG, oid: str) -> set[int]:
        try:
            return {
                int(a[len(self.WATCH_ATTR):])
                for a in self.store.list_attrs(
                    pg.cid, OBJ_PREFIX + oid
                )
                if a.startswith(self.WATCH_ATTR)
            }
        except (StoreError, ValueError):
            return set()

    def _notify_watchers(
        self, pg: PG, oid: str, payload: bytes, timeout: float = 2.0
    ) -> list[dict]:
        """Fan a notify to every watcher and gather acks (Notify's
        completion gathering with a timeout for dead watchers).

        The watcher set is the union of live connections and the
        PERSISTED records in object metadata: after a primary
        failover the new primary has records but no connections yet —
        a notify posted in that window waits for the watchers'
        lingers to re-attach (instead of being silently lost) and
        delivers within the timeout."""
        key = (pg.pgid, oid)
        want = set(self._persisted_watchers(pg, oid))
        with self._watch_lock:
            want |= set(self._watchers.get(key, {}))
        # a blocklisted client's watches are dead to the cluster: its
        # persisted records neither receive notifies nor hold up the
        # ack gather (Watch::is_discardable via is_blocklisted)
        osdmap = self.monc.osdmap
        if osdmap is not None and osdmap.blocklist:
            want = {
                c for c in want
                if not osdmap.is_blocklisted(f"{c >> 16:012x}")
            }
        if not want:
            return []
        notify_id = next(self._notify_seq)
        state = {
            "want": set(want),
            "acks": {},
            "event": threading.Event(),
        }
        self._notify_pending[notify_id] = state
        sent: set[int] = set()
        deadline = time.monotonic() + max(timeout, 0.0)
        while True:
            with self._watch_lock:
                connected = dict(self._watchers.get(key, {}))
            for cookie in state["want"] - sent:
                conn = connected.get(cookie)
                if conn is None:
                    continue  # awaiting the linger re-attach
                sent.add(cookie)
                try:
                    conn.send(
                        MWatchNotify(
                            tid=self.messenger.new_tid(),
                            oid=oid, notify_id=notify_id,
                            cookie=cookie, payload=payload,
                        )
                    )
                except (MessageError, OSError):
                    # re-send when the linger re-attaches this cookie
                    sent.discard(cookie)
                    with self._watch_lock:
                        self._watchers.get(key, {}).pop(cookie, None)
            if set(state["acks"]) >= state["want"]:
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            state["event"].wait(min(remaining, 0.1))
        self._notify_pending.pop(notify_id, None)
        return [
            {
                "cookie": cookie,
                "acked": cookie in state["acks"],
                "reply": state["acks"].get(cookie, b"").decode(
                    "latin-1"
                ),
            }
            for cookie in sorted(state["want"])
        ]

    def _handle_notify_ack(self, msg: MWatchNotifyAck) -> None:
        state = self._notify_pending.get(msg.notify_id)
        if state is None:
            return
        state["acks"][msg.cookie] = msg.reply
        if set(state["acks"]) >= state["want"]:
            state["event"].set()

    def _cls_ctx(self, pg: PG, store_oid: str) -> MethodContext:
        exists = self.store.exists(pg.cid, store_oid)
        attrs = {}
        if exists:
            attrs = {
                k[2:]: v
                for k, v in self.store.list_attrs(
                    pg.cid, store_oid
                ).items()
                if k.startswith("c_")
            }
        omap_fn = lambda: self._omap_of(pg, store_oid)  # noqa: E731
        if self._is_ec(pg):
            # class attrs and omap replicate on every shard, so the
            # local reads stand; the DATA read decodes across shards
            ecs = self._ec_store_for(pg)
            return MethodContext(
                read_fn=lambda: ecs.get(store_oid),
                attrs=attrs,
                exists=exists,
                omap_fn=omap_fn,
            )
        return MethodContext(
            read_fn=lambda: self.store.read(pg.cid, store_oid),
            attrs=attrs,
            exists=exists,
            omap_fn=omap_fn,
        )

    def _mutate(
        self,
        pg: PG,
        epoch: int,
        msg: MOSDOp,
        store_oid: str,
        pre_encoded=None,
    ):
        """Append a log entry + apply data in ONE transaction, fan the
        same transaction to the acting peers (issue_repop).  Raises
        StoreError to surface op errors; replica failures surface as
        -EAGAIN so the client retries after the interval changes.
        ``pre_encoded`` is a coalesced-dispatch (shards, meta) pair
        for this op's payload (EC WRITEFULL only)."""
        if self._is_ec(pg):
            return self._mutate_ec(
                pg, epoch, msg, store_oid, pre_encoded=pre_encoded
            )
        if msg.reqid and msg.reqid in pg.reqid_cache:
            # retried op already applied (osd_reqid_t dedup; the cache
            # outlives log trimming, like the log's dups) — replay the
            # original outdata so retried CALLs keep their result
            return pg.reqid_cache[msg.reqid][1]
        existed = self.store.exists(pg.cid, store_oid)
        if msg.op == OSD_OP_DELETE and not existed:
            # only the SAME client op retried is idempotent; a fresh
            # delete of a missing object is -ENOENT (rados semantics)
            raise StoreError(f"no object {msg.oid} (-ENOENT)")
        # snap context: preserve the pre-mutation head if the pool has
        # a snap this object has not been cloned for (make_writeable)
        self._maybe_clone(
            pg, epoch, msg.oid, existed, msg.snap_seq
        )
        ctx = None
        outdata = b""
        if msg.op == OSD_OP_CALL:
            # run the stored procedure BEFORE any state advances: a
            # method failure must leave no trace (no seq bump, no log
            # entry, no transaction)
            cls_name, _, method = msg.attr.partition(".")
            ctx = self._cls_ctx(pg, store_oid)
            outdata = self._cls_call(cls_name, method, ctx, msg.data)
        pg.seq += 1
        version = (epoch, pg.seq)
        op = DELETE if (
            msg.op == OSD_OP_DELETE
        ) else MODIFY
        prior = pg.log.object_op(msg.oid)
        entry = LogEntry(
            op=op, oid=msg.oid, version=version, reqid=msg.reqid,
            # the OBJECT's previous version: EV_ZERO means it did not
            # exist before this op (drives divergent rollback); if the
            # log no longer says, (1, 0) marks "existed, version
            # unknown" — still nonzero, still rolls back via re-pull
            prior_version=(
                prior.version if prior is not None
                else ((1, 0) if existed else EV_ZERO)
            ),
        )
        txn = Transaction()
        if msg.op == OSD_OP_WRITEFULL:
            if existed:
                txn.remove(pg.cid, store_oid)
            txn.touch(pg.cid, store_oid)
            if msg.data:
                txn.write(pg.cid, store_oid, 0, msg.data)
        elif msg.op == OSD_OP_WRITE:
            txn.write(pg.cid, store_oid, msg.offset, msg.data)
        elif msg.op == OSD_OP_APPEND:
            # offset resolved HERE, inside the primary's per-PG op
            # stream — that is what makes append atomic
            size = self.store.stat(pg.cid, store_oid) if existed else 0
            if not existed:
                txn.touch(pg.cid, store_oid)
            txn.write(pg.cid, store_oid, size, msg.data)
        elif msg.op == OSD_OP_SETXATTR:
            txn.touch(pg.cid, store_oid)
            txn.setattr(pg.cid, store_oid, "u_" + msg.attr, msg.data)
        elif msg.op == OSD_OP_OMAPSET:
            kv = Decoder(msg.data).map(
                lambda d: d.string(), lambda d: d.bytes()
            )
            txn.touch(pg.cid, store_oid)
            txn.omap_setkeys(pg.cid, store_oid, kv)
        elif msg.op == OSD_OP_OMAPRM:
            keys = Decoder(msg.data).list(lambda d: d.string())
            txn.touch(pg.cid, store_oid)
            txn.omap_rmkeys(pg.cid, store_oid, keys)
        elif msg.op == OSD_OP_OMAPCLEAR:
            txn.touch(pg.cid, store_oid)
            txn.omap_clear(pg.cid, store_oid)
        elif msg.op == OSD_OP_CALL:
            # fold the staged mutations into THIS logged, replicated
            # transaction (do_osd_ops CEPH_OSD_OP_CALL)
            if ctx.removed:
                if existed:
                    txn.remove(pg.cid, store_oid)
            else:
                surviving: dict[str, bytes] = {}
                surviving_omap: dict[str, bytes] = {}
                if ctx.new_data is not None:
                    if existed:
                        # a rewrite must not destroy the object's
                        # OTHER attrs or its omap —
                        # cls_cxx_write_full keeps them
                        surviving = self.store.list_attrs(
                            pg.cid, store_oid
                        )
                        surviving_omap = self._omap_of(pg, store_oid)
                        txn.remove(pg.cid, store_oid)
                    txn.touch(pg.cid, store_oid)
                    if ctx.new_data:
                        txn.write(pg.cid, store_oid, 0, ctx.new_data)
                else:
                    # idempotent: the same txn must apply on a lagging
                    # replica that does not hold the object yet
                    txn.touch(pg.cid, store_oid)
                for k, v in surviving.items():
                    if not (
                        k.startswith("c_") and k[2:] in ctx.new_attrs
                    ):
                        txn.setattr(pg.cid, store_oid, k, v)
                if surviving_omap:
                    txn.omap_setkeys(
                        pg.cid, store_oid, surviving_omap
                    )
                for k, v in ctx.new_attrs.items():
                    txn.setattr(pg.cid, store_oid, "c_" + k, v)
                if ctx.rm_omap:
                    txn.omap_rmkeys(
                        pg.cid, store_oid, sorted(ctx.rm_omap)
                    )
                if ctx.new_omap:
                    txn.omap_setkeys(pg.cid, store_oid, ctx.new_omap)
        elif msg.op == OSD_OP_DELETE:
            txn.remove(pg.cid, store_oid)
        if (
            not existed
            and msg.op != OSD_OP_DELETE
            and not (ctx is not None and ctx.removed)
        ):
            # birth stamp: reads at snaps older than creation resolve
            # to -ENOENT (the clone/head born-before-snap check)
            pool = self._pool_of(pg)
            txn.setattr(
                pg.cid, store_oid, BORN_ATTR,
                str(pool.snap_seq if pool else 0).encode(),
            )
        tpool = self._pool_of(pg)
        if (
            tpool is not None
            and tpool.tier_of >= 0
            and tpool.cache_mode == "writeback"
            and msg.op != OSD_OP_DELETE
            and not (ctx is not None and ctx.removed)
            and not msg.reqid.startswith("tier-")
        ):
            # writeback bookkeeping (maybe_handle_cache_detail's
            # dirty tracking): the agent flushes b"1" objects to the
            # base pool; internal tier- ops (promotions) stay clean
            txn.setattr(pg.cid, store_oid, TIER_DIRTY, b"1")
        txn_by_osd = {
            osd: txn
            for osd in pg.acting
            if osd != CRUSH_ITEM_NONE
        }
        out = self._commit_and_replicate(
            pg, epoch, msg, entry, txn_by_osd, outdata
        )
        if msg.op == OSD_OP_WRITEFULL:
            # the committed payload IS the object's full content:
            # register it device-resident so a deep scrub digests it
            # without a second host→device upload (ops/residency.py;
            # any later txn on the object invalidates by generation)
            from ..ops.residency import residency_cache

            residency_cache().put_committed(
                self.store, pg.cid, store_oid, data=msg.data
            )
        if ctx is not None:
            for payload in ctx.notifies:
                # post-commit, fire-and-forget (cls_cxx_notify)
                self._notify_watchers(pg, msg.oid, payload, timeout=0)
        return out

    def _commit_and_replicate(
        self,
        pg: PG,
        epoch: int,
        msg: MOSDOp,
        entry: LogEntry,
        txn_by_osd: dict[int, "Transaction"],
        outdata: bytes,
    ):
        """Shared commit tail for both backends (issue_repop): stamp
        the log entry + advanced info into every transaction, apply
        our own with rollback-on-failure, dedup-cache, fan the rest
        out as MOSDRepOp, and surface live replica failures as
        -EAGAIN.  Replicated pools pass ONE shared Transaction for all
        targets; erasure pools pass a distinct per-position one."""
        version = entry.version
        # advance pg.info inside the txn, but only adopt it in memory
        # once the local apply succeeded — a failed transaction must
        # not leave a phantom entry in the in-memory log
        saved_last = pg.info.last_update
        pg.info.last_update = version
        for txn in {id(t): t for t in txn_by_osd.values()}.values():
            self._persist_entry(pg, entry, txn)
            self._persist_info(pg, txn)
        commit_t0 = time.perf_counter()
        try:
            self.store.queue_transaction(txn_by_osd[self.whoami])
        except StoreError:
            pg.info.last_update = saved_last
            pg.seq -= 1
            raise
        # commit latency × request size into the per-OSD grid (the
        # PerfHistogram seat `ceph tell osd.N perf histogram dump`
        # serves) and the 1D histogram `ceph osd perf` windows
        commit_lat = time.perf_counter() - commit_t0
        txn_bytes = sum(
            len(op[4])
            for op in txn_by_osd[self.whoami].ops
            if op[0] == "write"
        )
        self._commit_grid.add(commit_lat, float(max(txn_bytes, 1)))
        self._commit_hist.add(commit_lat)
        pg.log.append(entry)
        if msg.reqid:
            pg.reqid_cache[msg.reqid] = (version, outdata)
            while len(pg.reqid_cache) > 4 * self.log_keep:
                pg.reqid_cache.pop(next(iter(pg.reqid_cache)))
        entry_blob = _encode_entry(entry)
        failed: list[int] = []
        for osd, txn in txn_by_osd.items():
            if osd == self.whoami:
                continue
            if self._cur_op is not None:
                self._cur_op.mark_event(f"sub_op_sent osd.{osd}")
            tracing.current_span().mark_event(
                f"sub_op_sent osd.{osd}"
            )
            try:
                ack = self._peer_conn(osd).call(
                    MOSDRepOp(
                        pgid=pg.pgid, epoch=epoch, txn=txn,
                        entry_blob=entry_blob, trace=msg.reqid,
                    ),
                    timeout=self.repop_timeout,
                )
                if isinstance(ack, MOSDRepOpReply) and not ack.ok:
                    failed.append(osd)
                else:
                    if self._cur_op is not None:
                        self._cur_op.mark_event(
                            f"sub_op_commit_rec osd.{osd}"
                        )
                    tracing.current_span().mark_event(
                        f"sub_op_commit_rec osd.{osd}"
                    )
            except (MessageError, OSError):
                failed.append(osd)
        live_failures = [
            osd for osd in failed if self.monc.osdmap.is_up(osd)
        ]
        if live_failures:
            pg.repop_clean = False
            # an up replica missed the write: re-peer to push it, and
            # make the client retry rather than acking a write that is
            # not on the full acting set (the reference blocks the op
            # until every acting replica commits).  Clearing the
            # peered interval defeats the unchanged-interval skip so
            # the walk really re-peers (a lost fire-and-forget
            # activation would otherwise NAK forever).
            pg.peered_interval = None
            self._workq.put(("map", epoch))
            raise StoreError(
                f"replicas {live_failures} missed the write (-EAGAIN)"
            )
        self._maybe_trim(pg)
        return outdata

    def _mutate_ec(
        self,
        pg: PG,
        epoch: int,
        msg: MOSDOp,
        store_oid: str,
        pre_encoded=None,
    ):
        """Erasure-pool mutation: encode the new logical object and fan
        one per-position transaction (shard + HashInfo + log entry +
        info) down the same MOSDRepOp path replicated pools use
        (ECBackend::submit_transaction under PrimaryLogPG,
        ECBackend.cc:1502).  Partial writes and appends go through the
        stripe-granular RMW pipeline (ec_pg.rmw_write_txns wrapping
        the shared ec/stripe.rmw_encode plan): only the covered
        stripe range is read/encoded/shipped, gated on pg.repop_clean
        so a range write can never land on a replica whose shard may
        be stale."""
        if msg.reqid and msg.reqid in pg.reqid_cache:
            return pg.reqid_cache[msg.reqid][1]
        osdmap = self.monc.osdmap
        pool = self._pool_of(pg)
        codec = self._ec_codec(pg)
        ecs = self._ec_store_for(pg)
        present = [
            (pos, osd)
            for pos, osd in enumerate(pg.acting)
            if osd != CRUSH_ITEM_NONE
            and (osd == self.whoami or osdmap.is_up(osd))
        ]
        if len(present) < max(codec.k, pool.min_size):
            # the reference refuses writes below min_size (undersized)
            raise StoreError(
                f"pg {pg.pgid} undersized: {len(present)} shards < "
                f"min_size {max(codec.k, pool.min_size)} (-EAGAIN)"
            )
        try:
            old_meta = ecs.meta(store_oid)
        except ErasureCodeError:
            old_meta = None
        existed = old_meta is not None
        if msg.op == OSD_OP_DELETE and not existed:
            raise StoreError(f"no object {msg.oid} (-ENOENT)")
        # snap context (make_writeable): the clone op copies each
        # position's LOCAL shard, so one logged txn preserves the
        # erasure-coded head too
        self._maybe_clone(
            pg, epoch, msg.oid, existed, msg.snap_seq
        )
        ctx = None
        outdata = b""
        if msg.op == OSD_OP_CALL:
            # method runs BEFORE any state advances (failure must
            # leave no trace), same contract as the replicated path
            cls_name, _, method = msg.attr.partition(".")
            ctx = self._cls_ctx(pg, store_oid)
            outdata = self._cls_call(cls_name, method, ctx, msg.data)

        def read_old() -> bytes:
            try:
                return ecs.get(store_oid) if existed else b""
            except ErasureCodeError as e:
                raise StoreError(str(e))

        txns: dict[int, Transaction] = {}
        my_shard: list = []  # [bytes] when a full encode ran

        def encode_all(new_data: bytes, extra_attrs=None) -> None:
            if (
                pre_encoded is not None
                and msg.op == OSD_OP_WRITEFULL
                and new_data is msg.data
            ):
                # coalesced dispatch already encoded this payload
                # (byte-identical to encode_object; tests prove it)
                shards, meta = pre_encoded
            else:
                shards, meta = codec.encode_object(new_data)
            for pos, _osd in present:
                txns[pos] = shard_write_txn(
                    pg.cid, store_oid, shards[pos], meta, extra_attrs
                )
                if _osd == self.whoami:
                    my_shard[:] = [shards[pos]]

        def remove_all() -> None:
            for pos, _osd in present:
                # touch-then-remove applies cleanly whether or not the
                # replica holds the object (a lagging shard must still
                # accept the logged removal)
                txns[pos] = (
                    Transaction()
                    .touch(pg.cid, store_oid)
                    .remove(pg.cid, store_oid)
                )

        if msg.op == OSD_OP_WRITEFULL:
            encode_all(msg.data)
        elif msg.op in (OSD_OP_WRITE, OSD_OP_APPEND):
            old_size = old_meta["size"] if existed else 0
            # append IS a write at old_size — one branch, one gate
            offset = (
                old_size if msg.op == OSD_OP_APPEND else msg.offset
            )
            end = offset + len(msg.data)
            partial = existed and (offset > 0 or end < old_size)
            if (
                partial
                and offset <= old_size
                and msg.data
                and pg.repop_clean
            ):
                # stripe-granular RMW (ECBackend.cc:1858): only the
                # covered stripe range is read/encoded/shipped, not
                # the whole object
                txns.update(
                    rmw_write_txns(
                        codec, ecs, pg.cid, store_oid,
                        offset, msg.data,
                        [pos for pos, _osd in present],
                        old_size,
                    )
                )
            else:
                old = read_old()
                buf = bytearray(max(len(old), end))
                buf[: len(old)] = old
                buf[offset:end] = msg.data
                encode_all(bytes(buf))
        elif msg.op == OSD_OP_SETXATTR:
            if existed:
                # touch first: the txn must apply unconditionally on a
                # lagging shard that does not hold the object yet
                for pos, _osd in present:
                    txns[pos] = (
                        Transaction()
                        .touch(pg.cid, store_oid)
                        .setattr(
                            pg.cid, store_oid, "u_" + msg.attr,
                            msg.data,
                        )
                    )
            else:
                encode_all(b"", {"u_" + msg.attr: msg.data})
        elif msg.op == OSD_OP_DELETE:
            remove_all()
        elif msg.op in (OSD_OP_OMAPSET, OSD_OP_OMAPRM, OSD_OP_OMAPCLEAR):
            # omap replicates identically on every shard (attr-like);
            # an omap write on a fresh object first creates the empty
            # encoded object so meta/stat stay coherent
            if not existed:
                if msg.op != OSD_OP_OMAPSET:
                    raise StoreError(f"no object {msg.oid} (-ENOENT)")
                encode_all(b"")
            for pos, _osd in present:
                txn = txns.setdefault(
                    pos, Transaction().touch(pg.cid, store_oid)
                )
                if msg.op == OSD_OP_OMAPSET:
                    kv = Decoder(msg.data).map(
                        lambda d: d.string(), lambda d: d.bytes()
                    )
                    txn.omap_setkeys(pg.cid, store_oid, kv)
                elif msg.op == OSD_OP_OMAPRM:
                    keys = Decoder(msg.data).list(lambda d: d.string())
                    txn.omap_rmkeys(pg.cid, store_oid, keys)
                else:
                    txn.omap_clear(pg.cid, store_oid)
        elif msg.op == OSD_OP_CALL:
            if ctx.removed:
                if existed:
                    remove_all()
            else:
                new_attrs = {
                    "c_" + k: v for k, v in ctx.new_attrs.items()
                }
                if ctx.new_data is not None:
                    # shard rewrites truncate in place, so the object's
                    # other attrs and omap survive (cls_cxx_write_full
                    # keeps them)
                    encode_all(ctx.new_data, new_attrs)
                elif new_attrs and existed:
                    for pos, _osd in present:
                        txn = Transaction().touch(pg.cid, store_oid)
                        for k, v in new_attrs.items():
                            txn.setattr(pg.cid, store_oid, k, v)
                        txns[pos] = txn
                elif not existed:
                    encode_all(b"", new_attrs)
                if ctx.rm_omap or ctx.new_omap:
                    for pos, _osd in present:
                        txn = txns.setdefault(
                            pos,
                            Transaction().touch(pg.cid, store_oid),
                        )
                        if ctx.rm_omap:
                            txn.omap_rmkeys(
                                pg.cid, store_oid, sorted(ctx.rm_omap)
                            )
                        if ctx.new_omap:
                            txn.omap_setkeys(
                                pg.cid, store_oid, ctx.new_omap
                            )
        else:
            raise StoreError(f"op {msg.op} unsupported on EC (-EOPNOTSUPP)")
        if (
            not existed
            and msg.op != OSD_OP_DELETE
            and not (ctx is not None and ctx.removed)
        ):
            born = str(pool.snap_seq if pool else 0).encode()
            for pos, _osd in present:
                txn = txns.setdefault(
                    pos, Transaction().touch(pg.cid, store_oid)
                )
                txn.setattr(pg.cid, store_oid, BORN_ATTR, born)

        pg.seq += 1
        version = (epoch, pg.seq)
        op = DELETE if msg.op == OSD_OP_DELETE else MODIFY
        prior = pg.log.object_op(msg.oid)
        entry = LogEntry(
            op=op, oid=msg.oid, version=version, reqid=msg.reqid,
            prior_version=(
                prior.version if prior is not None
                else ((1, 0) if existed else EV_ZERO)
            ),
        )
        txn_by_osd = {
            osd: txns.setdefault(pos, Transaction())
            for pos, osd in present
        }
        out = self._commit_and_replicate(
            pg, epoch, msg, entry, txn_by_osd, outdata
        )
        if my_shard:
            # our position's freshly committed shard stays resident:
            # the deep-scrub crc32c and the re-encode verify of this
            # object consume it without re-paying the link
            # (generation-invalidated by any later txn)
            from ..ops.residency import residency_cache

            residency_cache().put_committed(
                self.store, pg.cid, store_oid, data=my_shard[0]
            )
        if ctx is not None:
            for payload in ctx.notifies:
                self._notify_watchers(pg, msg.oid, payload, timeout=0)
        return out

    def _maybe_trim(self, pg: PG) -> None:
        """Bound the pg log (PGLog::trim), removing the trimmed
        entries' persisted objects and recording the new tail."""
        if len(pg.log.entries) <= self.log_keep:
            return
        cut = pg.log.entries[: len(pg.log.entries) - self.log_keep]
        pg.log.trim(self.log_keep)
        pg.info.log_tail = pg.log.log_tail
        txn = Transaction()
        for entry in cut:
            txn.remove(pg.cid, _log_oid(entry.version))
        self._persist_info(pg, txn)
        try:
            self.store.queue_transaction(txn)
        except StoreError:
            pass

    # -- replica-side inline handlers --------------------------------------
    def _handle_rep_op(self, conn: Connection, msg: MOSDRepOp) -> None:
        pg = self.pgs.get(msg.pgid)
        reply = MOSDRepOpReply(tid=msg.tid, from_osd=self.whoami)
        top = self.op_tracker.create_op(
            f"rep_op({msg.trace} {msg.pgid})", trace=msg.trace
        )
        span = self.tracer.start_span(
            "rep_op",
            trace_id=msg.trace or "",
            role=tracing.ROLE_REPLICA,
            tags={"pgid": msg.pgid},
        )
        if pg is None or pg.activated_epoch == 0:
            # an unactivated replica must not splice mid-stream
            # entries into an empty log (its hole-filled log could
            # later win find_best_info's tie-break)
            reply.ok = False
            reply.error = "pg not activated (-EAGAIN)"
            top.mark_event("rejected: pg not activated")
            top.finish()
            span.mark_event("rejected: pg not activated")
            span.finish()
            conn.send(reply)
            return
        try:
            self.store.queue_transaction(msg.txn)
            entry = _decode_entry(msg.entry_blob)
            if entry.version > pg.log.head:
                pg.log.append(entry)
            pg.info.last_update = pg.log.head
            pg.seq = max(pg.seq, entry.version[1])
            # replicas bound their logs too (the primary's trim txn is
            # local; unbounded replica logs would grow forever)
            self._maybe_trim(pg)
        except StoreError as e:
            reply.ok = False
            reply.error = str(e)
        top.mark_event("applied" if reply.ok else "failed")
        top.finish()
        span.mark_event("applied" if reply.ok else "failed")
        span.finish()
        conn.send(reply)

    def _handle_query(self, conn: Connection, msg: MPGQuery) -> None:
        pg = self.pgs.get(msg.pgid)
        notify = MPGNotify(tid=msg.tid, from_osd=self.whoami)
        if pg is not None:
            notify.info_blob = _encode_info(pg.info)
            # recent suffix so the primary can locate the divergence
            # point (proc_replica_log input)
            notify.entry_blobs = [
                _encode_entry(e) for e in pg.log.entries[-64:]
            ]
        conn.send(notify)

    def _handle_log_req(self, conn: Connection, msg: MPGLogReq) -> None:
        pg = self.pgs.get(msg.pgid)
        reply = MPGLogReply(tid=msg.tid, from_osd=self.whoami)
        if pg is not None:
            reply.info_blob = _encode_info(pg.info)
            since = max(msg.since, pg.log.log_tail)
            reply.entry_blobs = [
                _encode_entry(e) for e in pg.log.entries_after(since)
            ]
        conn.send(reply)

    def _handle_pull(self, conn: Connection, msg: MPGPull) -> None:
        pg = self.pgs.get(msg.pgid)
        if pg is None:
            push = MPGPush(
                tid=msg.tid, pgid=msg.pgid, oid=msg.oid, exists=False
            )
        elif msg.shard >= 0:
            # erasure pull: reconstruct the requester's shard (runs on
            # the worker — the gather is nested sub-op RPC)
            try:
                push = self._ec_push_for(
                    pg, msg.epoch, msg.oid, msg.shard
                )
            except (StoreError, ErasureCodeError, MessageError, OSError):
                push = MPGPush(
                    tid=msg.tid, pgid=msg.pgid, oid=msg.oid,
                    exists=False,
                )
            push.tid = msg.tid
        elif self._is_ec(pg):
            # whole-object pulls are meaningless on an erasure pool
            push = MPGPush(
                tid=msg.tid, pgid=msg.pgid, oid=msg.oid, exists=False
            )
        else:
            push = self._push_for(pg, msg.epoch, msg.oid)
            push.tid = msg.tid
            if not self.store.exists(pg.cid, OBJ_PREFIX + msg.oid):
                push.exists = False
        conn.send(push)

    def _get_or_create_pg(self, pgid: str) -> PG:
        with self._pg_lock:
            pg = self.pgs.get(pgid)
            if pg is None:
                pg = PG(pgid, int(pgid.split(".")[0]))
                self._ensure_coll(pg)
                self.pgs[pgid] = pg
            return pg

    def _handle_push(self, conn: Connection, msg: MPGPush) -> None:
        """Recovery push: apply the object DATA only.  The log entry
        deliberately does NOT splice in here — the authoritative
        suffix arrives with MPGActivate, whose rewind point was
        computed from this peer's pre-recovery log; appending pushed
        entries early would make that rewind classify them as
        divergent and roll back the objects just pushed."""
        pg = self._get_or_create_pg(msg.pgid)
        self._apply_push(pg, msg)
        conn.send(MPGPushReply(tid=msg.tid, from_osd=self.whoami))

    def _apply_activate(self, conn: Connection, msg: MPGActivate):
        """Worker-side activation: rewind divergent entries (removing
        their objects, re-pulling survivors from the primary over the
        SAME connection), adopt the authoritative suffix, go active
        (PGLog::rewind_divergent_log + merge_log).  Runs on the worker
        because the re-pulls are nested RPC."""
        pg = self._get_or_create_pg(msg.pgid)
        if msg.epoch < pg.activated_epoch or (
            pg.primary == self.whoami
            and pg.state == "active"
            and msg.epoch <= self.monc.epoch
        ):
            # stale activation (generation check): an older epoch is
            # a dead interval's late send, and an ACTING PRIMARY
            # never applies one from an epoch it has already seen —
            # the failover storm exposed a dead primary's queued
            # activation rewinding the NEW primary's freshly adopted
            # log (same epoch, so the epoch test alone cannot catch
            # it).  An activation from a FUTURE epoch still applies:
            # it means our own primacy knowledge is the stale side
            # (a newer interval's primary is activating us before
            # our map walk caught up).  Ack and drop.
            try:
                conn.send(
                    MPGPushReply(tid=msg.tid, from_osd=self.whoami)
                )
            except (MessageError, OSError):
                pass
            return
        divergent = pg.log.truncate_after(msg.rewind_to)
        repull: set[str] = set()
        for entry in divergent:  # newest first
            txn = Transaction()
            store_oid = OBJ_PREFIX + entry.oid
            if self.store.exists(pg.cid, store_oid):
                txn.remove(pg.cid, store_oid)
            txn.remove(pg.cid, _log_oid(entry.version))
            try:
                self.store.queue_transaction(txn)
            except StoreError:
                pass
            if entry.prior_version != EV_ZERO:
                # the object existed before the divergent op: its
                # authoritative state must come back from the primary
                repull.add(entry.oid)
        shard = -1
        if self._is_ec(pg):
            # my acting position from the authoritative map (this PG
            # may be freshly created here with no acting cached yet)
            osdmap = self.monc.osdmap
            ps = int(pg.pgid.split(".")[1])
            acting = []
            if osdmap is not None and pg.pool_id in osdmap.pools:
                _u, _up, acting, _p = osdmap.pg_to_up_acting_osds(
                    pg.pool_id, ps
                )
            if self.whoami in acting:
                shard = acting.index(self.whoami)
            else:
                repull = set()  # stray shard: next peering re-places it
        for oid in sorted(repull):
            try:
                # bounded: an activating primary that died right
                # after sending must not wedge this worker for the
                # full default call timeout PER OBJECT
                reply = conn.call(
                    MPGPull(
                        pgid=pg.pgid, epoch=msg.epoch, oid=oid,
                        shard=shard,
                    ),
                    timeout=self.repop_timeout,
                )
            except (MessageError, OSError):
                # the primary is gone: every further pull on this
                # conn eats another timeout — stop; the objects stay
                # missing and the NEXT interval's primary pushes them
                break
            if isinstance(reply, MPGPush):
                self._apply_push(pg, reply)
        for blob in msg.entry_blobs:
            entry = _decode_entry(blob)
            if entry.version > pg.log.head:
                pg.log.append(entry)
                self._persist_entry(pg, entry)
        pg.info = _decode_info(msg.info_blob)
        pg.info.last_update = pg.log.head
        # the primary encodes info_blob before bumping its own
        # last_epoch_started; activation IS the epoch start, so stamp
        # it here too or replicas carry a stale les forever and
        # find_best_info's les-first ordering compares garbage
        pg.info.last_epoch_started = max(
            pg.info.last_epoch_started, msg.epoch
        )
        pg.seq = max(pg.seq, pg.info.last_update[1])
        pg.state = "replica"
        pg.activated_epoch = msg.epoch
        # the adopted suffix counts against the log bound like any
        # other appends (rep-ops trim; activation must too)
        self._maybe_trim(pg)
        self._persist_info(pg)
        conn.send(MPGPushReply(tid=msg.tid, from_osd=self.whoami))

    # -- dispatch ----------------------------------------------------------
    def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        if isinstance(msg, MOSDOp):
            # nested RPC needed → worker queue (enqueue_op), as a
            # weighted CLIENT-class item costed by payload size;
            # admission-controlled by the client throttle
            cost = len(msg.data) + 1024
            if not self.client_throttle.get_or_fail(cost):
                reply = MOSDOpReply(
                    tid=msg.tid, ok=False,
                    error="client throttle full (-EAGAIN)",
                )
                try:
                    conn.send(reply)
                except (MessageError, OSError):
                    pass
                return True
            self._workq.enqueue(
                self._qos_class_of(msg), cost, ("op", conn, msg, cost)
            )
            return True
        if isinstance(msg, MOSDRepOp):
            self._handle_rep_op(conn, msg)
            return True
        if isinstance(msg, MPGQuery):
            self._handle_query(conn, msg)
            return True
        if isinstance(msg, MPGLogReq):
            self._handle_log_req(conn, msg)
            return True
        if isinstance(msg, MPGPull):
            if msg.shard >= 0:
                # erasure reconstruct = nested sub-op RPC → worker
                # recovery traffic shares by weight; strict-queueing
                # it would starve queued client ops behind a
                # sustained pull stream
                self._workq.enqueue(
                    CLASS_RECOVERY, 4096, ("pull", conn, msg)
                )
            else:
                self._handle_pull(conn, msg)
            return True
        if isinstance(msg, (MECSubRead, MECSubWrite)):
            # shard-side sub-op service (handle_sub_read/-write,
            # ECBackend.cc:934,1010): pure store access, serve inline
            return self._shard_server.ms_dispatch(conn, msg)
        if isinstance(msg, MWatchNotifyAck):
            self._handle_notify_ack(msg)
            return True
        if isinstance(msg, MPGPush):
            self._handle_push(conn, msg)
            return True
        if isinstance(msg, MRecoveryReserve):
            key = (msg.pgid, msg.from_osd)
            if msg.op == "request":
                now = time.monotonic()
                with self._recovery_lock:
                    for k, (t0, _c) in list(
                        self._remote_reservations.items()
                    ):
                        if now - t0 > self.reservation_timeout:
                            del self._remote_reservations[k]
                    if (
                        key in self._remote_reservations
                        or len(self._remote_reservations)
                        < self.max_backfills
                    ):
                        self._remote_reservations[key] = (now, conn)
                        verdict = "grant"
                    else:
                        verdict = "deny"
                try:
                    conn.send(MRecoveryReserve(
                        tid=msg.tid, op=verdict, pgid=msg.pgid,
                        epoch=msg.epoch, from_osd=self.whoami,
                    ))
                except (MessageError, OSError):
                    pass
            elif msg.op == "release":
                with self._recovery_lock:
                    self._remote_reservations.pop(key, None)
            return True
        if isinstance(msg, MRepScrub):
            if msg.op in ("reserve", "release"):
                self._handle_rep_scrub(conn, msg)
            else:
                threading.Thread(
                    target=self._handle_rep_scrub,
                    args=(conn, msg),
                    name=f"osd.{self.whoami}.scrubscan",
                    daemon=True,
                ).start()
            return True
        if isinstance(msg, MScrubCommand):
            self._handle_scrub_command(conn, msg)
            return True
        if isinstance(msg, MCommand):
            self._handle_tell(conn, msg)
            return True
        if isinstance(msg, MPGActivate):
            # rollback may re-pull objects (nested RPC) → worker queue
            self._workq.put(("activate", conn, msg))
            return True
        if isinstance(msg, MPing):
            if msg.is_reply:
                self.hb.handle_ping(msg.from_osd, time.monotonic())
                if msg.from_osd in self._reported:
                    self._reported.discard(msg.from_osd)
                    try:
                        self.monc.report_failure(msg.from_osd, -1.0)
                    except (MessageError, OSError):
                        pass
            else:
                conn.send(
                    MPing(
                        tid=msg.tid, from_osd=self.whoami,
                        stamp=msg.stamp, is_reply=True,
                    )
                )
            return True
        return False

    # -- backoff protocol + full-space degradation -------------------------
    _READ_OPS = frozenset(
        (
            OSD_OP_READ, OSD_OP_STAT, OSD_OP_GETXATTR,
            OSD_OP_OMAPGET, OSD_OP_LIST,
        )
    )

    def _op_is_write(self, msg: MOSDOp) -> bool:
        """True for ops that consume the mutation path (fullness
        gates these; watch/notify bookkeeping and reads pass)."""
        if msg.op in self._READ_OPS or msg.op in (
            OSD_OP_WATCH, OSD_OP_UNWATCH, OSD_OP_NOTIFY,
        ):
            return False
        if msg.op == OSD_OP_CALL:
            cls_name, _, method = msg.attr.partition(".")
            try:
                return bool(
                    self.class_handler.flags_of(cls_name, method)
                    & CLS_WR
                )
            except Exception:  # noqa: BLE001 — unknown method: the
                # op will fail anyway; classify conservatively
                return True
        return True

    def statfs(self) -> dict:
        """Store statfs, cached at ~tick granularity (the walk is
        O(objects); the op path consults this per mutation)."""
        now = time.monotonic()
        cached = self._statfs_cache
        if cached is not None and now - cached[0] < 0.5:
            return cached[1]
        stats = self.store.statfs()
        self._statfs_cache = (now, stats)
        return stats

    def _check_full(self) -> bool:
        stats = self.statfs()
        total = stats["total"]
        if total <= 0:
            return False
        ratio = (
            self._mon_full_ratio
            if self._mon_full_ratio is not None
            else float(self.config.get("mon_osd_full_ratio"))
        )
        return stats["used"] / total >= ratio

    def _send_block(
        self, conn: Connection, msg: MOSDOp, pgid: str, reason: str
    ) -> None:
        """Answer the op with a tid-paired BLOCK backoff and record
        it; the tick loop unblocks when the condition clears.  One
        logical backoff per (conn, pgid): a parked client's bounded
        re-probes re-use the existing id instead of growing the
        registry for the life of the condition."""
        with self._backoff_lock:
            existing = next(
                (
                    b for b in self._backoffs.values()
                    if b["conn"] is conn and b["pgid"] == pgid
                ),
                None,
            )
            if existing is not None:
                existing["reason"] = reason
                bid = existing["id"]
            else:
                bid = next(self._backoff_seq)
                self._backoffs[bid] = {
                    "id": bid,
                    "pgid": pgid,
                    "reason": reason,
                    "conn": conn,
                    "since": time.monotonic(),
                }
        try:
            conn.send(
                MOSDBackoff(
                    tid=msg.tid, op=BACKOFF_OP_BLOCK, pgid=pgid,
                    id=bid, reason=reason, epoch=self.monc.epoch,
                )
            )
        except (MessageError, OSError):
            with self._backoff_lock:
                self._backoffs.pop(bid, None)

    def _release_backoffs(self) -> None:
        """Tick-driven unblock: a backoff whose condition cleared
        (space freed, PG finished peering) releases the client's
        parked ops; dead connections drop theirs."""
        with self._backoff_lock:
            snapshot = list(self._backoffs.values())
        if not snapshot:
            return
        full = self._check_full()
        for b in snapshot:
            conn = b["conn"]
            if getattr(conn, "is_closed", False):
                with self._backoff_lock:
                    self._backoffs.pop(b["id"], None)
                continue
            if b["reason"] == "full":
                release = not full
            else:  # peering
                pg = self.pgs.get(b["pgid"])
                release = (
                    pg is None
                    or pg.primary != self.whoami
                    or pg.state == "active"
                )
            if not release:
                continue
            with self._backoff_lock:
                self._backoffs.pop(b["id"], None)
            try:
                conn.send(
                    MOSDBackoff(
                        # even tid space: an accepting-side send must
                        # never collide with the client's in-flight
                        # odd call tids (it would be consumed as that
                        # op's reply and the release lost)
                        tid=self.messenger.new_even_tid(),
                        op=BACKOFF_OP_UNBLOCK,
                        pgid=b["pgid"], id=b["id"],
                        reason=b["reason"], epoch=self.monc.epoch,
                    )
                )
            except (MessageError, OSError):
                pass  # the client's map-change fallback unparks it

    def dump_backoffs(self) -> list[dict]:
        now = time.monotonic()
        with self._backoff_lock:
            return [
                {
                    "id": b["id"],
                    "pgid": b["pgid"],
                    "reason": b["reason"],
                    "age": round(now - b["since"], 3),
                }
                for b in self._backoffs.values()
            ]

    def _report_stats(self, now: float) -> None:
        """Push kb/kb_used/kb_avail to the mon (~1 Hz) — the
        osd_stat_t report feeding OSD_NEARFULL/OSD_FULL.  The command
        round-trip runs OFF the tick thread (at most one in flight):
        a partitioned mon must not stall the heartbeat path — ticks
        blocked behind a 2s command timeout would make THIS OSD file
        spurious failure reports for every reachable peer."""
        if now - self._stat_report_last < self.stat_report_interval:
            return
        self._stat_report_last = now
        stats = self.statfs()
        self.perf.set("stat_bytes", stats["total"])
        self.perf.set("stat_bytes_used", stats["used"])
        self.perf.set("stat_bytes_avail", stats["avail"])
        if self._stat_report_inflight:
            return
        self._stat_report_inflight = True
        if self.shared_services:
            # ride the shared offload pool: no short-lived thread per
            # report at 100-daemon scale
            self._stack().offload.submit(
                lambda: self._send_stat_report(stats)
            )
        else:
            threading.Thread(
                target=self._send_stat_report,
                args=(stats,),
                name=f"osd.{self.whoami}.statrep",
                daemon=True,
            ).start()

    def _commit_latency_ms(self) -> float:
        """Mean commit latency since the last stat report (the
        osd_stat_t commit_latency_ms seat `ceph osd perf` serves)."""
        snap = self._commit_hist.snapshot()
        psum, pcount = self._commit_last
        dsum = snap["sum"] - psum
        dcount = snap["count"] - pcount
        self._commit_last = (snap["sum"], snap["count"])
        return round(1000.0 * dsum / dcount, 3) if dcount > 0 else 0.0

    def _send_stat_report(self, stats: dict) -> None:
        try:
            reply = self.monc.command(
                {
                    "prefix": "osd stat report",
                    "osd": self.whoami,
                    "kb": stats["total"] // 1024,
                    "kb_used": stats["used"] // 1024,
                    "kb_avail": stats["avail"] // 1024,
                    # our store has no journal/apply split: apply
                    # mirrors commit (documented deviation)
                    "commit_latency_ms": self._commit_latency_ms(),
                },
                timeout=2.0,
            )
            if reply.rc == 0 and reply.outb:
                ratio = json.loads(reply.outb).get("full_ratio")
                if ratio is not None:
                    self._mon_full_ratio = float(ratio)
        except (MessageError, OSError, ValueError, TypeError):
            pass  # the next tick's report retries
        finally:
            self._stat_report_inflight = False

    def _dispatch_history(self, args: dict) -> dict:
        """`dispatch history` (tell + admin socket): the raw
        flight-recorder ring — process-global, like the kernel
        counters it feeds."""
        from ..ops.profiler import dispatch_profiler

        try:
            limit = int(args.get("limit", 0) or 0)
        except (TypeError, ValueError):
            limit = 0
        return dispatch_profiler().history(
            kind=str(args.get("kind", "") or "") or None,
            limit=limit,
        )

    def _dispatch_summary(self, args: dict) -> dict:
        """`dispatch summary` (tell + admin socket): per-kind
        rollup with the derived time-split/occupancy/residency
        ratios."""
        from ..ops.profiler import dispatch_profiler

        return dispatch_profiler().summary(
            kind=str(args.get("kind", "") or "") or None
        )

    def _handle_tell(self, conn: Connection, msg: MCommand) -> None:
        """`ceph tell osd.N ...` service (MCommand): the fault-plane
        commands and dump_backoffs, answered inline."""
        from ..msg.message import MMonCommandReply

        reply = MMonCommandReply(tid=msg.tid)
        try:
            cmd = json.loads(msg.cmd)
            prefix = str(cmd.get("prefix", ""))
            if prefix.startswith("fault"):
                op = prefix.split(" ", 1)[1] if " " in prefix else ""
                args = {
                    k: v for k, v in cmd.items() if k != "prefix"
                }
                args["op"] = op or args.get("op", "list")
                reply.outb = json.dumps(
                    self.messenger.faults.command(args)
                )
            elif prefix == "dump_backoffs":
                reply.outb = json.dumps(self.dump_backoffs())
            elif prefix == "perf dump":
                from ..msg.stack import stack_perf_dump

                dump = dict(self.perf.dump())
                dump.update(self.messenger.faults.perf.dump())
                dump.update(stack_perf_dump())
                wal_perf = getattr(self.store, "wal_perf", None)
                if wal_perf is not None:
                    dump.update(wal_perf.dump())
                reply.outb = json.dumps(dump)
            elif prefix == "perf histogram dump":
                # the `ceph daemonperf`/`perf histogram dump` tell
                # surface: raw grids, not rollups — per-(qos, type)
                # completion + per-stage gaps + the commit grid
                out = self.op_tracker.dump_histograms()
                out["osd"] = self.whoami
                out["commit_latency_histogram"] = (
                    self._commit_grid.dump()
                )
                reply.outb = json.dumps(out)
            elif prefix == "dump_historic_slow_ops":
                reply.outb = json.dumps(
                    self.op_tracker.dump_historic_slow_ops(
                        float(cmd.get("threshold", 0.0)),
                        str(cmd.get("qos_class", "")),
                    )
                )
            elif prefix == "dispatch history":
                reply.outb = json.dumps(self._dispatch_history(cmd))
            elif prefix == "dispatch summary":
                reply.outb = json.dumps(self._dispatch_summary(cmd))
            else:
                reply.rc = -22
                reply.outs = f"unknown tell command {prefix!r}"
        except (ValueError, TypeError, KeyError) as e:
            reply.rc = -22
            reply.outs = f"{type(e).__name__}: {e}"
        try:
            conn.send(reply)
        except (MessageError, OSError):
            pass

    # -- scrub plane (osd/scrub.py drives; these are the wire ends) --------
    def _handle_rep_scrub(self, conn: Connection, msg: MRepScrub):
        """Acting-set member side of one scrub round: reservation
        verdicts answer inline; ``ls``/``scan`` are local store reads
        plus one batched digest pass — they run on a side thread so a
        long digest can stall neither the messenger loop (heartbeats)
        nor the worker (whose own in-flight scrub may be waiting on
        THIS osd, the classic cross-scrub deadlock)."""
        reply = MScrubMap(
            tid=msg.tid, pgid=msg.pgid, from_osd=self.whoami
        )
        pg = self.pgs.get(msg.pgid)
        try:
            if msg.op == "reserve":
                reply.ok = self.scrubber.handle_reserve(
                    msg.pgid, msg.from_osd
                )
            elif msg.op == "release":
                self.scrubber.handle_release(msg.pgid, msg.from_osd)
            elif pg is None:
                reply.ok = False
                reply.error = f"pg {msg.pgid} unknown here"
            elif msg.op == "ls":
                names = [
                    o
                    for o in self.store.list_objects(pg.cid)
                    if o.startswith(OBJ_PREFIX)
                ]
                reply.map_json = json.dumps(sorted(names))
            elif msg.op == "scan":
                reply.map_json = json.dumps(
                    build_scrub_map(
                        self.store, pg.cid, msg.oids, msg.deep,
                        with_hinfo=self._is_ec(pg),
                    )
                )
            else:
                reply.ok = False
                reply.error = f"unknown scrub op {msg.op!r}"
        except StoreError as e:
            reply.ok = False
            reply.error = str(e)
        try:
            conn.send(reply)
        except (MessageError, OSError):
            pass

    def _handle_scrub_command(self, conn: Connection, msg: MScrubCommand):
        """On-demand scrub plane (`ceph pg (deep-)scrub/repair`,
        `rados list-inconsistent-obj`): the mon names this primary,
        the client dispatches here.  Orders are acknowledged when
        QUEUED (the reference's "instructing pg ..." contract);
        list-inconsistent serves the persisted ScrubStore records."""
        from ..msg.message import MMonCommandReply

        reply = MMonCommandReply(tid=msg.tid)
        pg = self.pgs.get(msg.pgid)
        if (
            pg is None
            or pg.primary != self.whoami
            or pg.state != "active"
        ):
            reply.rc = -11
            reply.outs = f"not primary for pg {msg.pgid} (-EAGAIN)"
        elif msg.op == "list-inconsistent-obj":
            reply.outb = json.dumps(
                {
                    "epoch": self.monc.epoch,
                    "inconsistents": ScrubStore.load(
                        self.store, pg.cid
                    ),
                }
            )
        elif msg.op in ("scrub", "deep-scrub", "repair"):
            self.scrubber.request(
                msg.pgid,
                deep=msg.op != "scrub",
                repair=msg.op == "repair",
            )
            reply.outs = (
                f"instructing pg {msg.pgid} on osd.{self.whoami} "
                f"to {msg.op}"
            )
        else:
            reply.rc = -22
            reply.outs = f"unknown scrub command {msg.op!r}"
        try:
            conn.send(reply)
        except (MessageError, OSError):
            pass

    def ms_handle_reset(self, conn: Connection) -> None:
        """A dead client connection takes its watches with it
        (watch_disconnect_t without the grace timer) — and a dead
        PRIMARY connection returns its recovery reservation leases."""
        with self._recovery_lock:
            for k, (_t0, c) in list(
                self._remote_reservations.items()
            ):
                if c is conn:
                    del self._remote_reservations[k]
        # a dead client takes its backoffs: nothing to unblock
        with self._backoff_lock:
            for bid, b in list(self._backoffs.items()):
                if b["conn"] is conn:
                    del self._backoffs[bid]
        with self._watch_lock:
            for key in list(self._watchers):
                watchers = self._watchers[key]
                for cookie, c in list(watchers.items()):
                    if c is conn:
                        del watchers[cookie]
                if not watchers:
                    del self._watchers[key]

    # -- write coalescing (ROADMAP item 1's batched dispatch) --------------
    def _coalesce_op_items(self, item) -> list:
        """After dequeuing an EC full-object write, drain up to
        ``osd_tpu_batch_max - 1`` more CONSECUTIVE same-pool
        WRITEFULLs from the SAME QoS class queue (the reference's
        op-shard batching shape, OSDMapMapping.h:18's amortize-the-
        setup lesson applied to the link): they ride one batched
        encode dispatch while every op still dedups, commits,
        replicates, traces, and replies individually, in queue order
        — per-class QoS ordering is untouched because only the head
        run of the class that was ALREADY being served drains."""
        if self.osd_tpu_batch_max <= 1:
            return []
        msg = item[2]
        if msg.op != OSD_OP_WRITEFULL or not msg.data:
            return []
        pg = self.pgs.get(msg.pgid)
        if (
            pg is None
            or pg.primary != self.whoami
            or pg.state != "active"
            or not self._is_ec(pg)
        ):
            return []
        klass = self._workq.last_class()
        if not klass or klass == CLASS_STRICT:
            return []
        pool_prefix = msg.pgid.split(".", 1)[0] + "."

        def matches(it) -> bool:
            # cheap + lock-free: runs under the scheduler lock
            return (
                isinstance(it, tuple)
                and len(it) == 4
                and it[0] == "op"
                and it[2].op == OSD_OP_WRITEFULL
                and bool(it[2].data)
                and it[2].pgid.startswith(pool_prefix)
            )

        return self._workq.drain_class(
            klass, matches, self.osd_tpu_batch_max - 1
        )

    def _handle_op_batch(self, items: list) -> None:
        """Serve a coalesced batch: ONE batched encode dispatch
        (ECCodec.encode_object_batch → the pipelined device pass with
        double-buffered transfers), then each op runs its normal
        per-op path with its shards precomputed — dedup/snap/log/
        replication/reply semantics unchanged, completions fan back
        out per op in queue order."""
        pre: dict[int, tuple] = {}
        pg = self.pgs.get(items[0][2].pgid)
        if pg is not None:
            try:
                codec = self._ec_codec(pg)
                encs = codec.encode_object_batch(
                    [it[2].data for it in items]
                )
                pre = {
                    id(it[2]): enc for it, enc in zip(items, encs)
                }
            except Exception:  # noqa: BLE001 — coalescing is an
                # optimization: a batch-encode failure degrades every
                # op to its own per-op encode, never drops it
                pre = {}
        for it in items:
            try:
                self._handle_op(
                    it[1], it[2], pre_encoded=pre.get(id(it[2]))
                )
            except Exception as e:  # noqa: BLE001 — one op's death
                # must not drop the rest of the drained batch (their
                # clients would never get a reply) nor leak their
                # throttle tickets; capture it exactly like the
                # worker loop's catch-all does
                import traceback

                traceback.print_exc()
                crash_util.capture(
                    f"osd.{self.whoami}",
                    e,
                    sink=self._pending_crashes,
                    clog=self.clog,
                    extra_meta={"work_item": "op(coalesced)"},
                )
            finally:
                self.client_throttle.put(it[3])

    # -- worker / ticker ---------------------------------------------------
    def _work_loop(self) -> None:
        while not self._stop.is_set():
            item = self._workq.get()
            if item is None:
                return
            self._process_work_item(item)

    # -- shared-services drain (strand-kicked, no dedicated thread) --------
    def _kick_workq(self) -> None:
        with self._workq_kick_lock:
            if self._workq_kicked:
                return
            self._workq_kicked = True
        self._op_strand.submit(self._drain_workq)

    def _drain_workq(self) -> None:
        """Drain the op scheduler until empty on the offload strand —
        serial per daemon (the exact single-worker-thread semantics),
        but on a shared pool thread only while there is work."""
        with self._workq_kick_lock:
            self._workq_kicked = False
        while not self._stop.is_set():
            try:
                item = self._workq.get(timeout=0)
            except TimeoutError:
                if self._workq.qlen() > 0:
                    # heads exist but are rate-limited (mclock tags
                    # not yet due): come back shortly instead of
                    # parking a pool thread on the condvar
                    self._stack().timers.after(0.01, self._kick_workq)
                return
            if item is None:
                return  # draining for shutdown
            self._process_work_item(item)

    def _tick_safe(self) -> None:
        if self._stop.is_set():
            return
        try:
            self._tick()
        except Exception as e:  # noqa: BLE001 — same containment as
            # the dedicated tick thread: a tick crash is reportable,
            # the timer keeps firing
            crash_util.capture(
                f"osd.{self.whoami}",
                e,
                sink=self._pending_crashes,
                clog=self.clog,
                extra_meta={"thread": "tick"},
            )

    def _mgr_report_safe(self) -> None:
        if self._stop.is_set():
            return
        try:
            self._report_to_mgr()
        except Exception:  # noqa: BLE001 — reporting best-effort
            pass

    def _process_work_item(self, item) -> None:
        kind = item[0]
        try:
            if kind == "map":
                self._walk_pgs(item[1])
            elif kind == "op":
                extra = self._coalesce_op_items(item)
                if extra:
                    self._handle_op_batch([item] + extra)
                else:
                    try:
                        self._handle_op(item[1], item[2])
                    finally:
                        self.client_throttle.put(item[3])
            elif kind == "activate":
                self._apply_activate(item[1], item[2])
            elif kind == "pull":
                self._handle_pull(item[1], item[2])
            elif kind == "recover_push":
                extra = self._coalesce_recovery_items(item)
                if extra:
                    self._do_recover_push_batch([item] + extra)
                else:
                    self._do_recover_push(item[1], item[2])
            elif kind == "split":
                pg = self.pgs.get(item[1])
                if (
                    pg is not None
                    and pg.primary == self.whoami
                    and pg.state == "active"
                    and item[1] not in self._splitting
                ):
                    # the scan blocks on PEER primaries (who may
                    # be splitting toward us at the same moment):
                    # a side thread keeps this worker serving ops,
                    # breaking the mutual-starvation cycle; local
                    # mutations marshal back via _on_worker
                    self._splitting.add(item[1])

                    def run(pg=pg, epoch=item[2], pgid=item[1]):
                        try:
                            self._split_scan(pg, epoch)
                        finally:
                            self._splitting.discard(pgid)

                    threading.Thread(
                        target=run,
                        name=f"osd.{self.whoami}.split",
                        daemon=True,
                    ).start()
            elif kind == "splitcall":
                _k, fn, fut = item
                try:
                    fut.set_result(fn())
                except Exception as e:  # noqa: BLE001
                    fut.set_exception(e)
            elif kind == "tier_agent":
                pg = self.pgs.get(item[1])
                try:
                    if pg is not None:
                        self._tier_agent(pg)
                finally:
                    self._tier_running.discard(item[1])
            elif kind == "scrub":
                pg = self.pgs.get(item[1])
                if pg is None:
                    self._scrubbing.discard(item[1])
                else:
                    # one CHUNK per work item: the scrubber
                    # re-enqueues itself until done, so client
                    # ops interleave between chunks (scrub
                    # preemption); it owns the _scrubbing guard
                    self.scrubber.run(pg, item[2], item[3])
        except Exception as e:  # noqa: BLE001 — worker must
            # survive, but the death of the op IS a daemon crash:
            # capture traceback + dout tail for the mgr crash
            # module and announce it on the cluster log
            import traceback

            traceback.print_exc()
            crash_util.capture(
                f"osd.{self.whoami}",
                e,
                sink=self._pending_crashes,
                clog=self.clog,
                extra_meta={"work_item": str(kind)},
            )

    def _peers_of_interest(self) -> set[int]:
        peers: set[int] = set()
        with self._pg_lock:
            for pg in self.pgs.values():
                if pg.state in ("active", "replica", "peering"):
                    peers.update(pg.acting)
        peers.discard(self.whoami)
        peers.discard(CRUSH_ITEM_NONE)  # EC holes are not peers
        return peers

    def collect_pg_stats(self) -> list[dict]:
        """Per-PG pg_stat_t-analog dicts for the PGs this OSD leads
        (src/osd/PG.cc publish_stats_to_osd role): state string with
        qualifiers, object/byte counts from the store, and the
        degraded/misplaced/unfound accounting the mgr PGMap digest
        rolls up.  Primary-only — exactly one report per PG cluster-
        wide, like the reference."""
        osdmap = self.monc.osdmap
        with self._pg_lock:
            pgs = [
                pg for pg in self.pgs.values()
                if pg.primary == self.whoami
                and pg.state in ("active", "peering", "initial")
            ]
        recovering = list(self._recovering.items())
        out: list[dict] = []
        for pg in pgs:
            pool = osdmap.pools.get(pg.pool_id)
            if pool is None:
                continue
            try:
                ps = int(pg.pgid.split(".")[1])
                up, _upp, _a, _p = osdmap.pg_to_up_acting_osds(
                    pg.pool_id, ps
                )
            except (ValueError, IndexError, KeyError):
                up = []
            live_acting = [
                o for o in pg.acting if o != CRUSH_ITEM_NONE
            ]
            holes = max(pool.size - len(live_acting), 0)
            num_objects = 0
            num_bytes = 0
            try:
                for o in self.store.list_objects(pg.cid):
                    if not o.startswith(OBJ_PREFIX) or "@" in o:
                        continue
                    num_objects += 1
                    num_bytes += self.store.stat(pg.cid, o)
            except StoreError:
                pass  # collection racing a remap/removal
            ops = [
                op for (pid, _osd), op in recovering
                if pid == pg.pgid and not op.failed
            ]
            remaining = sum(len(op.remaining) for op in ops)
            pushed = sum(len(op.pushed) for op in ops)
            degraded = (
                num_objects * holes
                + remaining
                + len(pg.self_missing)
            )
            misplaced = num_objects * sum(
                1 for o in live_acting if o not in up
            )
            unfound = len(pg.self_missing)
            quals = []
            if pg.state != "active":
                base = "peering"
            else:
                base = "active"
                if holes:
                    quals.append("undersized")
                if degraded:
                    quals.append("degraded")
                if list(up) != list(pg.acting):
                    quals.append("remapped")
                if ops:
                    quals.append(
                        "backfilling"
                        if any(op.since == (0, 0) for op in ops)
                        else "recovering"
                    )
                if pg.scrub_errors:
                    quals.append("inconsistent")
                if not quals:
                    quals.append("clean")
            state = "+".join([base] + quals)
            out.append({
                "pgid": pg.pgid,
                "state": state,
                "num_objects": num_objects,
                "num_bytes": num_bytes,
                "num_objects_degraded": degraded,
                "num_objects_misplaced": misplaced,
                "num_objects_unfound": unfound,
                "recovery": {
                    "planned": remaining + pushed,
                    "pushed": pushed,
                },
                "up": list(up),
                "acting": list(pg.acting),
                "reported_epoch": osdmap.epoch,
            })
        return out

    def collect_progress_events(self) -> list[dict]:
        """Progress events for this OSD's long-running local work —
        currently scrub/repair runs (fraction = chunk index over the
        run's object list).  A run that leaves the scrubber emits a
        final done=True record exactly once (``_progress_seen``), so
        the mgr progress module can retire the bar."""
        events: list[dict] = []
        live: set[str] = set()
        for pgid, run in list(self.scrubber._runs.items()):
            kind = (
                "repair" if run.repair
                else "deep-scrub" if run.deep
                else "scrub"
            )
            eid = f"{kind} pg {pgid} (osd.{self.whoami})"
            live.add(eid)
            events.append({
                "id": eid,
                "message": eid,
                "fraction": min(
                    run.idx / max(len(run.oids), 1), 1.0
                ),
                "done": False,
            })
        for eid in list(self._progress_seen):
            if eid not in live:
                self._progress_seen.discard(eid)
                events.append({
                    "id": eid,
                    "message": eid,
                    "fraction": 1.0,
                    "done": True,
                })
        self._progress_seen |= live
        return events

    def _mgr_report_loop(self) -> None:
        """Dedicated thread: mgr discovery + MMgrReport pushes must
        never stall the tick (a slow/unreachable mgr would otherwise
        delay heartbeat pings past the grace and flap this OSD)."""
        while not self._stop.wait(1.0):
            try:
                self._report_to_mgr()
            except Exception:  # noqa: BLE001 — reporting best-effort
                pass

    def _report_to_mgr(self) -> None:
        """Push a perf dump to the mgr (MMgrReport): discover the
        active mgr through the monitor at a slow cadence, keep one
        cached connection, drop it on any failure."""
        now = time.monotonic()
        gate = self.mgr_discovery_interval
        if self._mgr_addr is None and now - self._mgr_addr_checked < gate:
            return
        try:
            if self._mgr_addr is None or now - self._mgr_addr_checked > gate:
                self._mgr_addr_checked = now
                # SHORT timeout: discovery is periodic best-effort —
                # at 100-daemon scale a backlogged mon must not hold
                # one offload thread per OSD for the default 15 s
                reply = self.monc.command(
                    {"prefix": "mgr stat"}, timeout=3.0
                )
                active = (
                    json.loads(reply.outb).get("active")
                    if reply.rc == 0
                    else None
                )
                addr = active["addr"] if active else None
                if addr != self._mgr_addr:
                    self._mgr_addr = addr
                    self._mgr_conn = None
            if self._mgr_addr is None:
                return
            self.perf.set("numpg", len(self.pgs))
            self.perf.set("recovery_active", self._recovery_active)
            # last-scrubbed age: the STALEST primary PG (feeds the
            # ceph_osd_scrub_last_age_seconds prometheus family).  A
            # never-scrubbed PG counts from daemon boot — reading 0
            # there would make "never scrubbed" look like "just
            # scrubbed", the one state a staleness alert exists for
            mono = time.monotonic()
            with self._pg_lock:
                ages = [
                    mono - (pg.last_scrub or self._boot_stamp)
                    for pg in self.pgs.values()
                    if pg.primary == self.whoami
                    and pg.state == "active"
                ]
            self.perf.set(
                "scrub_last_age", int(max(ages)) if ages else 0
            )
            if self._mgr_conn is None or self._mgr_conn.is_closed:
                host, _, port = self._mgr_addr.rpartition(":")
                self._mgr_conn = self.messenger.connect(
                    host, int(port), timeout=5.0
                )
            # device-kernel counters (ops/kernel_stats.py) merge into
            # the same flat dump, so `l_tpu_*` series ride the
            # existing perf dump → MMgrReport → /metrics pipeline
            from ..ops.kernel_stats import kernel_stats

            with self._backoff_lock:
                self.perf.set("backoffs_active", len(self._backoffs))
            dump = dict(self.perf.dump())
            dump.update(kernel_stats().dump())
            # fault-plane counters (l_msgr_fault_*) ride the same
            # perf → MMgrReport → prometheus pipe
            dump.update(self.messenger.faults.perf.dump())
            # shared-stack worker telemetry (l_msgr_worker_*):
            # process-global like kernel_stats, merged the same way
            from ..msg.stack import stack_perf_dump

            dump.update(stack_perf_dump())
            # WAL-plane counters (l_os_wal_*) ride the same perf →
            # MMgrReport → prometheus pipe when the store is wrapped
            wal_perf = getattr(self.store, "wal_perf", None)
            if wal_perf is not None:
                dump.update(wal_perf.dump())
            # latency histograms (op_hist.<qos>.<type> + the commit
            # distribution): the mgr slo module merges these
            # cluster-wide; the exporter renders native histogram
            # families from the same entries
            dump.update(self.op_tracker.histogram_perf_entries())
            dump["commit_lat_hist"] = self._commit_hist.snapshot()
            spans = (
                self.tracer.drain()
                if self.config.get("tracing_enabled")
                else []
            )
            # crash reports ride the same push (MMgrReport piggyback).
            # send() is fire-and-forget — an exception-free send does
            # NOT prove delivery — so each report rides
            # CRASH_RESEND_COUNT pushes before we drop our only copy
            # (the mgr dedupes repeats by crash_id); removal targets
            # the exact objects sent because capture() may append (or
            # overflow-evict) concurrently
            crashes = list(self._pending_crashes)
            self._mgr_conn.send(
                MMgrReport(
                    daemon=f"osd.{self.whoami}",
                    perf=json.dumps(dump),
                    spans=json.dumps(spans),
                    crashes=json.dumps(crashes),
                )
            )
            for sent in crashes:
                cid = sent.get("crash_id", "")
                sends = self._crash_sends.get(cid, 0) + 1
                if sends < self.CRASH_RESEND_COUNT:
                    self._crash_sends[cid] = sends
                    continue
                self._crash_sends.pop(cid, None)
                try:
                    self._pending_crashes.remove(sent)
                except ValueError:
                    pass  # evicted by overflow while we sent
            # drop send-counts for reports overflow evicted mid-cycle
            # (they will never hit the resend threshold)
            live = {c.get("crash_id") for c in self._pending_crashes}
            for cid in [
                c for c in self._crash_sends if c not in live
            ]:
                del self._crash_sends[cid]
            # the PG-stats plane rides the same tick/connection: one
            # MPGStats per push with this OSD's primary-PG stat dicts
            # plus local progress events (scrub/repair)
            self._mgr_conn.send(
                MPGStats(
                    osd=self.whoami,
                    epoch=self.monc.osdmap.epoch,
                    stats=json.dumps(self.collect_pg_stats()),
                    events=json.dumps(
                        self.collect_progress_events()
                    ),
                )
            )
        except (MessageError, OSError, ValueError):
            self._mgr_conn = None

    def _on_worker(self, fn):
        """Run ``fn`` on the op worker (PG mutations are serialized
        there) and wait for the result — used by split side threads,
        which must never touch PG state directly."""
        import concurrent.futures as _f

        fut: _f.Future = _f.Future()
        self._workq.put(("splitcall", fn, fut))
        return fut.result(30.0)

    def _pg_num_grew(self, pg: PG) -> bool:
        """True when the pool's pg_num grew past what this PG last
        split against (persisted on PG_META; only a COMPLETED split
        scan advances it, so failures and restarts rescan).  First
        sight of a PG records the current pg_num — objects written
        before that are wherever the client put them."""
        pool = self._pool_of(pg)
        if pool is None:
            return False
        try:
            seen = int(
                self.store.getattr(pg.cid, PG_META, "pg_num_seen")
            )
        except StoreError:
            self._record_pg_num_seen(pg, pool.pg_num)
            return False
        return pool.pg_num > seen

    def _record_pg_num_seen(self, pg: PG, value: int) -> None:
        try:
            txn = Transaction().touch(pg.cid, PG_META)
            txn.setattr(
                pg.cid, PG_META, "pg_num_seen", str(value).encode()
            )
            self.store.queue_transaction(txn)
        except StoreError:
            pass

    def _split_scan(self, pg: PG, epoch: int) -> None:
        """Re-home objects whose stable_mod slot moved to a child PG
        after a pg_num increase (PG splitting, OSD::split_pgs role,
        re-rendered as primary-driven logged migration): read the
        object here, write it through the child primary's normal op
        path, then logged-delete it locally — every step rides the
        replicated machinery, so any acting-set topology works."""
        from ..osdc.objecter import object_to_pg

        pool = self._pool_of(pg)
        if pool is None:
            return
        try:
            oids = self.store.list_objects(pg.cid)
        except StoreError:
            return
        failed = 0
        for store_oid in oids:
            if not store_oid.startswith(OBJ_PREFIX) or "@" in store_oid:
                continue
            oid = store_oid[len(OBJ_PREFIX):]
            target = object_to_pg(pool, oid)
            if target == pg.pgid:
                continue
            try:
                self._migrate_object(pg, epoch, oid, store_oid, target)
            except (
                StoreError, MessageError, OSError, ErasureCodeError
            ):
                failed += 1  # keep going; a later pass rescans
        if failed == 0:
            # only a complete pass advances the split watermark
            self._record_pg_num_seen(pg, pool.pg_num)

    def _migrate_object(
        self, pg: PG, epoch: int, oid: str, store_oid: str, target: str
    ) -> None:
        if self._child_has_object(pg, oid, target):
            # the child already holds this object: either a client on
            # the new map wrote a NEWER version there (shipping our
            # pre-split copy would silently revert it) or an earlier
            # migration pass completed the write.  Either way the
            # child copy is authoritative — just retire the parent's.
            self._split_delete_parent(pg, oid, store_oid)
            return
        if self._is_ec(pg):
            # the local store holds only THIS osd's shard: decode the
            # whole object across the acting set, then ship it through
            # the child primary's normal EC write path — shards
            # re-home positionally under the child's acting set
            data = bytes(self._ec_store_for(pg).get(store_oid))
        else:
            data = self.store.read(pg.cid, store_oid)
        xattrs = {
            k: v
            for k, v in self.store.list_attrs(pg.cid, store_oid).items()
            if k.startswith("u_")
        }
        omap = self.store.omap_get(pg.cid, store_oid)
        ps = int(target.split(".")[1])
        deadline = time.monotonic() + 15.0
        ops = [(OSD_OP_WRITEFULL, data, "", b"")]
        for name, val in sorted(xattrs.items()):
            ops.append((OSD_OP_SETXATTR, val, name[2:], b""))
        if omap:
            e = Encoder()
            e.map(
                omap,
                lambda e2, k: e2.string(k),
                lambda e2, v: e2.bytes(v),
            )
            ops.append((OSD_OP_OMAPSET, e.getvalue(), "", b""))
        for i, (op, payload, attr, _x) in enumerate(ops):
            while True:
                osdmap = self.monc.osdmap
                _u, _up, _acting, primary = osdmap.pg_to_up_acting_osds(
                    pg.pool_id, ps
                )
                msg = MOSDOp(
                    pool=pg.pool_id, pgid=target, oid=oid, op=op,
                    data=payload, length=-1, attr=attr,
                    reqid=f"split.{pg.pgid}.{oid}.{i}",
                    epoch=osdmap.epoch,
                )
                try:
                    if primary == self.whoami:
                        tpg = self.pgs.get(target)
                        if tpg is not None and tpg.state == "active":
                            self._on_worker(
                                lambda tpg=tpg, msg=msg: self._mutate(
                                    tpg, self.monc.epoch, msg,
                                    OBJ_PREFIX + oid,
                                )
                            )
                            break
                        raise StoreError("child pg not active yet")
                    conn = self._peer_conn(primary)
                    reply = conn.call(msg, timeout=5.0)
                    if getattr(reply, "ok", False):
                        break
                    raise StoreError(getattr(reply, "error", "nak"))
                except (StoreError, MessageError, OSError):
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.2)
        self._split_delete_parent(pg, oid, store_oid)

    def _child_has_object(self, pg: PG, oid: str, target: str) -> bool:
        """STAT the child through its primary's op path — the
        guard against reverting a post-split client write with the
        parent's stale copy."""
        ps = int(target.split(".")[1])
        osdmap = self.monc.osdmap
        _u, _up, _acting, primary = osdmap.pg_to_up_acting_osds(
            pg.pool_id, ps
        )
        msg = MOSDOp(
            pool=pg.pool_id, pgid=target, oid=oid, op=OSD_OP_STAT,
            length=-1, reqid=f"split.{pg.pgid}.{oid}.stat",
            epoch=osdmap.epoch,
        )
        try:
            if primary == self.whoami:
                tpg = self.pgs.get(target)
                if tpg is None or tpg.state != "active":
                    return False
                if self._is_ec(tpg):
                    try:
                        self._ec_store_for(tpg).size(
                            OBJ_PREFIX + oid
                        )
                        return True
                    except (StoreError, ErasureCodeError):
                        return False
                return self.store.exists(tpg.cid, OBJ_PREFIX + oid)
            reply = self._peer_conn(primary).call(msg, timeout=5.0)
            return bool(getattr(reply, "ok", False))
        except (MessageError, OSError, StoreError):
            return False

    def _split_delete_parent(
        self, pg: PG, oid: str, store_oid: str
    ) -> None:
        # logged local delete: replicas of the PARENT drop it too.
        # Current epoch, not the enqueue-time one — a stale epoch
        # would log a non-monotonic version that peering could judge
        # divergent and roll back (resurrecting the object)
        cur_epoch = self.monc.epoch
        del_msg = MOSDOp(
            pool=pg.pool_id, pgid=pg.pgid, oid=oid, op=OSD_OP_DELETE,
            length=-1, reqid=f"split.{pg.pgid}.{oid}.del",
            epoch=cur_epoch,
        )
        self._on_worker(
            lambda: self._mutate(pg, cur_epoch, del_msg, store_oid)
        )

    # -- cache tiering (PrimaryLogPG maybe_handle_cache_detail +
    # TierAgentState, src/osd/PrimaryLogPG.cc:2492,2215 reduced) ------------
    def _tier_front(
        self, pg: PG, pool, epoch: int, msg: MOSDOp, store_oid: str
    ) -> None:
        """Cache-pool front end for one client op: record recency and
        PROMOTE the object from the base pool when the op needs its
        prior state and the cache misses (promote_object's role).
        WRITEFULL/DELETE overwrite wholesale — no promote needed."""
        atime = getattr(pg, "tier_atime", None)
        if atime is None:
            atime = pg.tier_atime = {}
        atime[msg.oid] = time.monotonic()
        if msg.op in (OSD_OP_WRITEFULL, OSD_OP_DELETE):
            return
        if self.store.exists(pg.cid, store_oid):
            return
        self._tier_promote(pg, pool, epoch, msg.oid)

    def _tier_promote(self, pg: PG, pool, epoch: int, oid: str) -> None:
        """Copy (data + user attrs + omap) up from the base pool into
        the cache pg through the normal logged/replicated write path;
        the promoted copy is CLEAN (tier- reqids skip dirty marking).
        A base miss is simply a cache miss (the op then sees -ENOENT
        exactly as it should)."""
        push = self._tier_base_fetch(pool, epoch, oid)
        if push is None or not push.exists:
            return
        rq = f"tier-promote.{pg.pgid}.{oid}"
        self._mutate(pg, epoch, MOSDOp(
            pool=pg.pool_id, pgid=pg.pgid, oid=oid,
            op=OSD_OP_WRITEFULL, data=push.data, length=-1,
            reqid=rq + ".d", epoch=self.monc.epoch,
        ), OBJ_PREFIX + oid)
        for name, val in sorted(push.attrs.items()):
            if name.startswith("u_"):
                self._mutate(pg, epoch, MOSDOp(
                    pool=pg.pool_id, pgid=pg.pgid, oid=oid,
                    op=OSD_OP_SETXATTR, attr=name[2:], data=val,
                    length=-1, reqid=f"{rq}.x.{name}",
                    epoch=self.monc.epoch,
                ), OBJ_PREFIX + oid)
        if push.omap:
            e = Encoder()
            e.map(
                push.omap,
                lambda e2, k: e2.string(k),
                lambda e2, v: e2.bytes(v),
            )
            self._mutate(pg, epoch, MOSDOp(
                pool=pg.pool_id, pgid=pg.pgid, oid=oid,
                op=OSD_OP_OMAPSET, data=e.getvalue(), length=-1,
                reqid=rq + ".o", epoch=self.monc.epoch,
            ), OBJ_PREFIX + oid)

    def _tier_base_target(self, pool, oid: str):
        """(base_pool, base_pgid, primary) for an object's base copy."""
        from ..osdc.objecter import object_to_pg

        base = self.monc.osdmap.pools.get(pool.tier_of)
        if base is None:
            raise StoreError(f"tier base pool {pool.tier_of} gone")
        pgid = object_to_pg(base, oid)
        ps = int(pgid.split(".")[1])
        _u, _up, _a, primary = self.monc.osdmap.pg_to_up_acting_osds(
            base.pool_id, ps
        )
        return base, pgid, primary

    def _tier_base_fetch(self, pool, epoch: int, oid: str):
        """Whole object (data+attrs+omap) from the base primary — the
        recovery pull machinery doubles as copy-up (copy_from role)."""
        base, pgid, primary = self._tier_base_target(pool, oid)
        if primary == self.whoami:
            bpg = self.pgs.get(pgid)
            if bpg is None:
                return None
            return self._push_for(bpg, epoch, oid)
        try:
            reply = self._peer_conn(primary).call(
                MPGPull(
                    pgid=pgid, epoch=epoch, oid=oid, shard=-1
                ),
                timeout=10.0,
            )
        except (MessageError, OSError) as e:
            raise StoreError(f"tier base fetch failed: {e} (-EAGAIN)")
        return reply if isinstance(reply, MPGPush) else None

    def _tier_base_op(
        self,
        pool,
        oid: str,
        op: int,
        data: bytes = b"",
        attr: str = "",
        reqid: str = "",
        ignore_enoent: bool = False,
    ) -> None:
        """One op against the base pool's primary (flush/delete
        propagation), targeted DIRECTLY at the base pgid so the
        overlay redirection cannot bounce it back to us."""
        base, pgid, primary = self._tier_base_target(pool, oid)
        msg = MOSDOp(
            pool=base.pool_id, pgid=pgid, oid=oid, op=op, data=data,
            attr=attr, length=-1, reqid=reqid,
            epoch=self.monc.epoch,
        )
        if primary == self.whoami:
            bpg = self.pgs.get(pgid)
            if bpg is None or bpg.state != "active":
                raise StoreError("base pg not active (-EAGAIN)")
            try:
                self._mutate(bpg, self.monc.epoch, msg, OBJ_PREFIX + oid)
            except StoreError as e:
                if not (ignore_enoent and "ENOENT" in str(e)):
                    raise
            return
        try:
            reply = self._peer_conn(primary).call(msg, timeout=10.0)
        except (MessageError, OSError) as e:
            raise StoreError(f"tier base op failed: {e} (-EAGAIN)")
        if not getattr(reply, "ok", False):
            err = getattr(reply, "error", "nak")
            if not (ignore_enoent and "ENOENT" in err):
                raise StoreError(err)

    def _tier_agent(self, pg: PG) -> None:
        """One agent pass over a cache pg (TierAgentState flush/evict
        modes): flush every dirty object to the base pool, then evict
        the least-recently-used CLEAN objects down to the pool's
        per-pg share of target_max_objects.  A lost clean-marker
        (failover) merely causes an idempotent re-flush."""
        pool = self._pool_of(pg)
        if (
            pool is None or pool.tier_of < 0
            or pool.cache_mode != "writeback"
            or pg.primary != self.whoami or pg.state != "active"
        ):
            return
        try:
            oids = [
                o for o in self.store.list_objects(pg.cid)
                if o.startswith(OBJ_PREFIX) and "@" not in o
            ]
        except StoreError:
            return
        atime = getattr(pg, "tier_atime", {})
        for store_oid in oids:
            oid = store_oid[len(OBJ_PREFIX):]
            try:
                dirty = self.store.getattr(
                    pg.cid, store_oid, TIER_DIRTY
                ) == b"1"
            except StoreError:
                dirty = False
            if not dirty:
                continue
            try:
                self._tier_flush_object(pg, pool, oid, store_oid)
                self.perf.inc("tier_flush")
            except (StoreError, MessageError, OSError):
                pass  # next pass retries
        if pool.target_max_objects <= 0:
            return
        budget = max(1, pool.target_max_objects // max(pool.pg_num, 1))
        live = [
            o for o in oids
            if self.store.exists(pg.cid, o)
        ]
        if len(live) <= budget:
            return
        # evict clean LRU first (hit-set recency, in-memory deviation)
        def last_access(store_oid):
            return atime.get(store_oid[len(OBJ_PREFIX):], 0.0)

        for store_oid in sorted(live, key=last_access):
            if len(live) <= budget:
                break
            try:
                if self.store.getattr(
                    pg.cid, store_oid, TIER_DIRTY
                ) == b"1":
                    continue  # never evict unflushed data
            except StoreError:
                pass
            oid = store_oid[len(OBJ_PREFIX):]
            try:
                self._mutate(pg, self.monc.epoch, MOSDOp(
                    pool=pg.pool_id, pgid=pg.pgid, oid=oid,
                    op=OSD_OP_DELETE, length=-1,
                    reqid=f"tier-evict.{pg.pgid}.{oid}",
                    epoch=self.monc.epoch,
                ), store_oid)
                live.remove(store_oid)
                atime.pop(oid, None)
                self.perf.inc("tier_evict")
            except StoreError:
                pass

    def _tier_flush_object(
        self, pg: PG, pool, oid: str, store_oid: str
    ) -> None:
        """Write the cache copy back to the base pool (agent flush),
        then mark it clean — locally only: the clean bit is an
        optimization; a replica's stale dirty bit after failover just
        re-flushes idempotently."""
        data = self.store.read(pg.cid, store_oid)
        attrs = self.store.list_attrs(pg.cid, store_oid)
        omap = self.store.omap_get(pg.cid, store_oid)
        rq = f"tier-flush.{pg.pgid}.{oid}"
        self._tier_base_op(
            pool, oid, OSD_OP_WRITEFULL, data=data, reqid=rq + ".d"
        )
        for name, val in sorted(attrs.items()):
            if name.startswith("u_"):
                self._tier_base_op(
                    pool, oid, OSD_OP_SETXATTR, data=val,
                    attr=name[2:], reqid=f"{rq}.x.{name}",
                )
        if omap:
            e = Encoder()
            e.map(
                omap,
                lambda e2, k: e2.string(k),
                lambda e2, v: e2.bytes(v),
            )
            self._tier_base_op(
                pool, oid, OSD_OP_OMAPSET, data=e.getvalue(),
                reqid=rq + ".o",
            )
        try:
            self.store.queue_transaction(
                Transaction().setattr(
                    pg.cid, store_oid, TIER_DIRTY, b"0"
                )
            )
        except StoreError:
            pass

    def _tick_loop(self) -> None:
        while not self._stop.wait(self.tick_interval):
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — a tick crash is a
                # daemon crash worth a report, but the ticker (and its
                # heartbeats) must keep running
                crash_util.capture(
                    f"osd.{self.whoami}",
                    e,
                    sink=self._pending_crashes,
                    clog=self.clog,
                    extra_meta={"thread": "tick"},
                )

    def _tick(self) -> None:
        now = time.monotonic()
        # expired remote recovery leases purge on the TICK, not just
        # on the next reservation request: a primary that died
        # without releasing would otherwise pin its slot (and look
        # like a leak) until some future primary happens to ask
        with self._recovery_lock:
            for k, (t0, _c) in list(self._remote_reservations.items()):
                if now - t0 > self.reservation_timeout:
                    del self._remote_reservations[k]
        # retry peering for primary PGs whose recovery pushes
        # failed (peered_interval cleared) — at tick rate, never
        # as a hot worker loop
        retry = False
        with self._pg_lock:
            for pg in self.pgs.values():
                if (
                    pg.primary == self.whoami
                    and pg.acting
                    and pg.peered_interval is None
                ):
                    retry = True
                    break
        if retry:
            self._workq.put(("map", self.monc.epoch))
        # scheduled + on-demand scrub (OSD::sched_scrub's tick path:
        # interval-due PGs plus `ceph pg (deep-)scrub/repair` orders)
        for pgid, deep, repair in self.scrubber.due(now):
            if pgid in self._scrubbing:
                continue
            self._scrubbing.add(pgid)
            self._workq.enqueue(
                CLASS_BACKGROUND, 1, ("scrub", pgid, deep, repair)
            )
        # withdraw/refresh the scrub-error health contribution when
        # it changed (e.g. a damaged PG remapped away from us)
        self.scrubber.maybe_report(now)
        # cache-tier agent (TierAgentState flush/evict, scheduled
        # like scrub, executed on the worker off the tick thread)
        with self._pg_lock:
            tier_due = [
                pg.pgid
                for pg in self.pgs.values()
                if pg.primary == self.whoami
                and pg.state == "active"
                and pg.pgid not in self._tier_running
                and (
                    (p := self._pool_of(pg)) is not None
                    and p.tier_of >= 0
                    and p.cache_mode == "writeback"
                )
            ]
        for pgid in tier_due:
            self._tier_running.add(pgid)
            self._workq.enqueue(
                CLASS_BACKGROUND, 1, ("tier_agent", pgid)
            )
        # mon session failover (MonClient reconnect)
        try:
            self.monc.ensure_connected()
        except (MessageError, OSError):
            pass
        # re-announce until the map marks us up — a boot report
        # can be lost while the mon quorum is electing
        # (OSD::start_boot retries the same way)
        osdmap = self.monc.osdmap
        if (
            osdmap is not None
            and self.addr is not None
            and not osdmap.is_up(self.whoami)
        ):
            try:
                self.monc.boot(
                    self.whoami,
                    addr=f"{self.addr[0]}:{self.addr[1]}",
                )
            except (MessageError, OSError):
                pass
        interesting = self._peers_of_interest()
        # peers that left every acting set (e.g. marked down) stop
        # being tracked — a stale last-rx stamp would otherwise
        # keep generating failure reports forever and instantly
        # re-down a rebooted peer (the reference prunes its
        # heartbeat_peers on map change too, OSD::maybe_update_heartbeat_peers)
        for osd in self.hb.peers() - interesting:
            self.hb.remove_peer(osd)
        for osd in interesting:
            if osd not in self.hb.peers():
                self.hb.add_peer(osd, now)
            try:
                self._peer_conn(osd).send(
                    MPing(
                        tid=self.messenger.new_tid(),
                        from_osd=self.whoami,
                        stamp=now,
                    )
                )
            except (MessageError, OSError, KeyError, ValueError):
                pass
        for osd, silent_for in self.hb.failures(now):
            try:
                self.monc.report_failure(osd, silent_for)
                self._reported.add(osd)
            except (MessageError, OSError):
                pass
        self._check_slow_ops(now)
        # backoff releases (space freed / peering done) + the space
        # stats that feed the mon's OSD_NEARFULL/OSD_FULL checks
        self._release_backoffs()
        self._report_stats(now)
        self._flush_clog()

    def _flush_clog(self) -> None:
        self._log_client.flush(self.monc)

    def _check_slow_ops(self, now: float) -> None:
        """SLOW_OPS watchdog (OSD::check_ops_in_flight →
        get_health_metrics): in-flight ops older than
        osd_op_complaint_time degrade mon health; a report of 0
        clears our complaint.  Reports are throttled to ~1/s and only
        sent on a change or while nonzero (refreshing the mon's
        staleness grace)."""
        if now - self._slow_ops_last_report < 1.0:
            return
        try:
            threshold = float(
                self.config.get("osd_op_complaint_time")
            )
            summary = self.op_tracker.slow_op_summary(threshold)
            count = summary["num_slow_ops"]
            self.perf.set("slow_ops", count)
            if count == 0 and self._slow_ops_reported == 0:
                return
            self._slow_ops_last_report = now
            # bounded like the stat report: this fires exactly when
            # the cluster is ALREADY slow — the default 15 s timeout
            # would park one offload thread per complaining OSD on a
            # backlogged mon
            self.monc.command(
                {
                    "prefix": "osd slow ops",
                    "daemon": f"osd.{self.whoami}",
                    "count": count,
                    "oldest_age": summary["oldest_age"],
                },
                timeout=3.0,
            )
            # clog the TRANSITIONS (not every refresh), and only
            # AFTER the mon report succeeded — clogging before it
            # would requeue one duplicate warn per tick for the whole
            # length of a mon outage and bury the health timeline
            if count > 0 and self._slow_ops_reported == 0:
                self.clog.warn(
                    f"{count} slow requests (oldest blocked for "
                    f"{summary['oldest_age']:.0f} sec)"
                )
            elif count == 0 and self._slow_ops_reported > 0:
                self.clog.info("slow requests cleared")
            self._slow_ops_reported = count
        except (MessageError, OSError, ValueError):
            pass
