"""Scrub subsystem — chunked, preemptible data-integrity verification
with persisted findings and repair (src/osd/scrubber/: PgScrubber,
ScrubStore, ScrubMap; PrimaryLogPG::do_repair_op).

Shape vs the reference:

- The primary drives scrub in CHUNKS of objects: each chunk lists,
  digests, and compares the acting set's copies, then the run yields
  the worker back to the op scheduler before taking the next chunk —
  client ops interleave between chunks by QoS weight, which is the
  preemption the reference implements with scrub ranges and
  ``scrubs_local``/``scrubs_remote`` wait lists.
- Replica participation is message-driven: ``MRepScrub`` carries
  reserve/release (the osd_max_scrubs reservation handshake,
  ScrubReserver role), ``ls`` (object listing so primary-missing
  objects are still found), and ``scan`` (a digest map over one chunk
  — the MOSDRepScrub → ScrubMap round).  Scan answers are pure local
  store reads + one batched device crc call, so replicas serve them
  inline off the messenger loop exactly like MECSubRead.
- Shallow scrub compares size/omap-digest/xattr-digest; deep scrub
  adds payload checksums — batched per chunk through
  ``ops/scrub_kernels.batch_crc32c`` (one device call per daemon per
  chunk instead of the reference's per-object CPU loop).
- Erasure pools audit each shard's crc against the object's stored
  HashInfo; overwritten objects (hinfo invalidated, matching the
  reference's ec_overwrites semantics) fall back to decode +
  re-encode with a device-side compare (``batch_compare``).
- Findings persist as omap records on a per-PG ``_scrub_`` object
  (the ScrubStore role) so ``rados list-inconsistent-obj`` serves
  structured results long after the scrub that found them.
- Repair selects the authoritative copy — digest majority on
  replicated pools, decode-from-surviving-shards on erasure pools —
  and pushes corrected objects through the existing recovery-push
  machinery, then re-verifies; only still-broken objects stay
  recorded (``ceph pg repair`` + the osd_scrub_auto_repair path).
"""

from __future__ import annotations

import json
import time

from ..common.log import dout
from ..ec.interface import ErasureCodeError
from ..msg import MessageError
from ..msg.message import MPGPull, MPGPush, MRepScrub, MScrubMap
from ..native import ceph_crc32c
from ..ops.scrub_kernels import batch_compare, batch_crc32c
from ..store.ec_store import HINFO_KEY
from ..store.objectstore import StoreError, Transaction

# the per-PG scrub metadata object: inconsistency records live in its
# omap (the ScrubStore's OMAP_DIR), outside the OBJ_PREFIX namespace
# so listings and client ops never see it
SCRUB_META = "_scrub_"
REC_PREFIX = "inc_"

# attrs excluded from the xattr digest: t_dirty is cleared locally
# only (cache-tier flush), hinfo is audited separately per shard
VOLATILE_ATTRS = frozenset({"t_dirty", HINFO_KEY})

# the digest seed (the reference's data_digest crc32c(-1) convention,
# shared with the EC HashInfo cumulative seeds)
DIGEST_SEED = 0xFFFFFFFF

# shard/object error vocabulary (rados list-inconsistent-obj codes)
ERR_MISSING = "missing"
ERR_SIZE = "size_mismatch"
ERR_DATA = "data_digest_mismatch"
ERR_OMAP = "omap_digest_mismatch"
ERR_ATTR = "attr_digest_mismatch"
ERR_EC_HASH = "ec_hash_mismatch"
ERR_EC_SIZE = "ec_size_mismatch"
ERR_READ = "read_error"
ERR_INCONSISTENT = "inconsistent"
KNOWN_ERRORS = frozenset(
    {
        ERR_MISSING, ERR_SIZE, ERR_DATA, ERR_OMAP, ERR_ATTR,
        ERR_EC_HASH, ERR_EC_SIZE, ERR_READ, ERR_INCONSISTENT,
    }
)


def _digest(parts: dict[str, bytes]) -> int:
    """Canonical crc32c over sorted (key, value) pairs."""
    crc = DIGEST_SEED
    for key in sorted(parts):
        crc = ceph_crc32c(crc, key.encode() + b"\0")
        crc = ceph_crc32c(crc, bytes(parts[key]) + b"\0")
    return crc


def _resident(store, cid: str, oid: str, expect_len=None):
    """Generation-checked residency lookup (ops/residency.py): a hit
    is the payload the last committed txn landed, already on device —
    the deep-scrub digest of a freshly written object costs zero
    host→device transfer.  Only scrub-trusted stores are consulted:
    proxies mutate out of our sight, and persistent media (whose
    out-of-band bit rot is exactly what deep scrub audits) must be
    READ, never served from cache."""
    from ..ops.residency import residency_cache, scrub_trusted

    if not scrub_trusted(store):
        return None
    return residency_cache().get(store, cid, oid, expect_len=expect_len)


def build_scrub_map(
    store, cid: str, oids, deep: bool, with_hinfo: bool = False
) -> dict[str, dict]:
    """One daemon's digest map over a chunk of store oids (the
    ScrubMap role, src/osd/scrubber_common.h): size + omap/xattr
    digests always, payload crc32c when ``deep`` (ALL payloads of the
    chunk in one batched device call; device-RESIDENT payloads — the
    bytes the EC/replicated write path just committed — digest with
    no re-upload)."""
    out: dict[str, dict] = {}
    datas: list[bytes] = []
    data_oids: list[str] = []
    for oid in oids:
        try:
            if not store.exists(cid, oid):
                out[oid] = {"exists": False}
                continue
            attrs = store.list_attrs(cid, oid)
            try:
                omap = store.omap_get(cid, oid)
            except StoreError:
                omap = {}
            ent: dict = {
                "exists": True,
                "size": store.stat(cid, oid),
                # omap cardinality feeds the LARGE_OMAP_OBJECTS
                # deep-scrub check (the bucket-index hot-spot signal)
                "omap_keys": len(omap),
                "omap_digest": _digest(omap),
                "attrs_digest": _digest(
                    {
                        k: v
                        for k, v in attrs.items()
                        if k not in VOLATILE_ATTRS
                    }
                ),
            }
            if with_hinfo:
                try:
                    ent["hinfo"] = json.loads(attrs[HINFO_KEY])
                except (KeyError, ValueError):
                    ent["hinfo"] = None
            if deep:
                buf = _resident(store, cid, oid, ent["size"])
                datas.append(
                    buf if buf is not None else store.read(cid, oid)
                )
                data_oids.append(oid)
            out[oid] = ent
        except StoreError:
            out[oid] = {"exists": True, "error": ERR_READ}
    if datas:
        for oid, crc in zip(
            data_oids, batch_crc32c(datas, DIGEST_SEED)
        ):
            out[oid]["data_digest"] = int(crc)
    return out


class ScrubStore:
    """Inconsistency records persisted in the PG's ``_scrub_`` omap
    (src/osd/scrubber/ScrubStore.cc): written by the scrub that found
    them, served by ``rados list-inconsistent-obj``, cleared by the
    scrub/repair that no longer reproduces them."""

    @staticmethod
    def save(store, cid: str, records: list[dict]) -> None:
        txn = Transaction().touch(cid, SCRUB_META)
        txn.omap_clear(cid, SCRUB_META)
        if records:
            txn.omap_setkeys(
                cid,
                SCRUB_META,
                {
                    REC_PREFIX
                    + rec["object"]["name"]: json.dumps(
                        rec, sort_keys=True
                    ).encode()
                    for rec in records
                },
            )
        store.queue_transaction(txn)

    @staticmethod
    def load(store, cid: str) -> list[dict]:
        try:
            kv = store.omap_get(cid, SCRUB_META)
        except StoreError:
            return []
        out = []
        for key in sorted(kv):
            if not key.startswith(REC_PREFIX):
                continue
            try:
                rec = json.loads(kv[key])
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
        return out

    @staticmethod
    def clear(store, cid: str) -> None:
        try:
            store.queue_transaction(
                Transaction()
                .touch(cid, SCRUB_META)
                .omap_clear(cid, SCRUB_META)
            )
        except StoreError:
            pass


def make_record(
    oid: str,
    shards: list[dict],
    errors: list[str],
    selected: dict | None = None,
) -> dict:
    """One inconsistency record in the ``rados list-inconsistent-obj``
    shape (src/include/rados/rados_types.hpp obj_err_t), with the
    compact legacy keys (oid/osd/corrupt/missing) the daemon's
    ``pg.scrub_errors`` consumers already read."""
    union = sorted(
        {e for sh in shards for e in sh.get("errors", ())}
    )
    bad = [sh for sh in shards if sh.get("errors")]
    rec = {
        "object": {"name": oid, "nspace": "", "snap": "head"},
        "errors": sorted(set(errors) | set(union)),
        "union_shard_errors": union,
        "selected_object_info": selected,
        "shards": shards,
        # legacy compact keys
        "oid": oid,
        "osd": bad[0]["osd"] if bad else -1,
        "missing": [
            sh.get("shard", sh["osd"])
            for sh in shards
            if ERR_MISSING in sh.get("errors", ())
        ],
        "corrupt": [
            sh.get("shard", sh["osd"])
            for sh in shards
            if {ERR_DATA, ERR_EC_HASH, ERR_EC_SIZE}
            & set(sh.get("errors", ()))
        ],
        "inconsistent": ERR_INCONSISTENT in errors,
    }
    return rec


def compare_replicated(
    oid: str, maps: dict[int, dict], primary: int, deep: bool
) -> dict | None:
    """Compare one object's per-osd scrub-map entries; returns an
    inconsistency record or None.  Authoritative selection is digest
    majority (ties break toward the group holding the primary, then
    the lowest osd) — the be_select_auth_object seat."""
    present = {
        osd: ent
        for osd, ent in maps.items()
        if ent is not None and ent.get("exists")
    }
    if not present:
        return None  # nobody holds it (fully deleted): not an error

    def key_of(ent):
        fields = [ent.get("size"), ent.get("omap_digest"),
                  ent.get("attrs_digest")]
        if deep:
            fields.append(ent.get("data_digest"))
        return tuple(fields)

    groups: dict[tuple, list[int]] = {}
    for osd, ent in present.items():
        if ent.get("error"):
            continue
        groups.setdefault(key_of(ent), []).append(osd)
    if not groups:
        auth_osd, auth_key = primary, None
    else:
        def rank(item):
            key, members = item
            return (
                len(members),
                primary in members,
                -min(members),
            )

        auth_key, members = max(groups.items(), key=rank)
        auth_osd = primary if primary in members else min(members)
    auth = present.get(auth_osd)
    shards = []
    clean = True
    for osd, ent in sorted(maps.items()):
        sh = {"osd": osd, "shard": -1, "errors": []}
        if ent is None:
            # unreachable peer: not scrubbed, not an inconsistency
            continue
        if not ent.get("exists"):
            sh["errors"].append(ERR_MISSING)
        elif ent.get("error"):
            sh["errors"].append(ent["error"])
        else:
            sh["size"] = ent.get("size")
            sh["omap_digest"] = ent.get("omap_digest")
            sh["attrs_digest"] = ent.get("attrs_digest")
            if deep:
                sh["data_digest"] = ent.get("data_digest")
            if auth is not None and ent is not auth:
                if ent.get("size") != auth.get("size"):
                    sh["errors"].append(ERR_SIZE)
                if deep and ent.get("data_digest") != auth.get(
                    "data_digest"
                ):
                    sh["errors"].append(ERR_DATA)
                if ent.get("omap_digest") != auth.get("omap_digest"):
                    sh["errors"].append(ERR_OMAP)
                if ent.get("attrs_digest") != auth.get(
                    "attrs_digest"
                ):
                    sh["errors"].append(ERR_ATTR)
        if sh["errors"]:
            clean = False
        shards.append(sh)
    if clean:
        return None
    selected = None
    if auth is not None:
        selected = {
            "osd": auth_osd,
            "size": auth.get("size"),
            "data_digest": auth.get("data_digest"),
        }
    rec = make_record(oid, shards, [], selected)
    # legacy peer-vs-primary fields the seed tests read
    pri = maps.get(primary) or {}
    rec["primary_crc"] = pri.get("data_digest")
    bad = [sh for sh in shards if sh["errors"]]
    if bad:
        peer = maps.get(bad[0]["osd"]) or {}
        rec["peer_crc"] = peer.get("data_digest")
    return rec


def compare_ec(
    oid: str,
    maps: dict[int, dict],
    acting: list[int],
    sinfo,
    deep: bool,
) -> tuple[dict | None, bool]:
    """Compare one EC object's per-position shard entries against the
    stored HashInfo.  Returns (record | None, needs_reencode): when
    the hinfo carries no per-shard hashes (partial overwrite
    invalidated it, the reference's ec_overwrites behavior) a deep
    scrub must fall back to decode + re-encode — the caller runs that
    batched."""
    by_pos = {
        pos: maps.get(osd)
        for pos, osd in enumerate(acting)
    }
    present = {
        pos: ent
        for pos, ent in by_pos.items()
        if ent is not None and ent.get("exists")
    }
    if not present:
        return None, False
    # authoritative hinfo: the value most shards agree on
    votes: dict[str, list[int]] = {}
    for pos, ent in present.items():
        hinfo = ent.get("hinfo")
        if hinfo is not None:
            votes.setdefault(
                json.dumps(hinfo, sort_keys=True), []
            ).append(pos)
    hinfo = None
    if votes:
        blob, _members = max(
            votes.items(), key=lambda kv: (len(kv[1]), kv[0])
        )
        hinfo = json.loads(blob)
    hashes = (hinfo or {}).get("hashes")
    size = (hinfo or {}).get("size", 0)
    expected_len = (
        sinfo.logical_to_next_chunk_offset(size)
        if sinfo is not None
        else None
    )
    shards = []
    clean = True
    for pos, osd in enumerate(acting):
        ent = by_pos.get(pos)
        if ent is None:
            continue  # unreachable: peering handles it, not scrub
        sh = {"osd": osd, "shard": pos, "errors": []}
        if not ent.get("exists"):
            sh["errors"].append(ERR_MISSING)
        elif ent.get("error"):
            sh["errors"].append(ent["error"])
        else:
            sh["size"] = ent.get("size")
            sh["omap_digest"] = ent.get("omap_digest")
            sh["attrs_digest"] = ent.get("attrs_digest")
            if deep:
                sh["data_digest"] = ent.get("data_digest")
            if (
                expected_len is not None
                and ent.get("size") != expected_len
            ):
                sh["errors"].append(ERR_EC_SIZE)
            if (
                deep
                and hashes is not None
                and pos < len(hashes)
                and ent.get("data_digest") != hashes[pos]
            ):
                sh["errors"].append(ERR_EC_HASH)
        if sh["errors"]:
            clean = False
        shards.append(sh)
    needs_reencode = deep and hashes is None and bool(size)
    if clean:
        return None, needs_reencode
    rec = make_record(oid, shards, [], {"size": size})
    return rec, needs_reencode


class _Run:
    """One in-flight scrub of one PG (resumable between chunks)."""

    __slots__ = (
        "pgid", "deep", "repair", "epoch", "acting", "oids", "idx",
        "records", "large_omap", "reserved", "started",
    )

    def __init__(self, pgid, deep, repair, epoch, acting):
        self.pgid = pgid
        self.deep = deep
        self.repair = repair
        self.epoch = epoch
        self.acting = list(acting)
        self.oids: list[str] = []
        self.idx = 0
        self.records: list[dict] = []
        self.large_omap: list[str] = []
        self.reserved: list[int] = []
        self.started = time.monotonic()


class Scrubber:
    """Per-OSD scrub engine: scheduling state, the osd_max_scrubs
    reservation ledger (both sides), and the chunked run loop the
    worker drains."""

    def __init__(self, osd):
        self.osd = osd
        self._runs: dict[str, _Run] = {}
        # remote grants this OSD handed out: (pgid, from_osd) -> stamp
        self._remote: dict[tuple[str, int], float] = {}
        self.remote_timeout = 120.0
        # on-demand requests: pgid -> (deep, repair)
        self.pending: dict[str, tuple[bool, bool]] = {}
        # last (errors, damaged) shipped to the mon, so the tick can
        # re-report on CHANGE: a primary that loses a damaged PG to
        # remapping must withdraw its contribution or the health
        # check pins forever (the mon no longer ages reports out)
        self._last_reported: tuple | None = None
        self._last_report_stamp = 0.0

    # -- config ------------------------------------------------------------
    @property
    def max_scrubs(self) -> int:
        """Constructor override wins; otherwise the osd_max_scrubs
        config option (so `ceph config set` / env actually works)."""
        if self.osd.osd_max_scrubs is not None:
            return max(1, int(self.osd.osd_max_scrubs))
        try:
            return max(
                1, int(self.osd.config.get("osd_max_scrubs"))
            )
        except (KeyError, ValueError):
            return 1

    @property
    def chunk_max(self) -> int:
        try:
            return max(
                1, int(self.osd.config.get("osd_scrub_chunk_max"))
            )
        except (KeyError, ValueError):
            return 25

    @property
    def auto_repair(self) -> bool:
        if self.osd.scrub_auto_repair is not None:
            return bool(self.osd.scrub_auto_repair)
        try:
            return bool(
                self.osd.config.get("osd_scrub_auto_repair")
            )
        except KeyError:
            return False

    # -- reservation ledger (replica side) ---------------------------------
    def _prune_remote(self, now: float) -> None:
        """Expire timed-out remote grants (a crashed primary never
        sends release; its lease must not block this OSD forever).
        pop(), not del: prune runs on the worker while reserve/
        release mutate the same dict on the messenger thread."""
        for key, stamp in list(self._remote.items()):
            if now - stamp > self.remote_timeout:
                self._remote.pop(key, None)

    def handle_reserve(self, pgid: str, from_osd: int) -> bool:
        now = time.monotonic()
        self._prune_remote(now)
        key = (pgid, from_osd)
        if (
            key in self._remote
            or len(self._remote) + len(self._runs) < self.max_scrubs
        ):
            self._remote[key] = now
            return True
        return False

    def handle_release(self, pgid: str, from_osd: int) -> None:
        self._remote.pop((pgid, from_osd), None)

    # -- scheduling (primary side) -----------------------------------------
    def request(self, pgid: str, deep: bool, repair: bool) -> None:
        """On-demand order (``ceph pg (deep-)scrub / repair``):
        overrides the interval on the next tick; repair implies deep."""
        prev = self.pending.get(pgid, (False, False))
        self.pending[pgid] = (deep or repair or prev[0],
                              repair or prev[1])

    def request_random(
        self, rng, deep: bool = False, repair: bool = False
    ) -> str | None:
        """Thrash hook: order a scrub on one caller-seeded-random PG
        this OSD currently leads (scrub-during-fault composition).
        ``rng`` is the caller's ``random.Random`` so target picks sit
        on the schedule's deterministic stream, not module state.
        Returns the chosen pgid, or None when nothing is eligible."""
        osd = self.osd
        with osd._pg_lock:
            eligible = sorted(
                pg.pgid
                for pg in osd.pgs.values()
                if pg.primary == osd.whoami and pg.state == "active"
            )
        if not eligible:
            return None
        pgid = eligible[rng.randrange(len(eligible))]
        self.request(pgid, deep=deep, repair=repair)
        return pgid

    def due(self, now: float) -> list[tuple[str, bool, bool]]:
        """(pgid, deep, repair) runs the tick should enqueue."""
        osd = self.osd
        out = []
        with osd._pg_lock:
            pgs = list(osd.pgs.values())
        for pg in pgs:
            if (
                pg.primary != osd.whoami
                or pg.state != "active"
                or pg.pgid in osd._scrubbing
            ):
                continue
            if pg.pgid in self.pending:
                deep, repair = self.pending.pop(pg.pgid)
                out.append((pg.pgid, deep, repair))
                continue
            if osd.scrub_interval <= 0:
                continue
            deep_int = (
                osd.deep_scrub_interval
                if osd.deep_scrub_interval is not None
                else osd.scrub_interval
            )
            last_deep = getattr(pg, "last_deep_scrub", 0.0)
            if deep_int > 0 and now - last_deep > deep_int:
                out.append((pg.pgid, True, False))
            elif now - pg.last_scrub > osd.scrub_interval:
                out.append((pg.pgid, False, False))
        return out

    # -- run loop (worker side) --------------------------------------------
    def run(self, pg, deep: bool, repair: bool) -> None:
        """Process ONE chunk (starting the run when none is in
        flight), then re-enqueue — the preemption point that lets
        client ops interleave.  Any abort releases reservations."""
        osd = self.osd
        run = self._runs.get(pg.pgid)
        try:
            if run is None:
                run = self._start(pg, deep, repair)
                if run is None:
                    osd._scrubbing.discard(pg.pgid)
                    return
            if (
                pg.primary != osd.whoami
                or pg.state != "active"
                or list(pg.acting) != run.acting
            ):
                # interval changed under the scrub: abandon, the next
                # schedule rescans (the reference aborts on a new map
                # interval too)
                self._finish(pg, run, aborted=True)
                return
            self._chunk(pg, run)
            if run.idx < len(run.oids):
                from .scheduler import CLASS_BACKGROUND

                osd._workq.enqueue(
                    CLASS_BACKGROUND, 1,
                    ("scrub", pg.pgid, run.deep, run.repair),
                )
                return
            self._finish(pg, run)
        except Exception:
            # a scrub crash must never leak reservations or the
            # _scrubbing guard (the worker's catch-all files the
            # crash report).  A crash inside _start leaves no run
            # registered — the guard still must drop or the PG is
            # unscrubbable until restart (due() skips guarded pgids
            # before it even reads pending orders)
            leaked = self._runs.get(pg.pgid)
            if leaked is not None:
                self._finish(pg, leaked, aborted=True)
            else:
                osd._scrubbing.discard(pg.pgid)
            raise

    def _start(self, pg, deep: bool, repair: bool) -> _Run | None:
        osd = self.osd
        if pg.primary != osd.whoami or pg.state != "active":
            return None
        # the cap counts in-flight runs AND slots granted to other
        # primaries (matching handle_reserve's replica-side count);
        # expired grants are pruned first, or a crashed primary's
        # lease would block this OSD's own scrubs forever
        self._prune_remote(time.monotonic())
        if (
            len(self._runs) + len(self._remote)
            >= self.max_scrubs
        ):
            self.request(pg.pgid, deep, repair)
            return None
        run = _Run(pg.pgid, deep, repair, osd.monc.epoch, pg.acting)
        try:
            return self._start_reserved(pg, run)
        except Exception:
            # partial remote grants must go back on ANY failure, not
            # just the clean deny path
            self._release(run)
            raise

    def _start_reserved(self, pg, run: _Run) -> _Run | None:
        from .daemon import CRUSH_ITEM_NONE

        osd = self.osd
        deep, repair = run.deep, run.repair
        peers = [
            o
            for o in dict.fromkeys(pg.acting)
            if o != osd.whoami
            and o != CRUSH_ITEM_NONE
            and osd.monc.osdmap.is_up(o)
        ]
        # two-sided osd_max_scrubs reservation (ScrubReserver):
        # a deny anywhere releases everything and retries later
        for peer in peers:
            granted = False
            try:
                reply = osd._peer_conn(peer).call(
                    MRepScrub(
                        tid=osd.messenger.new_tid(),
                        op="reserve", pgid=pg.pgid,
                        epoch=run.epoch, from_osd=osd.whoami,
                    ),
                    timeout=5.0,
                )
                granted = (
                    isinstance(reply, MScrubMap) and reply.ok
                )
            except (MessageError, OSError):
                pass
            if not granted:
                self._release(run)
                self.request(pg.pgid, deep, repair)
                return None
            run.reserved.append(peer)
        # object universe: union of every member's listing, so a copy
        # the primary lost is still scrubbed (and flagged missing)
        names = set(self._local_ls(pg))
        for peer in peers:
            try:
                reply = osd._peer_conn(peer).call(
                    MRepScrub(
                        tid=osd.messenger.new_tid(),
                        op="ls", pgid=pg.pgid, epoch=run.epoch,
                        from_osd=osd.whoami,
                    ),
                    timeout=10.0,
                )
                if isinstance(reply, MScrubMap) and reply.ok:
                    names.update(json.loads(reply.map_json))
            except (MessageError, OSError, ValueError):
                pass
        run.oids = sorted(names)
        self._runs[pg.pgid] = run
        what = self._what(run)
        osd.clog.info(f"pg {pg.pgid} {what} starts")
        osd.perf.set("scrubs_active", len(self._runs))
        return run

    def _what(self, run: _Run) -> str:
        if run.repair:
            return "repair"
        return "deep-scrub" if run.deep else "scrub"

    @staticmethod
    def _strip(store_oid: str) -> str:
        from .daemon import OBJ_PREFIX

        return (
            store_oid[len(OBJ_PREFIX):]
            if store_oid.startswith(OBJ_PREFIX)
            else store_oid
        )

    def _local_ls(self, pg) -> list[str]:
        from .daemon import OBJ_PREFIX

        try:
            return [
                o
                for o in self.osd.store.list_objects(pg.cid)
                if o.startswith(OBJ_PREFIX)
            ]
        except StoreError:
            return []

    def _release(self, run: _Run) -> None:
        osd = self.osd
        for peer in run.reserved:
            try:
                osd._peer_conn(peer).send(
                    MRepScrub(
                        tid=osd.messenger.new_tid(),
                        op="release", pgid=run.pgid,
                        epoch=run.epoch, from_osd=osd.whoami,
                    )
                )
            except (MessageError, OSError):
                pass
        run.reserved = []

    def _peer_map(
        self, run: _Run, peer: int, oids: list[str], deep: bool
    ) -> dict | None:
        osd = self.osd
        try:
            reply = osd._peer_conn(peer).call(
                MRepScrub(
                    tid=osd.messenger.new_tid(),
                    op="scan", pgid=run.pgid, epoch=run.epoch,
                    from_osd=osd.whoami, deep=deep, oids=oids,
                ),
                timeout=30.0,
            )
            if isinstance(reply, MScrubMap) and reply.ok:
                return json.loads(reply.map_json)
        except (MessageError, OSError, ValueError):
            pass
        return None

    def _gather_maps(
        self, pg, run: _Run, oids: list[str], deep: bool
    ) -> dict[int, dict | None]:
        """The acting set's digest maps for one chunk: one scan per
        member, each a single batched digest pass (None = unreachable
        peer, skipped by the compares)."""
        import threading

        from .daemon import CRUSH_ITEM_NONE

        osd = self.osd
        is_ec = osd._is_ec(pg)
        maps_by_osd: dict[int, dict | None] = {}
        # peer scans run CONCURRENTLY: they are independent, and a
        # wedged replica must cost the worker one timeout per chunk,
        # not one per peer per chunk (sum→max)
        threads = []
        for osd_id in dict.fromkeys(run.acting):
            if osd_id == CRUSH_ITEM_NONE:
                continue
            if osd_id == osd.whoami:
                maps_by_osd[osd_id] = build_scrub_map(
                    osd.store, pg.cid, oids, deep,
                    with_hinfo=is_ec,
                )
            elif osd.monc.osdmap.is_up(osd_id):
                def scan(osd_id=osd_id):
                    maps_by_osd[osd_id] = self._peer_map(
                        run, osd_id, oids, deep
                    )

                t = threading.Thread(
                    target=scan,
                    name=f"osd.{osd.whoami}.scrubgather",
                    daemon=True,
                )
                maps_by_osd[osd_id] = None
                t.start()
                threads.append(t)
            else:
                maps_by_osd[osd_id] = None
        for t in threads:
            t.join()
        return maps_by_osd

    def _compare_one(
        self, pg, run: _Run, oid: str,
        maps_by_osd: dict[int, dict | None], deep: bool,
        sinfo,
    ) -> tuple[dict | None, bool]:
        """One object's compare over gathered maps; returns
        (record | None, ec_needs_reencode)."""
        osd = self.osd
        per_osd = {
            o: (m.get(oid) if m is not None else None)
            for o, m in maps_by_osd.items()
        }
        if osd._is_ec(pg):
            return compare_ec(
                oid, per_osd, run.acting, sinfo, deep
            )
        return (
            compare_replicated(oid, per_osd, osd.whoami, deep),
            False,
        )

    def _sinfo_of(self, pg):
        if not self.osd._is_ec(pg):
            return None
        try:
            return self.osd._ec_codec(pg).sinfo
        except StoreError:
            return None

    def _chunk(self, pg, run: _Run) -> None:
        from .daemon import OBJ_PREFIX

        osd = self.osd
        oids = run.oids[run.idx : run.idx + self.chunk_max]
        run.idx += len(oids)
        if not oids:
            return
        maps_by_osd = self._gather_maps(pg, run, oids, run.deep)
        osd.perf.inc("scrub_chunks")
        if run.deep:
            # LARGE_OMAP_OBJECTS: the primary's own digest map
            # carries each object's omap cardinality (replicas hold
            # the same keys; one authoritative count suffices)
            thr = self._large_omap_threshold()
            own = maps_by_osd.get(osd.whoami) or {}
            for oid in oids:
                ent = own.get(oid) or {}
                if ent.get("omap_keys", 0) > thr:
                    run.large_omap.append(self._strip(oid))
        if run.deep:
            osd.perf.inc(
                "scrub_deep_bytes",
                sum(
                    (m or {}).get(oid, {}).get("size", 0)
                    for m in maps_by_osd.values()
                    for oid in oids
                ),
            )
        records: list[dict] = []
        reencode: list[str] = []
        sinfo = self._sinfo_of(pg)
        for oid in oids:
            rec, needs = self._compare_one(
                pg, run, oid, maps_by_osd, run.deep, sinfo
            )
            if needs:
                reencode.append(oid)
            if rec is not None:
                records.append(rec)
        if reencode:
            records.extend(
                self._reencode_verify(pg, run, reencode, records)
            )
        if run.repair and records:
            records = self._repair_chunk(pg, run, records)
        for rec in records:
            rec["object"]["name"] = rec["object"]["name"][
                len(OBJ_PREFIX):
            ]
            rec["oid"] = rec["object"]["name"]
        run.records.extend(records)

    def _reencode_verify(
        self, pg, run: _Run, oids: list[str], records: list[dict]
    ) -> list[dict]:
        """Deep-scrub fallback for hinfo-invalidated EC objects:
        decode the logical bytes, re-encode through the stripe seam
        (the packed-lane device kernel underneath), and compare every
        stored shard device-side.  A mismatch cannot be attributed to
        one shard without hashes — the record says so."""
        from ..ec.stripe import encode as stripe_encode

        osd = self.osd
        flagged = {r["object"]["name"] for r in records}
        out: list[dict] = []
        try:
            ecs = osd._ec_store_for(pg)
            codec = osd._ec_codec(pg)
        except StoreError:
            return out
        stored: list[bytes] = []
        expect: list[bytes] = []
        where: list[tuple[str, int]] = []
        for oid in oids:
            if oid in flagged:
                continue  # already recorded via per-shard errors
            try:
                logical = ecs.get(oid)
                padded = logical + b"\0" * (
                    codec.sinfo.logical_to_next_stripe_offset(
                        len(logical)
                    )
                    - len(logical)
                )
                shards = stripe_encode(
                    codec.sinfo, codec.ec, padded
                )
            except (ErasureCodeError, StoreError):
                continue
            for pos in range(codec.n):
                st = ecs.stores[pos]
                buf = _resident(st, pg.cid, oid)
                if buf is None:
                    try:
                        buf = st.read(pg.cid, oid)
                    except StoreError:
                        continue
                stored.append(buf)
                expect.append(bytes(shards.get(pos, b"")))
                where.append((oid, pos))
        if not stored:
            return out
        mismatch = batch_compare(stored, expect)
        bad: dict[str, list[int]] = {}
        for (oid, pos), is_bad in zip(where, mismatch):
            if is_bad:
                bad.setdefault(oid, []).append(pos)
        for oid, positions in bad.items():
            shards = [
                {
                    "osd": run.acting[pos],
                    "shard": pos,
                    "errors": [ERR_INCONSISTENT],
                }
                for pos in positions
            ]
            out.append(
                make_record(oid, shards, [ERR_INCONSISTENT], None)
            )
        return out

    # -- repair ------------------------------------------------------------
    def _repair_chunk(
        self, pg, run: _Run, records: list[dict]
    ) -> list[dict]:
        """Fix each finding through the recovery-push machinery, then
        re-verify; only objects still broken stay recorded (the
        PrimaryLogPG repair path: authoritative copy → push →
        rescrub)."""
        osd = self.osd
        is_ec = osd._is_ec(pg)
        fixed: list[str] = []
        for rec in records:
            oid = rec["object"]["name"]
            try:
                if is_ec:
                    self._repair_ec(pg, run, rec)
                else:
                    self._repair_replicated(pg, run, rec)
                fixed.append(oid)
            except (
                StoreError, ErasureCodeError, MessageError, OSError
            ) as e:
                dout(
                    "osd", 1,
                    f"osd.{osd.whoami} pg {pg.pgid} repair of "
                    f"{oid} failed: {e}",
                )
        if not fixed:
            return records
        # re-verify the repaired objects with a fresh deep compare
        still: list[dict] = []
        byname = {r["object"]["name"]: r for r in records}
        maps_by_osd = self._gather_maps(pg, run, fixed, True)
        sinfo = self._sinfo_of(pg)
        fixed_count = 0
        for oid in fixed:
            rec, _needs = self._compare_one(
                pg, run, oid, maps_by_osd, True, sinfo
            )
            if rec is not None:
                still.append(rec)
            else:
                fixed_count += 1
        still.extend(
            r for n, r in byname.items() if n not in fixed
        )
        if fixed_count:
            osd.clog.info(
                f"pg {pg.pgid} repair fixed {fixed_count} objects"
            )
        return still

    def _repair_replicated(self, pg, run: _Run, rec: dict) -> None:
        """Push the authoritative copy over every divergent one."""
        osd = self.osd
        from .daemon import OBJ_PREFIX

        sel = rec.get("selected_object_info") or {}
        source = sel.get("osd")
        if source is None:
            source = osd.whoami
        oid = rec["object"]["name"][len(OBJ_PREFIX):]
        bad = [
            sh["osd"] for sh in rec["shards"] if sh.get("errors")
        ]
        if source == osd.whoami:
            push = osd._push_for(pg, run.epoch, oid)
        else:
            reply = osd._peer_conn(source).call(
                MPGPull(
                    pgid=pg.pgid, epoch=run.epoch, oid=oid,
                    shard=-1,
                ),
                timeout=15.0,
            )
            if not isinstance(reply, MPGPush):
                raise StoreError(
                    f"repair pull of {oid} from osd.{source} failed"
                )
            push = reply
            if osd.whoami in bad:
                osd._apply_push(pg, push)
        for peer in bad:
            if peer == osd.whoami or peer == source:
                continue
            push.tid = osd.messenger.new_tid()
            osd._peer_conn(peer).call(push, timeout=15.0)

    def _repair_ec(self, pg, run: _Run, rec: dict) -> None:
        """Rebuild bad shards from the survivors (decode path); for
        unattributable re-encode mismatches, decode the logical bytes
        from the data shards and rewrite every divergent shard."""
        osd = self.osd
        oid = rec["object"]["name"]
        ecs = osd._ec_store_for(pg)
        codec = osd._ec_codec(pg)
        bad_pos = sorted(
            {
                sh["shard"]
                for sh in rec["shards"]
                if sh.get("errors") and sh.get("shard", -1) >= 0
            }
        )
        meta = None
        try:
            meta = ecs.meta(oid)
        except ErasureCodeError:
            pass
        if (
            rec.get("inconsistent")
            or meta is None
            or meta.get("hashes") is None
        ):
            # no per-shard truth: restore mutual consistency from the
            # data shards (decode-from-surviving-shards)
            logical = ecs.get(oid)
            padded = logical + b"\0" * (
                codec.sinfo.logical_to_next_stripe_offset(
                    len(logical)
                )
                - len(logical)
            )
            from ..ec.stripe import encode as stripe_encode

            shards = stripe_encode(codec.sinfo, codec.ec, padded)
            blob = json.dumps(
                meta or {"size": len(logical)}
            ).encode()
            for pos in bad_pos:
                txn = Transaction()
                if ecs.stores[pos].exists(pg.cid, oid):
                    txn.remove(pg.cid, oid)
                txn.touch(pg.cid, oid)
                txn.write(pg.cid, oid, 0, bytes(shards[pos]))
                txn.setattr(pg.cid, oid, HINFO_KEY, blob)
                ecs.stores[pos].queue_transaction(txn)
            return
        for pos in bad_pos:
            # hinfo-verified rebuild: corrupt helpers are filtered by
            # their own crc, the rebuilt shard must match its hash
            ecs.recover_shard(oid, pos, dict(meta))

    # -- completion --------------------------------------------------------
    def _finish(self, pg, run: _Run, aborted: bool = False) -> None:
        osd = self.osd
        self._release(run)
        self._runs.pop(pg.pgid, None)
        osd._scrubbing.discard(pg.pgid)
        osd.perf.set("scrubs_active", len(self._runs))
        what = self._what(run)
        if aborted:
            osd.clog.info(f"pg {pg.pgid} {what} aborted")
            return
        now = time.monotonic()
        records = run.records
        if not run.deep:
            # a shallow pass is BLIND to payload corruption: carry
            # forward deep-only findings it cannot re-test (a shallow
            # scrub must never clear OSD_SCRUB_ERRORS raised by a
            # deep one; only a deep scrub or repair re-judges them)
            deep_only = {ERR_DATA, ERR_EC_HASH, ERR_INCONSISTENT}
            new_names = {r["object"]["name"] for r in records}
            universe = {
                self._strip(o) for o in run.oids
            }
            records = records + [
                old
                for old in pg.scrub_errors
                if old["object"]["name"] not in new_names
                and old["object"]["name"] in universe
                and deep_only
                & (
                    set(old.get("errors", ()))
                    | set(old.get("union_shard_errors", ()))
                )
            ]
        pg.scrub_errors = records
        run.records = records
        pg.last_scrub = now
        if run.deep:
            pg.last_deep_scrub = now
            # only a deep pass re-judges omap cardinality (a shallow
            # one never counted keys and must not clear the finding)
            pg.large_omap = list(run.large_omap)
            if run.large_omap:
                osd.clog.warn(
                    f"pg {pg.pgid} {what} found "
                    f"{len(run.large_omap)} large omap object(s): "
                    f"{sorted(run.large_omap)[:4]}"
                )
        try:
            ScrubStore.save(osd.store, pg.cid, run.records)
        except StoreError:
            pass
        from .daemon import PG_META

        txn = Transaction().touch(pg.cid, PG_META)
        stamp = str(time.time()).encode()
        txn.setattr(pg.cid, PG_META, "scrub_stamp", stamp)
        if run.deep:
            txn.setattr(pg.cid, PG_META, "deep_scrub_stamp", stamp)
        try:
            osd.store.queue_transaction(txn)
        except StoreError:
            pass
        nerr = len(run.records)
        if nerr:
            osd.clog.error(
                f"pg {pg.pgid} {what} {nerr} errors"
            )
            dout(
                "osd", 1,
                f"osd.{osd.whoami} pg {pg.pgid} {what} found "
                f"{nerr} inconsistencies",
            )
        else:
            osd.clog.info(f"pg {pg.pgid} {what} ok")
        self.report_health()
        if run.deep and not run.repair and nerr and self.auto_repair:
            try:
                cap = int(
                    self.osd.config.get(
                        "osd_scrub_auto_repair_num_errors"
                    )
                )
            except (KeyError, ValueError):
                cap = 5
            if nerr <= cap:
                # osd_scrub_auto_repair: queue the repair pass
                self.request(pg.pgid, True, True)

    def maybe_report(self, now: float) -> None:
        """Tick hook: re-report when this OSD's contribution CHANGED
        since the last report — e.g. a damaged PG remapped to another
        primary (our count drops to 0 and must withdraw the health
        complaint, since the mon holds reports until cleared)."""
        if now - self._last_report_stamp < 5.0:
            return
        current = self._current_report()
        if current != self._last_reported or (
            (current[0] > 0 or current[2] > 0)
            and now - self._last_report_stamp > 30.0
        ):
            # nonzero findings RE-ASSERT periodically: the mon drops
            # a report when its daemon blips down, and without the
            # re-assert a recovered OSD whose state never changed
            # would leave known damage invisible in ceph health
            self.report_health()

    def _large_omap_threshold(self) -> int:
        try:
            return int(
                self.osd.config.get(
                    "osd_deep_scrub_large_omap_object_key_threshold"
                )
            )
        except (KeyError, TypeError, ValueError):
            return 200000

    def _current_report(self) -> tuple:
        osd = self.osd
        with osd._pg_lock:
            damaged = tuple(
                sorted(
                    pg.pgid
                    for pg in osd.pgs.values()
                    if pg.primary == osd.whoami and pg.scrub_errors
                )
            )
            errors = sum(
                len(pg.scrub_errors)
                for pg in osd.pgs.values()
                if pg.primary == osd.whoami
            )
            large = sum(
                len(pg.large_omap)
                for pg in osd.pgs.values()
                if pg.primary == osd.whoami
            )
        return errors, damaged, large

    def report_health(self) -> None:
        """Tell the mon how many scrub errors this OSD's primary PGs
        carry (feeds OSD_SCRUB_ERRORS / PG_DAMAGED; a zero report
        clears)."""
        osd = self.osd
        errors, damaged, large = self._current_report()
        osd.perf.set("scrub_errors", errors)
        self._last_report_stamp = time.monotonic()
        try:
            osd.monc.command(
                {
                    "prefix": "osd scrub errors",
                    "daemon": f"osd.{osd.whoami}",
                    "errors": errors,
                    "pgs": list(damaged),
                    # omap-cardinality findings ride the same upcall
                    # (LARGE_OMAP_OBJECTS)
                    "large_omap": large,
                },
                timeout=5.0,
            )
            self._last_reported = (errors, damaged, large)
        except (MessageError, OSError):
            pass
