"""OSDMap — cluster map + the scalar PG→OSD mapping oracle.

Pipeline semantics re-derived from src/osd/OSDMap.cc:
``pg_to_up_acting_osds`` (:2668) = raw_pg_to_pps seed → crush do_rule
(_pg_to_raw_osds :2436) → _apply_upmap (:2466) → _raw_to_up_osds
(:2513) → _pick_primary (:2456) → _apply_primary_affinity (:2540) →
_get_temp_osds (:2593).  PG seeds: pg_pool_t::raw_pg_to_pps
(src/osd/osd_types.cc:1793) with ceph_stable_mod
(src/include/rados.h:96-102) keeping splits stable.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crush.builder import CrushMap
from ..crush.hashing import crush_hash32_2
from ..crush.types import (
    CRUSH_ITEM_NONE,
    PG_POOL_TYPE_ERASURE,
    PG_POOL_TYPE_REPLICATED,
)

CEPH_OSD_DEFAULT_PRIMARY_AFFINITY = 0x10000
CEPH_OSD_MAX_PRIMARY_AFFINITY = 0x10000


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """Stable modulo: values keep their slot across pg_num doublings
    (src/include/rados.h:96-102)."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


def _pg_mask(n: int) -> int:
    """Smallest all-ones mask covering [0, n) (pg_pool_t pg_num_mask)."""
    return (1 << max(n - 1, 0).bit_length()) - 1


@dataclass
class PgPool:
    """pg_pool_t subset relevant to mapping (src/osd/osd_types.h)."""

    pool_id: int
    type: int = PG_POOL_TYPE_REPLICATED
    size: int = 3
    min_size: int = 2
    pg_num: int = 32
    pgp_num: int = 0  # defaults to pg_num
    crush_rule: int = 0
    erasure_code_profile: str = ""
    hashpspool: bool = True

    def __post_init__(self):
        if not self.pgp_num:
            self.pgp_num = self.pg_num

    @property
    def pg_num_mask(self) -> int:
        return _pg_mask(self.pg_num)

    @property
    def pgp_num_mask(self) -> int:
        return _pg_mask(self.pgp_num)

    def can_shift_osds(self) -> bool:
        """Replicated mappings compact holes; EC keeps positions."""
        return self.type == PG_POOL_TYPE_REPLICATED

    def raw_pg_to_pg_seed(self, ps: int) -> int:
        """raw ps → stable pg seed (pg_pool_t::raw_pg_to_pg)."""
        return ceph_stable_mod(ps, self.pg_num, self.pg_num_mask)

    def raw_pg_to_pps(self, ps: int) -> int:
        """Placement seed fed to CRUSH (osd_types.cc:1793-1809)."""
        m = ceph_stable_mod(ps, self.pgp_num, self.pgp_num_mask)
        if self.hashpspool:
            return crush_hash32_2(m, self.pool_id & 0xFFFFFFFF)
        return m + self.pool_id


@dataclass
class OSDMap:
    """Cluster map: OSD state vectors + pools + the crush map.

    pg ids are (pool_id, ps) tuples; override maps are keyed by the
    stable pg seed like the reference's pg_t keys."""

    crush: CrushMap
    max_osd: int = 0
    epoch: int = 1
    pools: dict[int, PgPool] = field(default_factory=dict)
    osd_exists: list[bool] = field(default_factory=list)
    osd_up: list[bool] = field(default_factory=list)
    osd_weight: list[int] = field(default_factory=list)  # 16.16 reweight
    osd_primary_affinity: list[int] | None = None  # 16.16, None = defaults
    pg_temp: dict[tuple[int, int], list[int]] = field(default_factory=dict)
    primary_temp: dict[tuple[int, int], int] = field(default_factory=dict)
    pg_upmap: dict[tuple[int, int], list[int]] = field(default_factory=dict)
    pg_upmap_items: dict[tuple[int, int], list[tuple[int, int]]] = field(
        default_factory=dict
    )

    @classmethod
    def build(cls, crush: CrushMap, num_osd: int) -> OSDMap:
        return cls(
            crush=crush,
            max_osd=num_osd,
            osd_exists=[True] * num_osd,
            osd_up=[True] * num_osd,
            osd_weight=[0x10000] * num_osd,
        )

    def add_pool(self, pool: PgPool) -> PgPool:
        self.pools[pool.pool_id] = pool
        return pool

    # -- state queries -----------------------------------------------------
    def exists(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and self.osd_exists[osd]

    def is_up(self, osd: int) -> bool:
        return self.exists(osd) and self.osd_up[osd]

    def is_down(self, osd: int) -> bool:
        return not self.is_up(osd)

    def mark_down(self, osd: int) -> None:
        self.osd_up[osd] = False

    def mark_out(self, osd: int) -> None:
        self.osd_weight[osd] = 0

    # -- mapping pipeline (scalar oracle) ----------------------------------
    def _pg_to_raw_osds(self, pool: PgPool, ps: int) -> tuple[list[int], int]:
        pps = pool.raw_pg_to_pps(ps)
        ruleno = self.crush.find_rule(pool.crush_rule, pool.type, pool.size)
        raw: list[int] = []
        if ruleno >= 0:
            raw = self.crush.do_rule(ruleno, pps, pool.size, self.osd_weight)
        self._remove_nonexistent(pool, raw)
        return raw, pps

    def _remove_nonexistent(self, pool: PgPool, osds: list[int]) -> None:
        if pool.can_shift_osds():
            osds[:] = [o for o in osds if self.exists(o)]
        else:
            for i, o in enumerate(osds):
                if o != CRUSH_ITEM_NONE and not self.exists(o):
                    osds[i] = CRUSH_ITEM_NONE

    def _apply_upmap(self, pool: PgPool, ps: int, raw: list[int]) -> list[int]:
        pg = (pool.pool_id, pool.raw_pg_to_pg_seed(ps))
        explicit = self.pg_upmap.get(pg)
        if explicit:
            if not any(
                o != CRUSH_ITEM_NONE
                and 0 <= o < self.max_osd
                and self.osd_weight[o] == 0
                for o in explicit
            ):
                raw = list(explicit)
        items = self.pg_upmap_items.get(pg)
        if items:
            for src, dst in items:
                pos = -1
                exists = False
                for i, o in enumerate(raw):
                    if o == dst:
                        exists = True
                        break
                    if o == src and pos < 0:
                        dst_out = (
                            dst != CRUSH_ITEM_NONE
                            and 0 <= dst < self.max_osd
                            and self.osd_weight[dst] == 0
                        )
                        if not dst_out:
                            pos = i
                if not exists and pos >= 0:
                    raw[pos] = dst
        return raw

    def _raw_to_up_osds(self, pool: PgPool, raw: list[int]) -> list[int]:
        if pool.can_shift_osds():
            return [o for o in raw if self.is_up(o)]
        return [
            o if o != CRUSH_ITEM_NONE and self.is_up(o) else CRUSH_ITEM_NONE
            for o in raw
        ]

    @staticmethod
    def _pick_primary(osds: list[int]) -> int:
        for o in osds:
            if o != CRUSH_ITEM_NONE:
                return o
        return -1

    def _apply_primary_affinity(
        self, pps: int, pool: PgPool, osds: list[int], primary: int
    ) -> tuple[list[int], int]:
        aff = self.osd_primary_affinity
        if aff is None:
            return osds, primary
        if not any(
            o != CRUSH_ITEM_NONE
            and aff[o] != CEPH_OSD_DEFAULT_PRIMARY_AFFINITY
            for o in osds
        ):
            return osds, primary
        pos = -1
        for i, o in enumerate(osds):
            if o == CRUSH_ITEM_NONE:
                continue
            a = aff[o]
            if (
                a < CEPH_OSD_MAX_PRIMARY_AFFINITY
                and (crush_hash32_2(pps, o) >> 16) >= a
            ):
                if pos < 0:
                    pos = i  # fallback, keep looking
            else:
                pos = i
                break
        if pos < 0:
            return osds, primary
        primary = osds[pos]
        if pool.can_shift_osds() and pos > 0:
            osds = [osds[pos]] + osds[:pos] + osds[pos + 1 :]
        return osds, primary

    def _get_temp_osds(
        self, pool: PgPool, ps: int
    ) -> tuple[list[int], int]:
        pg = (pool.pool_id, pool.raw_pg_to_pg_seed(ps))
        temp_pg: list[int] = []
        for o in self.pg_temp.get(pg, []):
            if not self.is_up(o):
                if pool.can_shift_osds():
                    continue
                temp_pg.append(CRUSH_ITEM_NONE)
            else:
                temp_pg.append(o)
        temp_primary = self.primary_temp.get(pg, -1)
        if temp_primary == -1 and temp_pg:
            temp_primary = self._pick_primary(temp_pg)
        return temp_pg, temp_primary

    def pg_to_up_acting_osds(
        self, pool_id: int, ps: int
    ) -> tuple[list[int], int, list[int], int]:
        """(up, up_primary, acting, acting_primary) — OSDMap.cc:2668."""
        pool = self.pools.get(pool_id)
        if pool is None or ps >= pool.pg_num:
            return [], -1, [], -1
        acting, acting_primary = self._get_temp_osds(pool, ps)
        raw, pps = self._pg_to_raw_osds(pool, ps)
        raw = self._apply_upmap(pool, ps, raw)
        up = self._raw_to_up_osds(pool, raw)
        up_primary = self._pick_primary(up)
        up, up_primary = self._apply_primary_affinity(
            pps, pool, up, up_primary
        )
        if not acting:
            acting = list(up)
            if acting_primary == -1:
                acting_primary = up_primary
        return up, up_primary, acting, acting_primary
