"""OSDMap — epoch-versioned cluster map + the scalar PG→OSD oracle.

Pipeline semantics re-derived from src/osd/OSDMap.cc:
``pg_to_up_acting_osds`` (:2668) = raw_pg_to_pps seed → crush do_rule
(_pg_to_raw_osds :2436) → _apply_upmap (:2466) → _raw_to_up_osds
(:2513) → _pick_primary (:2456) → _apply_primary_affinity (:2540) →
_get_temp_osds (:2593).  PG seeds: pg_pool_t::raw_pg_to_pps
(src/osd/osd_types.cc:1793) with ceph_stable_mod
(src/include/rados.h:96-102) keeping splits stable.

Epoch machinery re-derived from ``class OSDMap::Incremental``
(src/osd/OSDMap.h:354-425) and ``OSDMap::apply_incremental``
(src/osd/OSDMap.cc:2062): an incremental is a diff from epoch-1 to
epoch; new_state entries are XORed onto the per-OSD state bits with
the destroy special-case; empty new_pg_temp values remove entries;
primary_temp -1 removes; upmap maps have explicit old_* removal sets.
Wire encode/decode uses the framework's versioned envelope
(common/encoding.py) with a crc32c trailer — same design as the
reference's ENCODE_START/crc scheme, not its exact byte layout.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from ..common.encoding import (
    Decoder,
    DecodeError,
    Encoder,
    decode_versioned,
    encode_versioned,
)
from ..crush.builder import CrushMap
from ..crush.encode import decode_crush_map, encode_crush_map
from ..crush.hashing import crush_hash32_2
from ..crush.types import (
    CRUSH_ITEM_NONE,
    PG_POOL_TYPE_ERASURE,
    PG_POOL_TYPE_REPLICATED,
)
from ..native import ceph_crc32c

CEPH_OSD_DEFAULT_PRIMARY_AFFINITY = 0x10000
CEPH_OSD_MAX_PRIMARY_AFFINITY = 0x10000

# process-wide raw CRUSH mapping memo (OSDMapMapping role,
# src/osd/OSDMapMapping.h): keyed on the crush CONTENT fingerprint +
# the exact do_rule inputs, so every daemon in an in-process cluster
# shares one pure-Python straw2 descent per (map, PG) instead of
# re-walking it per daemon — the 100-OSD scale harness turns a
# minutes-long map walk into one
_RAW_MAP_CACHE: dict = {}
_RAW_MAP_CACHE_MAX = 65536


def _crush_fp(crush: CrushMap) -> bytes:
    """Content fingerprint of a CrushMap, memoized against its
    ``mutation`` counter (bumped by every mutator) — the encode runs
    once per distinct map content per object, not per mapping.
    128-bit digest: a 32-bit crc keyed placement for the whole
    process, where a silent collision would misdirect I/O."""
    import hashlib

    cached = getattr(crush, "_content_fp", None)
    if cached is not None and cached[0] == crush.mutation:
        return cached[1]
    fp = hashlib.blake2b(
        encode_crush_map(crush), digest_size=16
    ).digest()
    crush._content_fp = (crush.mutation, fp)
    return fp

# per-OSD state bits (src/include/rados.h:125-132)
CEPH_OSD_EXISTS = 1 << 0
CEPH_OSD_UP = 1 << 1
CEPH_OSD_AUTOOUT = 1 << 2
CEPH_OSD_NEW = 1 << 3
CEPH_OSD_DESTROYED = 1 << 7


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """Stable modulo: values keep their slot across pg_num doublings
    (src/include/rados.h:96-102)."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


def _pg_mask(n: int) -> int:
    """Smallest all-ones mask covering [0, n) (pg_pool_t pg_num_mask)."""
    return (1 << max(n - 1, 0).bit_length()) - 1


@dataclass
class PgPool:
    """pg_pool_t subset relevant to mapping (src/osd/osd_types.h)."""

    pool_id: int
    type: int = PG_POOL_TYPE_REPLICATED
    size: int = 3
    min_size: int = 2
    pg_num: int = 32
    pgp_num: int = 0  # defaults to pg_num
    crush_rule: int = 0
    erasure_code_profile: str = ""
    hashpspool: bool = True
    last_change: int = 0  # epoch of last pool modification
    # pool snapshots (pg_pool_t::snaps / snap_seq): snap id → name;
    # snap_seq is the newest snap id, the write path's snap context
    snap_seq: int = 0
    snaps: dict[int, str] = field(default_factory=dict)
    # cache tiering (pg_pool_t tier fields, src/osd/osd_types.h):
    # on a BASE pool, read_tier/write_tier name the overlay cache
    # pool clients route to; on a CACHE pool, tier_of names the base
    tier_of: int = -1
    read_tier: int = -1
    write_tier: int = -1
    cache_mode: str = ""  # "" | "writeback"
    target_max_objects: int = 0  # agent eviction pressure point

    def __post_init__(self):
        if not self.pgp_num:
            self.pgp_num = self.pg_num

    @property
    def pg_num_mask(self) -> int:
        return _pg_mask(self.pg_num)

    @property
    def pgp_num_mask(self) -> int:
        return _pg_mask(self.pgp_num)

    def can_shift_osds(self) -> bool:
        """Replicated mappings compact holes; EC keeps positions."""
        return self.type == PG_POOL_TYPE_REPLICATED

    def raw_pg_to_pg_seed(self, ps: int) -> int:
        """raw ps → stable pg seed (pg_pool_t::raw_pg_to_pg)."""
        return ceph_stable_mod(ps, self.pg_num, self.pg_num_mask)

    def raw_pg_to_pps(self, ps: int) -> int:
        """Placement seed fed to CRUSH (osd_types.cc:1793-1809)."""
        m = ceph_stable_mod(ps, self.pgp_num, self.pgp_num_mask)
        if self.hashpspool:
            return crush_hash32_2(m, self.pool_id & 0xFFFFFFFF)
        return m + self.pool_id


@dataclass
class OSDMap:
    """Cluster map: OSD state vectors + pools + the crush map.

    pg ids are (pool_id, ps) tuples; override maps are keyed by the
    stable pg seed like the reference's pg_t keys."""

    crush: CrushMap
    max_osd: int = 0
    epoch: int = 1
    pools: dict[int, PgPool] = field(default_factory=dict)
    osd_exists: list[bool] = field(default_factory=list)
    osd_up: list[bool] = field(default_factory=list)
    osd_weight: list[int] = field(default_factory=list)  # 16.16 reweight
    osd_primary_affinity: list[int] | None = None  # 16.16, None = defaults
    pg_temp: dict[tuple[int, int], list[int]] = field(default_factory=dict)
    primary_temp: dict[tuple[int, int], int] = field(default_factory=dict)
    pg_upmap: dict[tuple[int, int], list[int]] = field(default_factory=dict)
    pg_upmap_items: dict[tuple[int, int], list[tuple[int, int]]] = field(
        default_factory=dict
    )
    # epoch-machinery state (OSDMap.h map body beyond the mapping core)
    pool_max: int = -1  # highest pool id ever allocated
    flags: int = 0  # CEPH_OSDMAP_* cluster flags
    pool_names: dict[int, str] = field(default_factory=dict)
    erasure_code_profiles: dict[str, dict[str, str]] = field(
        default_factory=dict
    )
    # residual per-OSD state bits beyond EXISTS/UP (AUTOOUT/NEW/...)
    osd_flags: list[int] = field(default_factory=list)
    osd_addrs: dict[int, str] = field(default_factory=dict)
    osd_down_at: list[int] = field(default_factory=list)
    osd_up_from: list[int] = field(default_factory=list)
    blocklist: dict[str, float] = field(default_factory=dict)

    @classmethod
    def build(cls, crush: CrushMap, num_osd: int) -> OSDMap:
        return cls(
            crush=crush,
            max_osd=num_osd,
            osd_exists=[True] * num_osd,
            osd_up=[True] * num_osd,
            osd_weight=[0x10000] * num_osd,
            osd_flags=[0] * num_osd,
            osd_down_at=[0] * num_osd,
            osd_up_from=[0] * num_osd,
        )

    def add_pool(self, pool: PgPool) -> PgPool:
        self.pools[pool.pool_id] = pool
        self.pool_max = max(self.pool_max, pool.pool_id)
        return pool

    def is_blocklisted(self, addr: str, now: float | None = None) -> bool:
        """Client fencing check (OSDMap::is_blocklisted,
        src/osd/OSDMap.h:585).  ``addr`` is the client's entity
        address analog — here the objecter's client id.  Entries
        carry an absolute expiry; expired entries no longer fence
        (the mon trims them on later commits)."""
        until = self.blocklist.get(addr)
        if until is None:
            return False
        import time as _time

        return (now if now is not None else _time.time()) < until

    def set_max_osd(self, n: int) -> None:
        """Grow (or truncate) every per-OSD vector (OSDMap::set_max_osd).
        New slots exist but are down/out until an incremental boots them."""
        grow = n - self.max_osd
        for vec, fill in (
            (self.osd_exists, False),
            (self.osd_up, False),
            (self.osd_flags, 0),
            (self.osd_down_at, 0),
            (self.osd_up_from, 0),
        ):
            if grow > 0:
                vec.extend([fill] * grow)
            else:
                del vec[n:]
        if grow > 0:
            self.osd_weight.extend([0] * grow)
        else:
            del self.osd_weight[n:]
        if self.osd_primary_affinity is not None:
            if grow > 0:
                self.osd_primary_affinity.extend(
                    [CEPH_OSD_DEFAULT_PRIMARY_AFFINITY] * grow
                )
            else:
                del self.osd_primary_affinity[n:]
        self.max_osd = n

    # -- state bits --------------------------------------------------------
    def get_state(self, osd: int) -> int:
        """Composite CEPH_OSD_* bits for one OSD."""
        s = self.osd_flags[osd]
        if self.osd_exists[osd]:
            s |= CEPH_OSD_EXISTS
        if self.osd_up[osd]:
            s |= CEPH_OSD_UP
        return s

    def _set_state(self, osd: int, s: int) -> None:
        self.osd_exists[osd] = bool(s & CEPH_OSD_EXISTS)
        self.osd_up[osd] = bool(s & CEPH_OSD_UP)
        self.osd_flags[osd] = s & ~(CEPH_OSD_EXISTS | CEPH_OSD_UP)

    def set_primary_affinity(self, osd: int, aff: int) -> None:
        if self.osd_primary_affinity is None:
            self.osd_primary_affinity = [
                CEPH_OSD_DEFAULT_PRIMARY_AFFINITY
            ] * self.max_osd
        self.osd_primary_affinity[osd] = aff

    # -- state queries -----------------------------------------------------
    def exists(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and self.osd_exists[osd]

    def is_up(self, osd: int) -> bool:
        return self.exists(osd) and self.osd_up[osd]

    def is_down(self, osd: int) -> bool:
        return not self.is_up(osd)

    def mark_down(self, osd: int) -> None:
        self.osd_up[osd] = False

    def mark_out(self, osd: int) -> None:
        self.osd_weight[osd] = 0

    # -- mapping pipeline (scalar oracle) ----------------------------------
    def _pg_to_raw_osds(self, pool: PgPool, ps: int) -> tuple[list[int], int]:
        pps = pool.raw_pg_to_pps(ps)
        ruleno = self.crush.find_rule(pool.crush_rule, pool.type, pool.size)
        raw: list[int] = []
        if ruleno >= 0:
            # process-wide raw-mapping memo (the OSDMapMapping role,
            # src/osd/OSDMapMapping.h: the reference precomputes every
            # PG's mapping per epoch rather than re-walking CRUSH).
            # In-process clusters hold one OSDMap copy PER DAEMON with
            # identical contents, so keying on content — crush
            # fingerprint + the exact do_rule inputs — lets 100
            # daemons share one descent per PG per epoch instead of
            # paying the pure-Python straw2 walk 100 times.
            key = (
                _crush_fp(self.crush),
                struct.pack(
                    f"<{len(self.osd_weight)}I", *self.osd_weight
                ),
                bytes(self.osd_exists),
                ruleno,
                pps,
                pool.size,
                pool.can_shift_osds(),
            )
            hit = _RAW_MAP_CACHE.get(key)
            if hit is not None:
                return list(hit), pps
            raw = self.crush.do_rule(ruleno, pps, pool.size, self.osd_weight)
            self._remove_nonexistent(pool, raw)
            if len(_RAW_MAP_CACHE) >= _RAW_MAP_CACHE_MAX:
                _RAW_MAP_CACHE.clear()
            _RAW_MAP_CACHE[key] = tuple(raw)
            return raw, pps
        self._remove_nonexistent(pool, raw)
        return raw, pps

    def _remove_nonexistent(self, pool: PgPool, osds: list[int]) -> None:
        if pool.can_shift_osds():
            osds[:] = [o for o in osds if self.exists(o)]
        else:
            for i, o in enumerate(osds):
                if o != CRUSH_ITEM_NONE and not self.exists(o):
                    osds[i] = CRUSH_ITEM_NONE

    def _apply_upmap(self, pool: PgPool, ps: int, raw: list[int]) -> list[int]:
        pg = (pool.pool_id, pool.raw_pg_to_pg_seed(ps))
        explicit = self.pg_upmap.get(pg)
        if explicit:
            if not any(
                o != CRUSH_ITEM_NONE
                and 0 <= o < self.max_osd
                and self.osd_weight[o] == 0
                for o in explicit
            ):
                raw = list(explicit)
        items = self.pg_upmap_items.get(pg)
        if items:
            for src, dst in items:
                pos = -1
                exists = False
                for i, o in enumerate(raw):
                    if o == dst:
                        exists = True
                        break
                    if o == src and pos < 0:
                        dst_out = (
                            dst != CRUSH_ITEM_NONE
                            and 0 <= dst < self.max_osd
                            and self.osd_weight[dst] == 0
                        )
                        if not dst_out:
                            pos = i
                if not exists and pos >= 0:
                    raw[pos] = dst
        return raw

    def _raw_to_up_osds(self, pool: PgPool, raw: list[int]) -> list[int]:
        if pool.can_shift_osds():
            return [o for o in raw if self.is_up(o)]
        return [
            o if o != CRUSH_ITEM_NONE and self.is_up(o) else CRUSH_ITEM_NONE
            for o in raw
        ]

    @staticmethod
    def _pick_primary(osds: list[int]) -> int:
        for o in osds:
            if o != CRUSH_ITEM_NONE:
                return o
        return -1

    def _apply_primary_affinity(
        self, pps: int, pool: PgPool, osds: list[int], primary: int
    ) -> tuple[list[int], int]:
        aff = self.osd_primary_affinity
        if aff is None:
            return osds, primary
        if not any(
            o != CRUSH_ITEM_NONE
            and aff[o] != CEPH_OSD_DEFAULT_PRIMARY_AFFINITY
            for o in osds
        ):
            return osds, primary
        pos = -1
        for i, o in enumerate(osds):
            if o == CRUSH_ITEM_NONE:
                continue
            a = aff[o]
            if (
                a < CEPH_OSD_MAX_PRIMARY_AFFINITY
                and (crush_hash32_2(pps, o) >> 16) >= a
            ):
                if pos < 0:
                    pos = i  # fallback, keep looking
            else:
                pos = i
                break
        if pos < 0:
            return osds, primary
        primary = osds[pos]
        if pool.can_shift_osds() and pos > 0:
            osds = [osds[pos]] + osds[:pos] + osds[pos + 1 :]
        return osds, primary

    def _get_temp_osds(
        self, pool: PgPool, ps: int
    ) -> tuple[list[int], int]:
        pg = (pool.pool_id, pool.raw_pg_to_pg_seed(ps))
        temp_pg: list[int] = []
        for o in self.pg_temp.get(pg, []):
            if not self.is_up(o):
                if pool.can_shift_osds():
                    continue
                temp_pg.append(CRUSH_ITEM_NONE)
            else:
                temp_pg.append(o)
        temp_primary = self.primary_temp.get(pg, -1)
        if temp_primary == -1 and temp_pg:
            temp_primary = self._pick_primary(temp_pg)
        return temp_pg, temp_primary

    def pg_to_up_acting_osds(
        self, pool_id: int, ps: int
    ) -> tuple[list[int], int, list[int], int]:
        """(up, up_primary, acting, acting_primary) — OSDMap.cc:2668."""
        pool = self.pools.get(pool_id)
        if pool is None or ps >= pool.pg_num:
            return [], -1, [], -1
        acting, acting_primary = self._get_temp_osds(pool, ps)
        raw, pps = self._pg_to_raw_osds(pool, ps)
        raw = self._apply_upmap(pool, ps, raw)
        up = self._raw_to_up_osds(pool, raw)
        up_primary = self._pick_primary(up)
        up, up_primary = self._apply_primary_affinity(
            pps, pool, up, up_primary
        )
        if not acting:
            acting = list(up)
            if acting_primary == -1:
                acting_primary = up_primary
        return up, up_primary, acting, acting_primary

    # -- incrementals ------------------------------------------------------
    def new_incremental(self) -> "Incremental":
        """Start a diff producing epoch+1 (OSDMonitor pending_inc role)."""
        return Incremental(epoch=self.epoch + 1)

    def apply_incremental(self, inc: "Incremental") -> None:
        """OSDMap::apply_incremental (OSDMap.cc:2062), field for field in
        the reference's order; asserts the epoch chain is contiguous."""
        if inc.epoch != self.epoch + 1:
            raise ValueError(
                f"incremental epoch {inc.epoch} != map epoch "
                f"{self.epoch} + 1"
            )
        # validate BEFORE mutating anything: a bad osd id must not
        # leave a half-applied map at a phantom epoch
        effective_max = (
            inc.new_max_osd if inc.new_max_osd >= 0 else self.max_osd
        )
        for field_name in (
            "new_weight",
            "new_state",
            "new_primary_affinity",
            "new_up_client",
        ):
            for osd in getattr(inc, field_name):
                if not 0 <= osd < effective_max:
                    raise ValueError(
                        f"{field_name} osd.{osd} out of range "
                        f"[0, {effective_max})"
                    )
        self.epoch += 1

        if inc.fullmap is not None:
            full = OSDMap.decode(inc.fullmap)
            if full.epoch != self.epoch:
                raise ValueError("fullmap epoch mismatch")
            self.__dict__.update(full.__dict__)
            return
        if inc.crush is not None:
            self.crush = (
                decode_crush_map(inc.crush)
                if isinstance(inc.crush, bytes)
                else inc.crush
            )

        if inc.new_flags >= 0:
            self.flags = inc.new_flags
        if inc.new_max_osd >= 0:
            self.set_max_osd(inc.new_max_osd)
        if inc.new_pool_max != -1:
            self.pool_max = inc.new_pool_max
        for pool_id, pool in inc.new_pools.items():
            self.pools[pool_id] = pool
            pool.last_change = self.epoch
            self.pool_max = max(self.pool_max, pool_id)
        for pool_id, name in inc.new_pool_names.items():
            self.pool_names[pool_id] = name
        for pool_id in inc.old_pools:
            self.pools.pop(pool_id, None)
            self.pool_names.pop(pool_id, None)

        for osd, w in inc.new_weight.items():
            self.osd_weight[osd] = w
            if w:
                # marking in clears AUTOOUT/NEW (OSDMap.cc:2153-2159)
                self.osd_flags[osd] &= ~(CEPH_OSD_AUTOOUT | CEPH_OSD_NEW)
        for osd, aff in inc.new_primary_affinity.items():
            self.set_primary_affinity(osd, aff)

        for name in inc.old_erasure_code_profiles:
            self.erasure_code_profiles.pop(name, None)
        for name, profile in inc.new_erasure_code_profiles.items():
            self.erasure_code_profiles[name] = dict(profile)

        # up/down: XOR with the destroy special-case (OSDMap.cc:2177-2201)
        for osd, st in inc.new_state.items():
            s = st if st else CEPH_OSD_UP
            cur = self.get_state(osd)
            if (cur & CEPH_OSD_UP) and (s & CEPH_OSD_UP):
                self.osd_down_at[osd] = self.epoch
            if (cur & CEPH_OSD_EXISTS) and (s & CEPH_OSD_EXISTS):
                # destroyed: clear out anything interesting
                if self.osd_primary_affinity is not None:
                    self.osd_primary_affinity[osd] = (
                        CEPH_OSD_DEFAULT_PRIMARY_AFFINITY
                    )
                self.osd_addrs.pop(osd, None)
                self.osd_down_at[osd] = 0
                self.osd_up_from[osd] = 0
                self._set_state(osd, 0)
            else:
                self._set_state(osd, cur ^ s)
        for osd, addr in inc.new_up_client.items():
            cur = self.get_state(osd)
            self._set_state(osd, cur | CEPH_OSD_EXISTS | CEPH_OSD_UP)
            self.osd_addrs[osd] = addr
            self.osd_up_from[osd] = self.epoch

        for pg, osds in inc.new_pg_temp.items():
            if not osds:
                self.pg_temp.pop(pg, None)
            else:
                self.pg_temp[pg] = list(osds)
        for pg, primary in inc.new_primary_temp.items():
            if primary == -1:
                self.primary_temp.pop(pg, None)
            else:
                self.primary_temp[pg] = primary
        for pg, osds in inc.new_pg_upmap.items():
            self.pg_upmap[pg] = list(osds)
        for pg in inc.old_pg_upmap:
            self.pg_upmap.pop(pg, None)
        for pg, items in inc.new_pg_upmap_items.items():
            self.pg_upmap_items[pg] = list(items)
        for pg in inc.old_pg_upmap_items:
            self.pg_upmap_items.pop(pg, None)

        for addr, until in inc.new_blocklist.items():
            self.blocklist[addr] = until
        for addr in inc.old_blocklist:
            self.blocklist.pop(addr, None)

    # -- wire --------------------------------------------------------------
    def encode(self) -> bytes:
        e = Encoder()
        e.u32(self.epoch)
        e.s32(self.max_osd)
        e.s64(self.pool_max)
        e.u32(self.flags)
        e.bytes(encode_crush_map(self.crush))
        e.map(self.pools, lambda e2, k: e2.s64(k), _enc_pool)
        e.map(
            self.pool_names,
            lambda e2, k: e2.s64(k),
            lambda e2, v: e2.string(v),
        )
        e.map(
            self.erasure_code_profiles,
            lambda e2, k: e2.string(k),
            lambda e2, v: e2.map(
                v, lambda e3, k2: e3.string(k2), lambda e3, v2: e3.string(v2)
            ),
        )
        e.list(self.osd_exists, lambda e2, v: e2.bool(v))
        e.list(self.osd_up, lambda e2, v: e2.bool(v))
        e.list(self.osd_weight, lambda e2, v: e2.u64(v))
        e.list(self.osd_flags, lambda e2, v: e2.u32(v))
        e.list(self.osd_down_at, lambda e2, v: e2.u32(v))
        e.list(self.osd_up_from, lambda e2, v: e2.u32(v))
        if self.osd_primary_affinity is None:
            e.bool(False)
        else:
            e.bool(True)
            e.list(self.osd_primary_affinity, lambda e2, v: e2.u64(v))
        e.map(
            self.osd_addrs, lambda e2, k: e2.s32(k),
            lambda e2, v: e2.string(v),
        )
        _enc_pgmap(e, self.pg_temp, _enc_osd_list)
        _enc_pgmap(e, self.primary_temp, lambda e2, v: e2.s32(v))
        _enc_pgmap(e, self.pg_upmap, _enc_osd_list)
        _enc_pgmap(e, self.pg_upmap_items, _enc_pairs)
        e.map(
            self.blocklist, lambda e2, k: e2.string(k),
            lambda e2, v: e2.f64(v),
        )
        body = encode_versioned(1, 1, e.getvalue())
        return body + ceph_crc32c(0, body).to_bytes(4, "little")

    @classmethod
    def decode(cls, data: bytes) -> "OSDMap":
        if len(data) < 4:
            raise DecodeError("osdmap blob too short")
        body, crc = data[:-4], int.from_bytes(data[-4:], "little")
        if ceph_crc32c(0, body) != crc:
            raise DecodeError("osdmap crc mismatch")
        _version, d = decode_versioned(Decoder(body), 1)
        m = cls(crush=None)  # placeholder, replaced below
        m.epoch = d.u32()
        m.max_osd = d.s32()
        m.pool_max = d.s64()
        m.flags = d.u32()
        m.crush = decode_crush_map(d.bytes())
        m.pools = d.map(lambda d2: d2.s64(), _dec_pool)
        m.pool_names = d.map(lambda d2: d2.s64(), lambda d2: d2.string())
        m.erasure_code_profiles = d.map(
            lambda d2: d2.string(),
            lambda d2: d2.map(
                lambda d3: d3.string(), lambda d3: d3.string()
            ),
        )
        m.osd_exists = d.list(lambda d2: d2.bool())
        m.osd_up = d.list(lambda d2: d2.bool())
        m.osd_weight = d.list(lambda d2: d2.u64())
        m.osd_flags = d.list(lambda d2: d2.u32())
        m.osd_down_at = d.list(lambda d2: d2.u32())
        m.osd_up_from = d.list(lambda d2: d2.u32())
        m.osd_primary_affinity = (
            d.list(lambda d2: d2.u64()) if d.bool() else None
        )
        m.osd_addrs = d.map(lambda d2: d2.s32(), lambda d2: d2.string())
        m.pg_temp = _dec_pgmap(d, _dec_osd_list)
        m.primary_temp = _dec_pgmap(d, lambda d2: d2.s32())
        m.pg_upmap = _dec_pgmap(d, _dec_osd_list)
        m.pg_upmap_items = _dec_pgmap(d, _dec_pairs)
        m.blocklist = d.map(lambda d2: d2.string(), lambda d2: d2.f64())
        return m


@dataclass
class Incremental:
    """A diff from epoch-1 to epoch (OSDMap.h:354 class Incremental;
    the subset of its ~40 fields this framework models — addr vectors
    collapse to one string, info/xinfo to down_at/up_from epochs)."""

    epoch: int
    new_flags: int = -1
    new_max_osd: int = -1
    new_pool_max: int = -1
    fullmap: bytes | None = None
    crush: bytes | CrushMap | None = None
    new_pools: dict[int, PgPool] = field(default_factory=dict)
    new_pool_names: dict[int, str] = field(default_factory=dict)
    old_pools: set[int] = field(default_factory=set)
    new_erasure_code_profiles: dict[str, dict[str, str]] = field(
        default_factory=dict
    )
    old_erasure_code_profiles: list[str] = field(default_factory=list)
    new_up_client: dict[int, str] = field(default_factory=dict)
    new_state: dict[int, int] = field(default_factory=dict)  # XORed
    new_weight: dict[int, int] = field(default_factory=dict)
    new_primary_affinity: dict[int, int] = field(default_factory=dict)
    new_pg_temp: dict[tuple[int, int], list[int]] = field(
        default_factory=dict
    )
    new_primary_temp: dict[tuple[int, int], int] = field(
        default_factory=dict
    )
    new_pg_upmap: dict[tuple[int, int], list[int]] = field(
        default_factory=dict
    )
    old_pg_upmap: set[tuple[int, int]] = field(default_factory=set)
    new_pg_upmap_items: dict[
        tuple[int, int], list[tuple[int, int]]
    ] = field(default_factory=dict)
    old_pg_upmap_items: set[tuple[int, int]] = field(default_factory=set)
    new_blocklist: dict[str, float] = field(default_factory=dict)
    old_blocklist: list[str] = field(default_factory=list)

    # -- OSDMonitor-style convenience mutators -----------------------------
    def mark_down(self, osd: int) -> None:
        """Queue an up→down flip (prepare_failure outcome): XOR of UP."""
        self.new_state[osd] = self.new_state.get(osd, 0) | CEPH_OSD_UP

    def mark_up(self, osd: int, addr: str = "") -> None:
        self.new_up_client[osd] = addr

    def mark_out(self, osd: int) -> None:
        self.new_weight[osd] = 0

    def mark_in(self, osd: int, weight: int = 0x10000) -> None:
        self.new_weight[osd] = weight

    def destroy(self, osd: int) -> None:
        self.new_state[osd] = CEPH_OSD_EXISTS

    # -- wire --------------------------------------------------------------
    def encode(self) -> bytes:
        e = Encoder()
        e.u32(self.epoch)
        e.s32(self.new_flags)
        e.s32(self.new_max_osd)
        e.s64(self.new_pool_max)
        for blob in (self.fullmap, _crush_blob(self.crush)):
            if blob is None:
                e.bool(False)
            else:
                e.bool(True)
                e.bytes(blob)
        e.map(self.new_pools, lambda e2, k: e2.s64(k), _enc_pool)
        e.map(
            self.new_pool_names, lambda e2, k: e2.s64(k),
            lambda e2, v: e2.string(v),
        )
        e.list(sorted(self.old_pools), lambda e2, v: e2.s64(v))
        e.map(
            self.new_erasure_code_profiles,
            lambda e2, k: e2.string(k),
            lambda e2, v: e2.map(
                v, lambda e3, k2: e3.string(k2), lambda e3, v2: e3.string(v2)
            ),
        )
        e.list(
            sorted(self.old_erasure_code_profiles),
            lambda e2, v: e2.string(v),
        )
        e.map(
            self.new_up_client, lambda e2, k: e2.s32(k),
            lambda e2, v: e2.string(v),
        )
        e.map(self.new_state, lambda e2, k: e2.s32(k), lambda e2, v: e2.u32(v))
        e.map(self.new_weight, lambda e2, k: e2.s32(k), lambda e2, v: e2.u64(v))
        e.map(
            self.new_primary_affinity, lambda e2, k: e2.s32(k),
            lambda e2, v: e2.u64(v),
        )
        _enc_pgmap(e, self.new_pg_temp, _enc_osd_list)
        _enc_pgmap(e, self.new_primary_temp, lambda e2, v: e2.s32(v))
        _enc_pgmap(e, self.new_pg_upmap, _enc_osd_list)
        e.list(sorted(self.old_pg_upmap), _enc_pg)
        _enc_pgmap(e, self.new_pg_upmap_items, _enc_pairs)
        e.list(sorted(self.old_pg_upmap_items), _enc_pg)
        e.map(
            self.new_blocklist, lambda e2, k: e2.string(k),
            lambda e2, v: e2.f64(v),
        )
        e.list(sorted(self.old_blocklist), lambda e2, v: e2.string(v))
        body = encode_versioned(1, 1, e.getvalue())
        return body + ceph_crc32c(0, body).to_bytes(4, "little")

    @classmethod
    def decode(cls, data: bytes) -> "Incremental":
        if len(data) < 4:
            raise DecodeError("incremental blob too short")
        body, crc = data[:-4], int.from_bytes(data[-4:], "little")
        if ceph_crc32c(0, body) != crc:
            raise DecodeError("incremental crc mismatch")
        _version, d = decode_versioned(Decoder(body), 1)
        inc = cls(epoch=d.u32())
        inc.new_flags = d.s32()
        inc.new_max_osd = d.s32()
        inc.new_pool_max = d.s64()
        inc.fullmap = d.bytes() if d.bool() else None
        inc.crush = d.bytes() if d.bool() else None
        inc.new_pools = d.map(lambda d2: d2.s64(), _dec_pool)
        inc.new_pool_names = d.map(
            lambda d2: d2.s64(), lambda d2: d2.string()
        )
        inc.old_pools = set(d.list(lambda d2: d2.s64()))
        inc.new_erasure_code_profiles = d.map(
            lambda d2: d2.string(),
            lambda d2: d2.map(
                lambda d3: d3.string(), lambda d3: d3.string()
            ),
        )
        inc.old_erasure_code_profiles = d.list(lambda d2: d2.string())
        inc.new_up_client = d.map(
            lambda d2: d2.s32(), lambda d2: d2.string()
        )
        inc.new_state = d.map(lambda d2: d2.s32(), lambda d2: d2.u32())
        inc.new_weight = d.map(lambda d2: d2.s32(), lambda d2: d2.u64())
        inc.new_primary_affinity = d.map(
            lambda d2: d2.s32(), lambda d2: d2.u64()
        )
        inc.new_pg_temp = _dec_pgmap(d, _dec_osd_list)
        inc.new_primary_temp = _dec_pgmap(d, lambda d2: d2.s32())
        inc.new_pg_upmap = _dec_pgmap(d, _dec_osd_list)
        inc.old_pg_upmap = set(d.list(_dec_pg))
        inc.new_pg_upmap_items = _dec_pgmap(d, _dec_pairs)
        inc.old_pg_upmap_items = set(d.list(_dec_pg))
        inc.new_blocklist = d.map(
            lambda d2: d2.string(), lambda d2: d2.f64()
        )
        inc.old_blocklist = d.list(lambda d2: d2.string())
        return inc


# -- encode helpers --------------------------------------------------------


def _crush_blob(crush) -> bytes | None:
    if crush is None:
        return None
    return crush if isinstance(crush, bytes) else encode_crush_map(crush)


def _enc_pool(e: Encoder, p: PgPool) -> None:
    e.s64(p.pool_id).u8(p.type).u32(p.size).u32(p.min_size)
    e.u32(p.pg_num).u32(p.pgp_num).u32(p.crush_rule)
    e.string(p.erasure_code_profile).bool(p.hashpspool)
    e.u32(p.last_change)
    e.u64(p.snap_seq)
    e.map(
        p.snaps,
        lambda e2, k: e2.u64(k),
        lambda e2, v: e2.string(v),
    )
    e.s64(p.tier_of).s64(p.read_tier).s64(p.write_tier)
    e.string(p.cache_mode).u64(p.target_max_objects)


def _dec_pool(d: Decoder) -> PgPool:
    return PgPool(
        pool_id=d.s64(),
        type=d.u8(),
        size=d.u32(),
        min_size=d.u32(),
        pg_num=d.u32(),
        pgp_num=d.u32(),
        crush_rule=d.u32(),
        erasure_code_profile=d.string(),
        hashpspool=d.bool(),
        last_change=d.u32(),
        snap_seq=d.u64(),
        snaps=d.map(lambda d2: d2.u64(), lambda d2: d2.string()),
        tier_of=d.s64(),
        read_tier=d.s64(),
        write_tier=d.s64(),
        cache_mode=d.string(),
        target_max_objects=d.u64(),
    )


def _enc_pg(e: Encoder, pg: tuple[int, int]) -> None:
    e.s64(pg[0]).u32(pg[1])


def _dec_pg(d: Decoder) -> tuple[int, int]:
    return (d.s64(), d.u32())


def _enc_osd_list(e: Encoder, osds: list[int]) -> None:
    e.list(osds, lambda e2, o: e2.s32(o))


def _dec_osd_list(d: Decoder) -> list[int]:
    return d.list(lambda d2: d2.s32())


def _enc_pairs(e: Encoder, pairs: list[tuple[int, int]]) -> None:
    e.list(pairs, lambda e2, p: e2.s32(p[0]).s32(p[1]))


def _dec_pairs(d: Decoder) -> list[tuple[int, int]]:
    return d.list(lambda d2: (d2.s32(), d2.s32()))


def _enc_pgmap(e: Encoder, m: dict, val_fn) -> None:
    e.u32(len(m))
    for pg in sorted(m):
        _enc_pg(e, pg)
        val_fn(e, m[pg])


def _dec_pgmap(d: Decoder, val_fn) -> dict:
    return {_dec_pg(d): val_fn(d) for _ in range(d.u32())}
