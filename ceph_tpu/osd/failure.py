"""Failure detection — heartbeats + failure reports
(OSD::handle_osd_ping / send_failures, src/osd/OSD.cc:5235,5889, and
OSDMonitor::prepare_failure's reporter-count gate).

Each OSD pings its heartbeat peers; a peer silent past the grace
period generates a failure report, and the monitor-side aggregator
marks an OSD down once enough DISTINCT reporters agree — then the map
epoch bumps and the batched mapper recomputes placements (elasticity
is CRUSH remap, SURVEY.md §5.3)."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..common.log import dout
from .osdmap import OSDMap

OSD_HEARTBEAT_GRACE = 20.0  # osd_heartbeat_grace
MON_OSD_MIN_DOWN_REPORTERS = 2  # mon_osd_min_down_reporters


class HeartbeatTracker:
    """One OSD's view of its peers (the 4-messenger ping plane,
    collapsed to timestamps)."""

    def __init__(self, whoami: int, grace: float = OSD_HEARTBEAT_GRACE):
        self.whoami = whoami
        self.grace = grace
        self._last_rx: dict[int, float] = {}

    def peers(self) -> set[int]:
        return set(self._last_rx)

    def add_peer(self, osd: int, now: float) -> None:
        self._last_rx.setdefault(osd, now)

    def remove_peer(self, osd: int) -> None:
        self._last_rx.pop(osd, None)

    def handle_ping(self, from_osd: int, now: float) -> None:
        if from_osd in self._last_rx:
            self._last_rx[from_osd] = now

    def failures(self, now: float) -> list[tuple[int, float]]:
        """(peer, seconds_silent) past grace — the send_failures
        payload."""
        out = []
        for osd, last in self._last_rx.items():
            silent = now - last
            if silent >= self.grace:
                out.append((osd, silent))
        return out

    def reset(self, now: float) -> None:
        """Thrash-heal hook: forgive all accumulated silence (every
        peer counts as freshly heard).  After a partition heals, the
        stale rx stamps would otherwise keep reporting peers that are
        in fact fine until a ping happens to land."""
        for osd in self._last_rx:
            self._last_rx[osd] = now


@dataclass
class _Pending:
    reporters: set[int] = field(default_factory=set)


class FailureAggregator:
    """Monitor-side reporter-count gate
    (OSDMonitor::prepare_failure/check_failure, simplified to the
    distinct-reporter threshold)."""

    def __init__(
        self,
        osdmap: OSDMap,
        min_reporters=MON_OSD_MIN_DOWN_REPORTERS,
        mark_down_fn=None,
    ):
        """``mark_down_fn(target)`` commits the down marking; the
        default mutates the map in place with a bare epoch bump (test
        convenience).  The monitor passes its own committer so the
        marking becomes a real Incremental pushed to subscribers
        (mon/monitor.py).

        ``min_reporters`` may be an int or a zero-arg callable — the
        monitor passes a callable reading its centralized config
        (mon_osd_min_down_reporters), so `ceph config set mon
        mon_osd_min_down_reporters N` takes effect at runtime."""
        self.osdmap = osdmap
        self.min_reporters = min_reporters
        self.mark_down_fn = mark_down_fn
        self._pending: dict[int, _Pending] = {}

    def _threshold(self) -> int:
        mr = self.min_reporters
        return max(1, int(mr() if callable(mr) else mr))

    def report_failure(
        self, target: int, reporter: int, now: float
    ) -> bool:
        """Returns True when the report tipped ``target`` down."""
        if not self.osdmap.is_up(target):
            # target already down through some other path: drop any
            # stale pending entry so it cannot pre-count a future
            # down marking
            self._pending.pop(target, None)
            return False
        if not self.osdmap.is_up(reporter):
            return False  # dead reporters don't count
        p = self._pending.setdefault(target, _Pending())
        p.reporters.add(reporter)
        # reporters that died since reporting no longer count
        p.reporters = {
            r for r in p.reporters if self.osdmap.is_up(r)
        }
        threshold = self._threshold()
        dout(
            "osd",
            5,
            f"failure report: osd.{target} by osd.{reporter} "
            f"({len(p.reporters)}/{threshold})",
        )
        if len(p.reporters) >= threshold:
            self._mark_down(target)
            return True
        return False

    def cancel_report(self, target: int, reporter: int) -> None:
        """The MOSDFailure recovery path: a reporter hearing the target
        again withdraws its report."""
        p = self._pending.get(target)
        if p:
            p.reporters.discard(reporter)
            if not p.reporters:
                del self._pending[target]

    def _mark_down(self, target: int) -> None:
        if self.mark_down_fn is not None:
            self.mark_down_fn(target)
        else:
            # stand-alone mode: mutate in place (a real deployment
            # routes through the monitor's incremental commit)
            self.osdmap.mark_down(target)
            self.osdmap.epoch += 1
        self._pending.pop(target, None)
        dout("osd", 0, f"osd.{target} marked down, epoch -> {self.osdmap.epoch}")

    def pending_reports(self) -> dict[int, int]:
        return {t: len(p.reporters) for t, p in self._pending.items()}

    def reset(self) -> None:
        """Thrash-heal hook: drop every half-counted report.  A
        healed partition leaves reporter sets one short of threshold;
        an unrelated later report must not tip a healthy OSD down on
        those stale counts."""
        self._pending.clear()
