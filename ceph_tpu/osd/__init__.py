"""OSD cluster-map layer: pools, OSD state, and PG→OSD mapping.

The reference's OSDMap (src/osd/OSDMap.{h,cc}) is an epoch-versioned
cluster map whose hot path is ``pg_to_up_acting_osds`` — re-rendered
here as a scalar oracle (``osdmap``) plus a batched device pipeline
(``mapping``) that recomputes every PG of every pool in one call per
pool (the OSDMapMapping/ParallelPGMapper replacement,
src/osd/OSDMapMapping.h:18-156).
"""

from .osdmap import Incremental, OSDMap, PgPool
from .mapping import OSDMapMapping

__all__ = ["Incremental", "OSDMap", "OSDMapMapping", "PgPool"]
