"""PG log — per-PG ordered op journal and log-based recovery math
(src/osd/PGLog.{h,cc}, src/osd/osd_types.h pg_log_entry_t).

Every client op on a PG appends one entry (MODIFY or DELETE of an
object at an eversion).  Peering compares logs: the authoritative log
is chosen by greatest ``last_epoch_started`` then newest
``last_update`` (find_best_info), and a
peer's missing set is exactly the objects named by authoritative
entries newer than that peer's ``last_update`` (proc_replica_log /
PGLog::merge_log's missing accumulation).  A peer whose last_update
predates the authoritative ``log_tail`` cannot catch up by log and
needs backfill (a full object copy walk).

eversion = (epoch, version): epoch of the map the primary ruled
under, monotone op counter — ordered lexicographically, exactly
eversion_t (osd_types.h:633).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..common.encoding import Decoder, Encoder

EV_ZERO = (0, 0)

MODIFY = 1  # pg_log_entry_t::MODIFY
DELETE = 2  # pg_log_entry_t::DELETE


@dataclass
class LogEntry:
    op: int
    oid: str
    version: tuple[int, int]
    prior_version: tuple[int, int] = EV_ZERO
    # client request id (osd_reqid_t role): lets the primary detect a
    # retried op and ack it without re-applying (append idempotency)
    reqid: str = ""

    def encode(self, e: Encoder) -> None:
        e.u8(self.op).string(self.oid)
        e.u32(self.version[0]).u64(self.version[1])
        e.u32(self.prior_version[0]).u64(self.prior_version[1])
        e.string(self.reqid)

    @classmethod
    def decode(cls, d: Decoder) -> "LogEntry":
        return cls(
            op=d.u8(),
            oid=d.string(),
            version=(d.u32(), d.u64()),
            prior_version=(d.u32(), d.u64()),
            reqid=d.string(),
        )


@dataclass
class PGInfo:
    """pg_info_t subset driving peering (osd_types.h:3348)."""

    pgid: str = ""
    last_update: tuple[int, int] = EV_ZERO
    log_tail: tuple[int, int] = EV_ZERO
    last_epoch_started: int = 0

    def encode(self, e: Encoder) -> None:
        e.string(self.pgid)
        e.u32(self.last_update[0]).u64(self.last_update[1])
        e.u32(self.log_tail[0]).u64(self.log_tail[1])
        e.u32(self.last_epoch_started)

    @classmethod
    def decode(cls, d: Decoder) -> "PGInfo":
        return cls(
            pgid=d.string(),
            last_update=(d.u32(), d.u64()),
            log_tail=(d.u32(), d.u64()),
            last_epoch_started=d.u32(),
        )


class PGLog:
    """Bounded in-order entry list: append, trim, and the recovery
    queries peering needs."""

    def __init__(self, entries: list[LogEntry] | None = None):
        self.entries: list[LogEntry] = list(entries or [])
        self.log_tail: tuple[int, int] = EV_ZERO

    @property
    def head(self) -> tuple[int, int]:
        return self.entries[-1].version if self.entries else self.log_tail

    def append(self, entry: LogEntry) -> None:
        assert entry.version > self.head, (entry.version, self.head)
        self.entries.append(entry)

    def trim(self, keep: int) -> None:
        """Drop the oldest entries, advancing log_tail (PGLog::trim)."""
        if len(self.entries) > keep:
            cut = self.entries[: len(self.entries) - keep]
            self.log_tail = cut[-1].version
            self.entries = self.entries[len(cut) :]

    def entries_after(self, version: tuple[int, int]) -> list[LogEntry]:
        """Entries strictly newer than ``version``; valid only when
        version >= log_tail (else the caller needs backfill)."""
        assert version >= self.log_tail, (version, self.log_tail)
        return [e for e in self.entries if e.version > version]

    def missing_since(
        self, version: tuple[int, int]
    ) -> dict[str, tuple[int, int]]:
        """oid → newest needed version for a peer at ``version``
        (the missing-set accumulation of proc_replica_log): DELETEs
        supersede older modifies of the same object."""
        missing: dict[str, tuple[int, int]] = {}
        for entry in self.entries_after(version):
            # newest op wins — DELETEs are pushed too (the peer must
            # apply the removal)
            missing[entry.oid] = entry.version
        return missing

    def truncate_after(self, version: tuple[int, int]) -> list[LogEntry]:
        """Drop entries strictly newer than ``version`` (the divergent
        rewind of PGLog::rewind_divergent_log); returns them newest
        first, the order rollback wants."""
        removed = [e for e in self.entries if e.version > version]
        self.entries = [e for e in self.entries if e.version <= version]
        return list(reversed(removed))

    def object_op(self, oid: str) -> LogEntry | None:
        """Newest entry for an object, if still in the log."""
        for entry in reversed(self.entries):
            if entry.oid == oid:
                return entry
        return None


def find_best_info(infos: dict[int, PGInfo]) -> int | None:
    """Authoritative peer choice (PeeringState::find_best_info):
    greatest last_epoch_started first (a peer from a stale interval
    must never win on a higher last_update alone), then newest
    last_update, then longest log (smallest tail), then lowest osd id
    for determinism.  None when no peer has any history."""
    best = None
    for osd, info in sorted(infos.items()):
        if info.last_update == EV_ZERO and info.last_epoch_started == 0:
            continue
        if best is None:
            best = osd
            continue
        cur = infos[best]
        key = (info.last_epoch_started, info.last_update)
        cur_key = (cur.last_epoch_started, cur.last_update)
        if key > cur_key:
            best = osd
        elif key == cur_key and info.log_tail < cur.log_tail:
            best = osd
    return best


def needs_backfill(auth: PGInfo, peer: PGInfo) -> bool:
    """A peer older than the authoritative log tail cannot recover by
    log (PeeringState::choose_acting's backfill split)."""
    return peer.last_update < auth.log_tail
