"""cephx-analog ticket protocol (src/auth/cephx/CephxProtocol.{h,cc},
src/auth/Crypto.cc, src/auth/AuthRegistry.cc).

The reference's kerberos-like flow, kept whole but rendered on
stdlib crypto:

1. Entities share secrets with the auth authority via a keyring
   (``Keyring`` — the /etc/ceph/keyring role).
2. A client authenticates to the authority
   (``CephxServiceHandler.issue_ticket``): it receives a fresh
   SESSION KEY encrypted under its own secret, plus an opaque TICKET
   — {entity, session key, expiry} encrypted under the service's
   ROTATING secret (CephxTicketBlob).  The client cannot read or
   forge the ticket.
3. To open a connection the client builds an AUTHORIZER
   (``CephxClientHandler.build_authorizer``): the ticket plus an
   HMAC proof over a nonce using the session key.
4. The service (``CephxServiceHandler.verify_authorizer``) decrypts
   the ticket with its rotating secret, recovers the session key,
   verifies the proof and the expiry, and answers its own proof so
   the client can authenticate the SERVER too (mutual auth,
   CephxProtocol.cc's authorizer challenge).

Crypto: the reference uses AES-CBC via nss/openssl; the stdlib has
none, so encryption here is a SHA-256 counter-mode keystream XOR with
an encrypt-then-MAC HMAC-SHA256 tag — authenticated encryption built
from hashlib/hmac primitives only.  The protocol shape (tickets,
rotating service keys, session-key proofs) is the parity surface, not
the cipher choice.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import time
from dataclasses import dataclass, field

from ..common.encoding import Decoder, Encoder

TICKET_TTL = 3600.0  # auth_service_ticket_ttl default role


class AuthError(Exception):
    pass


class CryptoKey:
    """Symmetric key + the framework's authenticated encryption."""

    def __init__(self, secret: bytes | None = None):
        self.secret = secret if secret is not None else os.urandom(32)

    # -- sha256-ctr keystream + encrypt-then-mac ---------------------------
    def keystream(self, nonce: bytes, n: int) -> bytes:
        """CTR keystream for ``nonce`` — the one cipher primitive,
        shared by ticket sealing (random nonces) and the messenger's
        secure wire mode (per-connection counters)."""
        out = bytearray()
        counter = 0
        while len(out) < n:
            out += hashlib.sha256(
                self.secret + nonce + counter.to_bytes(8, "little")
            ).digest()
            counter += 1
        return bytes(out[:n])

    _keystream = keystream

    @staticmethod
    def xor(data: bytes, ks: bytes) -> bytes:
        """Whole-buffer XOR via big-int ops (the byte-loop would cost
        O(n) interpreter time per message on the wire hot path)."""
        n = len(data)
        return (
            int.from_bytes(data, "little")
            ^ int.from_bytes(ks[:n], "little")
        ).to_bytes(n, "little")

    def encrypt(self, plain: bytes) -> bytes:
        nonce = os.urandom(16)
        ct = self.xor(plain, self.keystream(nonce, len(plain)))
        tag = hmac.new(self.secret, nonce + ct, hashlib.sha256).digest()
        return nonce + ct + tag

    def decrypt(self, blob: bytes) -> bytes:
        if len(blob) < 48:
            raise AuthError("ciphertext too short")
        nonce, ct, tag = blob[:16], blob[16:-32], blob[-32:]
        want = hmac.new(self.secret, nonce + ct, hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise AuthError("ciphertext authentication failed")
        return self.xor(ct, self.keystream(nonce, len(ct)))

    def hmac(self, data: bytes) -> bytes:
        return hmac.new(self.secret, data, hashlib.sha256).digest()


class Keyring:
    """entity name → secret (the keyring file / AuthMonitor database)."""

    def __init__(self):
        self._keys: dict[str, CryptoKey] = {}

    def add(self, entity: str, key: CryptoKey | None = None) -> CryptoKey:
        key = key or CryptoKey()
        self._keys[entity] = key
        return key

    def get(self, entity: str) -> CryptoKey:
        key = self._keys.get(entity)
        if key is None:
            raise AuthError(f"entity {entity!r} has no key (-EACCES)")
        return key

    def entities(self) -> list[str]:
        return sorted(self._keys)


@dataclass
class Ticket:
    """Decrypted ticket contents (CephxServiceTicketInfo)."""

    entity: str
    session_key: bytes
    expires: float

    def encode(self) -> bytes:
        e = Encoder()
        e.string(self.entity).bytes(self.session_key).f64(self.expires)
        return e.getvalue()

    @classmethod
    def decode(cls, blob: bytes) -> "Ticket":
        d = Decoder(blob)
        return cls(
            entity=d.string(), session_key=d.bytes(), expires=d.f64()
        )


@dataclass
class TicketGrant:
    """What the authority hands the client (CephxResponse): the
    session key sealed under the CLIENT key, the ticket sealed under
    the SERVICE rotating key."""

    sealed_session: bytes
    ticket_blob: bytes

    def encode(self) -> bytes:
        e = Encoder()
        e.bytes(self.sealed_session).bytes(self.ticket_blob)
        return e.getvalue()

    @classmethod
    def decode(cls, blob: bytes) -> "TicketGrant":
        d = Decoder(blob)
        return cls(sealed_session=d.bytes(), ticket_blob=d.bytes())


class CephxServiceHandler:
    """Authority + service side: issues and verifies tickets.

    The monitor holds the keyring AND the rotating service secret (in
    the reference rotating secrets are pushed to OSDs by the monitor;
    here every service handler is constructed with the same rotating
    key object, the KeyServer role)."""

    def __init__(self, keyring: Keyring, rotating: CryptoKey | None = None):
        self.keyring = keyring
        self.rotating = rotating or CryptoKey()

    # -- authority ---------------------------------------------------------
    def issue_ticket(
        self, entity: str, ttl: float = TICKET_TTL
    ) -> bytes:
        """Encoded TicketGrant for an entity in the keyring; raises
        AuthError for unknown entities."""
        client_key = self.keyring.get(entity)
        session = os.urandom(32)
        ticket = Ticket(
            entity=entity,
            session_key=session,
            expires=time.time() + ttl,
        )
        return TicketGrant(
            sealed_session=client_key.encrypt(session),
            ticket_blob=self.rotating.encrypt(ticket.encode()),
        ).encode()

    # -- service -----------------------------------------------------------
    def make_challenge(self) -> bytes:
        """Fresh per-connection server challenge (the CEPHX_V2
        anti-replay challenge): the client's proof must cover it, so a
        captured authorizer cannot be replayed on a new connection."""
        return os.urandom(16)

    def verify_authorizer(
        self, authorizer_blob: bytes, challenge: bytes
    ) -> tuple[str, bytes, bytes]:
        """Check a client authorizer against THIS connection's
        challenge: decrypt the ticket with the rotating key, verify
        expiry and the session-key proof.  Returns
        (entity, server_proof, session_key) — the proof lets the
        client authenticate the server back; the session key keys the
        secure (AEAD) wire mode."""
        d = Decoder(authorizer_blob)
        ticket_blob = d.bytes()
        nonce = d.bytes()
        proof = d.bytes()
        ticket = Ticket.decode(self.rotating.decrypt(ticket_blob))
        if ticket.expires < time.time():
            raise AuthError(f"ticket for {ticket.entity!r} expired")
        session = CryptoKey(ticket.session_key)
        want = session.hmac(b"authorizer" + challenge + nonce)
        if not hmac.compare_digest(proof, want):
            raise AuthError("bad session-key proof")
        return (
            ticket.entity,
            session.hmac(b"server" + challenge + nonce),
            ticket.session_key,
        )


class CephxClientHandler:
    """Client side: unseal the grant, build authorizers."""

    def __init__(self, entity: str, key: CryptoKey):
        self.entity = entity
        self.key = key
        self.session: CryptoKey | None = None
        self.ticket_blob: bytes = b""

    def handle_response(self, grant_blob: bytes) -> None:
        grant = TicketGrant.decode(grant_blob)
        self.session = CryptoKey(self.key.decrypt(grant.sealed_session))
        self.ticket_blob = grant.ticket_blob

    def build_authorizer(self, challenge: bytes) -> tuple[bytes, bytes]:
        """(authorizer_blob, nonce): ticket + HMAC proof over the
        server's per-connection challenge and a fresh nonce."""
        if self.session is None:
            raise AuthError("no ticket yet (authenticate first)")
        nonce = os.urandom(16)
        e = Encoder()
        e.bytes(self.ticket_blob).bytes(nonce)
        e.bytes(self.session.hmac(b"authorizer" + challenge + nonce))
        return e.getvalue(), nonce

    def verify_server(
        self, challenge: bytes, nonce: bytes, server_proof: bytes
    ) -> None:
        want = self.session.hmac(b"server" + challenge + nonce)
        if not hmac.compare_digest(server_proof, want):
            raise AuthError("server failed mutual authentication")
