"""auth — cephx-analog ticket authentication (src/auth/)."""

from .cephx import (
    AuthError,
    CephxClientHandler,
    CephxServiceHandler,
    CryptoKey,
    Keyring,
    Ticket,
)

__all__ = [
    "AuthError",
    "CephxClientHandler",
    "CephxServiceHandler",
    "CryptoKey",
    "Keyring",
    "Ticket",
]
