"""Erasure-code framework: profiles, plugin registry, code families.

Mirrors the reference's plugin architecture (SURVEY.md §2.1) with the same
split of responsibilities:

- ``interface``  — ``ErasureCode`` base class: chunk sizing, padding,
  chunk remapping, greedy minimum_to_decode (ErasureCodeInterface.h:170,
  ErasureCode.cc semantics).
- ``registry``   — name → plugin factory (ErasureCodePlugin.cc:86), the
  insertion point where TPU-backed plugins register.
- ``jerasure``   — reed_sol_van / reed_sol_r6_op / cauchy_* technique
  family (jerasure-compatible semantics, GF math from ceph_tpu.gf).
- ``isa``        — isa-l compatible RS/Cauchy (w=8) with decode-table cache.
- ``lrc/shec/clay`` — layered codes composing over the base families.

Plugins accept a ``backend`` profile key: ``numpy`` (oracle, default off
device) or ``jax`` (TPU bit-matmul kernels from ceph_tpu.ops).
"""

from . import jerasure as _jerasure  # noqa: F401  (self-registration)
from . import isa as _isa  # noqa: F401
from . import lrc as _lrc  # noqa: F401
from . import shec as _shec  # noqa: F401
from . import clay as _clay  # noqa: F401
from . import example as _example  # noqa: F401
from .interface import ErasureCode, ErasureCodeProfile
from .registry import ErasureCodePluginRegistry, instance as registry_instance

__all__ = [
    "ErasureCode",
    "ErasureCodeProfile",
    "ErasureCodePluginRegistry",
    "registry_instance",
]
