"""isa-l-compatible Reed-Solomon (w=8) code family.

Re-design of src/erasure-code/isa/ErasureCodeIsa.{h,cc}: Vandermonde
(gf_gen_rs_matrix walk) or Cauchy (gf_gen_cauchy1_matrix) coding matrices,
per-chunk 32-byte alignment (EC_ISA_ADDRESS_ALIGNMENT, xor_op.h:28), and a
decode-matrix LRU cache keyed by the erasure signature exactly like
ErasureCodeIsaTableCache (ErasureCodeIsa.cc:249,303).  k+m <= 32.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from .. import gf
from ._matrix_ops import matrix_decode
from .backend import get_backend
from .interface import (
    ErasureCode,
    ErasureCodeError,
    ErasureCodeProfile,
    sanity_check_k_m,
    to_int,
    to_string,
)
from .registry import ErasureCodePlugin, register

EC_ISA_ADDRESS_ALIGNMENT = 32


class IsaTableCache:
    """LRU of decode matrices keyed by (k, m, matrixtype, signature).

    The reference caches expanded SIMD lookup tables; the analog here is
    the assembled GF decode rows (and, for the TPU backend, their
    bit-expanded form is cached by XLA compilation)."""

    def __init__(self, capacity: int = 2516):  # reference default pool size
        self._lru: OrderedDict[tuple, tuple] = OrderedDict()
        self.capacity = capacity
        self.hits = 0
        self.misses = 0

    def get(self, key):
        hit = self._lru.get(key)
        if hit is not None:
            self.hits += 1
            self._lru.move_to_end(key)
        else:
            self.misses += 1
        return hit

    def put(self, key, value):
        self._lru[key] = value
        self._lru.move_to_end(key)
        while len(self._lru) > self.capacity:
            self._lru.popitem(last=False)


_table_cache = IsaTableCache()


class ErasureCodeIsa(ErasureCode):
    """matrixtype: reed_sol_van (default) or cauchy."""

    def __init__(self, matrixtype: str = "reed_sol_van"):
        super().__init__()
        self.matrixtype = matrixtype
        self.matrix: np.ndarray | None = None
        self.backend = None

    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse(profile)
        super().init(profile)
        self.prepare()

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.k = to_int("k", profile, 7)
        self.m = to_int("m", profile, 3)
        sanity_check_k_m(self.k, self.m)
        if self.k + self.m > 32:
            raise ErasureCodeError("(k + m) must be <= 32")
        self.backend = get_backend(to_string("backend", profile, "numpy"))

    def prepare(self) -> None:
        if self.matrixtype == "reed_sol_van":
            self.matrix = gf.isa_rs_matrix(self.k, self.m)
        elif self.matrixtype == "cauchy":
            self.matrix = gf.isa_cauchy_matrix(self.k, self.m)
        else:
            raise ErasureCodeError(f"unknown matrixtype {self.matrixtype}")

    def get_chunk_size(self, object_size: int) -> int:
        # ErasureCodeIsa.cc:66-80: ceil(object_size / k) rounded up to 32
        chunk_size = (object_size + self.k - 1) // self.k
        modulo = chunk_size % EC_ISA_ADDRESS_ALIGNMENT
        if modulo:
            chunk_size += EC_ISA_ADDRESS_ALIGNMENT - modulo
        return chunk_size

    def encode_chunks(self, want_to_encode, encoded) -> None:
        data = np.stack(
            [encoded[self.chunk_index(i)] for i in range(self.k)]
        )
        coding = self.backend.matrix_regions(self.matrix, data, 8)
        for i in range(self.m):
            np.copyto(encoded[self.chunk_index(self.k + i)], coding[i])

    def _decode_rows_cached(self, erasures):
        """ErasureCodeIsaTableCache analog: decode rows keyed by the
        erasure signature (ErasureCodeIsa.cc:249,303)."""
        signature = "".join(f"+{i}" for i in erasures)
        key = (self.k, self.m, self.matrixtype, signature)
        cached = _table_cache.get(key)
        if cached is None:
            cached = gf.make_decoding_matrix(
                self.matrix, erasures, self.k, 8
            )
            _table_cache.put(key, cached)
        return cached

    def decode_chunks(self, want_to_read, chunks, decoded) -> None:
        erasures = [
            i
            for i in range(self.k + self.m)
            if self.chunk_index(i) not in chunks
        ]
        if not erasures:
            return
        logical = {
            i: decoded[self.chunk_index(i)] for i in range(self.k + self.m)
        }
        matrix_decode(
            self.backend,
            self.matrix,
            erasures,
            logical,
            self.k,
            8,
            decode_rows_fn=self._decode_rows_cached,
        )


@register("isa")
class ErasureCodePluginIsa(ErasureCodePlugin):
    def make(self, profile: ErasureCodeProfile):
        technique = profile.get("technique", "reed_sol_van")
        if technique not in ("reed_sol_van", "cauchy"):
            raise ErasureCodeError(
                f"technique={technique} must be reed_sol_van or cauchy"
            )
        return ErasureCodeIsa(technique)
