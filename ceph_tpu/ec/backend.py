"""Compute backends for erasure-code region math.

The reference dispatches its GF region kernels to CPU SIMD libraries
(gf-complete / isa-l asm); here the same seam dispatches to either the
numpy oracle or the TPU kernels in ``ceph_tpu.ops`` (registered lazily on
first use of ``backend=jax``).  Both implement:

- ``matrix_regions(matrix, regions, w)``      — GF(2^w) matrix x chunk
  regions (the jerasure_matrix_encode / ec_encode_data contract).
- ``bitmatrix_regions(bm, regions, w, packetsize)`` — GF(2) bitmatrix over
  packet-interleaved regions (the jerasure_bitmatrix_dotprod contract:
  each chunk is blocks of w packets of ``packetsize`` bytes; output packet
  (i) of a block = XOR of input packets (j) where bm[i, j] == 1).
"""

from __future__ import annotations

import numpy as np

from ..gf import matrix_vector_mul_region
from ..layout import fold_stripes, unfold_stripes


def _host_row(r) -> np.ndarray:
    """1-D uint8 view of a survivor payload: DeviceBuf tokens fetch
    host-side, bytes-likes go through frombuffer (ascontiguousarray
    would parse bytes as a scalar literal)."""
    if hasattr(r, "host"):
        r = r.host()
    if isinstance(r, (bytes, bytearray, memoryview)):
        return np.frombuffer(bytes(r), dtype=np.uint8)
    return np.ascontiguousarray(r, dtype=np.uint8).ravel()


class NumpyBackend:
    name = "numpy"

    def matrix_regions(
        self, matrix: np.ndarray, regions: np.ndarray, w: int
    ) -> np.ndarray:
        if w == 8:
            # C region-MAC fast path (native/gf8.c, the
            # jerasure/ISA-L pshufb hot loop): bit-exact with the
            # numpy fallback below; None when no compiler exists
            from ..native import gf8_matrix_regions

            out = gf8_matrix_regions(matrix, regions)
            if out is not None:
                return out
        return matrix_vector_mul_region(matrix, regions, w)

    def matrix_stripes(
        self, matrix: np.ndarray, stripes: np.ndarray, w: int
    ) -> np.ndarray:
        """Batched (B, k, chunk) → (B, m, chunk): stripes fold into the
        region byte dimension (same layout as the jax backend)."""
        stripes = np.ascontiguousarray(stripes, dtype=np.uint8)
        b, _k, chunk = stripes.shape
        out = self.matrix_regions(matrix, fold_stripes(stripes), w)
        return unfold_stripes(out, b, chunk)

    def matrix_stripes_batch(
        self, matrix: np.ndarray, stripe_batches, w: int
    ) -> list[np.ndarray]:
        """Coalesced-encode seam (the jax backend double-buffers
        device transfers here); the oracle just loops — coalescing is
        a dispatch-cost optimization, and the oracle has no dispatch
        cost to amortize.  Still records a flight-recorder host entry
        so the dispatch plane stays populated deviceless."""
        from ..ops.profiler import dispatch_profiler

        batches = list(stripe_batches)
        with dispatch_profiler().dispatch(
            "ec_encode", backend=self.name
        ) as dp:
            dp.set_ops(len(batches))
            dp.set_stripes(sum(s.shape[0] for s in batches))
            dp.add_bytes_in(sum(s.nbytes for s in batches))
            return [
                self.matrix_stripes(matrix, s, w) for s in batches
            ]

    def decode_stripes_batch(
        self, matrix: np.ndarray, row_sets, w: int, chunk: int
    ) -> list[np.ndarray]:
        """Batched decode-from-survivors seam (the jax backend
        double-buffers uploads and keeps outputs device-born here).
        ``row_sets`` is one list per object of equal-length 1-D
        survivor payloads (ndarray or DeviceBuf — resident tokens
        fetch host-side on this oracle path); each reshapes to
        (nstripes, s, chunk) and multiplies by the reconstruction
        matrix.  The oracle loops — it has no dispatch cost to
        amortize — through the same C region-MAC fast path the
        encode side uses."""
        from ..ops.profiler import dispatch_profiler

        with dispatch_profiler().dispatch(
            "ec_decode", backend=self.name
        ) as dp:
            dp.set_ops(len(row_sets))
            dp.add_bytes_in(
                sum(len(r) for rows in row_sets for r in rows)
            )
            outs: list[np.ndarray] = []
            for rows in row_sets:
                arr = np.stack(
                    [_host_row(r).reshape(-1, chunk) for r in rows],
                    axis=1,
                )
                outs.append(self.matrix_stripes(matrix, arr, w))
            dp.set_stripes(sum(o.shape[0] for o in outs))
            return outs

    def bitmatrix_regions(
        self,
        bm: np.ndarray,
        regions: np.ndarray,
        w: int,
        packetsize: int,
    ) -> np.ndarray:
        n, size = regions.shape
        out_rows = bm.shape[0] // w
        block = w * packetsize
        assert size % block == 0, (size, block)
        nblocks = size // block
        # (n, nblocks, w, p) -> (nblocks, n*w, p)
        planes = (
            regions.reshape(n, nblocks, w, packetsize)
            .transpose(1, 0, 2, 3)
            .reshape(nblocks, n * w, packetsize)
        )
        bits = np.unpackbits(planes, axis=2)
        out_bits = (
            bm.astype(np.int32) @ bits.astype(np.int32)
        ) & 1
        out = np.packbits(out_bits.astype(np.uint8), axis=2)
        return (
            out.reshape(nblocks, out_rows, w, packetsize)
            .transpose(1, 0, 2, 3)
            .reshape(out_rows, size)
        )


_backends: dict[str, object] = {"numpy": NumpyBackend()}


def register_backend(name: str, backend) -> None:
    _backends[name] = backend


def get_backend(name: str):
    if name == "jax" and "jax" not in _backends:
        from .. import ops  # self-registers the jax backend

        assert "jax" in _backends
    if name not in _backends:
        raise ValueError(f"unknown EC backend {name!r} (have {sorted(_backends)})")
    return _backends[name]
