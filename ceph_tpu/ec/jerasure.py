"""jerasure-compatible Reed-Solomon code family.

Re-design of src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc}: the
technique classes keep the reference's geometry rules (alignment, chunk
sizing, parameter validation) while the GF math comes from ceph_tpu.gf
and region compute is dispatched through a backend (numpy oracle or TPU).

Techniques (ErasureCodePluginJerasure.cc:40-57 dispatch):
- reed_sol_van   — Vandermonde RS, w in {8,16,32}       (matrix)
- reed_sol_r6_op — RAID6 optimized, m=2, w in {8,16,32} (matrix)
- cauchy_orig    — original Cauchy                      (bitmatrix)
- cauchy_good    — ones-minimized Cauchy                (bitmatrix)
- liberation     — minimal-density RAID6, w prime       (bitmatrix)
- blaum_roth     — w+1 prime RAID6                      (bitmatrix)
- liber8tion     — w=8 RAID6                            (bitmatrix)
"""

from __future__ import annotations

import numpy as np

from .. import gf
from ._matrix_ops import matrix_decode
from .backend import get_backend
from .interface import (
    ErasureCode,
    ErasureCodeError,
    ErasureCodeProfile,
    sanity_check_k_m,
    to_bool,
    to_int,
    to_string,
)
from .registry import ErasureCodePlugin, register

LARGEST_VECTOR_WORDSIZE = 16  # ErasureCodeJerasure.cc:30


class ErasureCodeJerasure(ErasureCode):
    DEFAULT_K = 2
    DEFAULT_M = 1
    DEFAULT_W = 8
    technique = "undefined"

    def __init__(self):
        super().__init__()
        self.w = 8
        self.per_chunk_alignment = False
        self.backend = None

    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse(profile)
        super().init(profile)
        self.prepare()

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.k = to_int("k", profile, self.DEFAULT_K)
        self.m = to_int("m", profile, self.DEFAULT_M)
        self.w = to_int("w", profile, self.DEFAULT_W)
        if self.chunk_mapping and len(self.chunk_mapping) != self.k + self.m:
            self.chunk_mapping = []
            raise ErasureCodeError("mapping size != k+m")
        sanity_check_k_m(self.k, self.m)
        self.backend = get_backend(to_string("backend", profile, "numpy"))

    def prepare(self) -> None:
        raise NotImplementedError

    def get_alignment(self) -> int:
        raise NotImplementedError

    def get_chunk_size(self, object_size: int) -> int:
        """ErasureCodeJerasure.cc:80-103 semantics."""
        alignment = self.get_alignment()
        if self.per_chunk_alignment:
            chunk_size = (object_size + self.k - 1) // self.k
            if alignment > chunk_size:
                chunk_size = alignment
            modulo = chunk_size % alignment
            if modulo:
                chunk_size += alignment - modulo
            return chunk_size
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k

    # Chunk dicts are keyed by physical position; the math runs in logical
    # order (data 0..k-1, coding k..k+m-1) through chunk_index().  NOTE:
    # deliberate deviation from the reference, whose base-family
    # encode_chunks reads the map by raw index and silently corrupts data
    # under a non-identity ``mapping`` profile (only CLAY overrides it
    # mapping-aware); here the remap is honored for every family.
    def encode_chunks(self, want_to_encode, encoded) -> None:
        data = np.stack(
            [encoded[self.chunk_index(i)] for i in range(self.k)]
        )
        coding = self._encode_regions(data)
        for i in range(self.m):
            np.copyto(encoded[self.chunk_index(self.k + i)], coding[i])

    def decode_chunks(self, want_to_read, chunks, decoded) -> None:
        erasures = [
            i
            for i in range(self.k + self.m)
            if self.chunk_index(i) not in chunks
        ]
        if not erasures:
            return
        logical = {
            i: decoded[self.chunk_index(i)] for i in range(self.k + self.m)
        }
        self._decode_regions(erasures, logical)

    def _encode_regions(self, data: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _decode_regions(self, erasures, decoded) -> None:
        raise NotImplementedError


class _MatrixTechnique(ErasureCodeJerasure):
    """Techniques encoded by a GF(2^w) matrix over w-bit words."""

    def __init__(self):
        super().__init__()
        self.matrix: np.ndarray | None = None

    def _encode_regions(self, data):
        return self.backend.matrix_regions(self.matrix, data, self.w)

    def _decode_regions(self, erasures, decoded):
        matrix_decode(
            self.backend, self.matrix, erasures, decoded, self.k, self.w
        )


class _BitmatrixTechnique(ErasureCodeJerasure):
    """Techniques encoded by a GF(2) bitmatrix over w packet planes."""

    DEFAULT_PACKETSIZE = 2048  # ErasureCodeJerasure.h:141

    def __init__(self):
        super().__init__()
        self.bitmatrix: np.ndarray | None = None  # (m*w, k*w)
        self.packetsize = self.DEFAULT_PACKETSIZE

    def parse(self, profile):
        super().parse(profile)
        self.packetsize = to_int(
            "packetsize", profile, self.DEFAULT_PACKETSIZE
        )
        if self.packetsize <= 0:
            raise ErasureCodeError(
                f"packetsize={self.packetsize} must be positive"
            )

    def _encode_regions(self, data):
        return self.backend.bitmatrix_regions(
            self.bitmatrix, data, self.w, self.packetsize
        )

    def _decode_regions(self, erasures, decoded):
        k, m, w = self.k, self.m, self.w
        erased = set(erasures)
        survivors = [i for i in range(k + m) if i not in erased][:k]
        if len(survivors) < k:
            raise ErasureCodeError("not enough chunks to decode (-EIO)")
        data_erasures = sorted(e for e in erased if e < k)
        if data_erasures:
            # binary survivor matrix (k*w, k*w): identity blocks for data
            # rows, bitmatrix rows for coding survivors
            # (jerasure_make_decoding_bitmatrix)
            b = np.zeros((k * w, k * w), dtype=np.uint8)
            for r, chunk in enumerate(survivors):
                if chunk < k:
                    b[
                        r * w : (r + 1) * w, chunk * w : (chunk + 1) * w
                    ] = np.eye(w, dtype=np.uint8)
                else:
                    b[r * w : (r + 1) * w, :] = self.bitmatrix[
                        (chunk - k) * w : (chunk - k + 1) * w, :
                    ]
            binv = _invert_bitmatrix(b)
            sel = np.concatenate(
                [binv[e * w : (e + 1) * w, :] for e in data_erasures]
            )
            surv = np.stack([decoded[i] for i in survivors])
            rec = self.backend.bitmatrix_regions(
                sel, surv, w, self.packetsize
            )
            for idx, e in enumerate(data_erasures):
                np.copyto(decoded[e], rec[idx])
        coding_erasures = [e for e in erased if e >= k]
        if coding_erasures:
            data = np.stack([decoded[i] for i in range(k)])
            sel = np.concatenate(
                [
                    self.bitmatrix[(e - k) * w : (e - k + 1) * w, :]
                    for e in coding_erasures
                ]
            )
            rec = self.backend.bitmatrix_regions(
                sel, data, w, self.packetsize
            )
            for idx, e in enumerate(coding_erasures):
                np.copyto(decoded[e], rec[idx])


def _invert_bitmatrix(mat: np.ndarray) -> np.ndarray:
    """Gauss-Jordan over GF(2)."""
    mat = mat.astype(np.uint8).copy()
    n = mat.shape[0]
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        pivot = col
        while pivot < n and mat[pivot, col] == 0:
            pivot += 1
        if pivot == n:
            raise ErasureCodeError("singular bitmatrix")
        if pivot != col:
            mat[[col, pivot]] = mat[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        rows = np.nonzero(mat[:, col])[0]
        rows = rows[rows != col]
        mat[rows] ^= mat[col]
        inv[rows] ^= inv[col]
    return inv


class ReedSolomonVandermonde(_MatrixTechnique):
    DEFAULT_K, DEFAULT_M, DEFAULT_W = 7, 3, 8
    technique = "reed_sol_van"

    def parse(self, profile):
        super().parse(profile)
        if self.w not in (8, 16, 32):
            raise ErasureCodeError(f"w={self.w} must be one of 8, 16, 32")
        self.per_chunk_alignment = to_bool(
            "jerasure-per-chunk-alignment", profile, "false"
        )

    def get_alignment(self):
        # ErasureCodeJerasure.cc:174-184
        if self.per_chunk_alignment:
            return self.w * LARGEST_VECTOR_WORDSIZE
        alignment = self.k * self.w * 4
        if (self.w * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * LARGEST_VECTOR_WORDSIZE
        return alignment

    def prepare(self):
        self.matrix = gf.reed_sol_vandermonde_coding_matrix(
            self.k, self.m, self.w
        )


class ReedSolomonRAID6(_MatrixTechnique):
    DEFAULT_K, DEFAULT_M, DEFAULT_W = 7, 2, 8
    technique = "reed_sol_r6_op"

    def parse(self, profile):
        super().parse(profile)
        self.m = 2
        profile["m"] = "2"
        if self.w not in (8, 16, 32):
            raise ErasureCodeError(f"w={self.w} must be one of 8, 16, 32")

    def get_alignment(self):
        return self.k * self.w * 4

    def prepare(self):
        self.matrix = gf.reed_sol_r6_coding_matrix(self.k, self.w)


class _Cauchy(_BitmatrixTechnique):
    DEFAULT_K, DEFAULT_M, DEFAULT_W = 7, 3, 8

    def parse(self, profile):
        super().parse(profile)
        self.per_chunk_alignment = to_bool(
            "jerasure-per-chunk-alignment", profile, "false"
        )

    def get_alignment(self):
        # ErasureCodeJerasureCauchy::get_alignment
        if self.per_chunk_alignment:
            alignment = self.w * self.packetsize
            modulo = alignment % LARGEST_VECTOR_WORDSIZE
            if modulo:
                alignment += LARGEST_VECTOR_WORDSIZE - modulo
            return alignment
        alignment = self.k * self.w * self.packetsize * 4
        if (self.w * self.packetsize * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = (
                self.k * self.w * self.packetsize * LARGEST_VECTOR_WORDSIZE
            )
        return alignment

    def _gf_matrix(self):
        raise NotImplementedError

    def prepare(self):
        self.matrix = self._gf_matrix()
        self.bitmatrix = gf.jerasure_bitmatrix(self.matrix, self.w)


class CauchyOrig(_Cauchy):
    technique = "cauchy_orig"

    def _gf_matrix(self):
        return gf.cauchy_original_matrix(self.k, self.m, self.w)


class CauchyGood(_Cauchy):
    technique = "cauchy_good"

    def _gf_matrix(self):
        return gf.cauchy_good_matrix(self.k, self.m, self.w)


def _is_prime(value: int) -> bool:
    if value < 2:
        return False
    f = 2
    while f * f <= value:
        if value % f == 0:
            return False
        f += 1
    return True


class Liberation(_BitmatrixTechnique):
    """Minimal-density RAID6 (Plank's Liberation codes): m=2, w prime,
    k <= w.  P row: identity blocks; Q block j: the rotation matrix
    row i -> (i + j) mod w, plus for j > 0 one extra bit at
    (i, (i + j - 1) mod w) with i = (j * (w - 1) / 2) mod w."""

    DEFAULT_K, DEFAULT_M, DEFAULT_W = 2, 2, 7
    technique = "liberation"

    def parse(self, profile):
        super().parse(profile)
        self.m = 2
        profile["m"] = "2"
        self._check_kw()
        self._check_packetsize()

    def _check_kw(self):
        if self.k > self.w:
            raise ErasureCodeError(f"k={self.k} must be <= w={self.w}")
        if not _is_prime(self.w):
            raise ErasureCodeError(f"w={self.w} must be prime")

    def _check_packetsize(self):
        if (self.packetsize % 8) != 0:
            raise ErasureCodeError(
                f"packetsize={self.packetsize} must be multiple of 8"
            )

    def get_alignment(self):
        alignment = self.k * self.w * self.packetsize * 4
        if (self.w * self.packetsize * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = (
                self.k * self.w * self.packetsize * LARGEST_VECTOR_WORDSIZE
            )
        return alignment

    def prepare(self):
        k, w = self.k, self.w
        bm = np.zeros((2 * w, k * w), dtype=np.uint8)
        for j in range(k):
            bm[:w, j * w : (j + 1) * w] = np.eye(w, dtype=np.uint8)
            for i in range(w):
                bm[w + i, j * w + (j + i) % w] = 1
            if j > 0:
                i = (j * ((w - 1) // 2)) % w
                bm[w + i, j * w + (i + j - 1) % w] = 1
        self.bitmatrix = bm


class BlaumRoth(Liberation):
    """Blaum-Roth minimal-density RAID6: m=2 over the polynomial ring
    R = GF(2)[x]/M_p(x) with p = w+1 prime and M_p = 1+x+...+x^w.
    Q block for data column j is multiplication by x^j in R (the
    mult-by-x matrix shifts coefficients up and folds the top
    coefficient into every row, since x^w = Σ_{i<w} x^i).

    Re-derivation note for parity review: the reference's generator
    (blaum_roth_coding_bitmatrix) lives in the absent jerasure
    submodule; this construction is the published Blaum-Roth code and
    is validated by exhaustive-erasure roundtrips, not byte-parity
    against the C library.
    """

    DEFAULT_K, DEFAULT_M, DEFAULT_W = 7, 2, 6
    technique = "blaum_roth"

    def _check_kw(self):
        if self.k > self.w:
            raise ErasureCodeError(f"k={self.k} must be <= w={self.w}")
        # w=7 tolerated for Firefly compatibility
        # (ErasureCodeJerasure.cc check_w)
        if self.w != 7 and (self.w <= 2 or not _is_prime(self.w + 1)):
            raise ErasureCodeError(
                f"w={self.w} must be greater than two and w+1 must "
                "be prime"
            )

    def prepare(self):
        k, w = self.k, self.w
        mult_x = np.zeros((w, w), dtype=np.uint8)
        for i in range(w - 1):
            mult_x[i + 1, i] = 1  # shift up
        mult_x[:, w - 1] = 1  # fold x^w = sum of lower powers
        bm = np.zeros((2 * w, k * w), dtype=np.uint8)
        block = np.eye(w, dtype=np.uint8)
        for j in range(k):
            bm[:w, j * w : (j + 1) * w] = np.eye(w, dtype=np.uint8)
            bm[w:, j * w : (j + 1) * w] = block
            block = (mult_x @ block) % 2
        self.bitmatrix = bm


class Liber8tion(Liberation):
    """RAID6 for w=8: m=2, k <= 8, packetsize multiple of 8
    (ErasureCodeJerasure.cc ErasureCodeJerasureLiber8tion — w is forced
    to 8 and m to 2 regardless of the profile, like the reference).

    Construction note for parity review: upstream's bitmatrix is
    Plank's search-found minimal-density table (71 ones), shipped only
    inside the jerasure submodule that is absent from the reference
    mount, so the exact table cannot be reproduced here.  This class
    keeps the technique's parameter slot and RAID6 geometry with a
    provably-MDS low-density construction instead: Q block j is the
    GF(2) bitmatrix of multiply-by-``c_j`` over GF(2^8), with the
    constants chosen as the eight nonzero bytes whose multiply
    bitmatrices are sparsest (111 ones total vs the 71 bound).  MDS is
    immediate: every block is invertible (c_j != 0) and every pairwise
    sum is multiply-by-(c_i ^ c_j) != 0, hence invertible.  Chunk
    bytes therefore do NOT match upstream liber8tion output —
    deviation tracked in docs/PARITY.md alongside blaum_roth.
    """

    DEFAULT_K, DEFAULT_M, DEFAULT_W = 2, 2, 8
    technique = "liber8tion"
    # The 8 sparsest multiply-by-c bitmatrices over GF(2^8)/0x11d,
    # sorted by density then value (ones: 8,11,11,14,14,17,18,18).
    CONSTANTS = (1, 2, 142, 4, 71, 8, 70, 173)

    def parse(self, profile):
        profile["w"] = "8"  # forced, reference parse() does the same
        super().parse(profile)

    def _check_kw(self):
        if self.k > self.w:
            raise ErasureCodeError(f"k={self.k} must be <= w={self.w}")

    def prepare(self):
        k, w = self.k, self.w
        bm = np.zeros((2 * w, k * w), dtype=np.uint8)
        for j in range(k):
            bm[:w, j * w : (j + 1) * w] = np.eye(w, dtype=np.uint8)
            cmat = np.array([[self.CONSTANTS[j]]], dtype=np.uint32)
            bm[w:, j * w : (j + 1) * w] = gf.jerasure_bitmatrix(cmat, w)
        self.bitmatrix = bm


@register("jerasure")
class ErasureCodePluginJerasure(ErasureCodePlugin):
    TECHNIQUES = {
        "reed_sol_van": ReedSolomonVandermonde,
        "reed_sol_r6_op": ReedSolomonRAID6,
        "cauchy_orig": CauchyOrig,
        "cauchy_good": CauchyGood,
        "liberation": Liberation,
        "blaum_roth": BlaumRoth,
        "liber8tion": Liber8tion,
    }

    def make(self, profile: ErasureCodeProfile):
        technique = profile.get("technique", "reed_sol_van")
        cls = self.TECHNIQUES.get(technique)
        if cls is None:
            raise ErasureCodeError(
                f"technique={technique} is not a valid coding technique "
                f"(have {sorted(self.TECHNIQUES)})"
            )
        return cls()
