"""CLAY — coupled-layer MSR code (src/erasure-code/clay/ErasureCodeClay.cc).

Minimum-bandwidth single-node repair: chunks are arrays of q^t
sub-chunks laid out on a q×t grid of nodes; an inner MDS code (mds,
(k+nu)+m) works on "uncoupled" sub-chunks U, and a 2+2 pairwise
transform (pft) couples sub-chunk pairs across the grid diagonal.
Repairing one node reads only 1/q of every helper chunk
(get_repair_subchunks / minimum_to_repair), which is the hook
ECBackend's subchunk plumbing consumes (src/osd/ECUtil.cc:82-116).

Structure mirrors the reference: encode = decode_layered(parity),
full decode = decode_layered(erasures), single-lost-chunk repair =
plane-ordered traversal with pairwise transforms.  numpy slice views
play the role of bufferlist::substr_of — pairwise transforms write
through them into the real chunk buffers.

nu pads k+m to a multiple of q with zeroed virtual data nodes; node
ids in grid space shift parity ids by nu.
"""

from __future__ import annotations

import numpy as np

from .interface import (
    ErasureCode,
    ErasureCodeError,
    ErasureCodeProfile,
    SIMD_ALIGN,
    sanity_check_k_m,
    to_int,
    to_string,
)
from .registry import ErasureCodePlugin, register


def _round_up_to(n: int, d: int) -> int:
    return ((n + d - 1) // d) * d


class ErasureCodeClay(ErasureCode):
    DEFAULT_K, DEFAULT_M = 4, 2

    def __init__(self):
        super().__init__()
        self.d = 0
        self.q = 0
        self.t = 0
        self.nu = 0
        self.sub_chunk_no = 1
        self.mds: ErasureCode | None = None
        self.pft: ErasureCode | None = None

    # -- profile -----------------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        from .registry import instance

        mds_profile, pft_profile = self.parse(profile)
        super().init(profile)
        self.mds = instance().factory(mds_profile["plugin"], mds_profile)
        self.pft = instance().factory(pft_profile["plugin"], pft_profile)

    def parse(self, profile: ErasureCodeProfile):
        super().parse(profile)
        self.k = to_int("k", profile, self.DEFAULT_K)
        self.m = to_int("m", profile, self.DEFAULT_M)
        sanity_check_k_m(self.k, self.m)
        self.d = to_int("d", profile, self.k + self.m - 1)

        scalar_mds = to_string("scalar_mds", profile, "jerasure")
        if scalar_mds not in ("jerasure", "isa", "shec"):
            raise ErasureCodeError(
                f"scalar_mds {scalar_mds} is not supported, use one of "
                "'jerasure', 'isa', 'shec'"
            )
        technique = profile.get("technique", "")
        if not technique:
            technique = (
                "reed_sol_van" if scalar_mds in ("jerasure", "isa")
                else "single"
            )
        allowed = {
            "jerasure": (
                "reed_sol_van", "reed_sol_r6_op", "cauchy_orig",
                "cauchy_good", "liber8tion",
            ),
            "isa": ("reed_sol_van", "cauchy"),
            "shec": ("single", "multiple"),
        }[scalar_mds]
        if technique not in allowed:
            raise ErasureCodeError(
                f"technique {technique} is not supported with "
                f"{scalar_mds}, use one of {allowed}"
            )

        if not (self.k <= self.d <= self.k + self.m - 1):
            raise ErasureCodeError(
                f"value of d {self.d} must be within "
                f"[{self.k}, {self.k + self.m - 1}]"
            )
        self.q = self.d - self.k + 1
        self.nu = (
            self.q - (self.k + self.m) % self.q
            if (self.k + self.m) % self.q
            else 0
        )
        if self.k + self.m + self.nu > 254:
            raise ErasureCodeError("k+m+nu must be <= 254")

        mds_profile = ErasureCodeProfile(
            plugin=scalar_mds,
            technique=technique,
            k=str(self.k + self.nu),
            m=str(self.m),
            w="8",
        )
        pft_profile = ErasureCodeProfile(
            plugin=scalar_mds,
            technique=technique,
            k="2",
            m="2",
            w="8",
        )
        if scalar_mds == "shec":
            mds_profile["c"] = "2"
            pft_profile["c"] = "2"
        backend = profile.get("backend")
        if backend:
            mds_profile["backend"] = backend
            pft_profile["backend"] = backend

        self.t = (self.k + self.m + self.nu) // self.q
        self.sub_chunk_no = self.q ** self.t
        return mds_profile, pft_profile

    # -- geometry ----------------------------------------------------------
    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_no

    def get_chunk_size(self, object_size: int) -> int:
        scalar = self.pft.get_chunk_size(1)
        alignment = self.sub_chunk_no * self.k * scalar
        return _round_up_to(object_size, alignment) // self.k

    # -- plane helpers -----------------------------------------------------
    def _plane_vector(self, z: int) -> list[int]:
        v = [0] * self.t
        for i in range(self.t):
            v[self.t - 1 - i] = z % self.q
            z //= self.q
        return v

    def _z_sw(self, z: int, x: int, zy: int, y: int) -> int:
        return z + (x - zy) * self.q ** (self.t - 1 - y)

    # -- encode / decode ---------------------------------------------------
    def encode_chunks(self, want_to_encode, encoded) -> None:
        k, m, nu = self.k, self.m, self.nu
        chunk_size = len(encoded[0])
        chunks = {}
        parity = set()
        for i in range(k + m):
            buf = encoded[self.chunk_index(i)]
            if i < k:
                chunks[i] = buf
            else:
                chunks[i + nu] = buf
                parity.add(i + nu)
        for i in range(k, k + nu):
            chunks[i] = np.zeros(chunk_size, dtype=np.uint8)
        self._decode_layered(set(parity), chunks)

    def decode_chunks(self, want_to_read, chunks, decoded) -> None:
        k, m, nu = self.k, self.m, self.nu
        erasures = set()
        coded = {}
        for i in range(k + m):
            node = i if i < k else i + nu
            if self.chunk_index(i) not in chunks:
                erasures.add(node)
            coded[node] = decoded[self.chunk_index(i)]
        chunk_size = len(coded[0])
        for i in range(k, k + nu):
            coded[i] = np.zeros(chunk_size, dtype=np.uint8)
        self._decode_layered(erasures, coded)

    def decode(self, want_to_read, chunks, chunk_size=0):
        avail = set(chunks)
        if self.is_repair(want_to_read, avail) and chunk_size > len(
            next(iter(chunks.values()))
        ):
            return self.repair(want_to_read, chunks, chunk_size)
        return self._decode(want_to_read, chunks)

    # -- repair interface --------------------------------------------------
    def is_repair(self, want_to_read, available) -> bool:
        """ErasureCodeClay.cc:304-323: single lost chunk, whole y-group
        of the lost node available, at least d helpers."""
        if set(want_to_read) <= set(available):
            return False
        if len(want_to_read) > 1:
            return False
        i = next(iter(want_to_read))
        lost = i if i < self.k else i + self.nu
        for x in range(self.q):
            node = (lost // self.q) * self.q + x
            node = node if node < self.k else node - self.nu
            if node != i and node not in available:
                return False
        return len(available) >= self.d

    def get_repair_subchunks(self, lost_node: int):
        """(offset, count) runs of the lost node's x-column planes
        (ErasureCodeClay.cc:363-377)."""
        q, t = self.q, self.t
        y_lost, x_lost = lost_node // q, lost_node % q
        seq = q ** (t - 1 - y_lost)
        out = []
        index = x_lost * seq
        for _ in range(q ** y_lost):
            out.append((index, seq))
            index += q * seq
        return out

    def minimum_to_decode(self, want_to_read, available):
        if self.is_repair(want_to_read, available):
            return self._minimum_to_repair(want_to_read, available)
        return super().minimum_to_decode(want_to_read, available)

    def _minimum_to_repair(self, want_to_read, available):
        i = next(iter(want_to_read))
        lost = i if i < self.k else i + self.nu
        sub_ind = self.get_repair_subchunks(lost)
        minimum: dict[int, list] = {}
        for j in range(self.q):
            if j != lost % self.q:
                rep = (lost // self.q) * self.q + j
                if rep < self.k:
                    minimum[rep] = list(sub_ind)
                elif rep >= self.k + self.nu:
                    minimum[rep - self.nu] = list(sub_ind)
        for chunk in sorted(available):
            if len(minimum) >= self.d:
                break
            if chunk not in minimum:
                minimum[chunk] = list(sub_ind)
        assert len(minimum) == self.d
        return minimum

    def repair(self, want_to_read, chunks, chunk_size):
        """Minimum-bandwidth repair of one chunk from d partial helper
        reads (ErasureCodeClay.cc:395-460)."""
        assert len(want_to_read) == 1 and len(chunks) == self.d
        k, m, nu, q, t = self.k, self.m, self.nu, self.q, self.t

        repair_sub_no = self._repair_sub_chunk_count(want_to_read)
        repair_blocksize = len(next(iter(chunks.values())))
        assert repair_blocksize % repair_sub_no == 0
        sub_chunksize = repair_blocksize // repair_sub_no
        chunksize = self.sub_chunk_no * sub_chunksize
        assert chunksize == chunk_size

        recovered = {}
        helper = {}
        aloof = set()
        repaired = {}
        lost_id = None
        sub_ind = None
        for i in range(k + m):
            if i in chunks:
                helper[i if i < k else i + nu] = np.ascontiguousarray(
                    chunks[i], dtype=np.uint8
                )
            elif i != next(iter(want_to_read)):
                aloof.add(i if i < k else i + nu)
            else:
                lost_id = i if i < k else i + nu
                repaired[i] = np.zeros(chunksize, dtype=np.uint8)
                recovered[lost_id] = repaired[i]
                sub_ind = self.get_repair_subchunks(lost_id)
        for i in range(k, k + nu):
            helper[i] = np.zeros(repair_blocksize, dtype=np.uint8)
        assert len(helper) + len(aloof) + len(recovered) == q * t

        self._repair_one_lost_chunk(
            recovered, aloof, helper, repair_blocksize, sub_ind
        )
        return repaired

    def _repair_sub_chunk_count(self, want_to_read) -> int:
        weight = [0] * self.t
        for c in want_to_read:
            node = c if c < self.k else c + self.nu
            weight[node // self.q] += 1
        remaining = 1
        for y in range(self.t):
            remaining *= self.q - weight[y]
        return self.sub_chunk_no - remaining

    def _repair_one_lost_chunk(
        self, recovered, aloof, helper, repair_blocksize, sub_ind
    ):
        """ErasureCodeClay.cc:462-644, in plane-order passes."""
        q, t = self.q, self.t
        repair_subchunks = self.sub_chunk_no // q
        sub = repair_blocksize // repair_subchunks
        scratch = np.zeros(sub, dtype=np.uint8)

        ordered_planes: dict[int, list[int]] = {}
        plane_to_ind: dict[int, int] = {}
        plane_ind = 0
        for index, count in sub_ind:
            for z in range(index, index + count):
                z_vec = self._plane_vector(z)
                order = sum(
                    1
                    for node in list(recovered) + sorted(aloof)
                    if node % q == z_vec[node // q]
                )
                assert order > 0
                ordered_planes.setdefault(order, []).append(z)
                plane_to_ind[z] = plane_ind
                plane_ind += 1

        U = {
            i: np.zeros(self.sub_chunk_no * sub, dtype=np.uint8)
            for i in range(q * t)
        }
        (lost_chunk,) = recovered

        erasures = {
            lost_chunk - lost_chunk % q + i for i in range(q)
        } | set(aloof)

        def uview(node, z):
            return U[node][z * sub : (z + 1) * sub]

        def hview(node, z):
            i = plane_to_ind[z]
            return helper[node][i * sub : (i + 1) * sub]

        order = 1
        while order in ordered_planes:
            for z in sorted(ordered_planes[order]):
                z_vec = self._plane_vector(z)
                for y in range(t):
                    for x in range(q):
                        node_xy = y * q + x
                        if node_xy in erasures:
                            continue
                        zy = z_vec[y]
                        z_sw = self._z_sw(z, x, zy, y)
                        node_sw = y * q + zy
                        i0, i1, i2, i3 = (
                            (0, 1, 2, 3) if zy <= x else (1, 0, 3, 2)
                        )
                        if node_sw in aloof:
                            known = {
                                i0: hview(node_xy, z),
                                i3: uview(node_sw, z_sw),
                            }
                            dec = {
                                i0: known[i0],
                                i1: scratch,
                                i2: uview(node_xy, z),
                                i3: known[i3],
                            }
                            self.pft.decode_chunks(
                                {i2}, known, dec
                            )
                        elif zy != x:
                            known = {
                                i0: hview(node_xy, z),
                                i1: hview(node_sw, z_sw),
                            }
                            dec = {
                                i0: known[i0],
                                i1: known[i1],
                                i2: uview(node_xy, z),
                                i3: scratch.copy(),
                            }
                            self.pft.decode_chunks(
                                {i2}, known, dec
                            )
                        else:
                            np.copyto(
                                uview(node_xy, z), hview(node_xy, z)
                            )
                self._decode_uncoupled(erasures, z, sub, U)

                for i in sorted(erasures):
                    x, y = i % q, i // q
                    zy = z_vec[y]
                    node_sw = y * q + zy
                    z_sw = self._z_sw(z, x, zy, y)
                    i0, i1, i2, i3 = (
                        (0, 1, 2, 3) if zy <= x else (1, 0, 3, 2)
                    )
                    if i in aloof:
                        continue
                    if x == zy:  # hole-dot pair (type 0)
                        np.copyto(
                            recovered[i][z * sub : (z + 1) * sub],
                            uview(i, z),
                        )
                    else:
                        assert y == lost_chunk // q
                        assert node_sw == lost_chunk
                        known = {
                            i0: hview(i, z),
                            i2: uview(i, z),
                        }
                        dec = {
                            i0: known[i0],
                            i1: recovered[node_sw][
                                z_sw * sub : (z_sw + 1) * sub
                            ],
                            i2: known[i2],
                            i3: scratch,
                        }
                        self.pft.decode_chunks({i1}, known, dec)
            order += 1

    # -- layered decode (full decode and encode) ---------------------------
    def _decode_layered(self, erased_chunks: set, chunks: dict) -> None:
        """ErasureCodeClay.cc:647-712."""
        q, t, m = self.q, self.t, self.m
        size = len(chunks[0])
        assert size % self.sub_chunk_no == 0
        sc = size // self.sub_chunk_no
        assert erased_chunks

        num = len(erased_chunks)
        if num > m:
            raise ErasureCodeError(
                f"{num} erasures exceed m={m} (-EIO)"
            )
        i = self.k + self.nu
        while num < m and i < q * t:
            if i not in erased_chunks:
                erased_chunks.add(i)
                num += 1
            i += 1
        assert num == m

        U = {
            i: np.zeros(size, dtype=np.uint8) for i in range(q * t)
        }
        order = [0] * self.sub_chunk_no
        for z in range(self.sub_chunk_no):
            z_vec = self._plane_vector(z)
            order[z] = sum(
                1 for e in erased_chunks if e % q == z_vec[e // q]
            )
        max_iscore = len({e // q for e in erased_chunks})

        for iscore in range(max_iscore + 1):
            for z in range(self.sub_chunk_no):
                if order[z] == iscore:
                    self._decode_erasures(erased_chunks, z, chunks, sc, U)

            for z in range(self.sub_chunk_no):
                if order[z] != iscore:
                    continue
                z_vec = self._plane_vector(z)
                for node_xy in sorted(erased_chunks):
                    x, y = node_xy % q, node_xy // q
                    zy = z_vec[y]
                    node_sw = y * q + zy
                    if zy != x:
                        if node_sw not in erased_chunks:
                            self._recover_type1(
                                chunks, x, y, z, z_vec, sc, U
                            )
                        elif zy < x:
                            self._coupled_from_uncoupled(
                                chunks, x, y, z, z_vec, sc, U
                            )
                    else:
                        np.copyto(
                            chunks[node_xy][z * sc : (z + 1) * sc],
                            U[node_xy][z * sc : (z + 1) * sc],
                        )

    def _decode_erasures(self, erased_chunks, z, chunks, sc, U):
        q, t = self.q, self.t
        z_vec = self._plane_vector(z)
        for x in range(q):
            for y in range(t):
                node_xy = q * y + x
                node_sw = q * y + z_vec[y]
                if node_xy in erased_chunks:
                    continue
                if z_vec[y] < x:
                    self._uncoupled_from_coupled(
                        chunks, x, y, z, z_vec, sc, U
                    )
                elif z_vec[y] == x:
                    np.copyto(
                        U[node_xy][z * sc : (z + 1) * sc],
                        chunks[node_xy][z * sc : (z + 1) * sc],
                    )
                elif node_sw in erased_chunks:
                    self._uncoupled_from_coupled(
                        chunks, x, y, z, z_vec, sc, U
                    )
        self._decode_uncoupled(erased_chunks, z, sc, U)

    def _decode_uncoupled(self, erased_chunks, z, sc, U):
        """Inner MDS decode of plane z over the U buffers
        (ErasureCodeClay.cc:743-761)."""
        known = {}
        allsub = {}
        for i in range(self.q * self.t):
            view = U[i][z * sc : (z + 1) * sc]
            if i not in erased_chunks:
                known[i] = view
            allsub[i] = view
        self.mds.decode_chunks(set(erased_chunks), known, allsub)

    def _pft_views(self, chunks, x, y, z, z_vec, sc, U):
        q = self.q
        node_xy = y * q + x
        node_sw = y * q + z_vec[y]
        z_sw = self._z_sw(z, x, z_vec[y], y)
        cxy = chunks[node_xy][z * sc : (z + 1) * sc]
        csw = chunks[node_sw][z_sw * sc : (z_sw + 1) * sc]
        uxy = U[node_xy][z * sc : (z + 1) * sc]
        usw = U[node_sw][z_sw * sc : (z_sw + 1) * sc]
        return cxy, csw, uxy, usw

    def _recover_type1(self, chunks, x, y, z, z_vec, sc, U):
        """Erased C_xy from C_sw and U_xy (ErasureCodeClay.cc:776-812)."""
        cxy, csw, uxy, _ = self._pft_views(chunks, x, y, z, z_vec, sc, U)
        i0, i1, i2, i3 = (0, 1, 2, 3) if z_vec[y] <= x else (1, 0, 3, 2)
        known = {i1: csw, i2: uxy}
        dec = {
            i0: cxy,
            i1: csw,
            i2: uxy,
            i3: np.zeros(sc, dtype=np.uint8),
        }
        self.pft.decode_chunks({i0}, known, dec)

    def _coupled_from_uncoupled(self, chunks, x, y, z, z_vec, sc, U):
        """Both coupled from both uncoupled (ErasureCodeClay.cc:814-839)."""
        cxy, csw, uxy, usw = self._pft_views(chunks, x, y, z, z_vec, sc, U)
        assert z_vec[y] < x
        known = {2: uxy, 3: usw}
        dec = {0: cxy, 1: csw, 2: uxy, 3: usw}
        self.pft.decode_chunks({0, 1}, known, dec)

    def _uncoupled_from_coupled(self, chunks, x, y, z, z_vec, sc, U):
        """Both uncoupled from both coupled (ErasureCodeClay.cc:841-871)."""
        cxy, csw, uxy, usw = self._pft_views(chunks, x, y, z, z_vec, sc, U)
        i0, i1, i2, i3 = (0, 1, 2, 3) if z_vec[y] <= x else (1, 0, 3, 2)
        known = {i0: cxy, i1: csw}
        dec = {i0: cxy, i1: csw, i2: uxy, i3: usw}
        self.pft.decode_chunks({i2, i3}, known, dec)


@register("clay")
class ErasureCodePluginClay(ErasureCodePlugin):
    def make(self, profile: ErasureCodeProfile):
        return ErasureCodeClay()
