"""Shared encode/decode drivers for GF-matrix code families.

One implementation of the stack-regions → matrix-multiply → scatter-back
dance, used by both the jerasure and isa families (the reference
duplicates this between ErasureCodeJerasure.cc and ErasureCodeIsa.cc; here
it is one seam so the TPU backend slots under both).

All functions speak *logical* chunk ids (data 0..k-1, coding k..k+m-1);
the callers translate physical positions through chunk_index().
"""

from __future__ import annotations

import numpy as np

from .. import gf


def matrix_decode(
    backend,
    matrix: np.ndarray,
    erasures: list[int],
    decoded: dict[int, np.ndarray],
    k: int,
    w: int,
    decode_rows_fn=None,
) -> None:
    """Reconstruct erased chunks in-place in ``decoded``.

    ``decode_rows_fn(erasures) -> (rows, survivors)`` lets callers cache
    the survivor-matrix inversion (the isa table-cache analog); defaults
    to computing it fresh.  Only runs the O(k^3) inversion when a data
    chunk is actually erased.
    """
    data_erasures = sorted(e for e in erasures if e < k)
    if data_erasures:
        try:
            if decode_rows_fn is None:
                rows, survivors = gf.make_decoding_matrix(
                    matrix, erasures, k, w
                )
            else:
                rows, survivors = decode_rows_fn(erasures)
        except ValueError as e:
            from .interface import ErasureCodeError

            raise ErasureCodeError(f"{e} (-EIO)")
        surv = np.stack([decoded[i] for i in survivors])
        rec = backend.matrix_regions(rows, surv, w)
        for idx, e in enumerate(data_erasures):
            np.copyto(decoded[e], rec[idx])
    coding_erasures = [e for e in erasures if e >= k]
    if coding_erasures:
        data = np.stack([decoded[i] for i in range(k)])
        sub = matrix[[e - k for e in coding_erasures]]
        rec = backend.matrix_regions(sub, data, w)
        for idx, e in enumerate(coding_erasures):
            np.copyto(decoded[e], rec[idx])
