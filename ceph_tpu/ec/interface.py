"""ErasureCode base class — the contract every code family implements.

Python rendering of the reference interface and base-class semantics
(src/erasure-code/ErasureCodeInterface.h:170-462, ErasureCode.cc:42-242):
systematic codes over k data + m coding chunks; objects are padded to k
equal chunks of ``get_chunk_size(object_size)`` bytes; ``encode`` splits,
pads and delegates to ``encode_chunks``; ``decode`` returns available
chunks directly or allocates and delegates to ``decode_chunks``; chunk
remapping via the ``mapping=DDD_D_`` profile string; greedy
``minimum_to_decode``.

Chunks are numpy uint8 arrays; the chunk dict is keyed by chunk id
(position), exactly like the reference's ``map<int, bufferlist>``.
"""

from __future__ import annotations

import numpy as np

SIMD_ALIGN = 32  # ErasureCode.cc:42 — kept for layout parity


class ErasureCodeError(Exception):
    """Profile or decode errors (the reference's -EINVAL/-EIO paths)."""


class ErasureCodeProfile(dict):
    """str->str map, as in ErasureCodeInterface.h:155."""


def to_int(name, profile, default, ss=None):
    v = profile.get(name, None)
    if v is None or v == "":
        profile[name] = str(default)
        return int(default)
    try:
        return int(v)
    except ValueError:
        raise ErasureCodeError(f"{name}={v} is not a valid int")


def to_bool(name, profile, default, ss=None):
    v = profile.get(name, None)
    if v is None or v == "":
        profile[name] = str(default)
        v = str(default)
    return str(v).lower() in ("yes", "true", "1")


def to_string(name, profile, default, ss=None):
    v = profile.get(name, None)
    if v is None:
        profile[name] = default
        return default
    return v


class ErasureCode:
    """Base class; subclasses set k/m and implement encode_chunks /
    decode_chunks / get_chunk_size."""

    def __init__(self):
        self.k = 0
        self.m = 0
        self.chunk_mapping: list[int] = []
        self._profile: ErasureCodeProfile = ErasureCodeProfile()
        self.rule_root = "default"
        self.rule_failure_domain = "host"
        self.rule_device_class = ""

    # -- profile ----------------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        self.rule_root = to_string("crush-root", profile, "default")
        self.rule_failure_domain = to_string(
            "crush-failure-domain", profile, "host"
        )
        self.rule_device_class = to_string("crush-device-class", profile, "")
        self._profile = profile

    def parse(self, profile: ErasureCodeProfile) -> None:
        """Parse the common ``mapping`` profile key (ErasureCode.cc:261-280):
        chunk_mapping[logical chunk, data first] = physical position."""
        mapping = profile.get("mapping")
        if mapping:
            data_positions = []
            coding_positions = []
            for position, c in enumerate(mapping):
                (data_positions if c == "D" else coding_positions).append(
                    position
                )
            self.chunk_mapping = data_positions + coding_positions

    def get_profile(self) -> ErasureCodeProfile:
        return self._profile

    # -- geometry ---------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_coding_chunk_count(self) -> int:
        return self.m

    def get_sub_chunk_count(self) -> int:
        return 1

    def get_chunk_size(self, object_size: int) -> int:
        raise NotImplementedError

    def get_chunk_mapping(self) -> list[int]:
        return self.chunk_mapping

    def chunk_index(self, i: int) -> int:
        return self.chunk_mapping[i] if i < len(self.chunk_mapping) else i

    # -- encode -----------------------------------------------------------
    def encode_prepare(self, raw: bytes | np.ndarray) -> dict[int, np.ndarray]:
        """Split + zero-pad input into k aligned data chunks and allocate m
        coding chunks (ErasureCode.cc:151-186 semantics, including the
        partial-trailing-chunk zero fill)."""
        raw = np.frombuffer(bytes(raw), dtype=np.uint8) if isinstance(
            raw, (bytes, bytearray, memoryview)
        ) else np.ascontiguousarray(raw, dtype=np.uint8).ravel()
        k, m = self.k, self.m
        if len(raw) == 0:
            raise ErasureCodeError("cannot encode an empty payload")
        blocksize = self.get_chunk_size(len(raw))
        padded_chunks = k - len(raw) // blocksize
        encoded: dict[int, np.ndarray] = {}
        for i in range(k - padded_chunks):
            encoded[self.chunk_index(i)] = raw[
                i * blocksize : (i + 1) * blocksize
            ].copy()
        if padded_chunks:
            remainder = len(raw) - (k - padded_chunks) * blocksize
            buf = np.zeros(blocksize, dtype=np.uint8)
            buf[:remainder] = raw[(k - padded_chunks) * blocksize :]
            encoded[self.chunk_index(k - padded_chunks)] = buf
            for i in range(k - padded_chunks + 1, k):
                encoded[self.chunk_index(i)] = np.zeros(
                    blocksize, dtype=np.uint8
                )
        for i in range(k, k + m):
            encoded[self.chunk_index(i)] = np.zeros(blocksize, dtype=np.uint8)
        return encoded

    def encode(
        self, want_to_encode: set[int], raw: bytes | np.ndarray
    ) -> dict[int, np.ndarray]:
        encoded = self.encode_prepare(raw)
        self.encode_chunks(set(range(self.k + self.m)), encoded)
        for i in range(self.k + self.m):
            if i not in want_to_encode:
                encoded.pop(i, None)
        return encoded

    def encode_chunks(
        self, want_to_encode: set[int], encoded: dict[int, np.ndarray]
    ) -> None:
        raise NotImplementedError

    # -- decode -----------------------------------------------------------
    def decode(
        self,
        want_to_read: set[int],
        chunks: dict[int, np.ndarray],
        chunk_size: int = 0,
    ) -> dict[int, np.ndarray]:
        return self._decode(want_to_read, chunks)

    def _decode(
        self, want_to_read: set[int], chunks: dict[int, np.ndarray]
    ) -> dict[int, np.ndarray]:
        """ErasureCode.cc:206-242; note there is deliberately no
        have-at-least-k guard — non-MDS codes (shec) decode from fewer
        than k chunks, and each code family raises -EIO itself when its
        recovery system is unsolvable."""
        have = set(chunks)
        if want_to_read <= have:
            return {i: chunks[i] for i in want_to_read}
        k, m = self.k, self.m
        if not chunks:
            raise ErasureCodeError("no chunks to decode from (-EIO)")
        blocksize = len(next(iter(chunks.values())))
        decoded: dict[int, np.ndarray] = {}
        for i in range(k + m):
            if i in chunks:
                decoded[i] = chunks[i].copy()
            else:
                decoded[i] = np.zeros(blocksize, dtype=np.uint8)
        self.decode_chunks(want_to_read, chunks, decoded)
        return decoded

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: dict[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        raise NotImplementedError

    def decode_concat(self, chunks: dict[int, np.ndarray]) -> np.ndarray:
        """Decode and concatenate the data chunks in logical order
        (ErasureCode.cc:332)."""
        want = {self.chunk_index(i) for i in range(self.k)}
        decoded = self._decode(want, chunks)
        return np.concatenate(
            [decoded[self.chunk_index(i)] for i in range(self.k)]
        )

    # -- minimum ----------------------------------------------------------
    def _minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> set[int]:
        if want_to_read <= available:
            return set(want_to_read)
        if len(available) < self.k:
            raise ErasureCodeError("not enough chunks to decode (-EIO)")
        return set(sorted(available)[: self.k])

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> dict[int, list[tuple[int, int]]]:
        ids = self._minimum_to_decode(want_to_read, available)
        sub = [(0, self.get_sub_chunk_count())]
        return {i: list(sub) for i in sorted(ids)}

    def minimum_to_decode_with_cost(
        self, want_to_read: set[int], available: dict[int, int]
    ) -> set[int]:
        return self._minimum_to_decode(want_to_read, set(available))

    # -- crush ------------------------------------------------------------
    def create_rule(self, name: str, crush, ss=None) -> int:
        """ErasureCode.cc:64-83: an ``indep`` rule under the profile's
        root/failure-domain/device-class."""
        return crush.add_simple_rule(
            name,
            self.rule_root,
            self.rule_failure_domain,
            self.rule_device_class,
            "indep",
        )


def sanity_check_k_m(k: int, m: int) -> None:
    if k < 2:
        raise ErasureCodeError(f"k={k} must be >= 2")
    if m < 1:
        raise ErasureCodeError(f"m={m} must be >= 1")
