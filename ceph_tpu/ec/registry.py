"""Plugin registry — name → erasure-code factory.

The reference lazily dlopens ``libec_<name>.so`` and lets the plugin
self-register (ErasureCodePlugin.cc:86-163); here plugins are python
classes that self-register at import, and ``factory`` instantiates and
``init``s them from a profile.  This registry is the insertion point for
TPU-backed codes, exactly as it is the reference's insertion point for
isa/jerasure: the same code family runs with ``backend=numpy`` (CPU
oracle) or ``backend=jax`` (MXU kernels).
"""

from __future__ import annotations

import threading

from ..version import FRAMEWORK_VERSION
from .interface import ErasureCodeError, ErasureCodeProfile

# The registry refuses plugins built against another framework version,
# mirroring the __erasure_code_version == CEPH_GIT_NICE_VER check at
# dlopen time (ErasureCodePlugin.cc:138).


class ErasureCodePlugin:
    """Factory base: subclass and implement make(profile)."""

    version = FRAMEWORK_VERSION

    def make(self, profile: ErasureCodeProfile):
        raise NotImplementedError


class ErasureCodePluginRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._plugins: dict[str, ErasureCodePlugin] = {}
        self.disable_dlclose = False  # parity knob; unused

    def add(self, name: str, plugin: ErasureCodePlugin) -> None:
        version = getattr(plugin, "version", None)
        if version != FRAMEWORK_VERSION:
            raise ErasureCodeError(
                f"plugin {name}: version {version!r} does not match "
                f"{FRAMEWORK_VERSION!r}"
            )
        if not callable(getattr(plugin, "make", None)):
            raise ErasureCodeError(
                f"plugin {name}: missing entry point make()"
            )
        with self._lock:
            if name in self._plugins:
                raise ErasureCodeError(f"plugin {name} already registered")
            self._plugins[name] = plugin

    def get(self, name: str) -> ErasureCodePlugin | None:
        return self._plugins.get(name)

    def remove(self, name: str) -> None:
        with self._lock:
            self._plugins.pop(name, None)

    def factory(
        self,
        plugin_name: str,
        profile: ErasureCodeProfile,
        ss=None,
    ):
        """Instantiate + init a code from a profile
        (ErasureCodePlugin.cc:86 factory contract)."""
        plugin = self._plugins.get(plugin_name)
        if plugin is None:
            raise ErasureCodeError(
                f"failed to load plugin {plugin_name!r}: not registered "
                f"(have: {sorted(self._plugins)})"
            )
        ec = plugin.make(profile)
        ec.init(profile)
        return ec

    def preload(self, names: list[str]) -> None:
        """Parity with osd_erasure_code_plugins preload: verify the listed
        plugins resolve (all python plugins register at import here)."""
        for name in names:
            if name not in self._plugins:
                raise ErasureCodeError(f"cannot preload plugin {name!r}")


_instance = ErasureCodePluginRegistry()


def instance() -> ErasureCodePluginRegistry:
    return _instance


def register(name: str):
    """Decorator: register a plugin class (instantiated once) by name."""

    def deco(cls):
        _instance.add(name, cls())
        return cls

    return deco
