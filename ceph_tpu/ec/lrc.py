"""LRC — layered locally-repairable code (src/erasure-code/lrc/).

A stack of layers, each an inner code over a subset of chunk positions
(per-position roles 'D' data / 'c' coding / '_' absent).  Encode runs
every layer bottom-up over its subset (ErasureCodeLrc.cc:encode_chunks);
decode iterates layers in reverse, solving any layer whose erasures fit
its coding count, reusing chunks recovered by earlier layers
(decode_chunks); minimum_to_decode does the same reverse sweep to find
a minimal read set, falling back to recover-everything-possible
(_minimum_to_decode cases 1-3).  The simple k/m/l form generates the
global + local layers exactly as parse_kml does.

The inner codes are anything the registry provides — on the TPU
backend every layer's region math lands in the same batched GF kernel,
which is the reuse the reference gets from stacking plugins on
jerasure.
"""

from __future__ import annotations

import json

import numpy as np

from .interface import (
    ErasureCode,
    ErasureCodeError,
    ErasureCodeProfile,
    to_string,
)
from .registry import ErasureCodePlugin, register


class Layer:
    def __init__(self, chunks_map: str, profile: ErasureCodeProfile):
        self.chunks_map = chunks_map
        self.profile = profile
        self.data = [i for i, c in enumerate(chunks_map) if c == "D"]
        self.coding = [i for i, c in enumerate(chunks_map) if c == "c"]
        self.chunks = self.data + self.coding
        self.chunks_as_set = set(self.chunks)
        self.erasure_code: ErasureCode | None = None


class ErasureCodeLrc(ErasureCode):
    DEFAULT_KML = -1

    def __init__(self):
        super().__init__()
        self.layers: list[Layer] = []
        self.mapping = ""
        self._backend = ""
        self.rule_steps: list[tuple[str, str, int]] = []

    # -- profile -----------------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse(profile)
        super().init(profile)
        self._layers_init()

    def parse(self, profile: ErasureCodeProfile) -> None:
        # inner layers inherit the compute backend unless their own
        # profile overrides it (clay does the same)
        self._backend = profile.get("backend", "")
        self._parse_kml(profile)
        mapping = profile.get("mapping")
        if not mapping:
            raise ErasureCodeError("could not find 'mapping' in profile")
        self.mapping = mapping
        layers_str = profile.get("layers")
        if not layers_str:
            raise ErasureCodeError("could not find 'layers' in profile")
        self._layers_parse(layers_str)
        self._sanity_checks(layers_str)
        # base-class chunk remap from the same mapping string
        super().parse(profile)
        self.k = self.mapping.count("D")
        self.m = len(self.mapping) - self.k
        self.rule_failure_domain = to_string(
            "crush-failure-domain", profile, "host"
        )
        steps = profile.get("crush-steps")
        if steps:
            parsed = json.loads(steps)
            self.rule_steps = [
                (op, str(typ), int(n)) for op, typ, n in parsed
            ]
        elif not self.rule_steps:
            self.rule_steps = [
                ("chooseleaf", self.rule_failure_domain, 0)
            ]

    def _parse_kml(self, profile: ErasureCodeProfile) -> None:
        """Generate mapping/layers from k/m/l (parse_kml,
        ErasureCodeLrc.cc:293-397)."""
        D = self.DEFAULT_KML
        try:
            k = int(profile.get("k", D))
            m = int(profile.get("m", D))
            lp = int(profile.get("l", D))
        except (TypeError, ValueError) as e:
            raise ErasureCodeError(f"k/m/l must be integers: {e}")
        if k == D and m == D and lp == D:
            return
        if D in (k, m, lp):
            raise ErasureCodeError(
                "all of k, m, l must be set or none of them"
            )
        for generated in ("mapping", "layers", "crush-steps"):
            if generated in profile:
                raise ErasureCodeError(
                    f"the {generated} parameter cannot be set when "
                    "k, m, l are set"
                )
        if lp == 0 or (k + m) % lp:
            raise ErasureCodeError("k + m must be a multiple of l")
        groups = (k + m) // lp
        if k % groups:
            raise ErasureCodeError("k must be a multiple of (k + m) / l")
        if m % groups:
            raise ErasureCodeError("m must be a multiple of (k + m) / l")
        kg, mg = k // groups, m // groups
        profile["mapping"] = ("D" * kg + "_" * mg + "_") * groups
        layers = []
        layers.append([("D" * kg + "c" * mg + "_") * groups, ""])
        for i in range(groups):
            row = ""
            for j in range(groups):
                row += ("D" * lp + "c") if i == j else "_" * (lp + 1)
            layers.append([row, ""])
        profile["layers"] = json.dumps(layers)
        locality = profile.get("crush-locality", "")
        failure_domain = profile.get("crush-failure-domain", "host")
        if locality:
            self.rule_steps = [
                ("choose", locality, groups),
                ("chooseleaf", failure_domain, lp + 1),
            ]
        elif failure_domain:
            self.rule_steps = [("chooseleaf", failure_domain, 0)]

    def _layers_parse(self, description: str) -> None:
        try:
            desc = json.loads(description)
        except json.JSONDecodeError as e:
            raise ErasureCodeError(
                f"failed to parse layers='{description}': {e}"
            )
        if not isinstance(desc, list):
            raise ErasureCodeError("layers must be a JSON array")
        for position, entry in enumerate(desc):
            if not isinstance(entry, list) or not entry:
                raise ErasureCodeError(
                    f"layers[{position}] must be a non-empty JSON array"
                )
            chunks_map = entry[0]
            if not isinstance(chunks_map, str):
                raise ErasureCodeError(
                    f"layers[{position}][0] must be a string"
                )
            prof = ErasureCodeProfile()
            if len(entry) > 1:
                spec = entry[1]
                if isinstance(spec, dict):
                    prof.update({k: str(v) for k, v in spec.items()})
                elif isinstance(spec, str):
                    if spec.strip():
                        obj = json.loads(spec)
                        prof.update({k: str(v) for k, v in obj.items()})
                else:
                    raise ErasureCodeError(
                        f"layers[{position}][1] must be a string or object"
                    )
            self.layers.append(Layer(chunks_map, prof))

    def _sanity_checks(self, description: str) -> None:
        if not self.layers:
            raise ErasureCodeError("layers parameter needs at least one layer")
        n = len(self.mapping)
        for layer in self.layers:
            if len(layer.chunks_map) != n:
                raise ErasureCodeError(
                    f"layer '{layer.chunks_map}' must be {n} characters "
                    f"long like the mapping"
                )

    def _layers_init(self) -> None:
        from .registry import instance

        for layer in self.layers:
            prof = layer.profile
            prof.setdefault("k", str(len(layer.data)))
            prof.setdefault("m", str(len(layer.coding)))
            prof.setdefault("plugin", "jerasure")
            prof.setdefault("technique", "reed_sol_van")
            if self._backend:
                prof.setdefault("backend", self._backend)
            layer.erasure_code = instance().factory(prof["plugin"], prof)

    # -- geometry ----------------------------------------------------------
    def get_chunk_size(self, object_size: int) -> int:
        return self.layers[0].erasure_code.get_chunk_size(object_size)

    # -- encode ------------------------------------------------------------
    def encode_chunks(self, want_to_encode, encoded) -> None:
        top = len(self.layers)
        for layer in reversed(self.layers):
            top -= 1
            if set(want_to_encode) <= layer.chunks_as_set:
                break
        for layer in self.layers[top:]:
            layer_encoded = {
                j: encoded[c] for j, c in enumerate(layer.chunks)
            }
            layer_want = {
                j
                for j, c in enumerate(layer.chunks)
                if c in want_to_encode
            }
            layer.erasure_code.encode_chunks(layer_want, layer_encoded)
            for j, c in enumerate(layer.chunks):
                encoded[c] = layer_encoded[j]

    # -- decode ------------------------------------------------------------
    def decode_chunks(self, want_to_read, chunks, decoded) -> None:
        n = self.get_chunk_count()
        erasures = {i for i in range(n) if i not in chunks}
        want_err = set(want_to_read) & erasures
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures
            coding_count = layer.erasure_code.get_coding_chunk_count()
            if not layer_erasures or len(layer_erasures) > coding_count:
                continue
            layer_chunks = {}
            layer_decoded = {}
            layer_want = set()
            for j, c in enumerate(layer.chunks):
                if c not in erasures:
                    layer_chunks[j] = decoded[c]
                if c in want_to_read:
                    layer_want.add(j)
                layer_decoded[j] = decoded[c]
            layer.erasure_code.decode_chunks(
                layer_want, layer_chunks, layer_decoded
            )
            for j, c in enumerate(layer.chunks):
                decoded[c] = layer_decoded[j]
                erasures.discard(c)
            want_err = erasures & set(want_to_read)
            if not want_err:
                break
        if want_err:
            raise ErasureCodeError(
                f"unable to read chunks {sorted(want_err)} (-EIO)"
            )

    # -- minimum -----------------------------------------------------------
    def _minimum_to_decode(self, want_to_read, available):
        n = self.get_chunk_count()
        erasures_total = {i for i in range(n) if i not in available}
        erasures_not_recovered = set(erasures_total)
        erasures_want = erasures_total & set(want_to_read)

        if not erasures_want:
            return set(want_to_read)

        minimum: set[int] = set()
        for layer in reversed(self.layers):
            layer_want = set(want_to_read) & layer.chunks_as_set
            if not layer_want:
                continue
            layer_erasures = layer_want & erasures_want
            if not layer_erasures:
                layer_minimum = layer_want
            else:
                erasures = layer.chunks_as_set & erasures_not_recovered
                if (
                    len(erasures)
                    > layer.erasure_code.get_coding_chunk_count()
                ):
                    continue  # hope an upper layer does better
                layer_minimum = (
                    layer.chunks_as_set - erasures_not_recovered
                )
                erasures_not_recovered -= erasures
                erasures_want -= erasures
            minimum |= layer_minimum
        if not erasures_want:
            minimum |= set(want_to_read)
            minimum -= erasures_total
            return minimum

        # case 3: recover everything possible to help upper layers
        erasures_total = {i for i in range(n) if i not in available}
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures_total
            if not layer_erasures:
                continue
            if (
                len(layer_erasures)
                <= layer.erasure_code.get_coding_chunk_count()
            ):
                erasures_total -= layer_erasures
        if not erasures_total:
            return set(available)
        raise ErasureCodeError(
            f"not enough chunks in {sorted(available)} to read "
            f"{sorted(want_to_read)} (-EIO)"
        )

    # -- batched repair ----------------------------------------------------
    def decode_matrix(self, want_to_read, available):
        """The batched-repair plan (ec/stripe.decode_reconstruction
        hook): when ONE layer's local group covers every wanted chunk
        and its erasures fit that layer's coding count, the repair is
        the inner matrix code's solve over k_local ≪ k survivors —
        LRC's locality carried onto the coalesced device dispatch.
        Returns (rows, survivors, w, backend) in GLOBAL positions;
        raises ErasureCodeError when no single matrix layer solves it
        (the caller falls back to the layered per-object decode)."""
        from .stripe import _matrix_fast_path, reconstruction_rows

        want = set(want_to_read)
        available = set(available)
        for layer in reversed(self.layers):
            if not want <= layer.chunks_as_set:
                continue
            inner = layer.erasure_code
            avail_local = {
                j
                for j, c in enumerate(layer.chunks)
                if c in available
            }
            if len(layer.chunks) - len(avail_local) > (
                inner.get_coding_chunk_count()
            ):
                continue
            matrix, backend, ok = _matrix_fast_path(
                inner, "decode_stripes_batch"
            )
            if not ok:
                continue
            k_l, w = inner.get_data_chunk_count(), inner.w
            # the SAME row composition the flat families use
            # (stripe.reconstruction_rows), just run in layer-local
            # indices — then the rows re-order to the GLOBAL sorted
            # want (layer.chunks need not be globally monotonic) and
            # the survivors translate back to global positions
            want_local = {layer.chunks.index(p) for p in want}
            rows_local, surv_local = reconstruction_rows(
                matrix, want_local, avail_local, k_l, w
            )
            order = sorted(want_local)
            rows = [
                rows_local[order.index(layer.chunks.index(p))]
                for p in sorted(want)
            ]
            return (
                np.array(rows, dtype=np.int64).reshape(
                    len(rows), k_l
                ),
                [layer.chunks[s] for s in surv_local],
                w,
                backend,
            )
        raise ErasureCodeError(
            f"no single layer rebuilds {sorted(want)} from "
            f"{sorted(available)} as matrix math"
        )

    # -- crush -------------------------------------------------------------
    def create_rule(self, name: str, crush, ss=None) -> int:
        """Custom layered rule from rule_steps (ErasureCodeLrc.cc
        create_rule: take root, then one choose step per entry)."""
        from ..crush.types import (
            CRUSH_RULE_CHOOSELEAF_INDEP,
            CRUSH_RULE_CHOOSE_INDEP,
            CRUSH_RULE_EMIT,
            CRUSH_RULE_SET_CHOOSELEAF_TRIES,
            CRUSH_RULE_SET_CHOOSE_TRIES,
            CRUSH_RULE_TAKE,
            Rule,
            RuleStep,
        )

        root = crush._name_to_item(self.rule_root)
        steps = [
            RuleStep(CRUSH_RULE_SET_CHOOSELEAF_TRIES, 5),
            RuleStep(CRUSH_RULE_SET_CHOOSE_TRIES, 100),
            RuleStep(CRUSH_RULE_TAKE, root),
        ]
        for op, typ, n in self.rule_steps:
            type_id = crush._type_id(typ) if typ else 0
            steps.append(
                RuleStep(
                    CRUSH_RULE_CHOOSE_INDEP
                    if op == "choose"
                    else CRUSH_RULE_CHOOSELEAF_INDEP,
                    n,
                    type_id,
                )
            )
        steps.append(RuleStep(CRUSH_RULE_EMIT))
        ruleno = crush.add_rule(Rule(steps=steps, type=3))
        crush.rule_names[ruleno] = name
        return ruleno


@register("lrc")
class ErasureCodePluginLrc(ErasureCodePlugin):
    def make(self, profile: ErasureCodeProfile):
        return ErasureCodeLrc()
