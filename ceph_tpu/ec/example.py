"""Example XOR code — k=2, m=1 (src/test/erasure-code/ErasureCodeExample.h).

The trivial parity code the reference ships as plugin documentation and
as the registry's test subject; kept here for the same two purposes.
"""

from __future__ import annotations

import numpy as np

from .interface import ErasureCode, ErasureCodeError, ErasureCodeProfile
from .registry import ErasureCodePlugin, register


class ErasureCodeExample(ErasureCode):
    def __init__(self):
        super().__init__()
        self.k = 2
        self.m = 1

    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse(profile)
        super().init(profile)

    def get_chunk_size(self, object_size: int) -> int:
        return (object_size + self.k - 1) // self.k

    def encode_chunks(self, want_to_encode, encoded) -> None:
        a = encoded[self.chunk_index(0)]
        b = encoded[self.chunk_index(1)]
        np.bitwise_xor(a, b, out=encoded[self.chunk_index(2)])

    def decode_chunks(self, want_to_read, chunks, decoded) -> None:
        missing = [
            i for i in range(3) if self.chunk_index(i) not in chunks
        ]
        if len(missing) > 1:
            raise ErasureCodeError(
                f"{len(missing)} erasures exceed m=1 (-EIO)"
            )
        if not missing:
            return
        others = [
            decoded[self.chunk_index(i)] for i in range(3) if i != missing[0]
        ]
        np.bitwise_xor(
            others[0], others[1], out=decoded[self.chunk_index(missing[0])]
        )


@register("example")
class ErasureCodePluginExample(ErasureCodePlugin):
    def make(self, profile: ErasureCodeProfile):
        return ErasureCodeExample()
