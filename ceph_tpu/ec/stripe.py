"""Stripe layer — the batching seam (src/osd/ECUtil.{h,cc}).

``StripeInfo`` is the stripe_width/chunk_size offset algebra
(ECUtil.h:27-100).  ``encode``/``decode`` replace the reference's
per-stripe plugin-call loop (ECUtil.cc:123-162, :12-48) with ONE
batched device call across all stripes for matrix code families — the
hoisted seam SURVEY.md §3.1 identifies — falling back to the per-stripe
loop for layered codes.  ``HashInfo`` keeps the cumulative per-shard
crc32c persisted as the hinfo xattr (ECUtil.cc:164-248).
"""

from __future__ import annotations

import numpy as np

from ..native import ceph_crc32c
from .interface import ErasureCodeError


class StripeInfo:
    """stripe_width = k * chunk_size; logical↔chunk offset algebra."""

    def __init__(self, k: int, stripe_width: int):
        if stripe_width % k:
            raise ErasureCodeError(
                f"stripe_width {stripe_width} not divisible by k={k}"
            )
        self.stripe_width = stripe_width
        self.chunk_size = stripe_width // k

    def logical_aligned(self, offset: int) -> bool:
        return offset % self.stripe_width == 0

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return (
            (offset + self.stripe_width - 1) // self.stripe_width
        ) * self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        rem = offset % self.stripe_width
        return offset + (self.stripe_width - rem) if rem else offset

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0
        return (offset // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0
        return (offset // self.chunk_size) * self.stripe_width

    def offset_len_to_stripe_bounds(
        self, offset: int, length: int
    ) -> tuple[int, int]:
        start = self.logical_to_prev_stripe_offset(offset)
        end = self.logical_to_next_stripe_offset(offset + length)
        return start, end - start


def _kstats():
    """Lazy: ceph_tpu.ops pulls in the device runtime and registers
    the jax backend through ceph_tpu.ec — importing it at module
    scope here would be circular."""
    from ..ops.kernel_stats import kernel_stats

    return kernel_stats()


def _matrix_fast_path(ec, needs: str):
    """The ONE eligibility gate for the batched matrix device path
    (shared by encode and encode_batch so the two can never drift):
    returns (matrix, backend, ok) where ok means the code family's
    whole-word matrix math is safe to batch AND the backend has the
    ``needs`` entry point.  Bitmatrix techniques
    (cauchy/liberation/blaum_roth) carry a .matrix too, but encode
    through XOR schedules over packet planes — the word-wise matrix
    path would corrupt them; chunk remapping likewise bails."""
    matrix = getattr(ec, "matrix", None)
    backend = getattr(ec, "backend", None)
    ok = (
        matrix is not None
        and getattr(ec, "bitmatrix", None) is None
        and backend is not None
        and hasattr(backend, needs)
        and not ec.get_chunk_mapping()
    )
    return matrix, backend, ok


def _assemble_shards(
    stripes: np.ndarray, coding: np.ndarray, k: int, n: int, want=None
) -> dict[int, np.ndarray]:
    """(B, k, chunk) data stripes + (B, m, chunk) coding → the
    per-shard concatenated-chunk dict — the ONE layout assembly both
    encode and encode_batch share (byte identity between the two
    rests on there being a single copy of this)."""
    out: dict[int, np.ndarray] = {}
    for i in range(k):
        if want is None or i in want:
            out[i] = np.ascontiguousarray(
                stripes[:, i, :]
            ).reshape(-1)
    for j in range(n - k):
        if want is None or k + j in want:
            out[k + j] = np.ascontiguousarray(
                coding[:, j, :]
            ).reshape(-1)
    return out


def encode(
    sinfo: StripeInfo, ec, data: bytes | np.ndarray, want=None
) -> dict[int, np.ndarray]:
    """All stripes of ``data`` → per-shard concatenated chunks.

    Matrix code families take the batched path: (B, k, chunk) in one
    device call; others run the reference's per-stripe loop.  Either
    way the call lands in the ``l_tpu_ec_encode_*`` kernel counters
    (calls, bytes in/out, sync-bounded latency)."""
    buf = np.frombuffer(bytes(data), dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)
    ) else np.ascontiguousarray(data, dtype=np.uint8).ravel()
    if len(buf) % sinfo.stripe_width:
        raise ErasureCodeError(
            f"logical size {len(buf)} not stripe aligned"
        )
    n = ec.get_chunk_count()
    k = ec.get_data_chunk_count()
    if want is None:
        want = set(range(n))
    nstripes = len(buf) // sinfo.stripe_width
    if nstripes == 0:
        return {}

    with _kstats().timed("ec_encode", bytes_in=buf.nbytes) as kt:
        matrix, backend, ok = _matrix_fast_path(ec, "matrix_stripes")
        if ok:
            stripes = buf.reshape(nstripes, k, sinfo.chunk_size)
            coding = backend.matrix_stripes(matrix, stripes, ec.w)
            out = _assemble_shards(stripes, coding, k, n, want)
        else:
            # layered/bitmatrix per-stripe loop: one host-path
            # flight-recorder entry for the whole object (the inner
            # ec.encode calls record nothing themselves)
            from ..ops.profiler import dispatch_profiler

            bname = (
                getattr(getattr(ec, "backend", None), "name", None)
                or "cpu"
            )
            with dispatch_profiler().dispatch(
                "ec_encode", backend=bname
            ) as dp:
                dp.set_ops(1)
                dp.set_stripes(nstripes)
                dp.add_bytes_in(buf.nbytes)
                parts = {i: [] for i in range(n)}
                for s in range(nstripes):
                    stripe = buf[
                        s * sinfo.stripe_width : (s + 1) * sinfo.stripe_width
                    ]
                    encoded = ec.encode(set(range(n)), stripe)
                    for i, chunk in encoded.items():
                        parts[i].append(chunk)
                out = {
                    i: np.concatenate(p)
                    for i, p in parts.items()
                    if i in want
                }
        kt.bytes_out = sum(v.nbytes for v in out.values())
        return out


def encode_batch(
    sinfo: StripeInfo, ec, buffers
) -> list[dict[int, np.ndarray]]:
    """Coalesced multi-object encode: every buffer's stripes ride ONE
    pipelined device pass (``matrix_stripes_batch`` — async
    double-buffered transfers, sync at the end) instead of one
    dispatch per object.  Byte-identical to per-buffer :func:`encode`
    by construction (same per-stripe math), proven in
    tests/test_residency.py.  Falls back to the per-buffer loop for
    layered/bitmatrix codes or single-object batches.

    Each coalesced dispatch counts in
    ``l_tpu_batch_encode_{dispatches,ops_per_dispatch}``.
    """
    bufs = [
        np.frombuffer(bytes(b), dtype=np.uint8)
        if isinstance(b, (bytes, bytearray, memoryview))
        else np.ascontiguousarray(b, dtype=np.uint8).ravel()
        for b in buffers
    ]
    for buf in bufs:
        if len(buf) % sinfo.stripe_width:
            raise ErasureCodeError(
                f"logical size {len(buf)} not stripe aligned"
            )
    n = ec.get_chunk_count()
    k = ec.get_data_chunk_count()
    matrix, backend, ok = _matrix_fast_path(
        ec, "matrix_stripes_batch"
    )
    if not ok or len(bufs) < 2:
        return [encode(sinfo, ec, buf) for buf in bufs]

    stripe_arrays = [
        buf.reshape(
            len(buf) // sinfo.stripe_width, k, sinfo.chunk_size
        )
        for buf in bufs
    ]
    ks = _kstats()
    from ..ops.residency import ensure_counters

    ensure_counters(ks)
    total = sum(buf.nbytes for buf in bufs)
    with ks.timed("ec_encode", bytes_in=total) as kt:
        codings = backend.matrix_stripes_batch(
            matrix, stripe_arrays, ec.w
        )
        ks.perf.inc("l_tpu_batch_encode_dispatches")
        ks.perf.inc("l_tpu_batch_encode_ops_per_dispatch", len(bufs))
        out: list[dict[int, np.ndarray]] = []
        for stripes, coding in zip(stripe_arrays, codings):
            if stripes.shape[0] == 0:
                out.append({})
                continue
            out.append(_assemble_shards(stripes, coding, k, n))
        kt.bytes_out = sum(
            v.nbytes for shards in out for v in shards.values()
        )
    return out


def _as_row(x) -> np.ndarray:
    """1-D uint8 view of a survivor payload: the ONE coercion helper
    (ec/backend._host_row) shared by the stripe seam and both compute
    backends — DeviceBuf tokens fetch host-side, bytes-likes go
    through frombuffer."""
    from .backend import _host_row

    return _host_row(x)


def survivor_basis(
    matrix: np.ndarray, erasures, k: int, w: int
) -> tuple[np.ndarray, list[int]]:
    """The survivor basis B⁻¹ (k × k over GF(2^w)) and the k survivor
    ids it spans: B⁻¹ @ survivor_chunks = data_chunks.  A thin
    error-translating wrapper over :func:`gf.survivor_basis` — the
    SAME implementation the per-op decode's make_decoding_matrix
    builds on, so the batched and per-op paths can never pick
    different systems."""
    from .. import gf

    try:
        return gf.survivor_basis(matrix, erasures, k, w)
    except (ValueError, np.linalg.LinAlgError) as e:
        raise ErasureCodeError(f"{e} (-EIO)")


def reconstruction_rows(
    matrix: np.ndarray, want, available, k: int, w: int
) -> tuple[np.ndarray, list[int]]:
    """ONE GF(2^w) matrix that rebuilds every wanted chunk (data or
    coding) straight from the k chosen survivors — the whole-PG repair
    collapses to a single matrix × survivor-regions dispatch.  Wanted
    data chunks take their B⁻¹ row; wanted coding chunks compose the
    generator row with B⁻¹ (exact field algebra, so the result is
    byte-identical to decode-data-then-re-encode).  Returns
    (rows[len(want), k], survivors)."""
    from .. import gf

    n = k + matrix.shape[0]
    erasures = sorted(set(range(n)) - set(available))
    binv, survivors = survivor_basis(matrix, erasures, k, w)
    rows = []
    for p in sorted(want):
        if p < k:
            rows.append(binv[p])
        else:
            rows.append(
                gf.matrix_multiply(
                    matrix[p - k : p - k + 1], binv, w
                )[0]
            )
    return np.array(rows, dtype=np.int64).reshape(len(rows), k), survivors


def decode_reconstruction(ec, want, available):
    """The decode analog of :func:`_matrix_fast_path`: a
    (rows, survivors, w, backend) plan that rebuilds ``want`` from
    ``available`` in one batched device dispatch, or None when the
    code family cannot express its repair as whole-word matrix math
    (bitmatrix/layered codes without a ``decode_matrix`` hook, chunk
    remapping, unsolvable systems)."""
    hook = getattr(ec, "decode_matrix", None)
    if hook is not None:
        try:
            return hook(set(want), set(available))
        except ErasureCodeError:
            return None
    matrix, backend, ok = _matrix_fast_path(
        ec, "decode_stripes_batch"
    )
    if not ok:
        return None
    try:
        rows, survivors = reconstruction_rows(
            matrix, want, available, ec.get_data_chunk_count(), ec.w
        )
    except ErasureCodeError:
        return None
    return rows, survivors, ec.w, backend


def _decode_one(ec, shards: dict[int, np.ndarray], want) -> dict:
    """Per-object decode-from-survivors — the reference per-op repair
    path (ErasureCode::_decode) and the oracle the batched dispatch
    must match byte for byte."""
    chunks = {i: _as_row(v) for i, v in shards.items()}
    decoded = ec._decode(set(want), chunks)
    return {
        p: np.ascontiguousarray(decoded[p], dtype=np.uint8)
        for p in sorted(want)
    }


def decode_batch(
    sinfo: StripeInfo, ec, shard_sets, want
) -> list[dict]:
    """Coalesced decode-from-survivors: rebuild the SAME missing
    positions (``want`` — the dead OSD's shards) for MANY objects in
    one pipelined device pass, the repair-side twin of
    :func:`encode_batch` (ROADMAP open item 2).

    ``shard_sets`` is one dict per object of survivor shard payloads
    ({position: bytes | ndarray | DeviceBuf}); resident DeviceBufs
    ride the dispatch without re-uploading (the residency cache paid
    the link already), host payloads upload once, double-buffered
    against compute.  Returns one {position: reconstructed} dict per
    object — DeviceBuf tokens (device-born, zero extra transfer to
    register resident) when the device backend ran, numpy arrays on
    the host fallback.  Byte-identical to the per-object
    ``ec._decode`` repair by construction; ANY batched-path failure
    degrades to it.

    Each coalesced dispatch counts in
    ``l_tpu_batch_decode_{dispatches,ops_per_dispatch}``.
    """
    want = sorted(set(want))
    out: list[dict | None] = [None] * len(shard_sets)
    groups: dict[frozenset, list[int]] = {}
    for i, shards in enumerate(shard_sets):
        groups.setdefault(frozenset(shards), []).append(i)
    ks = _kstats()
    from ..ops.residency import ensure_counters

    ensure_counters(ks)
    cs = sinfo.chunk_size
    for key, idxs in groups.items():
        plan = (
            decode_reconstruction(ec, want, key)
            if len(idxs) >= 2 and not (set(want) & key)
            else None
        )
        batched = False
        if plan is not None:
            rows, survivors, w, backend = plan
            try:
                row_sets = []
                total = 0
                for i in idxs:
                    rows_i = [shard_sets[i][s] for s in survivors]
                    lengths = {len(r) for r in rows_i}
                    if len(lengths) != 1:
                        raise ErasureCodeError(
                            "survivor shards must be equal length"
                        )
                    (length,) = lengths
                    if length % cs or length == 0:
                        raise ErasureCodeError(
                            "shard length not chunk aligned"
                        )
                    total += length * len(rows_i)
                    row_sets.append(rows_i)
                with ks.timed("ec_decode", bytes_in=total) as kt:
                    outs = backend.decode_stripes_batch(
                        rows, row_sets, w, cs
                    )
                    kt.bytes_out = sum(
                        int(np.prod(o.shape)) for o in outs
                    )
                ks.perf.inc("l_tpu_batch_decode_dispatches")
                ks.perf.inc(
                    "l_tpu_batch_decode_ops_per_dispatch", len(idxs)
                )
                for i, rec in zip(idxs, outs):
                    out[i] = _wrap_decoded(rec, want)
                batched = True
            except Exception:  # noqa: BLE001 — batching is an
                # optimization: any device/shape/solve failure
                # degrades this group to the per-object repair path,
                # never drops or corrupts an object
                batched = False
        if not batched:
            # per-object repair loop: one host-path flight-recorder
            # entry per degraded group (the inner ec._decode calls
            # record nothing themselves)
            from ..ops.profiler import dispatch_profiler

            bname = (
                getattr(getattr(ec, "backend", None), "name", None)
                or "cpu"
            )
            with dispatch_profiler().dispatch(
                "ec_decode", backend=bname
            ) as dp:
                dp.set_ops(len(idxs))
                for i in idxs:
                    nbytes = sum(
                        len(v) for v in shard_sets[i].values()
                    )
                    dp.add_bytes_in(nbytes)
                    with ks.timed("ec_decode", bytes_in=nbytes) as kt:
                        out[i] = _decode_one(ec, shard_sets[i], want)
                        kt.bytes_out = sum(
                            len(v) for v in out[i].values()
                        )
    return out


def _wrap_decoded(rec, want) -> dict:
    """One object's (nstripes, len(want), chunk) reconstruction →
    {position: payload}.  Device arrays wrap as device-born
    DeviceBufs (the push/write path fetches host bytes at most once;
    registering them resident costs zero extra transfer); numpy
    results stay numpy."""
    if isinstance(rec, np.ndarray):
        return {
            p: np.ascontiguousarray(rec[:, j, :]).reshape(-1)
            for j, p in enumerate(want)
        }
    from ..ops.residency import DeviceBuf

    return {
        p: DeviceBuf(dev=rec[:, j, :].reshape(-1))
        for j, p in enumerate(want)
    }


def decode_concat(
    sinfo: StripeInfo, ec, shards: dict[int, np.ndarray]
) -> np.ndarray:
    """Concat-decode every stripe back to logical bytes
    (ECUtil.cc:12-48)."""
    lengths = {len(v) for v in shards.values()}
    if len(lengths) != 1:
        raise ErasureCodeError("shards must be equal length")
    (shard_len,) = lengths
    if shard_len % sinfo.chunk_size:
        raise ErasureCodeError("shard length not chunk aligned")
    nstripes = shard_len // sinfo.chunk_size
    views = {
        i: np.frombuffer(bytes(v), dtype=np.uint8)
        if isinstance(v, (bytes, bytearray, memoryview))
        else np.ascontiguousarray(v, dtype=np.uint8)
        for i, v in shards.items()
    }
    with _kstats().timed(
        "ec_decode", bytes_in=sum(v.nbytes for v in views.values())
    ) as kt:
        out = []
        for s in range(nstripes):
            chunks = {
                i: v[s * sinfo.chunk_size : (s + 1) * sinfo.chunk_size]
                for i, v in views.items()
            }
            out.append(ec.decode_concat(chunks))
        res = np.concatenate(out)
        kt.bytes_out = res.nbytes
        return res


class HashInfo:
    """Cumulative per-shard crc32c, persisted as the hinfo_key xattr
    (ECUtil.cc:164-248); seeds start at -1 like the reference."""

    def __init__(self, num_chunks: int):
        self.cumulative_shard_hashes = [0xFFFFFFFF] * num_chunks
        self.total_chunk_size = 0

    def append(self, old_size: int, to_append: dict[int, np.ndarray]):
        assert old_size == self.total_chunk_size
        size = len(next(iter(to_append.values())))
        for i, chunk in to_append.items():
            assert len(chunk) == size
            self.cumulative_shard_hashes[i] = ceph_crc32c(
                self.cumulative_shard_hashes[i], bytes(chunk)
            )
        self.total_chunk_size += size

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]

    def clear(self):
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [
            0xFFFFFFFF for _ in self.cumulative_shard_hashes
        ]


def rmw_range(
    sinfo: StripeInfo, offset: int, length: int, old_size: int
) -> tuple[int, int, set[int]]:
    """The WritePlan head/tail analysis (ECBackend.cc:1858 start_rmw):
    for a partial overwrite of [offset, offset+length), returns
    (first_stripe, end_stripe, stripes_to_read) — only the partially
    covered head/tail stripes that hold pre-existing bytes need
    reading; fully-covered and beyond-EOF stripes encode fresh."""
    sw = sinfo.stripe_width
    start, span = sinfo.offset_len_to_stripe_bounds(offset, length)
    first, end = start // sw, (start + span) // sw
    old_stripes = sinfo.logical_to_next_stripe_offset(old_size) // sw
    need: set[int] = set()
    if offset % sw and first < old_stripes:
        need.add(first)
    if (offset + length) % sw and end - 1 < old_stripes:
        need.add(end - 1)
    return first, end, need


def rmw_encode(
    sinfo: StripeInfo,
    ec,
    offset: int,
    data: bytes,
    old_size: int,
    read_stripes,
) -> tuple[int, int, np.ndarray, dict[int, np.ndarray]]:
    """Shared stripe-granular RMW assembly used by BOTH the store
    pipeline (ECStore.write) and the daemon's EC write path
    (osd/ec_pg.rmw_write_txns): read the needed stripes through the
    caller's ``read_stripes(sorted_stripe_list) -> {stripe: bytes}``
    (extent-cache-aware in the store, sub-op reads in the daemon),
    overlay the new bytes, and re-encode just the covered range.
    Returns (first_stripe, end_stripe, range_buffer, shards)."""
    data = bytes(data)
    sw = sinfo.stripe_width
    first, end, need = rmw_range(sinfo, offset, len(data), old_size)
    existing = read_stripes(sorted(need))
    buf = np.zeros((end - first) * sw, dtype=np.uint8)
    for s, stripe in existing.items():
        buf[(s - first) * sw : (s - first + 1) * sw] = np.frombuffer(
            bytes(stripe), dtype=np.uint8
        )
    lo = offset - first * sw
    buf[lo : lo + len(data)] = np.frombuffer(data, dtype=np.uint8)
    shards = encode(sinfo, ec, buf)
    return first, end, buf, shards
