"""SHEC — shingled erasure code (src/erasure-code/shec/).

k data + m parity chunks where each parity covers a sliding window of
the data; c is the durability floor.  The coding matrix is a
Vandermonde RS matrix with per-row windows zeroed out
(shec_reedsolomon_coding_matrix, ErasureCodeShec.cc:461-524); the
"multiple" technique splits the parities into two shingle stacks chosen
by the recovery-efficiency heuristic (shec_calc_recovery_efficiency1,
:420-459).  Decode searches all parity subsets for the smallest
invertible recovery system (shec_make_decoding_matrix, :526-760) and
caches the result per (want, avails) signature like
ErasureCodeShecTableCache.

Deviation noted for parity review: the reference validates candidate
recovery systems with a determinant computed in GF(2^8) regardless of w
(determinant.c); here the check is invertibility in GF(2^w) —
equivalent for the default and overwhelmingly common w=8.
"""

from __future__ import annotations

import numpy as np

from .. import gf
from .backend import get_backend
from .interface import (
    ErasureCode,
    ErasureCodeError,
    ErasureCodeProfile,
    to_int,
    to_string,
)
from .registry import ErasureCodePlugin, register

MULTIPLE, SINGLE = 0, 1


def _recovery_efficiency1(k, m1, m2, c1, c2) -> float:
    """shec_calc_recovery_efficiency1 (ErasureCodeShec.cc:420-459)."""
    if m1 < c1 or m2 < c2:
        return -1.0
    if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
        return -1.0
    r_eff_k = [100000000] * k
    r_e1 = 0.0
    for m_i, c_i in ((m1, c1), (m2, c2)):
        for rr in range(m_i):
            start = ((rr * k) // m_i) % k
            end = (((rr + c_i) * k) // m_i) % k
            width = ((rr + c_i) * k) // m_i - (rr * k) // m_i
            cc = start
            first = True
            while first or cc != end:
                first = False
                r_eff_k[cc] = min(r_eff_k[cc], width)
                cc = (cc + 1) % k
            r_e1 += width
    r_e1 += sum(r_eff_k)
    return r_e1 / (k + m1 + m2)


class ErasureCodeShec(ErasureCode):
    DEFAULT_K, DEFAULT_M, DEFAULT_C, DEFAULT_W = 4, 3, 2, 8

    def __init__(self, technique=MULTIPLE):
        super().__init__()
        self.c = 0
        self.w = 8
        self.technique = technique
        self.matrix: np.ndarray | None = None
        self.backend = None
        self._decode_cache: dict = {}

    # -- profile -----------------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse(profile)
        self.prepare()
        super().init(profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        has = [key in profile for key in ("k", "m", "c")]
        if not any(has):
            self.k, self.m, self.c = (
                self.DEFAULT_K, self.DEFAULT_M, self.DEFAULT_C
            )
        elif not all(has):
            raise ErasureCodeError("(k, m, c) must all be chosen")
        else:
            self.k = to_int("k", profile, self.DEFAULT_K)
            self.m = to_int("m", profile, self.DEFAULT_M)
            self.c = to_int("c", profile, self.DEFAULT_C)
            if self.k <= 0 or self.m <= 0 or self.c <= 0:
                raise ErasureCodeError("k, m, c must be positive")
            if self.m < self.c:
                raise ErasureCodeError(f"c={self.c} must be <= m={self.m}")
            if self.k > 12:
                raise ErasureCodeError(f"k={self.k} must be <= 12")
            if self.k + self.m > 20:
                raise ErasureCodeError(f"k+m={self.k + self.m} must be <= 20")
            if self.k < self.m:
                raise ErasureCodeError(f"m={self.m} must be <= k={self.k}")
        w = to_int("w", profile, self.DEFAULT_W)
        self.w = w if w in (8, 16, 32) else self.DEFAULT_W
        self.backend = get_backend(to_string("backend", profile, "numpy"))

    def prepare(self) -> None:
        self.matrix = self._coding_matrix(self.technique == SINGLE)

    def _coding_matrix(self, is_single: bool) -> np.ndarray:
        k, m, c = self.k, self.m, self.c
        if is_single:
            m1, c1 = 0, 0
        else:
            best = (-1, -1)
            min_r = 100.0
            for c1 in range(c // 2 + 1):
                for m1 in range(m + 1):
                    c2, m2 = c - c1, m - m1
                    if m1 < c1 or m2 < c2:
                        continue
                    if (m1 == 0) != (c1 == 0) or (m2 == 0) != (c2 == 0):
                        continue
                    r = _recovery_efficiency1(k, m1, m2, c1, c2)
                    if min_r - r > np.finfo(float).eps and r < min_r:
                        min_r = r
                        best = (c1, m1)
            c1, m1 = best
        m2, c2 = self.m - m1, self.c - c1
        matrix = gf.reed_sol_vandermonde_coding_matrix(k, m, self.w)
        for rows, cs, base in ((m1, c1, 0), (m2, c2, m1)):
            for rr in range(rows):
                end = ((rr * k) // rows) % k
                cc = (((rr + cs) * k) // rows) % k
                while cc != end:
                    matrix[base + rr, cc] = 0
                    cc = (cc + 1) % k
        return matrix

    # -- geometry ----------------------------------------------------------
    def get_alignment(self) -> int:
        return self.k * self.w * 4

    def get_chunk_size(self, object_size: int) -> int:
        alignment = self.get_alignment()
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k

    # -- encode/decode -----------------------------------------------------
    def encode_chunks(self, want_to_encode, encoded) -> None:
        data = np.stack(
            [encoded[self.chunk_index(i)] for i in range(self.k)]
        )
        coding = self.backend.matrix_regions(self.matrix, data, self.w)
        for i in range(self.m):
            np.copyto(encoded[self.chunk_index(self.k + i)], coding[i])

    def decode_chunks(self, want_to_read, chunks, decoded) -> None:
        k, m = self.k, self.m
        want = [0] * (k + m)
        avails = [0] * (k + m)
        erased_count = 0
        for i in range(k + m):
            if i in chunks:
                avails[i] = 1
            elif i in want_to_read:
                want[i] = 1
                erased_count += 1
        if erased_count == 0:
            return
        plan = self._make_decoding_matrix(False, tuple(want), tuple(avails))
        if plan is None:
            raise ErasureCodeError("cannot find recovery matrix (-EIO)")
        dec_matrix, dm_row, dm_column, _minimum = plan
        dm_size = len(dm_row)
        if dm_size:
            # sources per the remapped dm_row: < dm_size -> selected
            # data column, else parity (shec_matrix_decode)
            srcs = []
            for sid in dm_row:
                if sid < dm_size:
                    srcs.append(decoded[dm_column[sid]])
                else:
                    srcs.append(decoded[k + (sid - dm_size)])
            src = np.stack(srcs)
            rows = [
                i for i in range(dm_size) if not avails[dm_column[i]]
            ]
            if rows:
                rec = self.backend.matrix_regions(
                    dec_matrix[rows], src, self.w
                )
                for out_i, i in enumerate(rows):
                    np.copyto(decoded[dm_column[i]], rec[out_i])
        recode = [
            i for i in range(m) if want[k + i] and not avails[k + i]
        ]
        if recode:
            data = np.stack([decoded[i] for i in range(k)])
            rec = self.backend.matrix_regions(
                self.matrix[recode], data, self.w
            )
            for out_i, i in enumerate(recode):
                np.copyto(decoded[k + i], rec[out_i])

    # -- recovery-system search --------------------------------------------
    def _make_decoding_matrix(self, prepare, want_t, avails_t):
        """shec_make_decoding_matrix: smallest invertible recovery
        system over all parity subsets; returns (decoding_matrix,
        dm_row, dm_column, minimum) or None."""
        key = (want_t, avails_t)
        cached = self._decode_cache.get(key)
        if cached is not None:
            return cached
        k, m = self.k, self.m
        want = list(want_t)
        avails = list(avails_t)
        # wanted-but-missing parity pulls its window's data into want
        for i in range(m):
            if want[k + i] and not avails[k + i]:
                for j in range(k):
                    if self.matrix[i, j] > 0:
                        want[j] = 1

        mindup = k + 1
        minp = k + 1
        best_rows: list[int] | None = None
        best_cols: list[int] | None = None
        for pp in range(1 << m):
            parities = [i for i in range(m) if pp & (1 << i)]
            if len(parities) > minp:
                continue
            if any(not avails[k + p] for p in parities):
                continue
            tmprow = [0] * (k + m)
            tmpcol = [0] * k
            for i in range(k):
                if want[i] and not avails[i]:
                    tmpcol[i] = 1
            for p in parities:
                tmprow[k + p] = 1
                for j in range(k):
                    if self.matrix[p, j] != 0:
                        tmpcol[j] = 1
                        if avails[j]:
                            tmprow[j] = 1
            dup_rows = sum(tmprow)
            dup_cols = sum(tmpcol)
            if dup_rows != dup_cols:
                continue
            dup = dup_rows
            if dup == 0:
                mindup = 0
                best_rows, best_cols = [], []
                break
            if dup >= mindup:
                continue
            rows = [i for i in range(k + m) if tmprow[i]]
            cols = [j for j in range(k) if tmpcol[j]]
            tmpmat = self._system_matrix(rows, cols)
            if self._invertible(tmpmat):
                mindup = dup
                best_rows, best_cols = rows, cols
                minp = len(parities)

        if mindup == k + 1:
            return None

        minimum = [0] * (k + m)
        for r in best_rows:
            minimum[r] = 1
        for i in range(k):
            if want[i] and avails[i]:
                minimum[i] = 1
        for i in range(m):
            if want[k + i] and avails[k + i] and not minimum[k + i]:
                if any(
                    self.matrix[i, j] > 0 and not want[j]
                    for j in range(k)
                ):
                    minimum[k + i] = 1

        if mindup == 0:
            plan = (np.zeros((0, 0), dtype=np.int64), [], [], minimum)
            self._decode_cache[key] = plan
            return plan

        tmpmat = self._system_matrix(best_rows, best_cols)
        # remap rows to the compact source index space (the dm_row
        # rewrite at the end of shec_make_decoding_matrix)
        dm_row = []
        for r in best_rows:
            if r < k:
                dm_row.append(best_cols.index(r))
            else:
                dm_row.append(r - (k - mindup))
        dec = gf.matrix_invert(tmpmat, self.w)
        plan = (dec, dm_row, list(best_cols), minimum)
        if not prepare:
            self._decode_cache[key] = plan
        return plan

    def _system_matrix(self, rows, cols) -> np.ndarray:
        n = len(rows)
        mat = np.zeros((n, n), dtype=np.int64)
        for ri, r in enumerate(rows):
            for ci, c in enumerate(cols):
                if r < self.k:
                    mat[ri, ci] = 1 if r == c else 0
                else:
                    mat[ri, ci] = self.matrix[r - self.k, c]
        return mat

    def _invertible(self, mat: np.ndarray) -> bool:
        try:
            gf.matrix_invert(mat, self.w)
            return True
        except (ErasureCodeError, ValueError):
            return False

    # -- minimum -----------------------------------------------------------
    def _minimum_to_decode(self, want_to_read, available):
        k, m = self.k, self.m
        for i in want_to_read | available:
            if i < 0 or i >= k + m:
                raise ErasureCodeError(f"invalid chunk id {i} (-EINVAL)")
        want = [1 if i in want_to_read else 0 for i in range(k + m)]
        avails = [1 if i in available else 0 for i in range(k + m)]
        plan = self._make_decoding_matrix(
            True, tuple(want), tuple(avails)
        )
        if plan is None:
            raise ErasureCodeError("not enough chunks to decode (-EIO)")
        return {i for i in range(k + m) if plan[3][i] == 1}


@register("shec")
class ErasureCodePluginShec(ErasureCodePlugin):
    def make(self, profile: ErasureCodeProfile):
        technique = profile.get("technique", "multiple")
        if technique == "single":
            return ErasureCodeShec(SINGLE)
        if technique == "multiple":
            return ErasureCodeShec(MULTIPLE)
        raise ErasureCodeError(
            f"technique={technique} is not a valid coding technique: "
            "choose one of single, multiple"
        )
