"""Coding-matrix construction and linear algebra over GF(2^w).

Clean-room reimplementations of the matrix generators whose call contracts
the reference EC plugins rely on (SURVEY.md §2.1; the jerasure/gf-complete
and isa-l submodules are absent from the reference mount):

- ``reed_sol_vandermonde_coding_matrix`` — jerasure ``reed_sol_van``:
  extended Vandermonde matrix reduced to systematic form with an all-ones
  first coding row and all-ones first column (consumed by
  src/erasure-code/jerasure/ErasureCodeJerasure.cc:203 prepare()).
- ``reed_sol_r6_coding_matrix`` — jerasure RAID6 [1..1; 1,2,4,...].
- ``isa_rs_matrix`` / ``isa_cauchy_matrix`` — isa-l gf_gen_rs_matrix /
  gf_gen_cauchy1_matrix (consumed by ErasureCodeIsa.cc:385-387).
- ``cauchy_original_matrix`` / ``cauchy_good_matrix`` — jerasure cauchy
  plugin matrices (ErasureCodeJerasure.cc:259-336).
- ``matrix_invert`` — Gaussian elimination over GF(2^w), the decode path
  of every RS family (isa-l gf_invert_matrix, jerasure invert_matrix).
- ``jerasure_bitmatrix`` — w×w bit expansion of a GF matrix (the object
  cauchy/liberation XOR scheduling operates on).

All matrices are numpy int arrays shaped (m, k) holding GF elements.
"""

from __future__ import annotations

import numpy as np

from .arith import gf_div, gf_inv, gf_mul_scalar, gf_pow_scalar, region_mul


def matrix_vector_mul_region(
    matrix: np.ndarray, regions: np.ndarray, w: int = 8
) -> np.ndarray:
    """Apply a GF(2^w) matrix (m, k) to k byte regions (k, nbytes),
    producing (m, nbytes) — the semantics of jerasure_matrix_encode /
    isa-l ec_encode_data over w-bit little-endian words."""
    m, k = matrix.shape
    assert regions.shape[0] == k
    out = np.zeros((m, regions.shape[1]), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            c = int(matrix[i, j])
            if c:
                out[i] ^= region_mul(regions[j], c, w)
    return out


def _extended_vandermonde(rows: int, cols: int, w: int) -> np.ndarray:
    """Extended Vandermonde matrix: row 0 = e_0, last row = e_{cols-1},
    interior row i = [1, i, i^2, ...] in GF(2^w)."""
    if w < 30 and ((1 << w) < rows or (1 << w) < cols):
        raise ValueError(f"rows/cols too large for w={w}")
    vdm = np.zeros((rows, cols), dtype=np.int64)
    vdm[0, 0] = 1
    if rows == 1:
        return vdm
    vdm[rows - 1, cols - 1] = 1
    for i in range(1, rows - 1):
        acc = 1
        for j in range(cols):
            vdm[i, j] = acc
            acc = gf_mul_scalar(acc, i, w)
    return vdm


def _big_vandermonde_distribution(rows: int, cols: int, w: int) -> np.ndarray:
    """Reduce the extended Vandermonde matrix to a systematic distribution
    matrix: top cols×cols identity, row ``cols`` all ones, first column of
    every later row one.  Column-operation elimination, mirroring the
    jerasure reed_sol construction the reference plugins load."""
    if cols >= rows:
        raise ValueError("need rows > cols")
    dist = _extended_vandermonde(rows, cols, w)

    for i in range(1, cols):
        # find a row at or below i with a nonzero pivot in column i
        j = i
        while j < rows and dist[j, i] == 0:
            j += 1
        if j == rows:
            raise AssertionError("singular vandermonde — bad rows/w")
        if j > i:
            dist[[i, j], :] = dist[[j, i], :]
        # scale column i so the pivot is 1
        if dist[i, i] != 1:
            inv = gf_div(1, int(dist[i, i]), w)
            for r in range(rows):
                dist[r, i] = gf_mul_scalar(inv, int(dist[r, i]), w)
        # eliminate every other column of row i with column operations
        for jj in range(cols):
            e = int(dist[i, jj])
            if jj != i and e != 0:
                for r in range(rows):
                    dist[r, jj] = int(dist[r, jj]) ^ gf_mul_scalar(
                        e, int(dist[r, i]), w
                    )

    # make row ``cols`` (first coding row) all ones by scaling the coding
    # part of each column
    for j in range(cols):
        t = int(dist[cols, j])
        if t != 1:
            inv = gf_div(1, t, w)
            for r in range(cols, rows):
                dist[r, j] = gf_mul_scalar(inv, int(dist[r, j]), w)

    # make the first column of the remaining coding rows one by scaling rows
    for r in range(cols + 1, rows):
        t = int(dist[r, 0])
        if t != 1:
            inv = gf_div(1, t, w)
            for j in range(cols):
                dist[r, j] = gf_mul_scalar(int(dist[r, j]), inv, w)

    return dist


def reed_sol_vandermonde_coding_matrix(k: int, m: int, w: int) -> np.ndarray:
    """jerasure reed_sol_van coding matrix: the m coding rows (m, k)."""
    dist = _big_vandermonde_distribution(k + m, k, w)
    return dist[k:, :].copy()


def reed_sol_r6_coding_matrix(k: int, w: int) -> np.ndarray:
    """jerasure RAID6 (m=2): row0 all ones, row1 = [1, 2, 4, ... 2^j]."""
    mat = np.ones((2, k), dtype=np.int64)
    for j in range(k):
        mat[1, j] = gf_pow_scalar(2, j, w)
    return mat


def isa_rs_matrix(k: int, m: int) -> np.ndarray:
    """isa-l gf_gen_rs_matrix coding rows (w=8): row i = [g^0, g^1...] with
    g = 2^i walking powers per row (ErasureCodeIsa.cc kVandermonde)."""
    mat = np.zeros((m, k), dtype=np.int64)
    gen = 1
    for i in range(m):
        p = 1
        for j in range(k):
            mat[i, j] = p
            p = gf_mul_scalar(p, gen, 8)
        gen = gf_mul_scalar(gen, 2, 8)
    return mat


def isa_cauchy_matrix(k: int, m: int) -> np.ndarray:
    """isa-l gf_gen_cauchy1_matrix coding rows (w=8): a[i][j] = inv(i ^ j)
    for row index i in [k, k+m)."""
    mat = np.zeros((m, k), dtype=np.int64)
    for i in range(k, k + m):
        for j in range(k):
            mat[i - k, j] = gf_inv(i ^ j, 8)
    return mat


def cauchy_original_matrix(k: int, m: int, w: int) -> np.ndarray:
    """jerasure cauchy_original_coding_matrix: m[i][j] = 1/(i ^ (m+j))."""
    if w < 31 and (k + m) > (1 << w):
        raise ValueError("k+m too large for w")
    mat = np.zeros((m, k), dtype=np.int64)
    for i in range(m):
        for j in range(k):
            mat[i, j] = gf_div(1, i ^ (m + j), w)
    return mat


def cauchy_n_ones(n: int, w: int) -> int:
    """Number of ones in the w×w bitmatrix of multiply-by-n over GF(2^w)."""
    total = 0
    col = n
    for _ in range(w):
        total += bin(col).count("1")
        col = gf_mul_scalar(col, 2, w)
    return total


def cauchy_good_matrix(k: int, m: int, w: int) -> np.ndarray:
    """jerasure cauchy_good: original Cauchy matrix improved to minimize
    bitmatrix ones — divide each column by its row-0 element (making row 0
    all ones), then for each later row pick the element whose inverse,
    multiplied through the row, minimizes the row's total bitmatrix ones."""
    mat = cauchy_original_matrix(k, m, w)
    # normalize row 0 to all ones via column scaling
    for j in range(k):
        if mat[0, j] != 1:
            inv = gf_div(1, int(mat[0, j]), w)
            for i in range(m):
                mat[i, j] = gf_mul_scalar(int(mat[i, j]), inv, w)
    # improve each subsequent row
    for i in range(1, m):
        best_row = [int(x) for x in mat[i]]
        best = sum(cauchy_n_ones(x, w) for x in best_row)
        for j in range(k):
            e = int(mat[i, j])
            if e == 1:
                continue
            inv = gf_div(1, e, w)
            cand = [gf_mul_scalar(int(x), inv, w) for x in mat[i]]
            ones = sum(cauchy_n_ones(x, w) for x in cand)
            if ones < best:
                best = ones
                best_row = cand
        mat[i] = best_row
    return mat


def jerasure_bitmatrix(matrix: np.ndarray, w: int) -> np.ndarray:
    """Expand a GF(2^w) matrix (m, k) to its (m*w, k*w) GF(2) bitmatrix.

    Block (i, j) is the bit-level linear map of multiply-by-matrix[i][j]:
    column x holds the bits of matrix[i][j] * 2^x, bit l in row l — the
    layout jerasure's bitmatrix XOR scheduling consumes
    (jerasure_matrix_to_bitmatrix contract).
    """
    m, k = matrix.shape
    bm = np.zeros((m * w, k * w), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            elt = int(matrix[i, j])
            for x in range(w):
                for l in range(w):
                    bm[i * w + l, j * w + x] = (elt >> l) & 1
                elt = gf_mul_scalar(elt, 2, w)
    return bm


def matrix_multiply(a: np.ndarray, b: np.ndarray, w: int = 8) -> np.ndarray:
    """(r×n) @ (n×c) over GF(2^w)."""
    r, n = a.shape
    n2, c = b.shape
    assert n == n2
    out = np.zeros((r, c), dtype=np.int64)
    for i in range(r):
        for j in range(c):
            acc = 0
            for t in range(n):
                acc ^= gf_mul_scalar(int(a[i, t]), int(b[t, j]), w)
            out[i, j] = acc
    return out


def matrix_invert(mat: np.ndarray, w: int = 8) -> np.ndarray:
    """Invert a square matrix over GF(2^w) by Gauss-Jordan elimination."""
    mat = np.array(mat, dtype=np.int64)
    n = mat.shape[0]
    assert mat.shape == (n, n)
    inv = np.eye(n, dtype=np.int64)
    for col in range(n):
        pivot = col
        while pivot < n and mat[pivot, col] == 0:
            pivot += 1
        if pivot == n:
            raise np.linalg.LinAlgError("singular matrix over GF(2^w)")
        if pivot != col:
            mat[[col, pivot]] = mat[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        pv = gf_inv(int(mat[col, col]), w)
        for j in range(n):
            mat[col, j] = gf_mul_scalar(int(mat[col, j]), pv, w)
            inv[col, j] = gf_mul_scalar(int(inv[col, j]), pv, w)
        for r in range(n):
            e = int(mat[r, col])
            if r != col and e != 0:
                for j in range(n):
                    mat[r, j] = int(mat[r, j]) ^ gf_mul_scalar(
                        e, int(mat[col, j]), w
                    )
                    inv[r, j] = int(inv[r, j]) ^ gf_mul_scalar(
                        e, int(inv[col, j]), w
                    )
    return inv


def survivor_basis(
    coding_matrix: np.ndarray,
    erasures,
    k: int,
    w: int = 8,
) -> tuple[np.ndarray, list[int]]:
    """The survivor basis B⁻¹ (k × k over GF(2^w)) and the k survivor
    ids it spans (first k available, ascending — data-then-coding
    order): B⁻¹ @ survivor_chunks = data_chunks.  The ONE
    implementation both the per-op decode (make_decoding_matrix) and
    the batched reconstruction-matrix path (ec/stripe) build on —
    their byte identity rests on picking the SAME system."""
    m = coding_matrix.shape[0]
    erased = set(erasures)
    survivors = [i for i in range(k + m) if i not in erased][:k]
    if len(survivors) < k:
        raise ValueError("not enough surviving chunks to decode")
    # B[r] = unit row for surviving data chunk, coding row for surviving parity
    b = np.zeros((k, k), dtype=np.int64)
    for r, chunk in enumerate(survivors):
        if chunk < k:
            b[r, chunk] = 1
        else:
            b[r] = coding_matrix[chunk - k]
    return matrix_invert(b, w), survivors


def make_decoding_matrix(
    coding_matrix: np.ndarray,
    erasures: list[int],
    k: int,
    w: int = 8,
) -> tuple[np.ndarray, list[int]]:
    """Rows that reconstruct the erased *data* chunks from the first k
    surviving chunks (data-then-coding order), mirroring
    jerasure_make_decoding_matrix / isa-l's decode path
    (ErasureCodeIsa.cc:220-310).

    Returns (decode_rows, survivors): decode_rows is (len(data_erasures), k)
    and maps the survivor chunk vector to each erased data chunk; survivors
    is the list of k chunk ids used as input, ascending.
    """
    binv, survivors = survivor_basis(coding_matrix, erasures, k, w)
    data_erasures = sorted(e for e in set(erasures) if e < k)
    rows = np.array([binv[e] for e in data_erasures], dtype=np.int64).reshape(
        len(data_erasures), k
    )
    return rows, survivors
