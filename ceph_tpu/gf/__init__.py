"""GF(2^w) arithmetic — the executable spec for all erasure-code math.

Numpy implementation of the Galois-field arithmetic that the reference
delegates to the gf-complete/jerasure/isa-l submodules (absent from the
reference mount; call contracts documented in SURVEY.md §2.1).  Primitive
polynomials match gf-complete/isa-l defaults so coded chunks are
byte-compatible with the C plugins:

- w=8 : x^8+x^4+x^3+x^2+1           (0x11D)
- w=16: x^16+x^12+x^3+x+1           (0x1100B)
- w=32: x^32+x^22+x^2+x+1           (0x400007)
"""

from .arith import (
    PRIM_POLY,
    gf_div,
    gf_exp_table,
    gf_inv,
    gf_log_table,
    gf_mul,
    gf_mul_scalar,
    gf_pow_scalar,
    region_mul,
    region_xor,
)
from .matrix import (
    cauchy_good_matrix,
    cauchy_n_ones,
    cauchy_original_matrix,
    isa_cauchy_matrix,
    isa_rs_matrix,
    jerasure_bitmatrix,
    make_decoding_matrix,
    matrix_invert,
    survivor_basis,
    matrix_multiply,
    matrix_vector_mul_region,
    reed_sol_r6_coding_matrix,
    reed_sol_vandermonde_coding_matrix,
)

__all__ = [
    "PRIM_POLY",
    "gf_mul",
    "gf_div",
    "gf_inv",
    "gf_mul_scalar",
    "gf_pow_scalar",
    "gf_exp_table",
    "gf_log_table",
    "region_mul",
    "region_xor",
    "matrix_invert",
    "matrix_multiply",
    "matrix_vector_mul_region",
    "make_decoding_matrix",
    "survivor_basis",
    "reed_sol_vandermonde_coding_matrix",
    "reed_sol_r6_coding_matrix",
    "isa_rs_matrix",
    "isa_cauchy_matrix",
    "cauchy_original_matrix",
    "cauchy_good_matrix",
    "cauchy_n_ones",
    "jerasure_bitmatrix",
]
