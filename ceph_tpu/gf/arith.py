"""Scalar and vectorized GF(2^w) arithmetic over numpy.

This is the CPU oracle (SURVEY.md §7 Phase 0): every TPU kernel result is
checked byte-for-byte against these functions.  w=8 and w=16 use log/exp
tables (the generator alpha=2 is primitive for both default polynomials);
w=32 uses shift-and-add carryless multiplication (log tables would need
2^32 entries).
"""

from __future__ import annotations

import functools

import numpy as np

# Default primitive polynomials of gf-complete / isa-l (see package docstring).
PRIM_POLY = {8: 0x11D, 16: 0x1100B, 32: 0x400007}

_DTYPE = {8: np.uint8, 16: np.uint16, 32: np.uint32}


@functools.lru_cache(maxsize=None)
def _tables(w: int):
    """(exp, log) tables for GF(2^w), w in {8, 16}.

    exp has 2*(2^w - 1) entries so exp[log a + log b] never needs a mod.
    log[0] is unused (set to 0); gf_mul handles zeros explicitly.
    """
    if w not in (8, 16):
        raise ValueError(f"log/exp tables only for w in (8, 16), got {w}")
    order = (1 << w) - 1
    poly = PRIM_POLY[w]
    exp = np.zeros(2 * order, dtype=np.uint32)
    log = np.zeros(1 << w, dtype=np.uint32)
    x = 1
    for i in range(order):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x >> w:
            x ^= poly
    if x != 1:  # alpha=2 must be primitive for the chosen polynomial
        raise AssertionError(f"2 is not primitive for poly {poly:#x}")
    exp[order : 2 * order] = exp[:order]
    return exp, log


def gf_exp_table(w: int) -> np.ndarray:
    return _tables(w)[0]


def gf_log_table(w: int) -> np.ndarray:
    return _tables(w)[1]


def _clmul32(a: int, b: int) -> int:
    """Multiply in GF(2^32) by shift-and-add with reduction by PRIM_POLY[32]."""
    poly = PRIM_POLY[32]
    a &= 0xFFFFFFFF
    b &= 0xFFFFFFFF
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a >> 32:
            a = (a ^ poly) & 0xFFFFFFFF
    return r


def gf_mul_scalar(a: int, b: int, w: int = 8) -> int:
    """Scalar GF(2^w) product (python ints)."""
    if a == 0 or b == 0:
        return 0
    if w == 32:
        return _clmul32(a, b)
    exp, log = _tables(w)
    return int(exp[int(log[a]) + int(log[b])])


def gf_pow_scalar(a: int, n: int, w: int = 8) -> int:
    """a**n in GF(2^w) by square-and-multiply."""
    r = 1
    base = a
    while n:
        if n & 1:
            r = gf_mul_scalar(r, base, w)
        base = gf_mul_scalar(base, base, w)
        n >>= 1
    return r


def gf_inv(a: int, w: int = 8) -> int:
    if a == 0:
        raise ZeroDivisionError("inverse of 0 in GF(2^w)")
    if w == 32:
        # a^(2^32 - 2)
        return gf_pow_scalar(a, (1 << 32) - 2, w)
    exp, log = _tables(w)
    order = (1 << w) - 1
    return int(exp[(order - int(log[a])) % order])


def gf_div(a: int, b: int, w: int = 8) -> int:
    if b == 0:
        raise ZeroDivisionError("division by 0 in GF(2^w)")
    if a == 0:
        return 0
    return gf_mul_scalar(a, gf_inv(b, w), w)


def gf_mul(a: np.ndarray, b: np.ndarray, w: int = 8) -> np.ndarray:
    """Elementwise GF(2^w) product of two arrays (w in {8, 16})."""
    if w == 32:
        raise NotImplementedError("vectorized w=32 mul: use region_mul")
    exp, log = _tables(w)
    a = np.asarray(a)
    b = np.asarray(b)
    out = exp[log[a.astype(np.uint32)] + log[b.astype(np.uint32)]]
    out = np.where((a == 0) | (b == 0), 0, out)
    return out.astype(_DTYPE[w])


def region_xor(dst: np.ndarray, src: np.ndarray) -> np.ndarray:
    """XOR src into dst (bytes); returns dst."""
    np.bitwise_xor(dst, src, out=dst)
    return dst


@functools.lru_cache(maxsize=256)
def _byte_table8(c: int) -> np.ndarray:
    """The 256-entry multiply-by-c table for w=8 (galois_w08 region
    table), cached per coefficient instead of rebuilt per call."""
    exp, log = _tables(8)
    table = np.zeros(256, dtype=np.uint8)
    nz = np.arange(1, 256, dtype=np.uint32)
    table[1:] = exp[log[nz] + int(log[c])].astype(np.uint8)
    return table


@functools.lru_cache(maxsize=256)
def _pair_table8(c: int) -> np.ndarray:
    """(65536,) LITTLE-ENDIAN uint16 pair table: entry for the
    little-endian byte pair (b0, b1) holds (T[b0], T[b1]) in the same
    order, so a region's free ``<u2`` view gathers two bytes per
    lookup.  The explicit ``<u2`` dtype keeps the output byte order
    right on big-endian hosts too (a native-endian view would swap
    the pair there)."""
    t = _byte_table8(c)
    idx = np.arange(65536, dtype=np.uint32)
    return (
        t[idx & 255].astype(np.uint16)
        | (t[idx >> 8].astype(np.uint16) << 8)
    ).astype("<u2")


def region_mul(region: np.ndarray, c: int, w: int = 8) -> np.ndarray:
    """Multiply every w-bit word of a byte region by constant c.

    Matches galois_wNN_region_multiply: the region is interpreted as
    native-little-endian w-bit words.  Returns a new uint8 array.
    """
    region = np.ascontiguousarray(region, dtype=np.uint8)
    if c == 0:
        return np.zeros_like(region)
    if c == 1:
        return region.copy()
    if w == 8:
        if region.nbytes % 2 == 0:
            # pair path: ONE gather maps TWO bytes — the u16 view of
            # the FLATTENED region indexes a cached 64K pair table
            # directly (no index arithmetic), halving the gather
            # traffic that bounds the host encode rate (the
            # gf-complete SPLIT_TABLE(8,16) idea in numpy terms).
            # Flatten first: a multi-dim region with an odd last axis
            # cannot be u16-viewed in place
            words = region.reshape(-1).view("<u2")
            return (
                _pair_table8(int(c))[words]
                .view(np.uint8)
                .reshape(region.shape)
            )
        return _byte_table8(int(c))[region]
    if w == 16:
        exp, log = _tables(16)
        words = region.view("<u2").astype(np.uint32)
        out = exp[log[words] + int(log[c])].astype(np.uint16)
        out[words == 0] = 0
        return out.astype("<u2").view(np.uint8).reshape(region.shape)
    if w == 32:
        words = region.view("<u4").astype(np.uint64)
        acc = np.zeros_like(words)
        a = np.uint64(c)
        poly = np.uint64(PRIM_POLY[32])
        cur = words.copy()
        for bit in range(32):
            if (int(a) >> bit) & 1:
                acc ^= cur
            carry = (cur >> np.uint64(31)) & np.uint64(1)
            cur = (cur << np.uint64(1)) & np.uint64(0xFFFFFFFF)
            cur ^= carry * poly
        return acc.astype("<u4").view(np.uint8).reshape(region.shape)
    raise ValueError(f"unsupported w={w}")
