"""CephFS analog — the file layer over rados
(src/mds + src/client reduced to the load-bearing layout).

What carries over from the reference's on-disk design:

- **Directories are omap objects**: dirfrag ``mds_dir.<ino>`` maps
  entry name → dentry JSON (ino/type) — exactly how the real MDS
  persists dirfrags in the metadata pool's omap.
- **Inodes** carry their attributes in the dentry + a backtrace-style
  inode object ``mds_ino.<ino>`` (size/layout/mtime as omap keys) so
  partial metadata updates are single-key writes.
- **File DATA uses the real CephFS object naming**:
  ``<ino:x>.<objectno:08x>`` in the data pool, striped through
  osdc/striper.py with the file_layout_t math — a framework client
  and a reference-format-aware tool agree on where bytes live.

Surface (the libcephfs/Client.cc verbs): mkdir/rmdir/readdir,
create/open/unlink/rename, read/write (sparse, striped), stat,
truncate.

Snapshots (round 4): ``snapshot(name)`` freezes the WHOLE filesystem
by snapshotting the metadata and data pools together (the pool-snap
delegation the rbd layer uses), and ``at_snap(name)`` returns a
READ-ONLY mount whose every lookup/readdir/read resolves at that
moment — metadata omaps and striped data objects alike ride the
clone-resolution machinery.  Deviation vs the reference's .snap
dirs: snapshots are filesystem-global, not per-directory snaprealms.

Deviations, documented: the MDS tier (ceph_tpu.mds) carries
capabilities/journal/failover; THIS module is the library-mode
single-writer client.
"""

from __future__ import annotations

import itertools
import json
import stat as statmod
import time

from ..osdc.objecter import ObjectNotFound, RadosError
from ..osdc.striper import StripeLayout, map_extent

__all__ = ["CephFS", "FSError", "NotFound"]

ROOT_INO = 1


class FSError(RadosError):
    pass


class NotFound(FSError):
    pass


def _dir_oid(ino: int) -> str:
    return f"mds_dir.{ino}"


def _ino_oid(ino: int) -> str:
    return f"mds_ino.{ino}"


def _data_oid(ino: int, objectno: int) -> str:
    # the REAL CephFS data-object naming: <ino hex>.<objno 08x>
    return f"{ino:x}.{objectno:08x}"


class CephFS:
    """One mounted filesystem (the Client.cc role, library-form)."""

    def __init__(self, meta_ioctx, data_ioctx=None,
                 layout: StripeLayout | None = None):
        self.meta = meta_ioctx
        self.data = data_ioctx or meta_ioctx
        self.layout = layout or StripeLayout(
            stripe_unit=1 << 20, stripe_count=1, object_size=1 << 22
        )
        self._mkfs_if_needed()

    def _mkfs_if_needed(self) -> None:
        try:
            self.meta.omap_get_vals(_ino_oid(ROOT_INO), max_return=1)
        except (ObjectNotFound, RadosError):
            self.meta.write_full(_ino_oid(ROOT_INO), b"")
            self.meta.omap_set(
                _ino_oid(ROOT_INO),
                {"type": b"dir", "next_ino": b"2"},
            )
            self.meta.write_full(_dir_oid(ROOT_INO), b"")

    def _alloc_ino(self) -> int:
        # the inode-number table lives on the root inode (InoTable role)
        cur = int(
            self.meta.omap_get_vals(_ino_oid(ROOT_INO))["next_ino"]
        )
        self.meta.omap_set(
            _ino_oid(ROOT_INO), {"next_ino": str(cur + 1).encode()}
        )
        return cur

    # -- path walking (Client::path_walk) ----------------------------------
    def _lookup(self, path: str) -> tuple[int, dict]:
        """path → (ino, dentry) — root is ('', {type: dir})."""
        ino = ROOT_INO
        dentry = {"type": "dir", "ino": ROOT_INO}
        for name in [p for p in path.split("/") if p]:
            if dentry["type"] != "dir":
                raise FSError(f"{name!r}: not a directory (-ENOTDIR)")
            entries = self._readdir_raw(ino)
            if name not in entries:
                raise NotFound(f"{path!r} (-ENOENT)")
            dentry = entries[name]
            ino = dentry["ino"]
        return ino, dentry

    def _parent_of(self, path: str) -> tuple[int, str]:
        parts = [p for p in path.split("/") if p]
        if not parts:
            raise FSError("root has no parent (-EINVAL)")
        parent = "/".join(parts[:-1])
        ino, dentry = self._lookup(parent)
        if dentry["type"] != "dir":
            raise FSError(f"{parent!r}: not a directory (-ENOTDIR)")
        return ino, parts[-1]

    def _readdir_raw(self, dir_ino: int) -> dict[str, dict]:
        try:
            vals = self.meta.omap_get_vals(_dir_oid(dir_ino))
        except (ObjectNotFound, RadosError):
            raise NotFound(f"dirfrag {dir_ino} missing")
        return {k: json.loads(v) for k, v in vals.items()}

    def _ino_meta(self, ino: int) -> dict[str, bytes]:
        return self.meta.omap_get_vals(_ino_oid(ino))

    # -- directories -------------------------------------------------------
    def mkdir(self, path: str) -> int:
        parent, name = self._parent_of(path)
        if name in self._readdir_raw(parent):
            raise FSError(f"{path!r} exists (-EEXIST)")
        ino = self._alloc_ino()
        self.meta.write_full(_ino_oid(ino), b"")
        self.meta.omap_set(
            _ino_oid(ino),
            {"type": b"dir", "mtime": str(time.time()).encode()},
        )
        self.meta.write_full(_dir_oid(ino), b"")
        self.meta.omap_set(
            _dir_oid(parent),
            {name: json.dumps({"type": "dir", "ino": ino}).encode()},
        )
        return ino

    def rmdir(self, path: str) -> None:
        parent, name = self._parent_of(path)
        entries = self._readdir_raw(parent)
        if name not in entries:
            raise NotFound(f"{path!r} (-ENOENT)")
        dentry = entries[name]
        if dentry["type"] != "dir":
            raise FSError(f"{path!r}: not a directory (-ENOTDIR)")
        if self._readdir_raw(dentry["ino"]):
            raise FSError(f"{path!r} not empty (-ENOTEMPTY)")
        self.meta.remove(_dir_oid(dentry["ino"]))
        self.meta.remove(_ino_oid(dentry["ino"]))
        self.meta.omap_rm_keys(_dir_oid(parent), [name])

    def readdir(self, path: str = "/") -> list[str]:
        ino, dentry = self._lookup(path)
        if dentry["type"] != "dir":
            raise FSError(f"{path!r}: not a directory (-ENOTDIR)")
        return sorted(self._readdir_raw(ino))

    # -- files -------------------------------------------------------------
    def create(self, path: str) -> int:
        parent, name = self._parent_of(path)
        if name in self._readdir_raw(parent):
            raise FSError(f"{path!r} exists (-EEXIST)")
        ino = self._alloc_ino()
        self.meta.write_full(_ino_oid(ino), b"")
        self.meta.omap_set(
            _ino_oid(ino),
            {
                "type": b"file",
                "size": b"0",
                "mtime": str(time.time()).encode(),
            },
        )
        self.meta.omap_set(
            _dir_oid(parent),
            {name: json.dumps({"type": "file", "ino": ino}).encode()},
        )
        return ino

    def stat(self, path: str) -> dict:
        ino, dentry = self._lookup(path)
        meta = self._ino_meta(ino)
        is_dir = dentry["type"] == "dir"
        return {
            "ino": ino,
            "mode": (
                statmod.S_IFDIR if is_dir else statmod.S_IFREG
            ),
            "type": dentry["type"],
            "size": int(meta.get("size", b"0")),
            "mtime": float(meta.get("mtime", b"0")),
        }

    def write(self, path: str, offset: int, data: bytes) -> int:
        ino, dentry = self._lookup(path)
        if dentry["type"] != "file":
            raise FSError(f"{path!r}: not a file (-EISDIR)")
        data = bytes(data)
        pos = 0
        # extents come back in logical order: slices are sequential
        for objectno, obj_off, n in map_extent(
            self.layout, offset, len(data)
        ):
            self.data.write(
                _data_oid(ino, objectno),
                data[pos : pos + n],
                offset=obj_off,
            )
            pos += n
        size = int(self._ino_meta(ino)["size"])
        new_size = max(size, offset + len(data))
        self.meta.omap_set(
            _ino_oid(ino),
            {
                "size": str(new_size).encode(),
                "mtime": str(time.time()).encode(),
            },
        )
        return len(data)

    def read(self, path: str, offset: int = 0, length: int = -1) -> bytes:
        ino, dentry = self._lookup(path)
        if dentry["type"] != "file":
            raise FSError(f"{path!r}: not a file (-EISDIR)")
        size = int(self._ino_meta(ino)["size"])
        if length < 0:
            length = size - offset
        length = max(0, min(length, size - offset))
        if length == 0:
            return b""
        parts = []
        for objectno, obj_off, n in map_extent(
            self.layout, offset, length
        ):
            try:
                got = self.data.read(
                    _data_oid(ino, objectno), length=n, offset=obj_off
                )
            except (ObjectNotFound, RadosError):
                got = b""
            parts.append(got + b"\0" * (n - len(got)))
        return b"".join(parts)

    def truncate(self, path: str, size: int) -> None:
        ino, dentry = self._lookup(path)
        if dentry["type"] != "file":
            raise FSError(f"{path!r}: not a file (-EISDIR)")
        old = int(self._ino_meta(ino)["size"])
        if size < old:
            # with striping the trimmed tail is NOT a contiguous
            # object range — zero it extent by extent so a later
            # write past the new end reads holes as zeros
            for objectno, obj_off, n in map_extent(
                self.layout, size, old - size
            ):
                try:
                    self.data.write(
                        _data_oid(ino, objectno),
                        b"\0" * n,
                        offset=obj_off,
                    )
                except RadosError:
                    pass
        self.meta.omap_set(
            _ino_oid(ino), {"size": str(size).encode()}
        )

    def unlink(self, path: str) -> None:
        parent, name = self._parent_of(path)
        entries = self._readdir_raw(parent)
        if name not in entries:
            raise NotFound(f"{path!r} (-ENOENT)")
        dentry = entries[name]
        if dentry["type"] == "dir":
            raise FSError(f"{path!r} is a directory (-EISDIR)")
        ino = dentry["ino"]
        # remove EVERY data object of the inode by name prefix — the
        # current size under-counts objects a truncate left zeroed
        prefix = f"{ino:x}."
        for oid in self.data.list_objects():
            if oid.startswith(prefix):
                try:
                    self.data.remove(oid)
                except (ObjectNotFound, RadosError):
                    pass
        self.meta.remove(_ino_oid(ino))
        self.meta.omap_rm_keys(_dir_oid(parent), [name])

    # -- snapshots (pool-snap delegation) ----------------------------------
    def snapshot(self, name: str) -> None:
        """Freeze the filesystem: one pool snap on the metadata pool
        and (when distinct) the data pool, under the fs namespace
        ``fs@<name>``."""
        self.meta.snap_create(f"fs@{name}")
        if self._distinct_data_pool():
            self.data.snap_create(f"fs@{name}")

    def _distinct_data_pool(self) -> bool:
        # POOL identity, not ioctx identity: two ioctxs over one pool
        # must not double-snap it
        return self.data.pool_id != self.meta.pool_id

    def remove_snapshot(self, name: str) -> None:
        self.meta.snap_remove(f"fs@{name}")
        if self._distinct_data_pool():
            self.data.snap_remove(f"fs@{name}")

    def list_snapshots(self) -> list[str]:
        return sorted(
            n[len("fs@"):]
            for n in self.meta.snap_list().values()
            if n.startswith("fs@")
        )

    def at_snap(self, name: str) -> "SnapMount":
        """A read-only view of the filesystem as of ``snapshot(name)``."""
        if name not in self.list_snapshots():
            raise NotFound(f"no fs snapshot {name!r} (-ENOENT)")
        return SnapMount(self, f"fs@{name}")

    def rename(self, src: str, dst: str) -> None:
        sparent, sname = self._parent_of(src)
        dparent, dname = self._parent_of(dst)
        entries = self._readdir_raw(sparent)
        if sname not in entries:
            raise NotFound(f"{src!r} (-ENOENT)")
        if dname in self._readdir_raw(dparent):
            raise FSError(f"{dst!r} exists (-EEXIST)")
        dentry = entries[sname]
        self.meta.omap_set(
            _dir_oid(dparent), {dname: json.dumps(dentry).encode()}
        )
        self.meta.omap_rm_keys(_dir_oid(sparent), [sname])


class SnapMount(CephFS):
    """Read-only mount at a filesystem snapshot: the same client code
    with both ioctx read contexts pinned to the snap (a fresh ioctx
    pair, so the live mount's contexts stay untouched), and every
    mutating verb refused."""

    _RO = (
        "mkdir", "rmdir", "create", "write", "truncate",
        "unlink", "rename", "snapshot", "remove_snapshot",
    )

    def __init__(self, live: "CephFS", snap_full: str):
        meta = live.meta.rados.open_ioctx(
            live.meta.rados.monc.osdmap.pool_names[live.meta.pool_id]
        )
        meta.snap_set_read(snap_full)
        if live.data.pool_id == live.meta.pool_id:
            data = meta
        else:
            data = live.data.rados.open_ioctx(
                live.data.rados.monc.osdmap.pool_names[
                    live.data.pool_id
                ]
            )
            data.snap_set_read(snap_full)
        self.meta = meta
        self.data = data
        self.layout = live.layout
        # NO _mkfs_if_needed: a snapshot view never writes

    def __getattribute__(self, name):
        if name in SnapMount._RO:
            raise FSError(
                f"{name}: read-only snapshot mount (-EROFS)"
            )
        return super().__getattribute__(name)
