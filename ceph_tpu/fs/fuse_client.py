"""ceph-fuse — a REAL kernel-mounted POSIX surface over the MDS tier
(src/ceph_fuse.cc + src/client/fuse_ll.cc, reduced to the high-level
libfuse API driven through ctypes — no C extension, no third-party
binding; the image ships libfuse.so.2 and that is all this needs).

    ceph-tpu-fuse /mnt/cephtpu --mon 127.0.0.1:6789

maps the mounted tree onto an ``MDSClient`` mount: metadata verbs go
through MDS sessions (multi-MDS subtree routing included), file DATA
stripes straight to the data pool — exactly the kernel/fuse client
split the reference has.  Runs foreground single-threaded (`-f -s`);
unmount with ``fusermount -u``.

Deviations: permissions/ownership are not enforced (single-tenant
dev mounts, like ceph-fuse with client permissions off); no
symlinks/hardlinks (the MDS tier does not model them); mtime is
advisory.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import os
import stat as statmod
import sys

c_off_t = ctypes.c_int64
c_mode_t = ctypes.c_uint32


class Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_int64), ("tv_nsec", ctypes.c_int64)]


class Stat(ctypes.Structure):
    """x86_64 glibc struct stat."""

    _fields_ = [
        ("st_dev", ctypes.c_uint64),
        ("st_ino", ctypes.c_uint64),
        ("st_nlink", ctypes.c_uint64),
        ("st_mode", ctypes.c_uint32),
        ("st_uid", ctypes.c_uint32),
        ("st_gid", ctypes.c_uint32),
        ("__pad0", ctypes.c_uint32),
        ("st_rdev", ctypes.c_uint64),
        ("st_size", ctypes.c_int64),
        ("st_blksize", ctypes.c_int64),
        ("st_blocks", ctypes.c_int64),
        ("st_atime", ctypes.c_int64),
        ("st_atime_nsec", ctypes.c_int64),
        ("st_mtime", ctypes.c_int64),
        ("st_mtime_nsec", ctypes.c_int64),
        ("st_ctime", ctypes.c_int64),
        ("st_ctime_nsec", ctypes.c_int64),
        ("__glibc_reserved", ctypes.c_int64 * 3),
    ]


_FN = ctypes.CFUNCTYPE
GETATTR_T = _FN(ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(Stat))
MKDIR_T = _FN(ctypes.c_int, ctypes.c_char_p, c_mode_t)
PATH1_T = _FN(ctypes.c_int, ctypes.c_char_p)
RENAME_T = _FN(ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p)
TRUNCATE_T = _FN(ctypes.c_int, ctypes.c_char_p, c_off_t)
OPEN_T = _FN(ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p)
RW_T = _FN(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_char),
    ctypes.c_size_t, c_off_t, ctypes.c_void_p,
)
CREATE_T = _FN(ctypes.c_int, ctypes.c_char_p, c_mode_t, ctypes.c_void_p)
FILL_DIR_T = _FN(
    ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p,
    ctypes.POINTER(Stat), c_off_t,
)
READDIR_T = _FN(
    ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p, FILL_DIR_T,
    c_off_t, ctypes.c_void_p,
)
UTIMENS_T = _FN(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(Timespec)
)


class FuseOperations(ctypes.Structure):
    """struct fuse_operations, FUSE_USE_VERSION 26 (libfuse 2.9)."""

    _fields_ = [
        ("getattr", GETATTR_T),
        ("readlink", ctypes.c_void_p),
        ("getdir", ctypes.c_void_p),
        ("mknod", ctypes.c_void_p),
        ("mkdir", MKDIR_T),
        ("unlink", PATH1_T),
        ("rmdir", PATH1_T),
        ("symlink", ctypes.c_void_p),
        ("rename", RENAME_T),
        ("link", ctypes.c_void_p),
        ("chmod", ctypes.c_void_p),
        ("chown", ctypes.c_void_p),
        ("truncate", TRUNCATE_T),
        ("utime", ctypes.c_void_p),
        ("open", OPEN_T),
        ("read", RW_T),
        ("write", RW_T),
        ("statfs", ctypes.c_void_p),
        ("flush", ctypes.c_void_p),
        ("release", ctypes.c_void_p),
        ("fsync", ctypes.c_void_p),
        ("setxattr", ctypes.c_void_p),
        ("getxattr", ctypes.c_void_p),
        ("listxattr", ctypes.c_void_p),
        ("removexattr", ctypes.c_void_p),
        ("opendir", ctypes.c_void_p),
        ("readdir", READDIR_T),
        ("releasedir", ctypes.c_void_p),
        ("fsyncdir", ctypes.c_void_p),
        ("init", ctypes.c_void_p),
        ("destroy", ctypes.c_void_p),
        ("access", ctypes.c_void_p),
        ("create", CREATE_T),
        ("ftruncate", ctypes.c_void_p),
        ("fgetattr", ctypes.c_void_p),
        ("lock", ctypes.c_void_p),
        ("utimens", UTIMENS_T),
        ("bmap", ctypes.c_void_p),
        ("flags", ctypes.c_uint),
        ("ioctl", ctypes.c_void_p),
        ("poll", ctypes.c_void_p),
        ("write_buf", ctypes.c_void_p),
        ("read_buf", ctypes.c_void_p),
        ("flock", ctypes.c_void_p),
        ("fallocate", ctypes.c_void_p),
    ]


class CephFuse:
    """The fuse_ll.cc seat: libfuse callbacks → MDSClient verbs."""

    def __init__(self, fs):
        self.fs = fs  # an MDSClient
        self._keep = []  # callback refs must outlive fuse_main

    # -- helpers -----------------------------------------------------------
    def _err(self, e) -> int:
        from ..mds.client import MDSError

        if isinstance(e, MDSError):
            table = {
                -2: -errno.ENOENT, -17: -errno.EEXIST,
                -20: -errno.ENOTDIR, -21: -errno.EISDIR,
                -39: -errno.ENOTEMPTY, -22: -errno.EINVAL,
            }
            return table.get(e.rc, -errno.EIO)
        return -errno.EIO

    # -- callbacks ---------------------------------------------------------
    def _getattr(self, path, stbuf):
        try:
            p = path.decode()
            st = self.fs.stat(p) if p != "/" else {
                "type": "dir", "size": 0, "mtime": 0, "ino": 1,
            }
        except Exception as e:  # noqa: BLE001
            return self._err(e)
        ctypes.memset(ctypes.byref(stbuf.contents), 0,
                      ctypes.sizeof(Stat))
        s = stbuf.contents
        is_dir = st["type"] == "dir"
        s.st_mode = (
            (statmod.S_IFDIR | 0o755) if is_dir
            else (statmod.S_IFREG | 0o644)
        )
        s.st_nlink = 2 if is_dir else 1
        s.st_ino = st.get("ino", 0)
        s.st_size = 0 if is_dir else int(st.get("size", 0))
        s.st_blksize = 4096
        s.st_blocks = (s.st_size + 511) // 512
        mt = int(st.get("mtime", 0))
        s.st_mtime = s.st_atime = s.st_ctime = mt
        s.st_uid = os.getuid()
        s.st_gid = os.getgid()
        return 0

    def _readdir(self, path, buf, filler, _off, _fi):
        try:
            names = self.fs.readdir(path.decode())
        except Exception as e:  # noqa: BLE001
            return self._err(e)
        filler(buf, b".", None, 0)
        filler(buf, b"..", None, 0)
        for n in names:
            filler(buf, n.encode(), None, 0)
        return 0

    def _mkdir(self, path, _mode):
        try:
            self.fs.mkdir(path.decode())
            return 0
        except Exception as e:  # noqa: BLE001
            return self._err(e)

    def _rmdir(self, path):
        try:
            self.fs.rmdir(path.decode())
            return 0
        except Exception as e:  # noqa: BLE001
            return self._err(e)

    def _unlink(self, path):
        try:
            self.fs.unlink(path.decode())
            return 0
        except Exception as e:  # noqa: BLE001
            return self._err(e)

    def _rename(self, src, dst):
        try:
            self.fs.rename(src.decode(), dst.decode())
            return 0
        except Exception as e:  # noqa: BLE001
            return self._err(e)

    def _create(self, path, _mode, _fi):
        try:
            self.fs.create(path.decode())
            return 0
        except Exception as e:  # noqa: BLE001
            return self._err(e)

    def _open(self, path, _fi):
        try:
            self.fs.stat(path.decode())
            return 0
        except Exception as e:  # noqa: BLE001
            return self._err(e)

    def _read(self, path, buf, size, off, _fi):
        try:
            data = self.fs.read(path.decode(), off, size)
        except Exception as e:  # noqa: BLE001
            return self._err(e)
        ctypes.memmove(buf, data, len(data))
        return len(data)

    def _write(self, path, buf, size, off, _fi):
        try:
            data = ctypes.string_at(buf, size)
            self.fs.write(path.decode(), off, data)
            return size
        except Exception as e:  # noqa: BLE001
            return self._err(e)

    def _truncate(self, path, length):
        try:
            self.fs.truncate(path.decode(), length)
            return 0
        except Exception as e:  # noqa: BLE001
            return self._err(e)

    def _utimens(self, _path, _times):
        return 0  # advisory

    def operations(self) -> FuseOperations:
        ops = FuseOperations()
        binds = [
            ("getattr", GETATTR_T, self._getattr),
            ("mkdir", MKDIR_T, self._mkdir),
            ("unlink", PATH1_T, self._unlink),
            ("rmdir", PATH1_T, self._rmdir),
            ("rename", RENAME_T, self._rename),
            ("truncate", TRUNCATE_T, self._truncate),
            ("open", OPEN_T, self._open),
            ("read", RW_T, self._read),
            ("write", RW_T, self._write),
            ("readdir", READDIR_T, self._readdir),
            ("create", CREATE_T, self._create),
            ("utimens", UTIMENS_T, self._utimens),
        ]
        for name, typ, fn in binds:
            cb = typ(fn)
            self._keep.append(cb)  # MUST outlive fuse_main
            setattr(ops, name, cb)
        return ops


def mount(fs, mountpoint: str, foreground: bool = True) -> int:
    """Block serving the mount until unmounted (fuse_main)."""
    libname = ctypes.util.find_library("fuse")
    if libname is None:
        raise OSError("libfuse not available")
    lib = ctypes.CDLL(libname)
    ceph = CephFuse(fs)
    ops = ceph.operations()
    argv_list = [b"ceph-tpu-fuse", mountpoint.encode()]
    if foreground:
        argv_list += [b"-f", b"-s"]
    # the MDS cap-recall protocol is the coherence authority; the
    # kernel must not serve its own stale dentry/attr caches over it
    argv_list += [b"-o", b"entry_timeout=0,attr_timeout=0"]
    argv = (ctypes.c_char_p * len(argv_list))(*argv_list)
    return lib.fuse_main_real(
        len(argv_list), argv, ctypes.byref(ops),
        ctypes.sizeof(ops), None,
    )


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="ceph-tpu-fuse")
    p.add_argument("mountpoint")
    p.add_argument("--mon", required=True, help="HOST:PORT")
    p.add_argument("--data-pool", default="fsdata")
    p.add_argument("--name", default="fuse")
    args = p.parse_args(argv)

    from ..mds import MDSClient
    from ..rados import Rados

    host, _, port = args.mon.rpartition(":")
    r = Rados(f"fuse-{args.name}").connect(host, int(port))
    fs = MDSClient(r, args.data_pool, name=args.name)
    try:
        return mount(fs, args.mountpoint)
    finally:
        fs.close()
        r.shutdown()


if __name__ == "__main__":
    sys.exit(main())
