"""Stripe batch layout — the one definition of the fold both backends use.

A stripe batch is (B, n, chunk_bytes); region math wants (n, bytes).
Folding the batch into the byte axis keeps the per-stripe chunk layout
and lets arbitrarily many stripes ride one kernel call (the hoisted
ECUtil::encode per-stripe loop, src/osd/ECUtil.cc:123-162).

Array-API generic: works on numpy and jax.numpy arrays alike.
"""

from __future__ import annotations


def fold_stripes(stripes):
    """(B, n, chunk) → (n, B*chunk)."""
    b, n, chunk = stripes.shape
    return stripes.transpose(1, 0, 2).reshape(n, b * chunk)


def unfold_stripes(flat, batch: int, chunk: int):
    """(m, B*chunk) → (B, m, chunk) (inverse of fold_stripes)."""
    m = flat.shape[0]
    return flat.reshape(m, batch, chunk).transpose(1, 0, 2)
