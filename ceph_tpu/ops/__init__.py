"""TPU kernels for the storage compute plane.

The reference's hot kernels are CPU SIMD loops (gf-complete/isa-l GF(2^8)
region MACs, jerasure bitmatrix XOR schedules — SURVEY.md §2.1/§3.1); here
the same math is reformulated MXU-first:

GF(2^w) arithmetic is GF(2)-linear over the bits of each w-bit word, so a
Reed-Solomon coding matrix lifts to a (m·w, k·w) GF(2) bitmatrix and
``parity = M ⊗ data`` becomes ``bits_out = (B @ bits_in) mod 2`` — one int8
matmul on the systolic array per stripe batch, instead of k·m table-lookup
region passes.  XOR-schedule (bitmatrix) techniques are the same primitive
with packet-interleaved bit layout.  See ``gf_matmul`` for layout contracts
and ``pallas_gf`` for the experimental fused VMEM kernel.

Importing this module registers the ``jax`` erasure-code backend.
"""

from .ec_backend import JaxBackend, get_jax_backend  # noqa: F401

# persistent compilation cache (CEPH_TPU_COMPILE_CACHE): configured
# before any kernel compiles so cold starts replay prior processes'
# programs (ops/residency.configure_compile_cache; no-op unset)
from .residency import configure_compile_cache as _configure_compile_cache

_configure_compile_cache()

__all__ = ["JaxBackend", "get_jax_backend"]
