"""Device-batched deep-scrub kernels — crc32c over a whole PG's
objects in one vectorized call, plus the re-encode compare reduce.

The reference deep scrub checksums every object with a per-object
CPU crc pass (``build_scrub_map_chunk`` → ``ceph_crc32c``,
src/osd/PGBackend.cc:1175); here the whole chunk of objects rides ONE
device call by lifting crc32c to GF(2) linear algebra over the
existing bit-plane matmul contract (ops/bitops.py conventions,
ops/gf_matmul.py mod-2 matmul idiom):

- The crc32c register update for one byte, ``crc' = (crc >> 8) ^
  T0[(crc ^ b) & 0xff]``, is linear over GF(2) in (crc, byte):
  ``crc' = L(crc ⊕ b)`` with L a fixed 32×32 bit matrix derived from
  the Castagnoli table (the SAME table ``native/crc32c.c`` builds).
- Four bytes at a time: with the little-endian u32 word w,
  ``crc' = F(crc ⊕ w)`` where ``F = L⁴`` (the slicing-by-4 identity
  the reference's slicing-by-8 loop is built on).
- So over m words, ``crc = F^m(init) ⊕ Σ_i F^(m-i)(w_i)`` — the data
  term is ONE (n, m·32) @ (m·32, 32) mod-2 matmul over the objects'
  word bits.  LSB-first byte unpacking IS the LE-u32 bit order, so no
  relayout is needed.
- Lengths vary per object: buffers are RIGHT-aligned (leading zero
  words contribute nothing to the data term, exactly like leading
  zeros keep a zero register at zero), and the per-object init term
  ``L^len(init)`` folds in host-side via 32×32 matrix powers.
- The matmul is two-level so the device matrix stays small: a cached
  per-chunk matrix (``_CHUNK`` bytes) computes chunk-local terms, and
  a cached combine matrix advances each chunk by ``F^(words/chunk)``
  to its distance from the end — both matrices compile/transfer once
  per shape (the ErasureCodeIsaTableCache idiom, counted in the
  ``l_tpu_compile_cache_*`` kernel stats).

Golden-checked against the reference crc32c test vectors
(src/test/common/test_crc32c.cc) and the native slicing-by-8 C
implementation.  ``batch_compare`` is the deep-scrub re-encode
verifier: stored shard bytes vs re-encoded shard bytes in one
device-side any-mismatch reduce.

Everything degrades to the native-C oracle when the device backend is
unavailable (``backend="oracle"`` forces it), so scrub itself never
depends on an accelerator being attached.
"""

from __future__ import annotations

import functools

import numpy as np

from ..native import ceph_crc32c

# reference test vectors (src/test/common/test_crc32c.cc): (init,
# payload, crc) — the parity tests AND the import-time self-check of
# the matrix construction both anchor on these
GOLDEN_VECTORS = (
    (0, b"foo bar baz", 4119623852),
    (4294967295, b"", 4294967295),
    (0, b"", 0),
    (1, b"", 1),
)

_CHUNK = 4096  # bytes per device chunk row (multiple of 4)


# -- host-side GF(2) matrix algebra (32x32, entries 0/1) --------------------


@functools.lru_cache(maxsize=1)
def _crc_table() -> list[int]:
    """T0 of the Castagnoli table — shared derivation with
    native/crc32c.c (reflected, poly 0x1EDC6F41)."""
    from ..native import _py_table

    return _py_table()


def _byte_step(x: int) -> int:
    """One crc32c register step with a zero input byte: L(x)."""
    return ((x >> 8) ^ _crc_table()[x & 0xFF]) & 0xFFFFFFFF


def _to_bits(x: int) -> np.ndarray:
    return np.array(
        [(x >> c) & 1 for c in range(32)], dtype=np.uint8
    )


def _from_bits(v: np.ndarray) -> int:
    return int(sum(int(b) << c for c, b in enumerate(v)))


@functools.lru_cache(maxsize=1)
def _L() -> np.ndarray:
    """The per-byte transition as a (32, 32) GF(2) matrix: column c is
    L(e_c)."""
    m = np.zeros((32, 32), dtype=np.uint8)
    for c in range(32):
        m[:, c] = _to_bits(_byte_step(1 << c))
    return m


def _matmul2(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    # 32-term dot products of 0/1 values: uint8 cannot overflow... it
    # can (max 32 < 256) — keep uint8, mask mod 2
    return (a.astype(np.uint16) @ b.astype(np.uint16) % 2).astype(
        np.uint8
    )


@functools.lru_cache(maxsize=1)
def _F() -> np.ndarray:
    """F = L⁴ — the one-u32-word transition."""
    l2 = _matmul2(_L(), _L())
    return _matmul2(l2, l2)


@functools.lru_cache(maxsize=256)
def _L_pow(n: int) -> np.ndarray:
    """L^n by square-and-multiply (init-term fold for a length-n
    buffer)."""
    if n == 0:
        return np.eye(32, dtype=np.uint8)
    half = _L_pow(n // 2)
    sq = _matmul2(half, half)
    return _matmul2(_L(), sq) if n % 2 else sq


def _apply(mat: np.ndarray, x: int) -> int:
    return _from_bits(mat @ _to_bits(x) % 2)


@functools.lru_cache(maxsize=8)
def _chunk_matrix(chunk_bytes: int) -> np.ndarray:
    """(chunk_bytes*8, 32) int8: rows 32i+b map bit b of word i to the
    chunk-local crc contribution F^(mc-i)(e_b)."""
    mc = chunk_bytes // 4
    f = _F()
    rows = np.empty((mc, 32, 32), dtype=np.int8)
    p = f  # F^1 belongs to the LAST word (i = mc-1)
    for i in range(mc - 1, -1, -1):
        rows[i] = p.T
        if i:
            p = _matmul2(p, f)
    return rows.reshape(chunk_bytes * 8, 32)


@functools.lru_cache(maxsize=64)
def _combine_matrix(chunk_bytes: int, nchunks: int) -> np.ndarray:
    """(nchunks*32, 32) int8: block j advances chunk j's local crc by
    Fc^(nchunks-1-j), Fc = F^(words per chunk)."""
    fc = np.eye(32, dtype=np.uint8)
    f = _F()
    for _ in range(chunk_bytes // 4):
        fc = _matmul2(fc, f)
    blocks = np.empty((nchunks, 32, 32), dtype=np.int8)
    p = np.eye(32, dtype=np.uint8)
    for j in range(nchunks - 1, -1, -1):
        blocks[j] = p.T
        if j:
            p = _matmul2(p, fc)
    return blocks.reshape(nchunks * 32, 32)


def _self_check() -> None:
    """The matrix construction must reproduce the reference vectors
    through the PURE-HOST path before any device math is trusted."""
    for init, payload, want in GOLDEN_VECTORS:
        got = _apply(_L_pow(len(payload)), init)
        m = np.zeros(32, dtype=np.uint8)
        for i, byte in enumerate(payload):
            adv = _L_pow(len(payload) - i)
            contrib = adv @ _to_bits(byte) % 2
            m = (m + contrib) % 2
        got ^= _from_bits(m)
        if got != want:
            raise AssertionError(
                f"crc32c matrix self-check failed: "
                f"crc({init:#x}, {payload!r}) = {got} != {want}"
            )


# -- device plane -----------------------------------------------------------


@functools.lru_cache(maxsize=8)
def _device_chunk_matrix(chunk_bytes: int):
    import jax.numpy as jnp

    return jnp.asarray(_chunk_matrix(chunk_bytes))


@functools.lru_cache(maxsize=64)
def _device_combine_matrix(chunk_bytes: int, nchunks: int):
    import jax.numpy as jnp

    return jnp.asarray(_combine_matrix(chunk_bytes, nchunks))


@functools.lru_cache(maxsize=8)
def _crc_call(chunk_bytes: int, nchunks: int):
    """The jitted two-matmul crc kernel for a padded shape."""
    import jax
    import jax.numpy as jnp

    def crc_bits(rows: jnp.ndarray, gc, hc) -> jnp.ndarray:
        n = rows.shape[0]
        flat = rows.reshape(n * nchunks, chunk_bytes)
        # LSB-first byte unpack == LE-u32 word-bit order (bitops.py
        # layout contract)
        bits = (
            jnp.right_shift(
                flat[:, :, None],
                jnp.arange(8, dtype=jnp.uint8)[None, None, :],
            )
            & 1
        ).astype(jnp.int8)
        x = bits.reshape(n * nchunks, chunk_bytes * 8)
        local = (
            jax.lax.dot_general(
                x, gc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            & 1
        ).astype(jnp.int8)
        folded = (
            jax.lax.dot_general(
                local.reshape(n, nchunks * 32), hc,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            & 1
        ).astype(jnp.uint32)
        weights = jnp.left_shift(
            jnp.uint32(1), jnp.arange(32, dtype=jnp.uint32)
        )
        return (folded * weights[None, :]).sum(
            axis=1, dtype=jnp.uint32
        )

    return jax.jit(crc_bits)


def _kstats():
    from .kernel_stats import kernel_stats

    return kernel_stats()


def _gather_rows(entries, width: int, *, align_right: bool, fillers: int = 0):
    """Build an (len(entries) + fillers, width) uint8 DEVICE matrix
    from mixed host-bytes / DeviceBuf entries — the ONE pad/stack/
    permute implementation both device kernels share: every host row
    (plus the zero filler rows) rides a single bulk ``device_put``,
    resident rows pad device-side (no second transfer), and one
    permutation gather restores entry order (fillers land after the
    real rows).  All-host batches skip the gather entirely."""
    import jax
    import jax.numpy as jnp

    from .residency import DeviceBuf

    n = len(entries)
    host_idx = [
        i for i, e in enumerate(entries)
        if not isinstance(e, DeviceBuf)
    ]
    res_idx = [
        i for i, e in enumerate(entries) if isinstance(e, DeviceBuf)
    ]
    # flight-recorder byte attribution: host rows cross the link this
    # dispatch; registered-resident tokens are served where they live
    # (a lazy unregistered DeviceBuf's device() upload is a transfer)
    from .profiler import record_resident, record_upload

    record_upload(sum(len(entries[i]) for i in host_idx))
    for i in res_idx:
        (
            record_resident
            if entries[i].resident
            else record_upload
        )(len(entries[i]))
    block = np.zeros((len(host_idx) + fillers, width), dtype=np.uint8)
    for r, i in enumerate(host_idx):
        raw = bytes(entries[i])
        if raw:
            if align_right:
                block[r, width - len(raw):] = np.frombuffer(
                    raw, dtype=np.uint8
                )
            else:
                block[r, : len(raw)] = np.frombuffer(
                    raw, dtype=np.uint8
                )
    dev_block = jax.device_put(block)
    if not res_idx:
        return dev_block  # already in entry order, fillers trailing
    res_rows = jnp.stack(
        [
            jnp.pad(
                entries[i].device(),
                (width - len(entries[i]), 0)
                if align_right
                else (0, width - len(entries[i])),
            )
            for i in res_idx
        ]
    )
    perm = np.empty(n + fillers, dtype=np.int32)
    for r, i in enumerate(host_idx):
        perm[i] = r
    for f in range(fillers):
        perm[n + f] = len(host_idx) + f
    base = len(host_idx) + fillers
    for r, i in enumerate(res_idx):
        perm[i] = base + r
    return jnp.concatenate([dev_block, res_rows])[jnp.asarray(perm)]


def _oracle(buffers, inits) -> np.ndarray:
    from .profiler import dispatch_profiler
    from .residency import as_host_bytes

    with dispatch_profiler().dispatch(
        "crc32c", backend="cpu"
    ) as dp:
        dp.set_ops(len(buffers))
        dp.add_bytes_in(sum(len(b) for b in buffers))
        return np.array(
            [
                ceph_crc32c(init, as_host_bytes(buf))
                for buf, init in zip(buffers, inits)
            ],
            dtype=np.uint32,
        )


def batch_crc32c(
    buffers, inits=0, *, backend: str | None = None
) -> np.ndarray:
    """crc32c of every buffer in one device call (uint32 array).

    ``inits`` is a scalar seed or a per-buffer sequence (ceph_crc32c
    running-crc semantics; the EC HashInfo convention seeds with
    0xffffffff).  ``backend``: None = device with oracle fallback,
    "device" = device or raise, "oracle" = the native C loop.

    Entries may be host bytes OR ``ops.residency.DeviceBuf`` tokens —
    a resident buffer (e.g. a shard the EC write path just encoded)
    is consumed where it already lives instead of paying a second
    host→device transfer per stage.
    """
    buffers = list(buffers)
    if not buffers:
        return np.zeros(0, dtype=np.uint32)
    if isinstance(inits, int):
        inits = [inits] * len(buffers)
    inits = [int(x) & 0xFFFFFFFF for x in inits]
    if backend == "oracle":
        return _oracle(buffers, inits)
    try:
        return _device_crc32c(buffers, inits)
    except Exception:  # noqa: BLE001 — no accelerator / broken
        # runtime must never fail a scrub; the oracle is byte-exact
        if backend == "device":
            raise
        return _oracle(buffers, inits)


def _device_crc32c(buffers, inits) -> np.ndarray:
    from .profiler import dispatch_profiler
    from .residency import bucket_pow2, note_shape

    _self_check()
    lens = [len(b) for b in buffers]
    n = len(buffers)
    padded = _CHUNK * bucket_pow2(-(-max(max(lens), 1) // _CHUNK))
    nchunks = padded // _CHUNK
    nrows = bucket_pow2(n)
    ks = _kstats()
    with ks.timed(
        "scrub_crc32c", bytes_in=sum(lens)
    ) as kt, dispatch_profiler().dispatch(
        "crc32c", backend="jax"
    ) as dp:
        dp.set_ops(n)
        dp.add_bytes_in(sum(lens))
        # right-align zeros + pow2 filler rows: device-visible bytes
        # the shape bucket padded in
        dp.add_pad(padded * nrows - sum(lens))
        gc = ks.counted_cache_call(_device_chunk_matrix, _CHUNK)
        hc = ks.counted_cache_call(
            _device_combine_matrix, _CHUNK, nchunks
        )
        call = _crc_call(_CHUNK, nchunks)
        note_shape("scrub_crc32c", nrows, nchunks)
        # resident payloads right-align ON DEVICE (no second
        # host→device transfer); host payloads + the pow2 filler rows
        # (which crc to 0 and slice away) ride ONE bulk device_put
        with dp.stage("upload"):
            rows = _gather_rows(
                buffers, padded, align_right=True, fillers=nrows - n
            ).reshape(nrows, nchunks, _CHUNK)
        with dp.stage("compute"):
            res = call(rows, gc, hc)
        with dp.stage("sync"):
            out = np.asarray(res).astype(np.uint32)[:n]
        kt.bytes_out = out.nbytes
    # per-object init fold: crc = data_term ⊕ L^len(init)
    for i, (ln, init) in enumerate(zip(lens, inits)):
        if init:
            out[i] ^= _apply(_L_pow(ln), init)
    return out


@functools.lru_cache(maxsize=8)
def _compare_call(ncols: int):
    import jax
    import jax.numpy as jnp

    def mismatch(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        return jnp.any(a != b, axis=1)

    return jax.jit(mismatch)


def batch_compare(stored, expected, *, backend: str | None = None):
    """Per-pair any-byte-differs verdict (bool array) — the device
    side of re-encode verification: ``stored[i]`` is the shard bytes
    on disk, ``expected[i]`` the re-encoded truth.  Length mismatches
    are verdicts on their own (no device trip needed for them).

    Entries in either list may be host bytes or
    ``ops.residency.DeviceBuf`` tokens — resident shard payloads are
    compared where they already live (no second ``device_put`` of
    bytes the EC path just uploaded); the compare width buckets to a
    power of two so ragged verify chunks replay compiled programs."""
    from .residency import as_host_bytes, bucket_pow2, note_shape

    stored = list(stored)
    expected = list(expected)
    assert len(stored) == len(expected)
    if not stored:
        return np.zeros(0, dtype=bool)
    out = np.zeros(len(stored), dtype=bool)
    same_len = [
        i for i in range(len(stored))
        if len(stored[i]) == len(expected[i])
    ]
    for i in range(len(stored)):
        if len(stored[i]) != len(expected[i]):
            out[i] = True
    if not same_len:
        return out
    width = max(len(stored[i]) for i in same_len)
    if width == 0:
        return out
    bwidth = bucket_pow2(width)

    def _host_rows(seq) -> np.ndarray:
        rows = np.zeros((len(same_len), bwidth), dtype=np.uint8)
        for row, i in enumerate(same_len):
            raw = as_host_bytes(seq[i])
            rows[row, : len(raw)] = np.frombuffer(raw, dtype=np.uint8)
        return rows

    from .profiler import dispatch_profiler

    total = sum(
        len(stored[i]) + len(expected[i]) for i in same_len
    )
    if backend != "oracle":
        try:
            ks = _kstats()
            with ks.timed(
                "scrub_verify", bytes_in=total
            ) as kt, dispatch_profiler().dispatch(
                "compare", backend="jax"
            ) as dp:
                dp.set_ops(len(same_len))
                dp.add_bytes_in(total)
                dp.add_pad(2 * bwidth * len(same_len) - total)
                with dp.stage("upload"):
                    a_dev = _gather_rows(
                        [stored[i] for i in same_len], bwidth,
                        align_right=False,
                    )
                    b_dev = _gather_rows(
                        [expected[i] for i in same_len], bwidth,
                        align_right=False,
                    )
                note_shape("scrub_verify", len(same_len), bwidth)
                with dp.stage("compute"):
                    vdev = _compare_call(bwidth)(a_dev, b_dev)
                with dp.stage("sync"):
                    verdict = np.asarray(vdev)
                kt.bytes_out = verdict.nbytes
            out[same_len] = verdict
            return out
        except Exception:  # noqa: BLE001 — fall through to numpy
            if backend == "device":
                raise
    with dispatch_profiler().dispatch(
        "compare", backend="cpu"
    ) as dp:
        dp.set_ops(len(same_len))
        dp.add_bytes_in(total)
        a = _host_rows(stored)
        b = _host_rows(expected)
        out[same_len] = (a != b).any(axis=1)
    return out
