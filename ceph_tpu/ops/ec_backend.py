"""The ``jax`` erasure-code backend: device dispatch of region math.

Slots under every code family through the same seam the reference uses
for gf-complete/isa-l (ceph_tpu.ec.backend); numpy in, numpy out, with
jit-compiled mod-2 matmuls in between.  The first call for a given
(shape, matrix-shape, w) pair compiles; later calls replay the cached
executable — the analog of the reference's one-time ec_init_tables SIMD
table expansion (src/erasure-code/isa/ErasureCodeIsa.cc:402).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ec.backend import register_backend
from . import mesh, packed_gf
from .gf_matmul import (
    bitmatrix_packet_regions,
    gf_matrix_regions,
    gf_matrix_stripes,
    matrix_to_device_bitmatrix,
)
from .kernel_stats import kernel_stats
from .profiler import dispatch_profiler, record_pad


def _on_tpu() -> bool:
    import jax

    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        # a configured-but-unreachable accelerator plugin raises from
        # the probe itself; that is "no TPU", not a crash
        return False


import functools

from ..ec.backend import _host_row as _row_u8

@functools.lru_cache(maxsize=512)
def _host_bitmatrix(key: bytes, shape: tuple, w: int):
    """Host-side bitmatrix + packed-kernel eligibility, cached per
    matrix (no device upload, no per-call supports() recompute)."""
    from .. import gf

    mat = np.frombuffer(key, dtype=np.int64).reshape(shape)
    bm = gf.jerasure_bitmatrix(mat, w)
    return bm, packed_gf.supports(bm, w)


def _host_bm(matrix: np.ndarray, w: int):
    mat = np.ascontiguousarray(matrix, dtype=np.int64)
    return kernel_stats().counted_cache_call(
        _host_bitmatrix, mat.tobytes(), mat.shape, w
    )


class JaxBackend:
    name = "jax"

    def matrix_regions(
        self, matrix: np.ndarray, regions: np.ndarray, w: int
    ) -> np.ndarray:
        # np.asarray inside the timer forces the device sync, so the
        # recorded latency is the kernel, not the dispatch
        with kernel_stats().timed(
            "gf_matmul", bytes_in=regions.nbytes
        ) as kt:
            if w == 8 and _on_tpu() and regions.shape[1] % 4 == 0:
                bm_np, ok = _host_bm(matrix, w)
                if ok:
                    out = np.asarray(
                        packed_gf.packed_bitmatrix_regions(
                            bm_np, regions
                        )
                    )
                    kt.bytes_out = out.nbytes
                    return out
            bm = matrix_to_device_bitmatrix(matrix, w)
            out = np.asarray(
                gf_matrix_regions(bm, jnp.asarray(regions), w=w)
            )
            kt.bytes_out = out.nbytes
            return out

    def bitmatrix_regions(
        self,
        bm: np.ndarray,
        regions: np.ndarray,
        w: int,
        packetsize: int,
    ) -> np.ndarray:
        with kernel_stats().timed(
            "gf_bitmatrix", bytes_in=regions.nbytes
        ) as kt:
            out = np.asarray(
                bitmatrix_packet_regions(
                    jnp.asarray(bm, dtype=jnp.int8),
                    jnp.asarray(regions),
                    w=w,
                    packetsize=packetsize,
                )
            )
            kt.bytes_out = out.nbytes
            return out

    def matrix_stripes(
        self, matrix: np.ndarray, stripes, w: int
    ) -> np.ndarray:
        """Batched (B, k, chunk) → (B, m, chunk); numpy in, numpy out.

        Device-array pipelines that want to keep results on-chip call
        ``ops.gf_matmul.gf_matrix_stripes`` (or
        ``ops.packed_gf.packed_matrix_stripes``) directly instead."""
        stripes = np.ascontiguousarray(stripes, dtype=np.uint8)
        b, _k, chunk = stripes.shape
        with kernel_stats().timed(
            "gf_matmul", bytes_in=stripes.nbytes
        ) as kt, dispatch_profiler().dispatch(
            "ec_encode", backend=self.name
        ) as dp:
            dp.set_ops(1)
            dp.set_stripes(b)
            dp.add_bytes_in(stripes.nbytes)
            # batch axis sharded across the device mesh when >1 device
            # exists and the batch is worth splitting — byte-identical
            # per-stripe math, just spread over chips (ops/mesh.py).
            # Checked BEFORE the packed fast path: N chips of bitplane
            # (~75 GB/s each) beat one chip of packed (~130 GB/s) for
            # every N >= 2; the packed kernel folds the batch into its
            # byte axis, so sharding it is future work
            dmesh = mesh.default_mesh()
            if dmesh is not None and b >= dmesh.n:
                bm = matrix_to_device_bitmatrix(matrix, w)
                dp.add_upload(stripes.nbytes)
                # upload/compute/sync all live inside the sharded
                # helper; attribute its wall to compute
                with dp.stage("compute"):
                    out = mesh.sharded_matrix_stripes(
                        bm, stripes, w, dmesh
                    )
                kt.bytes_out = out.nbytes
                return out
            if w == 8 and _on_tpu() and (b * chunk) % 4 == 0:
                bm_np, ok = _host_bm(matrix, w)
                if ok:
                    dp.add_upload(stripes.nbytes)
                    with dp.stage("compute"):
                        out = np.asarray(
                            packed_gf.packed_matrix_stripes(
                                bm_np, stripes
                            )
                        )
                    kt.bytes_out = out.nbytes
                    return out
            bm = matrix_to_device_bitmatrix(matrix, w)
            with dp.stage("upload"):
                dev = jnp.asarray(stripes)
            dp.add_upload(stripes.nbytes)
            with dp.stage("compute"):
                odev = self._bitplane_dispatch(bm, dev, w)
            with dp.stage("sync"):
                out = np.asarray(odev)[:b]
            kt.bytes_out = out.nbytes
            return out

    @staticmethod
    def _bitplane_call(bm, stripes: np.ndarray, w: int):
        """Upload + dispatch the generic bitplane encode.  Returns
        the UNSLICED device array — callers slice [:b] after their
        sync, so pipelined callers keep results on device."""
        return JaxBackend._bitplane_dispatch(bm, jnp.asarray(stripes), w)

    def matrix_stripes_batch(
        self,
        matrix: np.ndarray,
        stripe_batches,
        w: int,
        group_stripes: int = 256,
    ) -> list[np.ndarray]:
        """Coalesced encode of MANY stripe batches (one per queued
        object) with async double-buffered transfers: batches pack
        greedily into ~``group_stripes``-stripe groups, group j+1's
        ``jax.device_put`` is issued while group j's encode computes
        (both are async dispatches), and the ONLY sync is the final
        materialization — the commit point.  Per-group batch shapes
        bucket to powers of two so ragged coalesced batches replay
        compiled programs.  Byte-identical to per-batch
        ``matrix_stripes`` (same per-stripe math; padding is sliced
        away).  Returns one (Bi, m, chunk) array per input batch."""
        import jax

        batches = [
            np.ascontiguousarray(s, dtype=np.uint8)
            for s in stripe_batches
        ]
        if not batches:
            return []
        shapes = {s.shape[1:] for s in batches}
        if len(shapes) != 1:
            # heterogeneous geometry (should not happen for one
            # profile): encode per batch, still correct
            return [self.matrix_stripes(matrix, s, w) for s in batches]
        total = sum(s.nbytes for s in batches)
        with kernel_stats().timed(
            "gf_matmul", bytes_in=total
        ) as kt, dispatch_profiler().dispatch(
            "ec_encode", backend=self.name
        ) as dp:
            dp.set_ops(len(batches))
            dp.set_stripes(sum(s.shape[0] for s in batches))
            dp.add_bytes_in(total)
            bm = matrix_to_device_bitmatrix(matrix, w)
            groups: list[list[np.ndarray]] = []
            cur: list[np.ndarray] = []
            cur_b = 0
            for s in batches:
                if cur and cur_b + s.shape[0] > group_stripes:
                    groups.append(cur)
                    cur, cur_b = [], 0
                cur.append(s)
                cur_b += s.shape[0]
            if cur:
                groups.append(cur)

            def upload(group):
                arr = (
                    np.concatenate(group)
                    if len(group) > 1
                    else group[0]
                )
                # device_put is async: the transfer overlaps whatever
                # compute is already dispatched
                with dp.stage("upload"):
                    dev = jax.device_put(arr)
                dp.add_upload(arr.nbytes)
                return dev, arr.shape[0]

            dev, nb = upload(groups[0])
            pending: list[tuple] = []
            for j in range(len(groups)):
                with dp.stage("compute"):
                    out = self._bitplane_dispatch(bm, dev, w)
                pending.append((out, nb))
                if j + 1 < len(groups):
                    # next group's transfer overlaps this group's
                    # compute — the double buffer
                    dev, nb = upload(groups[j + 1])
            # sync ONLY here (the commit): every dispatched transfer
            # and encode drains together
            with dp.stage("sync"):
                mats = [np.asarray(o)[:b] for o, b in pending]
            kt.bytes_out = sum(m.nbytes for m in mats)
        outs: list[np.ndarray] = []
        gi = 0
        off = 0
        for s in batches:
            nb = s.shape[0]
            if off + nb > mats[gi].shape[0]:
                gi += 1
                off = 0
            outs.append(mats[gi][off : off + nb])
            off += nb
        return outs

    def decode_stripes_batch(
        self,
        matrix: np.ndarray,
        row_sets,
        w: int,
        chunk: int,
        group_stripes: int = 256,
    ) -> list:
        """Coalesced decode-from-survivors: the repair-side twin of
        :meth:`matrix_stripes_batch`.  ``row_sets`` is one list per
        object of equal-length 1-D survivor shard payloads — numpy
        arrays or resident DeviceBuf tokens.  Resident survivors ride
        the dispatch with ZERO re-upload (their link cost was paid at
        registration); host-only objects pack into
        ~``group_stripes``-stripe groups whose uploads double-buffer
        against compute, exactly like the write path.  The ONLY sync
        is the final block_until_ready, and the outputs stay DEVICE
        arrays — reconstructed shards leave device-born (the caller
        wraps them in DeviceBufs; host bytes are fetched at most once
        by whoever pushes/writes them)."""
        import jax

        from .residency import is_device_buf

        total = sum(len(r) for rows in row_sets for r in rows)
        with kernel_stats().timed(
            "gf_matmul", bytes_in=total
        ) as kt, dispatch_profiler().dispatch(
            "ec_decode", backend=self.name
        ) as dp:
            dp.set_ops(len(row_sets))
            dp.add_bytes_in(total)
            bm = matrix_to_device_bitmatrix(matrix, w)
            outs: list = [None] * len(row_sets)
            host_idx: list[int] = []
            pending: dict[int, tuple] = {}
            for i, rows in enumerate(row_sets):
                if any(is_device_buf(r) for r in rows):
                    # already-resident survivors ride with zero link
                    # cost; a lazy (unregistered-yet) DeviceBuf's
                    # device() upload is a real transfer
                    for r in rows:
                        if is_device_buf(r):
                            (
                                dp.add_resident
                                if r.resident
                                else dp.add_upload
                            )(len(r))
                    # ONE device_put for the object's host rows (a
                    # single resident survivor must not force the
                    # rest row-by-row — the PR 10 _gather_rows
                    # lesson), then a device-side stack interleaves
                    # them with the already-resident rows
                    host_js = [
                        j
                        for j, r in enumerate(rows)
                        if not is_device_buf(r)
                    ]
                    stacked = (
                        np.stack(
                            [
                                _row_u8(rows[j]).reshape(-1, chunk)
                                for j in host_js
                            ]
                        )
                        if host_js
                        else None
                    )
                    if stacked is not None:
                        dp.add_upload(stacked.nbytes)
                    with dp.stage("upload"):
                        blk = (
                            jax.device_put(stacked)
                            if stacked is not None
                            else None
                        )
                        hi = 0
                        devs = []
                        for j, r in enumerate(rows):
                            if is_device_buf(r):
                                devs.append(
                                    r.device().reshape(-1, chunk)
                                )
                            else:
                                devs.append(blk[hi])
                                hi += 1
                        dev = jnp.stack(devs, axis=1)
                    with dp.stage("compute"):
                        pending[i] = (
                            self._bitplane_dispatch(bm, dev, w),
                            dev.shape[0],
                        )
                else:
                    host_idx.append(i)
            arrays = {
                i: np.stack(
                    [
                        _row_u8(r).reshape(-1, chunk)
                        for r in row_sets[i]
                    ],
                    axis=1,
                )
                for i in host_idx
            }
            groups: list[list[int]] = []
            cur: list[int] = []
            cur_b = 0
            for i in host_idx:
                b = arrays[i].shape[0]
                if cur and cur_b + b > group_stripes:
                    groups.append(cur)
                    cur, cur_b = [], 0
                cur.append(i)
                cur_b += b
            if cur:
                groups.append(cur)

            def upload(group):
                arr = (
                    np.concatenate([arrays[i] for i in group])
                    if len(group) > 1
                    else arrays[group[0]]
                )
                # async transfer: overlaps the already-dispatched
                # decode of the previous group — the double buffer
                with dp.stage("upload"):
                    dev = jax.device_put(arr)
                dp.add_upload(arr.nbytes)
                return dev

            gouts = []
            if groups:
                dev = upload(groups[0])
                for j in range(len(groups)):
                    with dp.stage("compute"):
                        gouts.append(
                            self._bitplane_dispatch(bm, dev, w)
                        )
                    if j + 1 < len(groups):
                        dev = upload(groups[j + 1])
            for j, group in enumerate(groups):
                mat = gouts[j]
                off = 0
                for i in group:
                    b = arrays[i].shape[0]
                    outs[i] = mat[off : off + b]
                    off += b
            for i, (mat, b) in pending.items():
                outs[i] = mat[:b]
            dp.set_stripes(
                sum(b for _, b in pending.values())
                + sum(arrays[i].shape[0] for i in host_idx)
            )
            # sync ONLY here (the commit point); results STAY on
            # device for device-born registration downstream
            with dp.stage("sync"):
                outs = [jax.block_until_ready(o) for o in outs]
            kt.bytes_out = sum(int(np.prod(o.shape)) for o in outs)
        return outs

    @staticmethod
    def _bitplane_dispatch(bm, dev, w: int):
        """Bucketed dispatch for an ALREADY-uploaded (B, k, chunk)
        device array: the batch axis pads ON DEVICE to a power of two
        (the link carried exact bytes; only the compiled program sees
        the bucketed shape), so ragged object sizes and coalesced
        write batches replay compiled programs — reuse lands in the
        l_tpu_compile_cache_{hit,miss} counters
        (ops/residency.note_shape)."""
        from .residency import bucket_pow2, note_shape

        b, k, chunk = dev.shape
        bb = bucket_pow2(b)
        if bb != b:
            dev = jnp.pad(dev, ((0, bb - b), (0, 0), (0, 0)))
            record_pad((bb - b) * k * chunk)
        note_shape("ec_stripes", bb, k, chunk, w)
        return gf_matrix_stripes(bm, dev, w=w)


_backend = JaxBackend()
register_backend("jax", _backend)


def get_jax_backend() -> JaxBackend:
    return _backend
