"""The ``jax`` erasure-code backend: device dispatch of region math.

Slots under every code family through the same seam the reference uses
for gf-complete/isa-l (ceph_tpu.ec.backend); numpy in, numpy out, with
jit-compiled mod-2 matmuls in between.  The first call for a given
(shape, matrix-shape, w) pair compiles; later calls replay the cached
executable — the analog of the reference's one-time ec_init_tables SIMD
table expansion (src/erasure-code/isa/ErasureCodeIsa.cc:402).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ec.backend import register_backend
from .gf_matmul import (
    bitmatrix_packet_regions,
    gf_matrix_regions,
    gf_matrix_stripes,
    matrix_to_device_bitmatrix,
)


class JaxBackend:
    name = "jax"

    def matrix_regions(
        self, matrix: np.ndarray, regions: np.ndarray, w: int
    ) -> np.ndarray:
        bm = matrix_to_device_bitmatrix(matrix, w)
        out = gf_matrix_regions(bm, jnp.asarray(regions), w=w)
        return np.asarray(out)

    def bitmatrix_regions(
        self,
        bm: np.ndarray,
        regions: np.ndarray,
        w: int,
        packetsize: int,
    ) -> np.ndarray:
        out = bitmatrix_packet_regions(
            jnp.asarray(bm, dtype=jnp.int8),
            jnp.asarray(regions),
            w=w,
            packetsize=packetsize,
        )
        return np.asarray(out)

    def matrix_stripes(
        self, matrix: np.ndarray, stripes, w: int
    ) -> np.ndarray:
        """Batched (B, k, chunk) → (B, m, chunk); numpy in, numpy out.

        Device-array pipelines that want to keep results on-chip call
        ``ops.gf_matmul.gf_matrix_stripes`` directly instead."""
        bm = matrix_to_device_bitmatrix(matrix, w)
        return np.asarray(gf_matrix_stripes(bm, jnp.asarray(stripes), w=w))


_backend = JaxBackend()
register_backend("jax", _backend)


def get_jax_backend() -> JaxBackend:
    return _backend
