"""Bit-plane (un)packing for GF(2^w) word regions, in jax.numpy.

Layout contract (matches the jerasure bitmatrix convention consumed by
``ceph_tpu.gf.jerasure_bitmatrix``): a byte region is a sequence of
little-endian w-bit words; bit x of word j is indexed LSB-first, i.e.
``bit(word, x) = (word >> x) & 1``; with little-endian bytes this means
bit x lives in byte ``x // 8`` at in-byte position ``x % 8``.

``unpack_word_bits`` turns (n, nbytes) uint8 regions into (n*w, nwords)
0/1 planes, row ``j*w + x`` holding bit x of region j's words — exactly
the column index space of a (R, n*w) bitmatrix.  ``pack_word_bits`` is
the inverse.  Both are pure VPU element-wise code that XLA fuses.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_BIT_POS = np.left_shift(np.uint8(1), np.arange(8, dtype=np.uint8))


def unpack_word_bits(regions: jnp.ndarray, w: int) -> jnp.ndarray:
    """(n, nbytes) uint8 → (n*w, nwords) int8 bit planes (values 0/1)."""
    n, nbytes = regions.shape
    assert nbytes % (w // 8) == 0, (nbytes, w)
    nwords = nbytes // (w // 8)
    # byte-level LSB-first unpack: (n, nbytes, 8)
    bits = (
        jnp.right_shift(
            regions[:, :, None], jnp.arange(8, dtype=jnp.uint8)[None, None, :]
        )
        & 1
    )
    # little-endian bytes: word bit index = 8*byte_in_word + bit_in_byte
    bits = bits.reshape(n, nwords, w)
    return bits.transpose(0, 2, 1).reshape(n * w, nwords).astype(jnp.int8)


def pack_word_bits(bits: jnp.ndarray, w: int) -> jnp.ndarray:
    """(m*w, nwords) 0/1 → (m, nwords * w//8) uint8 regions (inverse)."""
    mw, nwords = bits.shape
    assert mw % w == 0
    m = mw // w
    bits = bits.reshape(m, w, nwords).transpose(0, 2, 1)  # (m, nwords, w)
    bits = bits.reshape(m, nwords, w // 8, 8).astype(jnp.uint8)
    by = (bits * _BIT_POS[None, None, None, :]).sum(
        axis=-1, dtype=jnp.uint8
    )
    return by.reshape(m, nwords * (w // 8))


def unpack_byte_bits(regions: jnp.ndarray) -> jnp.ndarray:
    """(r, c) uint8 → (r, c*8) 0/1 int8, LSB-first per byte.

    Order only needs to be self-consistent with ``pack_byte_bits`` —
    used for XOR-of-packet-regions where bytes are opaque."""
    r, c = regions.shape
    bits = (
        jnp.right_shift(
            regions[:, :, None], jnp.arange(8, dtype=jnp.uint8)[None, None, :]
        )
        & 1
    )
    return bits.reshape(r, c * 8).astype(jnp.int8)


def pack_byte_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(r, c*8) 0/1 → (r, c) uint8 (inverse of unpack_byte_bits)."""
    r, c8 = bits.shape
    assert c8 % 8 == 0
    bits = bits.reshape(r, c8 // 8, 8).astype(jnp.uint8)
    return (bits * _BIT_POS[None, None, :]).sum(axis=-1, dtype=jnp.uint8)
