"""GF(2^w) region math as mod-2 matmuls (the MXU formulation).

The reference computes ``coding[i] = Σ_j M[i,j] ⊗ data[j]`` with per-
coefficient table-lookup region passes (jerasure_matrix_encode /
ec_encode_data, SURVEY.md §3.1).  Multiplication by a constant in
GF(2^w) is linear over GF(2), so the whole matrix lifts to a
(m·w, k·w) bitmatrix B and the kernel is

    bits_out = (B @ bits_in) & 1

one int8 matmul with int32 accumulation — dense, static-shaped, and
tiled straight onto the systolic array.  Decode is the same kernel with
the inverted-survivor-submatrix rows (built host-side, tiny).

Two bit layouts share the primitive:

- word layout (matrix techniques, w ∈ {8,16,32}): bit x of each
  little-endian w-bit word → ``gf_matrix_regions``.
- packet layout (bitmatrix techniques: cauchy/liberation XOR schedules):
  regions are blocks of w packets of ``packetsize`` bytes; B works on
  whole packets, bytes are opaque → ``bitmatrix_packet_regions``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..layout import fold_stripes, unfold_stripes
from .bitops import (
    pack_byte_bits,
    pack_word_bits,
    unpack_byte_bits,
    unpack_word_bits,
)


def mod2_matmul(bm: jnp.ndarray, bits: jnp.ndarray) -> jnp.ndarray:
    """(R, C) 0/1 @ (C, N) 0/1 → (R, N) 0/1 via int8 matmul, int32 acc."""
    acc = jax.lax.dot_general(
        bm.astype(jnp.int8),
        bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return (acc & 1).astype(jnp.int8)


@functools.partial(jax.jit, static_argnames=("w",))
def gf_matrix_regions(
    bm: jnp.ndarray, regions: jnp.ndarray, *, w: int
) -> jnp.ndarray:
    """Apply a GF(2^w) coding matrix, given as its (m·w, k·w) bitmatrix,
    to (k, nbytes) uint8 regions → (m, nbytes) uint8."""
    bits = unpack_word_bits(regions, w)
    out = mod2_matmul(bm, bits)
    return pack_word_bits(out, w)


@functools.partial(jax.jit, static_argnames=("w", "packetsize"))
def bitmatrix_packet_regions(
    bm: jnp.ndarray, regions: jnp.ndarray, *, w: int, packetsize: int
) -> jnp.ndarray:
    """jerasure_bitmatrix_dotprod contract: each region is blocks of w
    packets of ``packetsize`` bytes; output packet i of each block is the
    XOR of input packets j where bm[i, j] == 1."""
    n, size = regions.shape
    out_rows = bm.shape[0] // w
    block = w * packetsize
    assert size % block == 0, (size, block)
    nblocks = size // block
    # (n, size) → packet planes (n*w, nblocks*packetsize): row j*w+p is
    # packet p of region j, blocks laid out contiguously per row.
    planes = (
        regions.reshape(n, nblocks, w, packetsize)
        .transpose(0, 2, 1, 3)
        .reshape(n * w, nblocks * packetsize)
    )
    bits = unpack_byte_bits(planes)
    out = pack_byte_bits(mod2_matmul(bm, bits))
    return (
        out.reshape(out_rows, w, nblocks, packetsize)
        .transpose(0, 2, 1, 3)
        .reshape(out_rows, size)
    )


@functools.partial(jax.jit, static_argnames=("w",))
def gf_matrix_stripes(
    bm: jnp.ndarray, stripes: jnp.ndarray, *, w: int
) -> jnp.ndarray:
    """Batched encode: (B, k, chunk_bytes) → (B, m, chunk_bytes).

    The ECUtil::encode per-stripe loop (src/osd/ECUtil.cc:123-162) hoisted
    into one device call: stripes fold into the matmul N dimension, so
    arbitrarily many stripes ride a single kernel launch."""
    b, _k, chunk = stripes.shape
    out = gf_matrix_regions(bm, fold_stripes(stripes), w=w)
    return unfold_stripes(out, b, chunk)


@functools.lru_cache(maxsize=512)
def _bitmatrix_cache(key: bytes, shape: tuple, w: int, dtype) -> jnp.ndarray:
    from .. import gf

    mat = np.frombuffer(key, dtype=np.int64).reshape(shape)
    return jnp.asarray(gf.jerasure_bitmatrix(mat, w), dtype=dtype)


def matrix_to_device_bitmatrix(
    matrix: np.ndarray, w: int, dtype=jnp.int8
) -> jnp.ndarray:
    """Lift a GF(2^w) matrix to its device-resident bitmatrix, cached by
    value — bitmatrix expansion AND host→device transfer happen once per
    distinct (matrix, dtype) (the analog of ErasureCodeIsaTableCache's
    one-time per-erasure-signature table preparation).  dtype jnp.int8
    for the XLA int-matmul path, jnp.bfloat16 for the pallas kernel."""
    from .kernel_stats import kernel_stats

    mat = np.ascontiguousarray(matrix, dtype=np.int64)
    return kernel_stats().counted_cache_call(
        _bitmatrix_cache, mat.tobytes(), mat.shape, w, dtype
    )
