"""Pallas TPU kernel for the GF(2^8) region matmul — experimental.

Each grid step streams a (k, TN) uint8 tile into VMEM, unpacks to bit
planes on the VPU, runs one (m*8, k*8) x (k*8, TN) MXU dot (bf16
operands are exact: entries are 0/1 and contraction sums are
<= k*8 <= 256), masks to mod 2 and repacks bytes.

MEASUREMENT (v-series chip, k=8 m=3, marginal throughput over the
dispatch overhead, chained dependent calls): XLA path 80 GB/s input,
this kernel 45 GB/s at TILE_N=4096 (15 GB/s at 512).  XLA already
fuses the unpack/matmul/pack pipeline without materializing bit planes
in HBM, so ops.gf_matmul stays the default backend; this kernel is
kept as the starting point for a smarter layout (packed-int32 lane
reads) and is exactness-tested in tests/test_pallas_gf.py.

w=8 only (the default and benchmark word size).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_N = 4096  # bytes per grid step (measured best of 512..65536)


def _kernel(bm_ref, in_ref, out_ref):
    k, tn = in_ref.shape
    r = bm_ref.shape[0]
    m = r // 8
    x = in_ref[:].astype(jnp.int32)  # (k, TN)
    shifts = jax.lax.broadcasted_iota(jnp.int32, (k, 8, tn), 1)
    bits = (x[:, None, :] >> shifts) & 1  # (k, 8, TN)
    bits = bits.reshape(k * 8, tn).astype(jnp.bfloat16)
    acc = jnp.dot(
        bm_ref[:], bits, preferred_element_type=jnp.float32
    )  # (R, TN)
    obits = acc.astype(jnp.int32) & 1
    obits = obits.reshape(m, 8, tn)
    weights = jax.lax.broadcasted_iota(jnp.int32, (m, 8, tn), 1)
    # dtype pinned: under jax_enable_x64 the default sum promotes to
    # int64, which Mosaic cannot lower
    packed = jnp.sum(obits << weights, axis=1, dtype=jnp.int32)
    out_ref[:] = packed.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("m", "interpret"))
def gf8_regions_pallas(bm_bf16, regions, *, m: int, interpret: bool = False):
    """(m*8, k*8) bitmatrix (bf16 0/1) x (k, N) uint8 -> (m, N) uint8.

    N must be a multiple of TILE_N."""
    k, n = regions.shape
    assert n % TILE_N == 0, (n, TILE_N)
    grid = (n // TILE_N,)
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (m * 8, k * 8),
                lambda i: (0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (k, TILE_N), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (m, TILE_N), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.uint8),
        interpret=interpret,
    )(bm_bf16, regions)


def gf8_matrix_regions(matrix: np.ndarray, regions) -> jnp.ndarray:
    """gf_matmul.gf_matrix_regions alternative at w=8 on TPU.

    Stricter than the XLA path: the region byte width must be a
    multiple of TILE_N (pad or fall back to gf_matmul otherwise)."""
    from .gf_matmul import matrix_to_device_bitmatrix

    bmd = matrix_to_device_bitmatrix(matrix, 8, dtype=jnp.bfloat16)
    m = bmd.shape[0] // 8
    n = regions.shape[1]
    if n % TILE_N:
        raise ValueError(
            f"pallas path needs width % {TILE_N} == 0, got {n}; "
            "use ops.gf_matmul.gf_matrix_regions"
        )
    return gf8_regions_pallas(bmd, jnp.asarray(regions), m=m)
