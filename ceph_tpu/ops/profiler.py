"""Device-dispatch flight recorder — transfer/compute/sync attribution
for the TPU data plane (the blkin-tracepoint + OpTracker-history idiom
applied to device dispatches instead of client ops).

The ``l_tpu_*`` kernel counters say *how many* dispatches ran; nothing
said *where each dispatch's wall time went*.  This module is that
instrument: every device dispatch — coalesced EC encode
(``matrix_stripes_batch``), batched decode-from-survivors
(``decode_stripes_batch``), the scrub crc/compare kernels
(``batch_crc32c``/``batch_compare``), batched CRUSH — opens a
:class:`DispatchProfiler` record and brackets its stages at the
existing double-buffer seams:

- ``upload``  — host→device transfers (``jax.device_put`` /
  ``jnp.asarray``), counted in ``transfer_s``
- ``compute`` — jitted kernel dispatch issue, counted in ``compute_s``
- ``sync``    — the commit-point materialization (``np.asarray`` /
  ``block_until_ready``), counted in ``sync_s``

Stage walls are SYNC-BOUNDED, not device-timeline truth: JAX
transfers and dispatches are async, so ``upload``/``compute`` measure
issue time and everything left drains inside the final ``sync`` — the
split says where the HOST thread waited, which is exactly the
host↔device round-trip cost the residency work needs attributed.

Each record carries batch occupancy (ops and stripes folded into the
dispatch), logical byte attribution (bytes uploaded this dispatch vs
bytes served already-resident via the ResidencyCache path — the two
always sum to the input bytes), pad waste from pow2 shape bucketing,
and the compile-cache events the dispatch produced.  Records land in
a bounded drop-oldest ring (``CEPH_TPU_DISPATCH_RING`` entries,
default 1024) served raw over ``ceph tell osd.N dispatch history``
and the admin socket, plus unbounded per-kind totals behind
``summary()`` and the bench breakdown.

Three surfaces ride one instrumentation:

- tracing — every stage opens a ``dev_upload``/``dev_compute``/
  ``dev_sync`` child span of the ambient op span (a no-op off the
  daemon op path), so ``ceph tracing dump`` shows where a slow op's
  device time went;
- telemetry — ``l_tpu_dispatch_*`` counters + LogHistogram variants
  on the process-global kernel set, flowing perf dump → MMgrReport →
  /metrics with no new plumbing;
- bench — :func:`breakdown` diffs two ``totals()`` snapshots into the
  artifact keys (``transfer_ms``/``compute_ms``/``sync_ms``/
  ``occupancy``/``pad_waste_ratio``/``resident_byte_ratio``).
"""

from __future__ import annotations

import os
import threading
import time

from ..common import tracing
from ..common.perf_counters import (
    PERFCOUNTER_HISTOGRAM,
    PERFCOUNTER_TIME,
)
from .kernel_stats import _LAT_HIST_BOUNDS, kernel_stats

# default ring capacity (entries); CEPH_TPU_DISPATCH_RING overrides
DEFAULT_RING = 1024

# stage name -> (record field, tracing child-span name)
_STAGES = {
    "upload": ("transfer_s", "dev_upload"),
    "compute": ("compute_s", "dev_compute"),
    "sync": ("sync_s", "dev_sync"),
}

_TOTAL_FIELDS = (
    "dispatches", "ops", "stripes", "bytes_in", "bytes_uploaded",
    "bytes_resident", "bytes_padded", "compile_hits",
    "compile_misses", "transfer_s", "compute_s", "sync_s", "wall_s",
)

_active = threading.local()  # .stack: list[_Dispatch]


def _stack() -> list:
    s = getattr(_active, "stack", None)
    if s is None:
        s = _active.stack = []
    return s


def current_dispatch():
    """The innermost active dispatch record on this thread (or
    None) — the hook deep sites (``_gather_rows``, ``note_shape``,
    the pad points) attach attribution through without threading a
    record parameter down every signature."""
    s = _stack()
    return s[-1] if s else None


def record_upload(nbytes: int) -> None:
    """Attribute logical payload bytes that crossed the link this
    dispatch (no-op outside a dispatch)."""
    d = current_dispatch()
    if d is not None and nbytes:
        d.bytes_uploaded += int(nbytes)


def record_resident(nbytes: int) -> None:
    """Attribute logical payload bytes served where they already
    lived (the ResidencyCache hit path — zero link cost)."""
    d = current_dispatch()
    if d is not None and nbytes:
        d.bytes_resident += int(nbytes)


def record_pad(nbytes: int) -> None:
    """Count device-visible bytes that exist only because of pow2
    shape bucketing (EC batch-axis zero pad, the CRUSH lane-0 repeat,
    crc filler rows / right-align zeros).  Always lands in the global
    ``l_tpu_pad_bytes_wasted`` counter; also attributed to the active
    dispatch record when one is open."""
    if not nbytes:
        return
    kernel_stats().record_pad(nbytes)
    d = current_dispatch()
    if d is not None:
        d.bytes_padded += int(nbytes)


def record_compile(hit: bool) -> None:
    """Attach one compile-cache event to the active dispatch record
    (the global counters are ``note_shape``'s job)."""
    d = current_dispatch()
    if d is not None:
        if hit:
            d.compile_hits += 1
        else:
            d.compile_misses += 1


class _Stage:
    """One stage bracket: accumulates wall time into the record field
    and opens the matching device-stage tracing child span (a no-op
    without an ambient tracer)."""

    __slots__ = ("_disp", "_field", "_span", "_t0")

    def __init__(self, disp: "_Dispatch", name: str):
        self._disp = disp
        self._field, span_name = _STAGES[name]
        self._span = tracing.span(
            span_name, tags={"kind": disp.kind, "backend": disp.backend}
        )

    def __enter__(self) -> "_Stage":
        self._span.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, *exc) -> bool:
        dt = time.perf_counter() - self._t0
        setattr(
            self._disp,
            self._field,
            getattr(self._disp, self._field) + dt,
        )
        self._span.__exit__(exc_type, *exc)
        return False


class _Dispatch:
    """One device dispatch in flight; commits a ring entry on clean
    exit (an exception means the dispatch fell back — the fallback
    path records its own host entry instead)."""

    __slots__ = (
        "_prof", "kind", "backend", "ops", "stripes", "bytes_in",
        "bytes_uploaded", "bytes_resident", "bytes_padded",
        "compile_hits", "compile_misses", "transfer_s", "compute_s",
        "sync_s", "wall_s", "_t0",
    )

    def __init__(self, prof: "DispatchProfiler", kind: str, backend: str):
        self._prof = prof
        self.kind = kind
        self.backend = backend
        self.ops = 0
        self.stripes = 0
        self.bytes_in = 0
        self.bytes_uploaded = 0
        self.bytes_resident = 0
        self.bytes_padded = 0
        self.compile_hits = 0
        self.compile_misses = 0
        self.transfer_s = 0.0
        self.compute_s = 0.0
        self.sync_s = 0.0
        self.wall_s = 0.0

    # -- attribution -------------------------------------------------------
    def set_ops(self, n: int) -> None:
        self.ops = int(n)

    def set_stripes(self, n: int) -> None:
        self.stripes = int(n)

    def add_bytes_in(self, nbytes: int) -> None:
        self.bytes_in += int(nbytes)

    def add_upload(self, nbytes: int) -> None:
        self.bytes_uploaded += int(nbytes)

    def add_resident(self, nbytes: int) -> None:
        self.bytes_resident += int(nbytes)

    def add_pad(self, nbytes: int) -> None:
        """Pad bytes for this dispatch; also lands in the global
        ``l_tpu_pad_bytes_wasted`` counter."""
        if nbytes:
            self.bytes_padded += int(nbytes)
            self._prof._ks.record_pad(nbytes)

    def stage(self, name: str) -> _Stage:
        """Bracket one ``upload``/``compute``/``sync`` stage; stages
        may open repeatedly (double-buffer loops accumulate)."""
        return _Stage(self, name)

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self) -> "_Dispatch":
        _stack().append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, *exc) -> bool:
        self.wall_s = time.perf_counter() - self._t0
        s = _stack()
        for i in range(len(s) - 1, -1, -1):
            if s[i] is self:
                del s[i]
                break
        if exc_type is None:
            # a stage-less record is a host-path dispatch: the whole
            # wall is compute, keeping Σstages <= wall an identity
            if not (self.transfer_s or self.compute_s or self.sync_s):
                self.compute_s = self.wall_s
            self._prof._commit(self)
        return False


class DispatchProfiler:
    """Process-wide flight recorder: a bounded drop-oldest ring of
    per-dispatch records plus unbounded per-kind totals, feeding the
    ``l_tpu_dispatch_*`` counters on commit."""

    def __init__(self, capacity: int | None = None, ks=None):
        if capacity is None:
            try:
                capacity = int(
                    os.environ.get("CEPH_TPU_DISPATCH_RING", "")
                    or DEFAULT_RING
                )
            except ValueError:
                capacity = DEFAULT_RING
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._ring: list[dict] = []
        self._seq = 0
        self.dropped = 0
        self._totals: dict[str, dict] = {}
        self._ks = ks or kernel_stats()
        ensure_dispatch_counters(self._ks)

    def dispatch(self, kind: str, backend: str = "jax") -> _Dispatch:
        """Context manager recording one device dispatch of ``kind``
        (``ec_encode``/``ec_decode``/``crc32c``/``compare``/
        ``crush``)."""
        return _Dispatch(self, kind, backend)

    # -- commit ------------------------------------------------------------
    def _commit(self, d: _Dispatch) -> None:
        entry = {
            "ts": time.time(),
            "kind": d.kind,
            "backend": d.backend,
            "ops": d.ops,
            "stripes": d.stripes,
            "bytes_in": d.bytes_in,
            "bytes_uploaded": d.bytes_uploaded,
            "bytes_resident": d.bytes_resident,
            "bytes_padded": d.bytes_padded,
            "compile_hits": d.compile_hits,
            "compile_misses": d.compile_misses,
            "transfer_s": round(d.transfer_s, 9),
            "compute_s": round(d.compute_s, 9),
            "sync_s": round(d.sync_s, 9),
            "wall_s": round(d.wall_s, 9),
        }
        dropped = False
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            if len(self._ring) >= self.capacity:
                self._ring.pop(0)
                self.dropped += 1
                dropped = True
            self._ring.append(entry)
            tot = self._totals.setdefault(
                d.kind, {f: 0 for f in _TOTAL_FIELDS}
            )
            tot["dispatches"] += 1
            tot["ops"] += d.ops
            tot["stripes"] += d.stripes
            tot["bytes_in"] += d.bytes_in
            tot["bytes_uploaded"] += d.bytes_uploaded
            tot["bytes_resident"] += d.bytes_resident
            tot["bytes_padded"] += d.bytes_padded
            tot["compile_hits"] += d.compile_hits
            tot["compile_misses"] += d.compile_misses
            tot["transfer_s"] += d.transfer_s
            tot["compute_s"] += d.compute_s
            tot["sync_s"] += d.sync_s
            tot["wall_s"] += d.wall_s
        perf = self._ks.perf
        perf.inc("l_tpu_dispatch_count")
        if d.ops:
            perf.inc("l_tpu_dispatch_ops", d.ops)
        if d.stripes:
            perf.inc("l_tpu_dispatch_stripes", d.stripes)
        if d.bytes_uploaded:
            perf.inc("l_tpu_dispatch_bytes_uploaded", d.bytes_uploaded)
        if d.bytes_resident:
            perf.inc("l_tpu_dispatch_bytes_resident", d.bytes_resident)
        if dropped:
            perf.inc("l_tpu_dispatch_ring_dropped")
        for stage, secs in (
            ("transfer", d.transfer_s),
            ("compute", d.compute_s),
            ("sync", d.sync_s),
        ):
            perf.tinc(f"l_tpu_dispatch_{stage}_lat", secs)
            perf.hinc(f"l_tpu_dispatch_{stage}_lat_hist", secs)

    # -- consumers ---------------------------------------------------------
    def history(self, kind: str | None = None, limit: int = 0) -> dict:
        """The raw ring, newest last (the ``dispatch history``
        tell/admin-socket surface); ``kind`` filters, ``limit`` keeps
        the newest N."""
        with self._lock:
            entries = list(self._ring)
            dropped = self.dropped
        if kind:
            entries = [e for e in entries if e["kind"] == kind]
        if limit and limit > 0:
            entries = entries[-limit:]
        return {
            "capacity": self.capacity,
            "dropped": dropped,
            "num_entries": len(entries),
            "entries": entries,
        }

    def totals(self) -> dict:
        """Cumulative per-kind raw sums since process start (survives
        ring wrap — the bench diffs two of these)."""
        with self._lock:
            return {k: dict(v) for k, v in self._totals.items()}

    def summary(self, kind: str | None = None) -> dict:
        """Per-kind rollup with the derived ratios (the ``dispatch
        summary`` tell surface)."""
        totals = self.totals()
        if kind:
            totals = {k: v for k, v in totals.items() if k == kind}
        with self._lock:
            ring = {
                "capacity": self.capacity,
                "entries": len(self._ring),
                "dropped": self.dropped,
            }
        return {
            "ring": ring,
            "kinds": {
                k: _derive(v) for k, v in sorted(totals.items())
            },
        }

    def clear(self) -> None:
        """Drop the ring and totals (tests/bench isolation; the
        perf counters are monotonic and stay)."""
        with self._lock:
            self._ring.clear()
            self._totals.clear()
            self.dropped = 0


def _derive(t: dict) -> dict:
    """Raw per-kind sums → the human/bench rollup shape."""
    nd = max(t.get("dispatches", 0), 1)
    bytes_in = t.get("bytes_in", 0)
    padded = t.get("bytes_padded", 0)
    return {
        "dispatches": t.get("dispatches", 0),
        "ops": t.get("ops", 0),
        "stripes": t.get("stripes", 0),
        "occupancy": round(t.get("ops", 0) / nd, 2),
        "stripes_per_dispatch": round(t.get("stripes", 0) / nd, 2),
        "bytes_in": bytes_in,
        "bytes_uploaded": t.get("bytes_uploaded", 0),
        "bytes_resident": t.get("bytes_resident", 0),
        "bytes_padded": padded,
        "compile_hits": t.get("compile_hits", 0),
        "compile_misses": t.get("compile_misses", 0),
        "transfer_ms": round(t.get("transfer_s", 0.0) * 1000, 3),
        "compute_ms": round(t.get("compute_s", 0.0) * 1000, 3),
        "sync_ms": round(t.get("sync_s", 0.0) * 1000, 3),
        "wall_ms": round(t.get("wall_s", 0.0) * 1000, 3),
        "pad_waste_ratio": (
            round(padded / (bytes_in + padded), 4)
            if (bytes_in + padded)
            else 0.0
        ),
        "resident_byte_ratio": (
            round(t.get("bytes_resident", 0) / bytes_in, 4)
            if bytes_in
            else 0.0
        ),
    }


def breakdown(
    before: dict, after: dict, backend: str = "jax"
) -> dict:
    """Diff two :meth:`DispatchProfiler.totals` snapshots into the
    bench artifact's dispatch-breakdown keys.  ALWAYS carries the six
    contract keys (``transfer_ms``/``compute_ms``/``sync_ms``/
    ``occupancy``/``pad_waste_ratio``/``resident_byte_ratio``) plus
    the ``backend`` marker — on a tunnel-down CPU path the values are
    the host-entry walls (or zero), never missing keys."""
    agg = {f: 0 for f in _TOTAL_FIELDS}
    kinds: dict[str, dict] = {}
    for kind, a in sorted(after.items()):
        b = before.get(kind, {})
        d = {f: a.get(f, 0) - b.get(f, 0) for f in _TOTAL_FIELDS}
        if d["dispatches"] <= 0:
            continue
        kinds[kind] = _derive(d)
        for f in _TOTAL_FIELDS:
            agg[f] += d[f]
    rolled = _derive(agg)
    return {
        "backend": backend,
        "dispatches": rolled["dispatches"],
        "transfer_ms": rolled["transfer_ms"],
        "compute_ms": rolled["compute_ms"],
        "sync_ms": rolled["sync_ms"],
        "occupancy": rolled["occupancy"],
        "pad_waste_ratio": rolled["pad_waste_ratio"],
        "resident_byte_ratio": rolled["resident_byte_ratio"],
        "kinds": kinds,
    }


def ensure_dispatch_counters(ks) -> None:
    """Force-register the ``l_tpu_dispatch_*`` family on a kernel set
    (check_metrics.py lints exactly these names; the profiler bumps
    them on every commit)."""
    ks.counter(
        "dispatch", "count",
        desc="device dispatches the flight recorder committed",
    )
    ks.counter(
        "dispatch", "ops",
        desc="client ops folded into recorded dispatches "
        "(cumulative; divide by count for mean occupancy)",
    )
    ks.counter(
        "dispatch", "stripes",
        desc="stripes/rows folded into recorded dispatches",
    )
    ks.counter(
        "dispatch", "bytes_uploaded",
        desc="logical payload bytes that crossed the host->device "
        "link in recorded dispatches",
    )
    ks.counter(
        "dispatch", "bytes_resident",
        desc="logical payload bytes served already-resident (the "
        "ResidencyCache hit path) in recorded dispatches",
    )
    ks.counter(
        "dispatch", "ring_dropped",
        desc="flight-recorder ring entries overwritten (drop-oldest)",
    )
    for stage, what in (
        ("transfer", "host->device upload issue"),
        ("compute", "kernel dispatch issue"),
        ("sync", "commit-point materialization"),
    ):
        ks.counter(
            "dispatch", f"{stage}_lat", kind=PERFCOUNTER_TIME,
            desc=f"per-dispatch {what} wall time (sync-bounded)",
        )
        ks.counter(
            "dispatch", f"{stage}_lat_hist",
            kind=PERFCOUNTER_HISTOGRAM,
            desc=f"per-dispatch {what} wall distribution "
            "(log2 buckets)",
            bounds=_LAT_HIST_BOUNDS,
        )


_instance: DispatchProfiler | None = None
_instance_lock = threading.Lock()


def dispatch_profiler() -> DispatchProfiler:
    """The process-global recorder (like the one JAX runtime whose
    dispatches it records)."""
    global _instance
    if _instance is None:
        with _instance_lock:
            if _instance is None:
                _instance = DispatchProfiler()
    return _instance
