"""Device-kernel telemetry — the perf-counter plane for the TPU hot
paths (the l_osd_* PerfCounters idiom, src/common/perf_counters.h,
applied to the device kernels the paper pins its metrics on).

One process-global ``PerfCounters`` set named ``tpu_kernels`` holds a
counter group per kernel entry point:

    l_tpu_<group>_calls      u64   kernel invocations
    l_tpu_<group>_bytes_in   u64   input bytes handed to the device
    l_tpu_<group>_bytes_out  u64   output bytes produced
    l_tpu_<group>_lat        time  wall latency (device-sync bounded:
                                   callers time through the
                                   np.asarray/block_until_ready sync)

plus the compile-cache counters:

    l_tpu_compile_cache_hit / l_tpu_compile_cache_miss

Groups registered by the instrumented modules: ``ec_encode`` /
``ec_decode`` (ec/stripe.py batched seam), ``gf_matmul`` /
``gf_bitmatrix`` (ops/ec_backend.py device dispatch), ``crush``
(osd/mapping.py batched PG mapping, where bytes_in counts PGs mapped
via the extra ``l_tpu_crush_pgs`` counter).

The set is a normal PerfCounters: daemons register it on their admin
socket collection (``perf dump``) and merge its dump into their
MMgrReport, so kernel telemetry flows through the existing
perf dump → MMgrReport → /metrics pipeline with no new plumbing.
Being process-global, co-hosted daemons (the test MiniCluster) share
one set — each reports the same process-wide kernel counters, the
same way they share the one JAX runtime.
"""

from __future__ import annotations

import threading
import time

from ..common.histogram import LATENCY_BUCKETS, LATENCY_MIN_S, log2_bounds
from ..common.perf_counters import (
    PERFCOUNTER_HISTOGRAM,
    PERFCOUNTER_TIME,
    PERFCOUNTER_U64,
    PerfCounters,
    _Counter,
)

# the shared log2 latency axis (common/histogram.py): every
# l_tpu_*_lat_hist uses it, so kernel latency histograms merge with
# the op-path ones under one bucket layout
_LAT_HIST_BOUNDS = log2_bounds(LATENCY_MIN_S, LATENCY_BUCKETS)


class KernelStats:
    def __init__(self, name: str = "tpu_kernels"):
        self.perf = PerfCounters(name)
        self._lock = threading.Lock()
        self._cache_call_lock = threading.Lock()
        self._groups: set[str] = set()
        self._ensure_counter("l_tpu_compile_cache_hit", PERFCOUNTER_U64,
                             "device bitmatrix/table cache hits")
        self._ensure_counter("l_tpu_compile_cache_miss", PERFCOUNTER_U64,
                             "device bitmatrix/table cache misses")
        # pow2 shape bucketing buys compile-cache hits by padding:
        # the EC batch-axis zero pad, the CRUSH lane-0 repeat, the
        # crc filler rows.  This counts those device-visible bytes so
        # the trade stops being invisible.
        self._ensure_counter(
            "l_tpu_pad_bytes_wasted", PERFCOUNTER_U64,
            "device bytes padded in by pow2 shape bucketing"
        )

    def _ensure_counter(
        self, name: str, kind: str, desc: str, bounds: tuple = ()
    ) -> None:
        with self.perf._lock:
            if name not in self.perf._counters:
                c = _Counter(name, kind, desc, bucket_bounds=bounds)
                if kind == PERFCOUNTER_HISTOGRAM:
                    c.buckets = [0] * (len(bounds) + 1)
                self.perf._counters[name] = c

    def _ensure_group(self, group: str) -> None:
        with self._lock:
            if group in self._groups:
                return
            base = f"l_tpu_{group}"
            self._ensure_counter(
                f"{base}_calls", PERFCOUNTER_U64, f"{group} kernel calls"
            )
            self._ensure_counter(
                f"{base}_bytes_in", PERFCOUNTER_U64, f"{group} input bytes"
            )
            self._ensure_counter(
                f"{base}_bytes_out", PERFCOUNTER_U64, f"{group} output bytes"
            )
            self._ensure_counter(
                f"{base}_lat", PERFCOUNTER_TIME, f"{group} kernel latency"
            )
            # histogram variant of the sync-bounded latency: the avg
            # pair answers "mean", the log2 buckets answer "p99"
            self._ensure_counter(
                f"{base}_lat_hist",
                PERFCOUNTER_HISTOGRAM,
                f"{group} kernel latency distribution (log2 buckets)",
                bounds=_LAT_HIST_BOUNDS,
            )
            self._groups.add(group)

    # -- recording ---------------------------------------------------------
    def record(
        self,
        group: str,
        bytes_in: int = 0,
        bytes_out: int = 0,
        seconds: float = 0.0,
    ) -> None:
        self._ensure_group(group)
        base = f"l_tpu_{group}"
        self.perf.inc(f"{base}_calls")
        if bytes_in:
            self.perf.inc(f"{base}_bytes_in", int(bytes_in))
        if bytes_out:
            self.perf.inc(f"{base}_bytes_out", int(bytes_out))
        self.perf.tinc(f"{base}_lat", seconds)
        self.perf.hinc(f"{base}_lat_hist", seconds)

    def record_cache(self, hits: int, misses: int) -> None:
        if hits:
            self.perf.inc("l_tpu_compile_cache_hit", hits)
        if misses:
            self.perf.inc("l_tpu_compile_cache_miss", misses)

    def counted_cache_call(self, cached_fn, *args):
        """Call an ``functools.lru_cache``-wrapped function and record
        the hit/miss it produced.  The snapshot-call-snapshot runs
        under one lock so concurrent callers cannot double- or
        zero-count against the shared cache_info (misses — the
        expensive bitmatrix builds — serialize; hits are dict
        lookups, so the lock is cheap where it matters)."""
        with self._cache_call_lock:
            before = cached_fn.cache_info()
            out = cached_fn(*args)
            after = cached_fn.cache_info()
            self.record_cache(
                after.hits - before.hits, after.misses - before.misses
            )
        return out

    def record_pad(self, nbytes: int) -> None:
        """Count shape-bucketing pad bytes (device-visible bytes that
        carry no payload)."""
        if nbytes:
            self.perf.inc("l_tpu_pad_bytes_wasted", int(nbytes))

    def counter(self, group: str, suffix: str, kind=PERFCOUNTER_U64,
                desc: str = "", bounds: tuple = ()):
        """Register an extra per-group counter (e.g. crush's
        l_tpu_crush_pgs) and return its full name."""
        name = f"l_tpu_{group}_{suffix}"
        self._ensure_counter(name, kind, desc, bounds=bounds)
        return name

    def timed(self, group: str, bytes_in: int = 0):
        """Context manager timing one kernel call; the caller must
        sync the device inside the block (np.asarray /
        block_until_ready) so the latency is real, not dispatch."""
        return _KernelTimer(self, group, bytes_in)

    def dump(self) -> dict:
        return self.perf.dump()

    def snapshot(self) -> dict:
        """Compact rollup for result artifacts (bench.py embeds this
        in the BENCH JSON line): compile-cache hit ratio plus per-group
        call/byte totals — kernel behavior, not just GB/s."""
        dump = self.dump()
        hits = int(dump.get("l_tpu_compile_cache_hit", 0))
        misses = int(dump.get("l_tpu_compile_cache_miss", 0))
        lookups = hits + misses
        groups = {}
        with self._lock:
            known = sorted(self._groups)
        for group in known:
            base = f"l_tpu_{group}"
            lat = dump.get(f"{base}_lat") or {}
            groups[group] = {
                "calls": int(dump.get(f"{base}_calls", 0)),
                "bytes_in": int(dump.get(f"{base}_bytes_in", 0)),
                "bytes_out": int(dump.get(f"{base}_bytes_out", 0)),
                "lat_sum_s": round(float(lat.get("sum", 0.0)), 6),
            }
        return {
            "compile_cache": {
                "hits": hits,
                "misses": misses,
                "hit_ratio": (
                    round(hits / lookups, 4) if lookups else None
                ),
            },
            "groups": groups,
        }


class _KernelTimer:
    __slots__ = ("_ks", "_group", "_bytes_in", "bytes_out", "_t0")

    def __init__(self, ks: KernelStats, group: str, bytes_in: int):
        self._ks = ks
        self._group = group
        self._bytes_in = bytes_in
        self.bytes_out = 0

    def __enter__(self) -> "_KernelTimer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, *exc) -> bool:
        if exc_type is None:
            self._ks.record(
                self._group,
                bytes_in=self._bytes_in,
                bytes_out=self.bytes_out,
                seconds=time.perf_counter() - self._t0,
            )
        return False


_instance: KernelStats | None = None
_instance_lock = threading.Lock()


def kernel_stats() -> KernelStats:
    """The process-global collector (like the one JAX runtime the
    kernels themselves share)."""
    global _instance
    if _instance is None:
        with _instance_lock:
            if _instance is None:
                _instance = KernelStats()
    return _instance
