"""Packed-lane GF(2^8) region kernel — the fast TPU encode/decode path.

The bitplane formulation (ops.gf_matmul) pays for an 8x unpack on the
VPU and a tiny (m·8, k·8) matmul that uses a few percent of the MXU.
This kernel keeps bytes PACKED four-per-u32 lane end to end:

- bit b of the four bytes in a lane extract together:
  ``(x >> b) & 0x01010101`` — one shift+and yields FOUR bitplane
  values, each in its own byte field;
- a GF(2) bitmatrix row is a fixed XOR-subset of input bit planes.
  Integer ADDs of the extracted fields accumulate each field
  independently (sums are bounded by the row's popcount <= 255, so
  carries never cross byte fields) and the low bit of each field is
  the mod-2 result;
- ``(acc & LSB) << b`` deposits output bit b of four output bytes at
  once, so the OR-accumulated result IS the byte-packed output lane.

Per input byte this costs ~15 single VPU ops (after the pair-CSE
schedule below) with NO 8x blowup and no MXU dependence; measured on
a v5e the k=8,m=3 encode runs at 124-139 GB/s of input vs 72-77 GB/s
for the bitplane matmul (bench.py methodology; ops/pallas_gf.py keeps
the older measurement history).  The add-chain is unrolled per
bitmatrix at trace time — kernels cache per matrix exactly like the
reference's per-signature table expansion (ErasureCodeIsa.cc:402
ec_init_tables).

LAYOUT CONTRACT — "word form".  Region bytes enter as little-endian
u32 words, one region per (1, nwords) array (byte 4w+q of the region
is field q of word w — exactly ``numpy.view(uint32)``).  Rows travel
as SEPARATE arrays because XLA assigns a pathological 16x-padded
layout to a stacked (k, nwords) u32 operand and materializes u8⇄u32
bitcasts of big arrays at ~2 GB/s; per-row 1D-ish arrays sidestep
both (measured >60x difference).  Host callers get the conversion for
free via numpy views (``to_words``/``from_words``); device-resident
pipelines should carry word form between calls.

w=8 only (the jerasure/isa default and the BASELINE.md configs);
other word sizes use the bitplane path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

TILE_WORDS = 8192  # u32 lanes per grid step (measured best 4096-8192)
_LSB = 0x01010101


def _rows_of(bm: np.ndarray) -> tuple[tuple[int, ...], ...]:
    return tuple(
        tuple(np.nonzero(bm[r])[0].tolist()) for r in range(bm.shape[0])
    )


def supports(bm: np.ndarray, w: int) -> bool:
    """Eligibility: w=8 and every output row's popcount fits a byte
    field (no carry into the neighbouring packed byte)."""
    return (
        w == 8
        and bm.shape[0] % 8 == 0
        and bm.shape[1] % 8 == 0
        and int(bm.sum(axis=1).max(initial=0)) <= 255
    )


def to_words(regions: np.ndarray) -> list[np.ndarray]:
    """(k, nbytes) u8 → k arrays of (1, nbytes//4) u32 — a free view."""
    regions = np.ascontiguousarray(regions, dtype=np.uint8)
    assert regions.shape[1] % 4 == 0, regions.shape
    return [
        row.view(np.uint32).reshape(1, -1) for row in regions
    ]


def from_words(words: list[np.ndarray]) -> np.ndarray:
    """k arrays of (1, nwords) u32 → (k, nwords*4) u8 — a free view."""
    return np.stack(
        [np.asarray(w).reshape(-1).view(np.uint8) for w in words]
    )


@functools.lru_cache(maxsize=512)
def _schedule(rows: tuple[tuple[int, ...], ...]):
    """Greedy pair-CSE over the add-chains (the packed-lane analog of
    jerasure's smart XOR schedules): the most frequent column pair
    across all rows becomes a shared node, repeatedly.  Safe for the
    carry bound: a shared node's field sum never exceeds the largest
    row popcount it appears in.

    Returns (pair_nodes, row_exprs): pair_nodes[t] = (a, b) defines
    node ``base+t`` as a+b; row_exprs[r] lists the node ids summed."""
    exprs = [list(t) for t in rows]
    base = 1 + max((c for t in rows for c in t), default=0)
    pairs: list[tuple[int, int]] = []
    while True:
        counts: dict[tuple[int, int], int] = {}
        for e in exprs:
            seen = sorted(set(e))
            for ai in range(len(seen)):
                for bi in range(ai + 1, len(seen)):
                    p = (seen[ai], seen[bi])
                    counts[p] = counts.get(p, 0) + 1
        if not counts:
            break
        (a, b), cnt = max(counts.items(), key=lambda kv: kv[1])
        if cnt < 2:
            break
        node = base + len(pairs)
        pairs.append((a, b))
        for e in exprs:
            if a in e and b in e:
                e.remove(a)
                e.remove(b)
                e.append(node)
    return tuple(pairs), tuple(tuple(e) for e in exprs)


def _make_kernel(rows: tuple[tuple[int, ...], ...], n_in: int, m_out: int):
    pair_nodes, row_exprs = _schedule(rows)
    base = 1 + max((c for t in rows for c in t), default=0)

    def kernel(*refs):
        ins, outs = refs[:n_in], refs[n_in:]
        lsb = jnp.uint32(_LSB)
        nodes: dict[int, jnp.ndarray] = {}

        def node(c):
            if c not in nodes:
                if c >= base:
                    a, b = pair_nodes[c - base]
                    nodes[c] = node(a) + node(b)
                else:
                    j, b = divmod(c, 8)
                    x = ins[j][:]
                    nodes[c] = (x >> b) & lsb if b else x & lsb
            return nodes[c]

        for i in range(m_out):
            ob = None
            for b in range(8):
                expr = row_exprs[i * 8 + b]
                if not expr:
                    continue
                acc = node(expr[0])
                for c in expr[1:]:
                    acc = acc + node(c)
                t = (acc & lsb) << b if b else acc & lsb
                ob = t if ob is None else ob | t
            outs[i][:] = (
                ob if ob is not None else jnp.zeros_like(ins[0][:])
            )

    return kernel


@functools.lru_cache(maxsize=512)
def _packed_call(
    rows: tuple[tuple[int, ...], ...],
    n_in: int,
    m_out: int,
    interpret: bool,
):
    kernel = _make_kernel(rows, n_in, m_out)

    @jax.jit
    def run(*xs):  # n_in arrays of (1, nwords) u32
        n4 = xs[0].shape[1]
        tile = min(TILE_WORDS, n4)
        pad = (-n4) % tile
        if pad:
            z = jnp.zeros((1, pad), dtype=jnp.uint32)
            xs = tuple(jnp.concatenate([x, z], axis=1) for x in xs)
            n4 += pad
        outs = pl.pallas_call(
            kernel,
            grid=(n4 // tile,),
            in_specs=[
                pl.BlockSpec((1, tile), lambda i: (0, i))
                for _ in range(n_in)
            ],
            out_specs=[
                pl.BlockSpec((1, tile), lambda i: (0, i))
                for _ in range(m_out)
            ],
            out_shape=[
                jax.ShapeDtypeStruct((1, n4), jnp.uint32)
                for _ in range(m_out)
            ],
            interpret=interpret,
        )(*xs)
        if pad:
            outs = [o[:, : n4 - pad] for o in outs]
        return outs

    return run


def prebuilt_word_call(bm: np.ndarray, w: int = 8, *, interpret: bool = False):
    """Public constructor of the cached word-form kernel for one
    bitmatrix: returns ``call(*k_word_arrays) -> m_word_arrays``.
    For callers (benchmarks, device-resident pipelines) that apply
    the same matrix repeatedly and want to hold the compiled callable
    rather than re-entering packed_word_regions' conversion layer."""
    bm = np.asarray(bm)
    assert supports(bm, w), "packed kernel needs w=8, row popcount <= 255"
    return _packed_call(
        _rows_of(bm), bm.shape[1] // 8, bm.shape[0] // 8, interpret
    )


def packed_word_regions(
    bm: np.ndarray, words, *, interpret: bool = False
):
    """Apply a (m·8, k·8) GF(2) bitmatrix (word layout, w=8) to k
    word-form regions → m word-form regions (each (1, nwords) u32)."""
    bm = np.asarray(bm)
    assert supports(bm, 8), "packed kernel needs w=8, row popcount <= 255"
    words = [jnp.asarray(x) for x in words]
    return _packed_call(
        _rows_of(bm), len(words), bm.shape[0] // 8, interpret
    )(*words)


def packed_bitmatrix_regions(
    bm: np.ndarray, regions: np.ndarray, *, interpret: bool = False
) -> np.ndarray:
    """numpy-in/numpy-out convenience: (k, nbytes) u8 → (m, nbytes)
    u8, converting at the host boundary where views are free."""
    outs = packed_word_regions(
        bm, to_words(np.asarray(regions)), interpret=interpret
    )
    return from_words([np.asarray(o) for o in outs])


def packed_matrix_stripes(
    bm: np.ndarray, stripes: np.ndarray, *, interpret: bool = False
) -> np.ndarray:
    """Batched (B, k, chunk) u8 → (B, m, chunk) u8 through the packed
    kernel (the hoisted ECUtil::encode seam).  Host-side fold: the
    device-side transpose is exactly the relayout this kernel exists
    to avoid."""
    from ..layout import fold_stripes, unfold_stripes

    stripes = np.ascontiguousarray(stripes, dtype=np.uint8)
    b, _k, chunk = stripes.shape
    out = packed_bitmatrix_regions(
        bm, fold_stripes(stripes), interpret=interpret
    )
    return unfold_stripes(out, b, chunk)
