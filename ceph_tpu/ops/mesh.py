"""Device-mesh execution plane: discovery, sharding, telemetry.

The single-device kernels (ops/gf_matmul.py, crush/jaxmap.py) batch a
whole workload into one device call; this module spreads that batch
across EVERY chip — real TPUs or the
``--xla_force_host_platform_device_count=8`` virtual CPU mesh the test
suite and the driver's multichip dryrun provision.  The reference's
CPU analog shards pgid ranges over a thread pool
(ParallelPGMapper, src/osd/OSDMapMapping.h:18-156); here the pool is
the device mesh and the shard axis is the batch dimension of an
already-jitted kernel, so sharding never changes the per-lane math —
outputs are byte-identical to the single-device path (asserted in
tests/test_mesh.py, ragged batch sizes included).

Pieces:

- discovery: ``available_devices()`` never raises (a configured but
  unreachable accelerator plugin means "no devices", not a crash) and
  ``build_mesh(n)`` / ``default_mesh()`` construct 1-D meshes over
  them.  Everything is device-count-agnostic: callers ask for a mesh
  and get however many chips exist.
- sharding specs: ``DeviceMesh.batch_spec(ndim, axis)`` names the
  batch axis of an operand, ``replicated_spec()`` the broadcast
  tables; ragged batches pad to a device-count multiple on the host
  and slice back after gather (``pad_to_devices``).
- sharded EC encode: ``sharded_matrix_stripes`` runs the bitplane
  stripe kernel with the object batch sharded across the mesh.
- telemetry: every sharded dispatch records per-device counters
  (``l_tpu_mesh_dev<i>_calls/_bytes``) plus the usual group totals
  through ops/kernel_stats.py, so mesh behavior flows perf dump →
  MMgrReport → /metrics like every other kernel counter.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from .kernel_stats import kernel_stats

_AXIS = "shard"


def available_devices() -> list:
    """``jax.devices()`` that never raises: a broken hardware plugin
    (e.g. the TPU tunnel down) reports as zero devices so callers
    degrade instead of crashing (the BENCH_r05 rc=1 class)."""
    import jax

    try:
        return list(jax.devices())
    except RuntimeError:
        return []


def probe_devices_subprocess(
    timeout: float | None = None,
) -> tuple[int | None, str | None, str | None]:
    """Count devices in a SUBPROCESS, because a HUNG hardware-plugin
    init (tunnel down but the plugin still registered) blocks
    ``jax.devices()`` forever in-process — the failure mode
    :func:`available_devices` cannot catch.  A bounded timeout turns
    that hang into ``(None, None, reason)``; callers then pin to the
    CPU fallback.  The one probe shared by ``bench.py`` and
    ``__graft_entry__`` (CEPH_TPU_BACKEND_PROBE_TIMEOUT, default
    60 s).  Returns ``(device_count, platform, None)`` on success or
    ``(None, None, reason)``."""
    import subprocess
    import sys

    if timeout is None:
        try:
            timeout = float(
                os.environ.get("CEPH_TPU_BACKEND_PROBE_TIMEOUT", "60")
            )
        except ValueError:
            timeout = 60.0
    code = (
        "import jax, sys; d = jax.devices(); "
        "sys.stdout.write(f'{len(d)} {d[0].platform}')"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            timeout=timeout,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return None, None, f"device probe hung > {timeout:.0f}s"
    except OSError as e:
        return None, None, f"probe spawn failed: {e}"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()
        return None, None, (
            tail[-1] if tail else f"probe rc={proc.returncode}"
        )
    try:
        fields = proc.stdout.strip().splitlines()[-1].split()
        return int(fields[0]), fields[1], None
    except (ValueError, IndexError):
        return (
            None,
            None,
            f"unparseable probe output: {proc.stdout[-80:]!r}",
        )


def device_count() -> int:
    return len(available_devices())


class DeviceMesh:
    """A 1-D ``jax.sharding.Mesh`` over explicit devices, axis
    ``shard`` — the batch axis every sharded kernel splits on."""

    def __init__(self, devices, axis: str = _AXIS):
        from jax.sharding import Mesh

        self.devices = list(devices)
        if not self.devices:
            raise ValueError("DeviceMesh needs at least one device")
        self.axis = axis
        self.mesh = Mesh(np.asarray(self.devices), (axis,))

    @property
    def n(self) -> int:
        return len(self.devices)

    @property
    def platform(self) -> str:
        return self.devices[0].platform

    def batch_spec(self, ndim: int, axis: int = 0):
        """NamedSharding splitting dimension ``axis`` of an
        ``ndim``-dimensional operand across the mesh."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = [None] * ndim
        spec[axis] = self.axis
        return NamedSharding(self.mesh, P(*spec))

    def replicated_spec(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    # skey-style cache identity: the same device set compiles once
    def cache_key(self) -> tuple:
        return tuple(d.id for d in self.devices)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"DeviceMesh({self.n}x{self.platform})"


def build_mesh(n: int | None = None, devices=None) -> DeviceMesh | None:
    """Mesh over the first ``n`` (default: all) devices; None when no
    device backend initializes at all."""
    devs = list(devices) if devices is not None else available_devices()
    if not devs:
        return None
    if n is not None:
        devs = devs[: max(int(n), 1)]
    return DeviceMesh(devs)


# -- the default product mesh ------------------------------------------------
# Probed once per process (like the one JAX runtime the kernels share).
# CEPH_TPU_MESH=0 disables sharding outright; CEPH_TPU_MESH_DEVICES=k
# caps the device count.  Single-device hosts get None so product
# paths keep their exact existing dispatch.

_default_lock = threading.Lock()
_default_probed = False
_default_mesh: DeviceMesh | None = None


def default_mesh() -> DeviceMesh | None:
    """The process mesh product paths shard over when >1 device
    exists; None on single-device (or deviceless, or disabled)
    hosts."""
    global _default_probed, _default_mesh
    if not _default_probed:
        with _default_lock:
            if not _default_probed:
                mesh = None
                if os.environ.get("CEPH_TPU_MESH", "1") != "0":
                    devs = available_devices()
                    try:
                        cap = int(
                            os.environ.get("CEPH_TPU_MESH_DEVICES", "0")
                        )
                    except ValueError:
                        cap = 0
                    if cap > 0:
                        devs = devs[:cap]
                    if len(devs) > 1:
                        mesh = DeviceMesh(devs)
                _default_mesh = mesh
                _default_probed = True
    return _default_mesh


def _reset_default_mesh_for_tests() -> None:
    global _default_probed, _default_mesh
    with _default_lock:
        _default_probed = False
        _default_mesh = None


# -- ragged-batch padding ----------------------------------------------------


def pad_to_devices(arr: np.ndarray, n_dev: int, axis: int = 0):
    """Pad ``axis`` up to a multiple of ``n_dev`` by repeating the
    last slice (any valid input works — padded lanes are discarded
    after gather).  Returns (padded, original_length)."""
    n = arr.shape[axis]
    pad = (-n) % max(n_dev, 1)
    if not pad:
        return arr, n
    tail = np.take(arr, [n - 1], axis=axis)
    reps = [1] * arr.ndim
    reps[axis] = pad
    return np.concatenate([arr, np.tile(tail, reps)], axis=axis), n


# -- telemetry ---------------------------------------------------------------


def record_shard_dispatch(
    dmesh: DeviceMesh, group: str, bytes_in: int, seconds: float
) -> None:
    """Per-device mesh counters: each device of the mesh saw one shard
    of ~bytes_in/n, plus the per-group rollup (``l_tpu_mesh_*``)."""
    ks = kernel_stats()
    ks.record(f"mesh_{group}", bytes_in=bytes_in, seconds=seconds)
    per_dev = bytes_in // max(dmesh.n, 1)
    for i in range(dmesh.n):
        ks.perf.inc(
            ks.counter("mesh", f"dev{i}_calls", desc="shards dispatched")
        )
        if per_dev:
            ks.perf.inc(
                ks.counter(
                    "mesh", f"dev{i}_bytes", desc="shard bytes in"
                ),
                per_dev,
            )


# -- sharded EC encode -------------------------------------------------------

_stripe_call_cache: dict[tuple, object] = {}
_stripe_call_lock = threading.Lock()


def _sharded_stripe_fn(dmesh: DeviceMesh, w: int):
    """Jitted ``gf_matrix_stripes`` with the (B, k, chunk) batch axis
    sharded across the mesh; compiled once per (device set, w)."""
    import jax

    from .gf_matmul import gf_matrix_stripes

    key = (dmesh.cache_key(), w)
    with _stripe_call_lock:
        fn = _stripe_call_cache.get(key)
        if fn is None:
            data_spec = dmesh.batch_spec(3)
            repl = dmesh.replicated_spec()
            fn = jax.jit(
                lambda bm, s: gf_matrix_stripes(bm, s, w=w),
                in_shardings=(repl, data_spec),
                out_shardings=data_spec,
            )
            _stripe_call_cache[key] = fn
    return fn


def sharded_matrix_stripes(
    bm, stripes: np.ndarray, w: int, dmesh: DeviceMesh
) -> np.ndarray:
    """Batched (B, k, chunk) → (B, m, chunk) encode with the object
    batch sharded across ``dmesh``.  Byte-identical to the
    single-device ``gf_matrix_stripes``: each stripe's math is
    lane-independent integer mod-2 arithmetic, so splitting B never
    changes a byte — ragged B pads on the host and slices back."""
    import time

    import jax
    import jax.numpy as jnp

    stripes = np.ascontiguousarray(stripes, dtype=np.uint8)
    padded, n = pad_to_devices(stripes, dmesh.n)
    t0 = time.perf_counter()
    data = jax.device_put(jnp.asarray(padded), dmesh.batch_spec(3))
    bm_d = jax.device_put(bm, dmesh.replicated_spec())
    out = np.asarray(_sharded_stripe_fn(dmesh, w)(bm_d, data))[:n]
    record_shard_dispatch(
        dmesh, "ec_encode", stripes.nbytes, time.perf_counter() - t0
    )
    return out
