"""Device-resident payload plane — upload once, reuse across stages.

The measured motivation (BENCH_r04, ROADMAP open item 1): the encode
kernels run at 134 GB/s but end-to-end storage throughput is
0.012 GB/s because every stage — EC encode, deep-scrub crc32c, EC
re-encode verify — does its own host→device ``device_put``, compute,
sync, fetch.  The reference amortizes the analogous cost (per-call
SIMD table setup) by keeping the plugin boundary coarse
(``ErasureCodeInterface.h:170-462``) and by batching whole-map work
(``ParallelPGMapper``); the TPU analog must amortize the *link*.

Three pieces live here:

- ``DeviceBuf`` — the token the kernel entry points accept in place
  of host ``bytes``: logical length host-side, payload either a
  device array (already resident: a batched-encode output slice) or
  host bytes uploaded lazily on FIRST device use and kept.  Either
  way the link is paid at most once per generation.
- ``ResidencyCache`` — bounded LRU of DeviceBufs keyed by
  ``(store, cid, oid)``.  Validity is generation-checked against
  ``store.objectstore.residency_gens``: every ``queue_transaction``
  bumps the named objects' generations BEFORE applying, so a stale
  resident buffer can never serve a scrub digest — any mutation
  (client write, recovery push, injected bit rot) makes the next
  lookup miss and re-read the store.  Counters:
  ``l_tpu_residency_{hits,misses,evictions,bytes_resident}``.
- shape bucketing + compile-cache plumbing — ``bucket_pow2`` pads
  batch axes to powers of two so coalesced writes and CRUSH remaps
  replay compiled programs instead of compiling per ragged shape;
  ``note_shape`` feeds the reuse into the existing
  ``l_tpu_compile_cache_{hit,miss}`` counters, and
  ``configure_compile_cache`` points JAX's persistent compilation
  cache at ``$CEPH_TPU_COMPILE_CACHE`` so the 4-6s cold CRUSH
  compile approaches the 0.64s cached-replay rate across processes.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

import numpy as np

from ..store.objectstore import residency_gens
from .kernel_stats import kernel_stats

# default capacity of the process-global cache (bytes of logical
# payload); CEPH_TPU_RESIDENCY_BYTES overrides
DEFAULT_CAPACITY = 256 << 20


class DeviceBuf:
    """One payload's device residency token.

    ``device()`` returns the uint8 device array (uploading once if the
    buf was registered from host bytes); ``host()`` returns the host
    bytes (fetching once if the buf was registered from a device
    array).  ``len()`` is always the logical byte length, host-side —
    callers pad/stack without touching the device.
    """

    __slots__ = ("length", "gen", "_host", "_dev", "_lock")

    def __init__(self, data=None, dev=None, gen=(0, 0)):
        if data is None and dev is None:
            raise ValueError("DeviceBuf needs host bytes or a device array")
        self._host = None if data is None else bytes(data)
        self._dev = dev
        self.length = (
            len(self._host) if self._host is not None else int(dev.shape[0])
        )
        self.gen = gen
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return self.length

    @property
    def resident(self) -> bool:
        """True once the payload is on device (upload already paid)."""
        return self._dev is not None

    def device(self):
        """The (length,) uint8 device array; uploads at most once.
        The host copy is DROPPED after the upload — keeping both
        would make real memory 2x what the cache accounts (and the
        device side is the one every consumer wants; a later
        ``host()`` pays one fetch)."""
        if self._dev is None:
            with self._lock:
                if self._dev is None:
                    import jax

                    arr = np.frombuffer(self._host, dtype=np.uint8)
                    self._dev = jax.device_put(arr)
                    self._host = None
        return self._dev

    def host(self) -> bytes:
        """Host bytes; fetches at most once for device-born bufs."""
        if self._host is None:
            with self._lock:
                if self._host is None:
                    self._host = bytes(
                        np.asarray(self._dev, dtype=np.uint8)
                    )
        return self._host


def is_device_buf(x) -> bool:
    return isinstance(x, DeviceBuf)


def scrub_trusted(store) -> bool:
    """True when DEEP SCRUB may digest a resident copy for this
    store: the store must both observe all its own mutations
    (``residency_local``) and be unable to diverge from the resident
    copy out-of-band (``residency_scrub_safe`` — in-memory stores).
    Persistent media (BlockStore) returns False: bit rot never runs
    a transaction, and auditing it is what deep scrub is FOR."""
    return getattr(store, "residency_local", False) and getattr(
        store, "residency_scrub_safe", False
    )


def as_host_bytes(x) -> bytes:
    """bytes for either a DeviceBuf or a bytes-like (the oracle /
    numpy fallback seam of the kernel entry points)."""
    return x.host() if isinstance(x, DeviceBuf) else bytes(x)


class ResidencyCache:
    """Bounded LRU of DeviceBufs keyed by (store, cid, oid), with
    generation-checked lookups (see module docstring)."""

    def __init__(self, capacity_bytes: int | None = None, ks=None):
        if capacity_bytes is None:
            try:
                capacity_bytes = int(
                    os.environ.get("CEPH_TPU_RESIDENCY_BYTES", "")
                    or DEFAULT_CAPACITY
                )
            except ValueError:
                capacity_bytes = DEFAULT_CAPACITY
        self.capacity_bytes = max(int(capacity_bytes), 0)
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, DeviceBuf] = OrderedDict()
        self._bytes = 0
        self._ks = ks or kernel_stats()
        ensure_counters(self._ks)

    # -- keying ------------------------------------------------------------
    @staticmethod
    def _key(store, cid: str, oid: str) -> tuple:
        return (residency_gens.store_token(store), cid, oid)

    # -- writes ------------------------------------------------------------
    def put_committed(
        self, store, cid: str, oid: str, data=None, dev=None
    ):
        """Register bytes a transaction THIS THREAD just committed.

        The generation captured is the one that txn itself assigned
        (``residency_gens.txn_gen``), NOT the current one — so a
        concurrent writer's txn landing in the commit-to-register
        window assigns a higher generation and the entry registered
        here simply misses, instead of absorbing the other writer's
        bytes.  This is the registration every product write path
        uses; returns None (no registration) when no own-thread txn
        is on record."""
        gen = residency_gens.txn_gen(store, cid, oid)
        if gen is None:
            return None
        return self.put(store, cid, oid, data=data, dev=dev, gen=gen)

    def put(
        self, store, cid: str, oid: str, data=None, dev=None, gen=None
    ):
        """Register a payload as resident for (store, cid, oid).

        Call AFTER the transaction that landed these bytes applied (the
        txn bumped the generation; registering first would record the
        pre-bump generation and self-invalidate).  ``data`` registers
        host bytes with a lazy upload; ``dev`` registers an
        already-resident device array (a batched-encode output slice —
        zero additional transfer).  Stores that cannot observe their
        own mutations (RemoteStore proxies) are refused.  ``gen``
        pins the registered generation (see put_committed); default
        is the object's CURRENT generation, which is only race-free
        when the caller serializes writers itself.  Returns the
        DeviceBuf, or None when registration is not applicable.
        """
        if not scrub_trusted(store):
            # every current consumer is scrub-side and gates on
            # scrub_trusted: registering for a store no reader will
            # ever consult (e.g. BlockStore media) would just pin
            # payload copies in RAM and churn the LRU
            return None
        if self.capacity_bytes <= 0:
            return None
        if gen is None:
            gen = residency_gens.gen_of(store, cid, oid)
        buf = DeviceBuf(data=data, dev=dev, gen=gen)
        if buf.length > self.capacity_bytes:
            return None  # larger than the whole cache: never resident
        key = self._key(store, cid, oid)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.length
            self._entries[key] = buf
            self._bytes += buf.length
            while self._bytes > self.capacity_bytes and self._entries:
                _k, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.length
                self._ks.perf.inc("l_tpu_residency_evictions")
            self._ks.perf.set("l_tpu_residency_bytes_resident", self._bytes)
        return buf

    def invalidate(self, store, cid: str, oid: str) -> None:
        """Explicit drop (mutation paths that want eager reclamation;
        generation checking already guarantees correctness)."""
        key = self._key(store, cid, oid)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.length
                self._ks.perf.set(
                    "l_tpu_residency_bytes_resident", self._bytes
                )

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._ks.perf.set("l_tpu_residency_bytes_resident", 0)

    # -- reads -------------------------------------------------------------
    def get(
        self, store, cid: str, oid: str, expect_len: int | None = None
    ) -> DeviceBuf | None:
        """Generation-checked lookup: returns the DeviceBuf only when
        no transaction has named the object since registration AND the
        length matches the caller's expectation; anything else is a
        miss (and a stale entry is dropped on sight)."""
        key = self._key(store, cid, oid)
        with self._lock:
            buf = self._entries.get(key)
            if buf is not None:
                if (
                    buf.gen != residency_gens.gen_of(store, cid, oid)
                    or (expect_len is not None and buf.length != expect_len)
                ):
                    self._entries.pop(key, None)
                    self._bytes -= buf.length
                    self._ks.perf.set(
                        "l_tpu_residency_bytes_resident", self._bytes
                    )
                    buf = None
                else:
                    self._entries.move_to_end(key)
            if buf is None:
                self._ks.perf.inc("l_tpu_residency_misses")
                return None
            self._ks.perf.inc("l_tpu_residency_hits")
            return buf

    # -- observability -----------------------------------------------------
    def stats(self) -> dict:
        dump = self._ks.dump()
        hits = int(dump.get("l_tpu_residency_hits", 0))
        misses = int(dump.get("l_tpu_residency_misses", 0))
        lookups = hits + misses
        with self._lock:
            nbytes, entries = self._bytes, len(self._entries)
        return {
            "hits": hits,
            "misses": misses,
            "evictions": int(dump.get("l_tpu_residency_evictions", 0)),
            "bytes_resident": nbytes,
            "entries": entries,
            "reuse_ratio": (
                round(hits / lookups, 4) if lookups else None
            ),
        }


def ensure_counters(ks) -> None:
    """Force-register the residency + batched-encode counter families
    (check_metrics.py lints exactly these names)."""
    ks.counter("residency", "hits", desc="resident payload reuses")
    ks.counter(
        "residency", "misses",
        desc="payload lookups that re-read the store",
    )
    ks.counter(
        "residency", "evictions", desc="LRU evictions under pressure"
    )
    from ..common.perf_counters import PERFCOUNTER_GAUGE

    ks.counter(
        "residency", "bytes_resident", kind=PERFCOUNTER_GAUGE,
        desc="logical bytes currently registered resident",
    )
    ks.counter(
        "batch_encode", "dispatches",
        desc="coalesced encode passes (one encode_batch call each; "
        "the backend may pipeline a pass as several device groups)",
    )
    ks.counter(
        "batch_encode", "ops_per_dispatch",
        desc="client writes folded into coalesced passes "
        "(cumulative; divide by dispatches for the mean writes "
        "folded per pass)",
    )
    ks.counter(
        "batch_decode", "dispatches",
        desc="coalesced decode-from-survivors passes (one "
        "decode_batch group each; the backend may pipeline a pass "
        "as several device groups)",
    )
    ks.counter(
        "batch_decode", "ops_per_dispatch",
        desc="objects rebuilt through coalesced decode passes "
        "(cumulative; divide by dispatches for the mean objects "
        "folded per pass)",
    )


_instance: ResidencyCache | None = None
_instance_lock = threading.Lock()


def residency_cache() -> ResidencyCache:
    """The process-global cache (like the one JAX runtime the resident
    buffers live in)."""
    global _instance
    if _instance is None:
        with _instance_lock:
            if _instance is None:
                _instance = ResidencyCache()
    return _instance


# -- shape bucketing ---------------------------------------------------------

def bucket_pow2(n: int, floor: int = 1) -> int:
    """Next power of two >= max(n, floor) — the pad-and-slice bucket
    batched shapes round to so ragged coalesced batches and remap
    sweeps replay compiled programs."""
    n = max(int(n), int(floor), 1)
    return 1 << (n - 1).bit_length()


_seen_shapes: set = set()
_shapes_lock = threading.Lock()


def note_shape(site: str, *shape) -> bool:
    """Record one bucketed-shape dispatch against the compile cache
    counters: a shape this process already dispatched is a compiled-
    program replay (hit), a fresh one is a compile (miss).  Returns
    True on hit."""
    key = (site, shape)
    with _shapes_lock:
        hit = key in _seen_shapes
        if not hit:
            _seen_shapes.add(key)
    kernel_stats().record_cache(int(hit), int(not hit))
    # attach the event to the active flight-recorder dispatch (the
    # global counters above are the source of truth)
    from .profiler import record_compile

    record_compile(hit)
    return hit


# -- persistent compilation cache --------------------------------------------

_compile_cache_dir: str | None = None


def configure_compile_cache() -> str | None:
    """Point JAX's persistent compilation cache at
    ``$CEPH_TPU_COMPILE_CACHE`` (idempotent; returns the active dir or
    None).  Cold CRUSH compile+first-batch costs 4-6s on this mount;
    a warm persistent cache replays in ~0.64s
    (``crush_remap_cached_sec``, BENCH_r04) — this extends that replay
    across process boundaries."""
    global _compile_cache_dir
    path = os.environ.get("CEPH_TPU_COMPILE_CACHE")
    if not path or _compile_cache_dir == path:
        return _compile_cache_dir
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # cache every program: the CRUSH kernels are large, but the
        # bucketed encode programs are small and just as hot
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        _compile_cache_dir = path
    except Exception:  # noqa: BLE001 — an old jax without the knobs
        # (or a broken backend) must not take the import down
        return None
    return _compile_cache_dir
