"""osdc — the client op engine (src/osdc/)."""

from .objecter import Objecter, ObjecterError, object_to_pg

__all__ = ["Objecter", "ObjecterError", "object_to_pg"]
