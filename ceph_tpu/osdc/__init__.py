"""osdc — the client op engine (src/osdc/)."""

from .objecter import (
    Objecter,
    ObjecterError,
    ObjectNotFound,
    RadosError,
    object_to_pg,
)

__all__ = [
    "Objecter",
    "ObjecterError",
    "ObjectNotFound",
    "RadosError",
    "object_to_pg",
]
