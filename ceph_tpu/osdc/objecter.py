"""Objecter — client op targeting and retry (src/osdc/Objecter.cc).

``_calc_target``: object name → ps (ceph_str_hash_rjenkins, the
pg_pool_t object_hash) → stable pg seed → up/acting/primary via the
client's OSDMap — exactly OSDMap::object_locator_to_pg +
pg_to_up_acting_osds (Objecter.cc:_calc_target).

``op_submit`` sends the MOSDOp to the computed primary and retries
when the target is wrong or gone: a -EAGAIN reply (peering, stale
primary), a connection reset, or a map epoch advance all re-target
and resend, the reference's resend-on-map-change contract
(Objecter::_scan_requests / op_submit retry loop).
"""

from __future__ import annotations

import itertools
import os
import threading
import time

from ..common import tracing
from ..crush.hashing import ceph_str_hash_rjenkins
from ..msg import (
    Messenger,
    MessageError,
    MOSDBackoff,
    MOSDOp,
    MOSDOpReply,
)
from ..msg.message import BACKOFF_OP_BLOCK, BACKOFF_OP_UNBLOCK
from ..msg.messenger import Connection, Dispatcher


class RadosError(Exception):
    """Base for every client-visible error (librados' rados.Error)."""


class ObjecterError(RadosError):
    pass


class ObjectNotFound(ObjecterError):
    pass


class BlocklistedError(ObjecterError):
    """This client has been fenced via the OSDMap blocklist
    (librados' -EBLOCKLISTED): every op will be rejected until the
    entry expires or is removed.  Not retried — the fence is the
    point."""


def object_to_pg(pool, oid: str) -> str:
    """pgid string for an object (object_locator_to_pg)."""
    raw_ps = ceph_str_hash_rjenkins(oid)
    ps = pool.raw_pg_to_pg_seed(raw_ps)
    return f"{pool.pool_id}.{ps}"


def build_objecter_perf(name: str = "objecter"):
    """Client-side op-path counters (the objecter block of
    ``perf dump``), linted by tools/check_metrics.py."""
    from ..common.perf_counters import PerfCountersBuilder

    return (
        PerfCountersBuilder(name)
        .add_u64_counter(
            "l_objecter_backoff_parks",
            "ops parked at least once on an MOSDBackoff BLOCK",
        )
        .create_perf_counters()
    )


class Objecter(Dispatcher):
    def __init__(self, monc, messenger: Messenger, op_timeout: float = 15.0):
        self.monc = monc
        self.messenger = messenger
        self.op_timeout = op_timeout
        self._conns: dict[int, Connection] = {}
        # RADOS backoffs (Objecter::_session_backoff role, keyed by
        # pgid): a BLOCKed pg parks its ops on the event instead of
        # resending; UNBLOCK (or a primary change) releases them
        self._backoffs: dict[str, dict] = {}
        self._backoff_lock = threading.Lock()
        self.perf = build_objecter_perf()
        messenger.add_dispatcher(self)  # UNBLOCK arrives un-paired
        # osd_reqid_t role: a stable id per logical op so retries are
        # deduped by the primary (append idempotency)
        self._client_id = os.urandom(6).hex()
        self._op_seq = itertools.count(1)
        # linger ops (Objecter::linger_watch): watches re-registered
        # on every map change so a new primary learns the watchers
        self._lingers: dict[int, tuple[int, str]] = {}  # cookie → (pool, oid)
        self._linger_epoch = 0
        # distributed tracing: the objecter opens the ROOT span of
        # every logical op (trace id = reqid, the id every sub-op
        # message already carries); spans buffer here until
        # flush_spans_to_mgr ships them on the MMgrReport path
        self.tracer = tracing.Tracer(f"client.{self._client_id}")
        self._mgr_addr: str | None = None

    def new_identity(self) -> None:
        """Adopt a fresh client id (the daemon-respawn analog): a
        blocklist fence keys on the OLD id, so a fenced daemon that
        is later re-promoted starts clean — exactly as a respawned
        reference daemon arrives with a new entity addr.  Watches are
        cookie-keyed to the old id; callers with live watches must
        re-register them (the MDS holds none)."""
        self._client_id = os.urandom(6).hex()

    # -- linger (watch re-registration) ------------------------------------
    def linger_register(self, cookie: int, pool_id: int, oid: str):
        self._lingers[cookie] = (pool_id, oid)

    def linger_unregister(self, cookie: int) -> None:
        self._lingers.pop(cookie, None)

    def handle_map_change(self, epoch: int) -> None:
        """Re-send WATCH for every linger (the watch re-registration
        after an interval change; watchers are primary-resident)."""
        from ..msg.message import OSD_OP_WATCH

        if epoch <= self._linger_epoch:
            return
        self._linger_epoch = epoch
        for cookie, (pool_id, oid) in list(self._lingers.items()):
            try:
                self.op_submit(
                    pool_id, oid, OSD_OP_WATCH, offset=cookie
                )
            except RadosError:
                pass  # next epoch retries

    # -- targeting ---------------------------------------------------------
    def _resolve_tier(self, pool_id: int, write: bool) -> int:
        """Cache-tier overlay redirection (Objecter::_calc_target's
        read_tier/write_tier handling): ops on a BASE pool with an
        overlay route to the cache pool; the cache primary promotes,
        proxies and flushes behind the scenes."""
        pool = self.monc.osdmap.pools.get(pool_id)
        if pool is None:
            return pool_id
        tier = pool.write_tier if write else pool.read_tier
        if tier >= 0 and tier in self.monc.osdmap.pools:
            return tier
        return pool_id

    def _target(self, pool_id: int, oid: str) -> tuple[str, int]:
        osdmap = self.monc.osdmap
        pool = osdmap.pools.get(pool_id)
        if pool is None:
            raise ObjecterError(f"pool {pool_id} does not exist")
        pgid = object_to_pg(pool, oid)
        ps = int(pgid.split(".")[1])
        _up, _upp, _acting, primary = osdmap.pg_to_up_acting_osds(
            pool_id, ps
        )
        return pgid, primary

    # -- backoff protocol (MOSDBackoff client half) -------------------------
    def ms_dispatch(self, conn, msg) -> bool:
        if not isinstance(msg, MOSDBackoff):
            return False
        # only an UNBLOCK releases — a duplicated or timed-out BLOCK
        # copy arriving un-paired must NOT wake the parked ops into
        # the still-blocked PG; and the id must match the backoff we
        # hold (a stale UNBLOCK for a dead incarnation is ignored —
        # the bounded re-probe covers truly lost releases)
        if msg.op != BACKOFF_OP_UNBLOCK:
            return True
        with self._backoff_lock:
            ent = self._backoffs.get(msg.pgid)
            if ent is None or ent.get("id") not in (0, msg.id):
                return True
            del self._backoffs[msg.pgid]
        ent["event"].set()
        return True

    def _register_backoff(self, msg: MOSDBackoff, osd: int) -> None:
        with self._backoff_lock:
            ent = self._backoffs.get(msg.pgid)
            if ent is None:
                ent = self._backoffs[msg.pgid] = {
                    "event": threading.Event(),
                    "since": time.monotonic(),
                }
            ent.update(
                {
                    "id": msg.id,
                    "reason": msg.reason,
                    "osd": osd,
                    "epoch": msg.epoch,
                }
            )

    # a lost UNBLOCK (it is a fire-and-forget frame — chaos rules can
    # drop it) must not park an op until its deadline: after this
    # long, re-probe with ONE resend (the OSD re-blocks if the
    # condition still holds)
    BACKOFF_RECHECK = 3.0

    def _wait_backoff(self, pgid: str, deadline: float) -> None:
        """PARK until the backoff releases: the unblock event, a
        primary change (the interval ended — the reference clears
        session backoffs on map change), a bounded re-probe, or the
        op deadline.  No sends happen while parked — that is the
        whole point (no futile resend storm)."""
        self.perf.inc("l_objecter_backoff_parks")
        recheck = time.monotonic() + self.BACKOFF_RECHECK
        while time.monotonic() < deadline:
            if time.monotonic() >= recheck:
                with self._backoff_lock:
                    self._backoffs.pop(pgid, None)
                return
            with self._backoff_lock:
                ent = self._backoffs.get(pgid)
            if ent is None:
                return  # unblocked
            if ent["event"].wait(0.25):
                return
            try:
                if self._pg_primary(pgid) != ent["osd"]:
                    # the blocking primary is gone: the backoff died
                    # with its interval — retarget and resend
                    with self._backoff_lock:
                        self._backoffs.pop(pgid, None)
                    return
            except (ObjecterError, ValueError, KeyError):
                pass
        # deadline lapsed while parked: drop the entry so the NEXT
        # op to this pg sends instead of parking against a backoff
        # the OSD may no longer hold
        with self._backoff_lock:
            self._backoffs.pop(pgid, None)

    @property
    def backoff_parks(self) -> int:
        """Compat view over the real counter (the historical int
        attribute predates the perf block)."""
        return int(self.perf.dump()["l_objecter_backoff_parks"])

    def dump_backoffs(self) -> list[dict]:
        """Client-side `dump_backoffs` (objecter_requests' backoff
        block): the pgs currently parked and why."""
        now = time.monotonic()
        with self._backoff_lock:
            return [
                {
                    "pgid": pgid,
                    "id": ent.get("id", 0),
                    "reason": ent.get("reason", ""),
                    "osd": ent.get("osd", -1),
                    "age": round(now - ent["since"], 3),
                }
                for pgid, ent in self._backoffs.items()
            ]

    def _conn_to(self, osd: int) -> Connection:
        conn = self._conns.get(osd)
        if conn is not None and not conn._closed:
            return conn
        addr = self.monc.osdmap.osd_addrs.get(osd, "")
        host, _, port = addr.partition(":")
        if not port:
            raise MessageError(f"osd.{osd} has no address")
        conn = self.messenger.connect(host, int(port))
        self._conns[osd] = conn
        return conn

    # -- submit ------------------------------------------------------------
    def op_submit(
        self,
        pool_id: int,
        oid: str,
        op: int,
        offset: int = 0,
        length: int = -1,
        data: bytes = b"",
        attr: str = "",
        pgid: str | None = None,
        snapid: int = 0,
        snap_seq: int = 0,
        flags: int = 0,
        qos: str = "",
    ) -> MOSDOpReply:
        """Target, send, and retry until acked or timed out.
        ``qos`` names the dmclock class the primary schedules this op
        under (empty = the default client class)."""
        from ..msg.message import (
            OSD_OP_GETXATTR,
            OSD_OP_LIST,
            OSD_OP_OMAPGET,
            OSD_OP_READ,
            OSD_OP_STAT,
        )

        is_read = op in (
            OSD_OP_READ, OSD_OP_STAT, OSD_OP_GETXATTR,
            OSD_OP_OMAPGET, OSD_OP_LIST,
        )
        deadline = time.monotonic() + self.op_timeout
        last_err = "no attempt"
        reqid = f"{self._client_id}.{next(self._op_seq)}"
        root = self.tracer.start_span(
            "client_op",
            trace_id=reqid,
            role=tracing.ROLE_CLIENT,
            # qos_class rides every span from the objecter down, so
            # the mgr tracing module and dump_historic_slow_ops can
            # filter/aggregate per class
            tags={
                "pool": pool_id, "oid": oid, "op": op,
                "qos_class": qos or "client",
            },
        )
        with root:
            return self._op_submit_attempts(
                root, deadline, last_err, reqid, pool_id, oid,
                op, offset, length, data, attr, pgid, snapid,
                snap_seq, is_read, flags, qos,
            )

    def _op_submit_attempts(
        self, root, deadline, last_err, reqid, pool_id, oid, op,
        offset, length, data, attr, pgid, snapid, snap_seq, is_read,
        flags, qos,
    ) -> MOSDOpReply:
        from ..msg.message import OSD_OP_LIST

        while time.monotonic() < deadline:
            try:
                # re-resolve the tier overlay every attempt: a map
                # change may add/remove the cache redirection mid-op
                # LIST stays on the BASE pool: the cache holds only
                # resident objects (deviation: objects written but
                # not yet flushed are invisible to listings until the
                # agent's next pass)
                eff_pool = (
                    self._resolve_tier(pool_id, not is_read)
                    if pgid is None and op != OSD_OP_LIST
                    else pool_id
                )
                tgt_pgid, primary = (
                    (pgid, self._pg_primary(pgid))
                    if pgid is not None
                    else self._target(eff_pool, oid)
                )
                if primary < 0:
                    raise MessageError("pg has no primary (all down?)")
                root.mark_event(f"send_op osd.{primary} pg {tgt_pgid}")
                reply = self._conn_to(primary).call(
                    MOSDOp(
                        pool=eff_pool, pgid=tgt_pgid, oid=oid, op=op,
                        offset=offset, length=length, data=data,
                        attr=attr, reqid=reqid, epoch=self.monc.epoch,
                        snapid=snapid, snap_seq=snap_seq, flags=flags,
                        qos=qos,
                    ),
                    timeout=min(5.0, self.op_timeout),
                )
                if isinstance(reply, MOSDBackoff):
                    # tid-paired BLOCK: the PG cannot take this op
                    # (peering / full) — PARK on the backoff instead
                    # of hammering resends; UNBLOCK (or a primary
                    # change) releases us back into the loop
                    if reply.op == BACKOFF_OP_BLOCK:
                        last_err = (
                            f"backoff pg {tgt_pgid} ({reply.reason})"
                        )
                        root.mark_event(
                            f"backoff_block pg {tgt_pgid} "
                            f"({reply.reason})"
                        )
                        self._register_backoff(reply, primary)
                        self._wait_backoff(tgt_pgid, deadline)
                        root.mark_event("backoff_release")
                    continue
                assert isinstance(reply, MOSDOpReply)
                if reply.ok:
                    root.mark_event("reply_ok")
                    return reply
                if "EAGAIN" in reply.error:
                    last_err = reply.error
                    root.mark_event("retry: EAGAIN")
                    # stale target / peering: wait for map movement
                    time.sleep(0.1)
                    continue
                if "ENOENT" in reply.error or "no object" in reply.error:
                    raise ObjectNotFound(reply.error)
                if "EBLOCKLISTED" in reply.error:
                    raise BlocklistedError(reply.error)
                raise ObjecterError(reply.error)
            except (MessageError, OSError) as e:
                last_err = str(e)
                time.sleep(0.1)
                continue
        raise ObjecterError(
            f"op on {pool_id}/{oid} timed out: {last_err}"
        )

    def _pg_primary(self, pgid: str) -> int:
        pool_id, ps = pgid.split(".")
        _u, _up, _a, primary = self.monc.osdmap.pg_to_up_acting_osds(
            int(pool_id), int(ps)
        )
        return primary

    # -- span delivery (the client half of the tracing plane) --------------
    def flush_spans_to_mgr(self) -> int:
        """Ship buffered client spans to the active mgr as an
        MMgrReport (perf stays empty — the spans piggyback exactly
        like the daemons').  Best-effort: no mgr, no spans, no error.
        Returns the number of spans shipped."""
        import json

        from ..msg.message import MMgrReport

        spans = self.tracer.drain()
        if not spans:
            return 0
        try:
            if self._mgr_addr is None:
                reply = self.monc.command({"prefix": "mgr stat"})
                active = (
                    json.loads(reply.outb).get("active")
                    if reply.rc == 0
                    else None
                )
                self._mgr_addr = active["addr"] if active else None
            if self._mgr_addr is None:
                return 0
            host, _, port = self._mgr_addr.rpartition(":")
            conn = self.messenger.connect(host, int(port), timeout=5.0)
            conn.send(
                MMgrReport(
                    daemon=f"client.{self._client_id}",
                    spans=json.dumps(spans),
                )
            )
            return len(spans)
        except (MessageError, OSError, ValueError, KeyError):
            self._mgr_addr = None
            return 0
