"""Objecter — client op targeting and retry (src/osdc/Objecter.cc).

``_calc_target``: object name → ps (ceph_str_hash_rjenkins, the
pg_pool_t object_hash) → stable pg seed → up/acting/primary via the
client's OSDMap — exactly OSDMap::object_locator_to_pg +
pg_to_up_acting_osds (Objecter.cc:_calc_target).

``op_submit`` sends the MOSDOp to the computed primary and retries
when the target is wrong or gone: a -EAGAIN reply (peering, stale
primary), a connection reset, or a map epoch advance all re-target
and resend, the reference's resend-on-map-change contract
(Objecter::_scan_requests / op_submit retry loop).
"""

from __future__ import annotations

import itertools
import os
import time

from ..common import tracing
from ..crush.hashing import ceph_str_hash_rjenkins
from ..msg import Messenger, MessageError, MOSDOp, MOSDOpReply
from ..msg.messenger import Connection


class RadosError(Exception):
    """Base for every client-visible error (librados' rados.Error)."""


class ObjecterError(RadosError):
    pass


class ObjectNotFound(ObjecterError):
    pass


class BlocklistedError(ObjecterError):
    """This client has been fenced via the OSDMap blocklist
    (librados' -EBLOCKLISTED): every op will be rejected until the
    entry expires or is removed.  Not retried — the fence is the
    point."""


def object_to_pg(pool, oid: str) -> str:
    """pgid string for an object (object_locator_to_pg)."""
    raw_ps = ceph_str_hash_rjenkins(oid)
    ps = pool.raw_pg_to_pg_seed(raw_ps)
    return f"{pool.pool_id}.{ps}"


class Objecter:
    def __init__(self, monc, messenger: Messenger, op_timeout: float = 15.0):
        self.monc = monc
        self.messenger = messenger
        self.op_timeout = op_timeout
        self._conns: dict[int, Connection] = {}
        # osd_reqid_t role: a stable id per logical op so retries are
        # deduped by the primary (append idempotency)
        self._client_id = os.urandom(6).hex()
        self._op_seq = itertools.count(1)
        # linger ops (Objecter::linger_watch): watches re-registered
        # on every map change so a new primary learns the watchers
        self._lingers: dict[int, tuple[int, str]] = {}  # cookie → (pool, oid)
        self._linger_epoch = 0
        # distributed tracing: the objecter opens the ROOT span of
        # every logical op (trace id = reqid, the id every sub-op
        # message already carries); spans buffer here until
        # flush_spans_to_mgr ships them on the MMgrReport path
        self.tracer = tracing.Tracer(f"client.{self._client_id}")
        self._mgr_addr: str | None = None

    def new_identity(self) -> None:
        """Adopt a fresh client id (the daemon-respawn analog): a
        blocklist fence keys on the OLD id, so a fenced daemon that
        is later re-promoted starts clean — exactly as a respawned
        reference daemon arrives with a new entity addr.  Watches are
        cookie-keyed to the old id; callers with live watches must
        re-register them (the MDS holds none)."""
        self._client_id = os.urandom(6).hex()

    # -- linger (watch re-registration) ------------------------------------
    def linger_register(self, cookie: int, pool_id: int, oid: str):
        self._lingers[cookie] = (pool_id, oid)

    def linger_unregister(self, cookie: int) -> None:
        self._lingers.pop(cookie, None)

    def handle_map_change(self, epoch: int) -> None:
        """Re-send WATCH for every linger (the watch re-registration
        after an interval change; watchers are primary-resident)."""
        from ..msg.message import OSD_OP_WATCH

        if epoch <= self._linger_epoch:
            return
        self._linger_epoch = epoch
        for cookie, (pool_id, oid) in list(self._lingers.items()):
            try:
                self.op_submit(
                    pool_id, oid, OSD_OP_WATCH, offset=cookie
                )
            except RadosError:
                pass  # next epoch retries

    # -- targeting ---------------------------------------------------------
    def _resolve_tier(self, pool_id: int, write: bool) -> int:
        """Cache-tier overlay redirection (Objecter::_calc_target's
        read_tier/write_tier handling): ops on a BASE pool with an
        overlay route to the cache pool; the cache primary promotes,
        proxies and flushes behind the scenes."""
        pool = self.monc.osdmap.pools.get(pool_id)
        if pool is None:
            return pool_id
        tier = pool.write_tier if write else pool.read_tier
        if tier >= 0 and tier in self.monc.osdmap.pools:
            return tier
        return pool_id

    def _target(self, pool_id: int, oid: str) -> tuple[str, int]:
        osdmap = self.monc.osdmap
        pool = osdmap.pools.get(pool_id)
        if pool is None:
            raise ObjecterError(f"pool {pool_id} does not exist")
        pgid = object_to_pg(pool, oid)
        ps = int(pgid.split(".")[1])
        _up, _upp, _acting, primary = osdmap.pg_to_up_acting_osds(
            pool_id, ps
        )
        return pgid, primary

    def _conn_to(self, osd: int) -> Connection:
        conn = self._conns.get(osd)
        if conn is not None and not conn._closed:
            return conn
        addr = self.monc.osdmap.osd_addrs.get(osd, "")
        host, _, port = addr.partition(":")
        if not port:
            raise MessageError(f"osd.{osd} has no address")
        conn = self.messenger.connect(host, int(port))
        self._conns[osd] = conn
        return conn

    # -- submit ------------------------------------------------------------
    def op_submit(
        self,
        pool_id: int,
        oid: str,
        op: int,
        offset: int = 0,
        length: int = -1,
        data: bytes = b"",
        attr: str = "",
        pgid: str | None = None,
        snapid: int = 0,
        snap_seq: int = 0,
    ) -> MOSDOpReply:
        """Target, send, and retry until acked or timed out."""
        from ..msg.message import (
            OSD_OP_GETXATTR,
            OSD_OP_LIST,
            OSD_OP_OMAPGET,
            OSD_OP_READ,
            OSD_OP_STAT,
        )

        is_read = op in (
            OSD_OP_READ, OSD_OP_STAT, OSD_OP_GETXATTR,
            OSD_OP_OMAPGET, OSD_OP_LIST,
        )
        deadline = time.monotonic() + self.op_timeout
        last_err = "no attempt"
        reqid = f"{self._client_id}.{next(self._op_seq)}"
        root = self.tracer.start_span(
            "client_op",
            trace_id=reqid,
            role=tracing.ROLE_CLIENT,
            tags={"pool": pool_id, "oid": oid, "op": op},
        )
        with root:
            return self._op_submit_attempts(
                root, deadline, last_err, reqid, pool_id, oid,
                op, offset, length, data, attr, pgid, snapid,
                snap_seq, is_read,
            )

    def _op_submit_attempts(
        self, root, deadline, last_err, reqid, pool_id, oid, op,
        offset, length, data, attr, pgid, snapid, snap_seq, is_read,
    ) -> MOSDOpReply:
        from ..msg.message import OSD_OP_LIST

        while time.monotonic() < deadline:
            try:
                # re-resolve the tier overlay every attempt: a map
                # change may add/remove the cache redirection mid-op
                # LIST stays on the BASE pool: the cache holds only
                # resident objects (deviation: objects written but
                # not yet flushed are invisible to listings until the
                # agent's next pass)
                eff_pool = (
                    self._resolve_tier(pool_id, not is_read)
                    if pgid is None and op != OSD_OP_LIST
                    else pool_id
                )
                tgt_pgid, primary = (
                    (pgid, self._pg_primary(pgid))
                    if pgid is not None
                    else self._target(eff_pool, oid)
                )
                if primary < 0:
                    raise MessageError("pg has no primary (all down?)")
                root.mark_event(f"send_op osd.{primary} pg {tgt_pgid}")
                reply = self._conn_to(primary).call(
                    MOSDOp(
                        pool=eff_pool, pgid=tgt_pgid, oid=oid, op=op,
                        offset=offset, length=length, data=data,
                        attr=attr, reqid=reqid, epoch=self.monc.epoch,
                        snapid=snapid, snap_seq=snap_seq,
                    ),
                    timeout=min(5.0, self.op_timeout),
                )
                assert isinstance(reply, MOSDOpReply)
                if reply.ok:
                    root.mark_event("reply_ok")
                    return reply
                if "EAGAIN" in reply.error:
                    last_err = reply.error
                    root.mark_event("retry: EAGAIN")
                    # stale target / peering: wait for map movement
                    time.sleep(0.1)
                    continue
                if "ENOENT" in reply.error or "no object" in reply.error:
                    raise ObjectNotFound(reply.error)
                if "EBLOCKLISTED" in reply.error:
                    raise BlocklistedError(reply.error)
                raise ObjecterError(reply.error)
            except (MessageError, OSError) as e:
                last_err = str(e)
                time.sleep(0.1)
                continue
        raise ObjecterError(
            f"op on {pool_id}/{oid} timed out: {last_err}"
        )

    def _pg_primary(self, pgid: str) -> int:
        pool_id, ps = pgid.split(".")
        _u, _up, _a, primary = self.monc.osdmap.pg_to_up_acting_osds(
            int(pool_id), int(ps)
        )
        return primary

    # -- span delivery (the client half of the tracing plane) --------------
    def flush_spans_to_mgr(self) -> int:
        """Ship buffered client spans to the active mgr as an
        MMgrReport (perf stays empty — the spans piggyback exactly
        like the daemons').  Best-effort: no mgr, no spans, no error.
        Returns the number of spans shipped."""
        import json

        from ..msg.message import MMgrReport

        spans = self.tracer.drain()
        if not spans:
            return 0
        try:
            if self._mgr_addr is None:
                reply = self.monc.command({"prefix": "mgr stat"})
                active = (
                    json.loads(reply.outb).get("active")
                    if reply.rc == 0
                    else None
                )
                self._mgr_addr = active["addr"] if active else None
            if self._mgr_addr is None:
                return 0
            host, _, port = self._mgr_addr.rpartition(":")
            conn = self.messenger.connect(host, int(port), timeout=5.0)
            conn.send(
                MMgrReport(
                    daemon=f"client.{self._client_id}",
                    spans=json.dumps(spans),
                )
            )
            return len(spans)
        except (MessageError, OSError, ValueError, KeyError):
            self._mgr_addr = None
            return 0
