"""ObjectCacher — the client-side object cache
(src/osdc/ObjectCacher.cc:1-2806 reduced to the load-bearing design).

librbd and the fs client put this between themselves and the cluster:
reads serve from cached extents, writes buffer DIRTY and write back
asynchronously (coalesced), a dirty limit throttles writers while the
flusher drains, and clean memory evicts LRU under a size cap.  Same
shape here, per backing object:

- extents: non-overlapping (offset, buffer, dirty) runs, overwritten/
  merged in place by writes, filled by reads.
- write-back: a flusher thread writes dirty runs (adjacent ones
  coalesced into one backend write) once they age past
  ``flush_age`` or whenever dirty bytes cross ``target_dirty``;
  writers block when dirty crosses ``max_dirty`` until the flusher
  catches up (the dirty throttle).
- eviction: clean extents drop LRU when the cache exceeds
  ``max_size``; dirty data is never dropped, only flushed.
- ``flush()`` barriers everything dirty to the cluster; ``close()``
  flushes and stops the flusher.

Coherence contract, documented: this caches for ONE client — the
reference guards it with rbd exclusive locks / MDS capabilities, and
here the rbd image (single writer) is the intended user.  Holes read
through the cache are cached as zeros; another client's concurrent
writes are invisible until ``discard``/``invalidate``.
"""

from __future__ import annotations

import logging
import threading
import time

from .objecter import BlocklistedError, ObjectNotFound, RadosError

log = logging.getLogger(__name__)


class _Extent:
    __slots__ = ("off", "buf", "dirty", "born")

    def __init__(self, off: int, buf: bytearray, dirty: bool):
        self.off = off
        self.buf = buf
        self.dirty = dirty
        self.born = time.monotonic()

    @property
    def end(self) -> int:
        return self.off + len(self.buf)


class ObjectCacher:
    def __init__(
        self,
        ioctx,
        max_dirty: int = 8 << 20,
        target_dirty: int = 4 << 20,
        max_size: int = 32 << 20,
        flush_age: float = 1.0,
    ):
        self.ioctx = ioctx
        self.max_dirty = max_dirty
        self.target_dirty = target_dirty
        self.max_size = max_size
        self.flush_age = flush_age
        self._lock = threading.Condition(threading.RLock())
        self._objects: dict[str, list[_Extent]] = {}
        self._lru: dict[str, float] = {}
        self.dirty_bytes = 0
        self.total_bytes = 0
        self.hits = 0
        self.misses = 0
        self.backend_writes = 0
        self._stop = threading.Event()
        self.fatal_error: Exception | None = None
        self._flusher = threading.Thread(
            target=self._flush_loop, name="objectcacher.flush",
            daemon=True,
        )
        self._flusher.start()

    # -- bookkeeping --------------------------------------------------------
    def _account(self, delta_total: int, delta_dirty: int) -> None:
        self.total_bytes += delta_total
        self.dirty_bytes += delta_dirty

    def _insert(self, oid: str, ext: _Extent) -> None:
        """Insert a run, carving away any overlap from existing runs
        (the newcomer's bytes win — it is either a fresh write or
        data just fetched into a gap)."""
        runs = self._objects.setdefault(oid, [])
        out: list[_Extent] = []
        for r in runs:
            if r.end <= ext.off or r.off >= ext.end:
                out.append(r)
                continue
            # overlap: keep the non-overlapped head/tail pieces
            if r.off < ext.off:
                head = _Extent(
                    r.off, r.buf[: ext.off - r.off], r.dirty
                )
                head.born = r.born
                out.append(head)
            if r.end > ext.end:
                tail = _Extent(
                    ext.end, r.buf[ext.end - r.off :], r.dirty
                )
                tail.born = r.born
                out.append(tail)
            dropped = len(r.buf) - (
                (ext.off - r.off if r.off < ext.off else 0)
                + (r.end - ext.end if r.end > ext.end else 0)
            )
            self._account(-dropped, -dropped if r.dirty else 0)
        out.append(ext)
        out.sort(key=lambda e: e.off)
        self._objects[oid] = out
        self._account(len(ext.buf), len(ext.buf) if ext.dirty else 0)
        self._lru[oid] = time.monotonic()

    # -- read path ----------------------------------------------------------
    def read(self, oid: str, offset: int, length: int) -> bytes:
        """Assemble from cache; fetch gaps from the backend (cached
        clean, holes as zeros).  Returns exactly ``length`` bytes.

        Assembly re-checks coverage under the lock: a concurrent
        reader's eviction may have dropped extents between the gap
        scan and the copy, and assembling zeros for data the backend
        holds would be silent corruption — so uncovered ranges loop
        back through the fetch."""
        fetched_any = False
        for attempt in range(6):
            with self._lock:
                gaps = self._gaps(oid, offset, length)
                if not gaps:
                    if not fetched_any:
                        self.hits += 1
                    out = bytearray(length)
                    for r in self._objects.get(oid, []):
                        if r.end <= offset or r.off >= offset + length:
                            continue
                        s = max(offset, r.off)
                        e = min(offset + length, r.end)
                        out[s - offset : e - offset] = r.buf[
                            s - r.off : e - r.off
                        ]
                    self._lru[oid] = time.monotonic()
                    self._evict_locked()
                    return bytes(out)
            fetched_any = True
            for g_off, g_len in gaps:
                self.misses += 1
                try:
                    got = self.ioctx.read(
                        oid, length=g_len, offset=g_off
                    )
                except (ObjectNotFound, RadosError):
                    got = b""
                buf = bytearray(got) + bytearray(g_len - len(got))
                with self._lock:
                    # a write may have raced into the gap: only fill
                    # what is STILL uncovered, never clobbering newer
                    # bytes
                    for s_off, s_len in self._gaps(oid, g_off, g_len):
                        self._insert(
                            oid,
                            _Extent(
                                s_off,
                                buf[
                                    s_off - g_off : s_off
                                    - g_off
                                    + s_len
                                ],
                                dirty=False,
                            ),
                        )
        # pathological eviction contention: serve directly from the
        # backend with the (never-evicted) dirty extents overlaid
        try:
            got = self.ioctx.read(oid, length=length, offset=offset)
        except (ObjectNotFound, RadosError):
            got = b""
        out = bytearray(got) + bytearray(length - len(got))
        with self._lock:
            for r in self._objects.get(oid, []):
                if not r.dirty or r.end <= offset or r.off >= offset + length:
                    continue
                s_ = max(offset, r.off)
                e_ = min(offset + length, r.end)
                out[s_ - offset : e_ - offset] = r.buf[
                    s_ - r.off : e_ - r.off
                ]
        return bytes(out)

    def _gaps(self, oid: str, offset: int, length: int):
        gaps = []
        pos = offset
        for r in self._objects.get(oid, []):
            if r.end <= pos or r.off >= offset + length:
                continue
            if r.off > pos:
                gaps.append((pos, r.off - pos))
            pos = max(pos, r.end)
        if pos < offset + length:
            gaps.append((pos, offset + length - pos))
        return gaps

    # -- write path ----------------------------------------------------------
    def write(self, oid: str, offset: int, data: bytes) -> None:
        if self.fatal_error is not None:
            # fenced: buffering more write-back data would only grow
            # the amount silently lost — fail fast with the cause
            raise self.fatal_error
        data = bytes(data)
        if not data:
            return
        with self._lock:
            self._insert(
                oid, _Extent(offset, bytearray(data), dirty=True)
            )
            self._lock.notify_all()
            # the dirty throttle: block while over the hard limit so
            # one writer cannot buffer unbounded dirty memory
            deadline = time.monotonic() + 30.0
            while self.dirty_bytes > self.max_dirty:
                self._lock.wait(0.05)
                if time.monotonic() > deadline:
                    raise RadosError("objectcacher flush stalled")
                self._flush_some_locked(self.target_dirty)

    # -- flush ---------------------------------------------------------------
    def _dirty_runs(self, oid: str):
        """Adjacent dirty extents coalesce into single writes."""
        runs = []
        cur = None
        for r in self._objects.get(oid, []):
            if not r.dirty:
                continue
            if cur is not None and cur[0] + len(cur[1]) == r.off:
                cur[1] += r.buf
                cur[2].append(r)
            else:
                cur = [r.off, bytearray(r.buf), [r]]
                runs.append(cur)
        return runs

    def _flush_object_locked(self, oid: str) -> None:
        for off, buf, members in self._dirty_runs(oid):
            # write OUTSIDE the lock would be ideal; the runs are
            # snapshots so a short critical section is correct and
            # the single-writer contract keeps latency acceptable
            self.ioctx.write(oid, bytes(buf), offset=off)
            self.backend_writes += 1
            for m in members:
                if m.dirty:
                    m.dirty = False
                    self._account(0, -len(m.buf))
        self._lock.notify_all()

    def _flush_some_locked(self, down_to: int) -> None:
        for oid in sorted(
            self._objects,
            key=lambda o: min(
                (r.born for r in self._objects[o] if r.dirty),
                default=float("inf"),
            ),
        ):
            if self.dirty_bytes <= down_to:
                break
            self._flush_object_locked(oid)

    def flush(self, oid: str | None = None) -> None:
        if self.fatal_error is not None:
            raise self.fatal_error
        with self._lock:
            if oid is not None:
                self._flush_object_locked(oid)
            else:
                self._flush_some_locked(0)

    def _flush_loop(self) -> None:
        while not self._stop.wait(self.flush_age / 2):
            now = time.monotonic()
            try:
                with self._lock:
                    if self.dirty_bytes > self.target_dirty:
                        self._flush_some_locked(self.target_dirty)
                        continue
                    for oid, runs in list(self._objects.items()):
                        if any(
                            r.dirty and now - r.born > self.flush_age
                            for r in runs
                        ):
                            self._flush_object_locked(oid)
            except BlocklistedError as e:
                # FATAL: this client has been fenced — every retry
                # would fail identically and the application must
                # learn its write-back data is lost.  Record the
                # error (surfaced by the next write()/flush()) and
                # stop the flusher.
                log.error("object cacher fenced, stopping flusher: %s", e)
                self.fatal_error = e
                return
            except Exception as e:
                # a transient backend failure (e.g. an op timing out
                # across a primary failover) must degrade to a delayed
                # flush, not kill the flusher thread for the image's
                # lifetime — dirty runs stay dirty and retry next tick
                log.warning("object cacher flush tick failed: %s", e)

    # -- eviction / invalidation --------------------------------------------
    def _evict_locked(self) -> None:
        if self.total_bytes <= self.max_size:
            return
        for oid in sorted(self._lru, key=self._lru.get):
            runs = self._objects.get(oid, [])
            keep = []
            for r in runs:
                if r.dirty:
                    keep.append(r)
                else:
                    self._account(-len(r.buf), 0)
            if keep:
                self._objects[oid] = keep
            else:
                self._objects.pop(oid, None)
                self._lru.pop(oid, None)
            if self.total_bytes <= self.max_size:
                break

    def invalidate_all(self) -> None:
        """Flush everything dirty, then drop the whole cache — the
        caller is changing what the backend returns (snapshot
        routing, external writers)."""
        with self._lock:
            self._flush_some_locked(0)
            self._objects.clear()
            self._lru.clear()
            self.dirty_bytes = 0
            self.total_bytes = 0
            self._lock.notify_all()

    def discard(self, oid: str) -> None:
        """Drop ALL cached state for an object (dirty included) —
        the caller is deleting/trimming it."""
        with self._lock:
            for r in self._objects.pop(oid, []):
                self._account(-len(r.buf), -len(r.buf) if r.dirty else 0)
            self._lru.pop(oid, None)
            self._lock.notify_all()

    def close(self) -> None:
        self._stop.set()
        self._flusher.join(timeout=5)
        if self.fatal_error is not None:
            # fenced: the dirty data is unrecoverable from here; the
            # failure already surfaced (or will) via write()/flush()
            log.error(
                "object cacher closed fenced; %d dirty bytes dropped",
                self.dirty_bytes,
            )
            return
        self.flush()
