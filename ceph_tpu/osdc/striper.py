"""Striper — file/image extents ⇄ object extents
(src/osdc/Striper.cc; the file_layout_t math of
src/include/ceph_fs.h: stripe_unit/stripe_count/object_size).

A logical byte range striped RAID-0 style across a rotating window of
``stripe_count`` objects: block b (of ``stripe_unit`` bytes) lands in
stripe ``b // stripe_count`` at position ``b % stripe_count``;
``object_size // stripe_unit`` stripes fill an object before the
window advances to the next object set.  This is the layout librbd,
libradosstriper and the MDS file layer all share.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class StripeLayout:
    """file_layout_t subset: all three in bytes/objects."""

    stripe_unit: int = 1 << 22  # 4 MiB
    stripe_count: int = 1
    object_size: int = 1 << 22

    def __post_init__(self):
        if self.stripe_unit <= 0 or self.stripe_count <= 0:
            raise ValueError("stripe_unit/stripe_count must be > 0")
        if (
            self.object_size <= 0
            or self.object_size % self.stripe_unit
        ):
            raise ValueError(
                "object_size must be a positive multiple of "
                "stripe_unit"
            )

    @property
    def stripes_per_object(self) -> int:
        return self.object_size // self.stripe_unit


def map_extent(
    layout: StripeLayout, offset: int, length: int
) -> list[tuple[int, int, int]]:
    """Logical [offset, offset+length) → ordered
    [(object_no, obj_offset, len)] (Striper::file_to_extents),
    adjacent runs within one object coalesced."""
    su = layout.stripe_unit
    sc = layout.stripe_count
    spo = layout.stripes_per_object
    out: list[tuple[int, int, int]] = []
    pos = offset
    end = offset + length
    while pos < end:
        blockno = pos // su
        stripeno = blockno // sc
        stripepos = blockno % sc
        objectsetno = stripeno // spo
        objectno = objectsetno * sc + stripepos
        block_off = pos % su
        obj_off = (stripeno % spo) * su + block_off
        n = min(su - block_off, end - pos)
        if out and out[-1][0] == objectno and (
            out[-1][1] + out[-1][2] == obj_off
        ):
            o, oo, ol = out[-1]
            out[-1] = (o, oo, ol + n)
        else:
            out.append((objectno, obj_off, n))
        pos += n
    return out
