"""Multi-process cluster runtime — the process model the reference
deploys (one OS process per daemon, `src/ceph_osd.cc` global_init;
respawn by ceph-run / systemd `Restart=on-failure`).

Everything in-process stays GIL-bound: PR 14's 100-OSD harness and
PR 15's sharded-index bench honestly cap at ~1.4x on one core.  This
package escapes that ceiling:

- ``spec``       the cluster-spec grammar: which daemons, where their
                 stores live, which ports the mon trio binds — one
                 JSON document shared by the supervisor and every
                 child (the ceph.conf seat).
- ``daemon``     the per-daemon entrypoint
                 (``python -m ceph_tpu.proc.daemon --role osd.3``):
                 boots exactly ONE mon/osd/mgr/mds/rgw daemon on the
                 shared-event-loop stack, publishes a readiness file,
                 and parks until SIGTERM.  All inter-daemon traffic
                 rides the messenger's real sockets.
- ``supervisor`` the ceph-run/systemd role: spawns the fleet as
                 setsid children with per-child log capture, monitors
                 them, respawns crashes with exponential backoff and
                 a crash-loop cap, and feeds every real process death
                 into the crash-report plane so RECENT_CRASH raises.
"""

from .spec import ClusterSpec
from .supervisor import Supervisor, build_proc_perf

__all__ = ["ClusterSpec", "Supervisor", "build_proc_perf"]
