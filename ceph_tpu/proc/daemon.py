"""Per-daemon process entrypoint (the ceph-osd/ceph-mon binary seat,
src/ceph_osd.cc global_init reduced to this framework's daemons)::

    python -m ceph_tpu.proc.daemon --role osd.3 --spec /c1/spec.json

Boots exactly ONE daemon from the cluster spec, on the per-process
shared-event-loop stack (``shared_services=True`` everywhere — a
child process carries the network stack's workers plus the offload
pool and nothing else), publishes a readiness file the supervisor
probes, then parks until SIGTERM.

Exit discipline (what the supervisor discriminates on):

- SIGTERM/SIGINT → clean shutdown, exit 0 (never respawned);
- uncaught boot/runtime exception → traceback on stderr (captured in
  the child log), exit 1 (respawned, crash-reported);
- SIGKILL/SIGSEGV → wait status carries the signal (respawned,
  crash-reported with the signal name).

The readiness file is JSON ``{"role", "pid", "addr"?, "replayed"?}``
written atomically NEXT TO the spec; a respawned daemon overwrites
it, so its pid always names the live incarnation.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import signal
import sys
import time

from .spec import SPEC_FILENAME, ClusterSpec


def _publish_ready(spec: ClusterSpec, role: str, extra: dict) -> None:
    info = {"role": role, "pid": os.getpid(), **extra}
    path = spec.ready_path(role)
    tmp = path.with_suffix(".tmp")
    tmp.write_text(json.dumps(info))
    tmp.replace(path)
    print(f"ready {role} {json.dumps(info)}", flush=True)


def _boot_mon(spec: ClusterSpec, rank: int):
    from ..mon.monitor import MonitorStore
    from ..mon.quorum import MonMap, QuorumMonitor
    from ..tools.cluster import _build_map

    store = None
    if not spec.data["memstore"]:
        from ..store import BlockStore

        store = MonitorStore(
            BlockStore(spec.dir / f"mon.{rank}", sync=False)
        )
    mon = QuorumMonitor(
        _build_map(spec.data["osds"]),
        MonMap(addrs=dict(enumerate(spec.mon_addrs))),
        rank,
        store=store,
        min_reporters=min(2, spec.data["osds"]),
        shared_services=True,
    )
    mon.start()
    _publish_ready(
        spec, f"mon.{rank}", {"addr": list(mon.addr)}
    )
    return mon


def _boot_mgr(spec: ClusterSpec, idx: int):
    from ..mgr import Manager

    mgr = Manager(name=str(idx), shared_services=True)
    mgr.start(spec.mon_addrs)
    _publish_ready(spec, f"mgr.{idx}", {"addr": mgr.addr})
    return mgr


def _boot_osd(spec: ClusterSpec, idx: int):
    from ..osd.daemon import OSD

    store = None
    if not spec.data["memstore"]:
        from ..store import BlockStore

        store = BlockStore(spec.dir / f"osd.{idx}", sync=False)
    osd = OSD(
        idx,
        store=store,
        wal_dir=(
            str(spec.dir / f"osd.{idx}-wal")
            if spec.data["wal"]
            else None
        ),
        admin_socket_path=str(spec.dir / f"osd.{idx}.asok"),
        shared_services=True,
    )
    osd.boot(mon_addrs=spec.mon_addrs)
    # WAL replay count in the readiness record: the chaos plane
    # asserts a SIGKILLed OSD's respawn actually replayed its log
    replayed = getattr(osd.store, "replayed_records", 0)
    _publish_ready(spec, f"osd.{idx}", {"replayed": replayed})
    return osd


def _ensure_pools(rados, pools: dict[str, dict]) -> None:
    existing = set(rados.monc.osdmap.pool_names.values())
    for name, kw in pools.items():
        if name not in existing:
            try:
                rados.pool_create(name, **kw)
            except Exception:  # noqa: BLE001 — a sibling gateway
                # racing the same create loses benignly
                pass


def _boot_mds(spec: ClusterSpec, idx: int):
    from ..mds import MDSDaemon
    from ..rados import Rados

    size = spec.data["pool_size"]
    r = Rados(f"mds-{idx}").connect_any(spec.mon_addrs)
    _ensure_pools(
        r,
        {
            "fsmeta": {"pg_num": 4, "size": size},
            "fsdata": {"pg_num": 8, "size": size},
        },
    )
    mds = MDSDaemon(
        f"mds{idx}", r, "fsmeta", shared_services=True
    )
    _publish_ready(spec, f"mds.{idx}", {"addr": mds.addr})
    return _Composite([mds, r])


def _boot_rgw(spec: ClusterSpec, idx: int):
    from ..rados import Rados
    from ..rgw import RGW

    r = Rados(f"rgw-{idx}").connect_any(spec.mon_addrs)
    _ensure_pools(
        r,
        {"rgwpool": {"pg_num": 8, "size": spec.data["pool_size"]}},
    )
    gw = RGW(r.open_ioctx("rgwpool"), name=f"rgw.{idx}")
    port = gw.serve(int(spec.data["rgw_ports"][idx]))
    gw.start_reshard()
    gw.start_mgr_reports(shared_services=True)
    _publish_ready(spec, f"rgw.{idx}", {"port": port})
    return _Composite([gw, r])


class _Composite:
    """Shut several objects down in order (daemon + its client)."""

    def __init__(self, parts):
        self.parts = parts

    def shutdown(self) -> None:
        for p in self.parts:
            try:
                p.shutdown()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


_BOOTERS = {
    "mon": _boot_mon,
    "mgr": _boot_mgr,
    "osd": _boot_osd,
    "mds": _boot_mds,
    "rgw": _boot_rgw,
}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ceph-tpu-daemon")
    p.add_argument(
        "--role", required=True,
        help="daemon to boot, e.g. mon.0 / osd.3 / mgr.0",
    )
    p.add_argument(
        "--spec", default=None,
        help=f"cluster spec path (default <--dir>/{SPEC_FILENAME})",
    )
    p.add_argument("-d", "--dir", default=".")
    args = p.parse_args(argv)

    spec_path = args.spec or (
        pathlib.Path(args.dir) / SPEC_FILENAME
    )
    spec = ClusterSpec.load(spec_path)
    kind, _, idx = args.role.partition(".")
    if kind not in _BOOTERS:
        print(f"unknown role {args.role!r}", file=sys.stderr)
        return 2

    stop = {"flag": False}

    def _sig(_s, _f):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)

    daemon = _BOOTERS[kind](spec, int(idx))
    try:
        while not stop["flag"]:
            time.sleep(0.2)
    finally:
        try:
            daemon.shutdown()
        finally:
            try:
                spec.ready_path(args.role).unlink()
            except OSError:
                pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
