"""Process supervisor — the ceph-run / systemd ``Restart=on-failure``
role: spawn the fleet from a :class:`~ceph_tpu.proc.spec.ClusterSpec`,
monitor the children, respawn crashes with exponential backoff and a
crash-loop cap, and feed every real process death into the crash
plane so RECENT_CRASH raises for it.

State machine per child (the supervisor discriminates clean shutdown
from crash by wait status, like systemd)::

    spawned ── exit 0 ──────────────▶ exited   (never respawned)
       │  ╲─ SIGTERM via stop() ───▶ stopped  (never respawned)
       │
       └─ nonzero / signal ─▶ crashed ─▶ backoff ─▶ spawned
                                 │   (delay = base·2^(n-1), capped)
                                 └─ n > crash_loop_cap ─▶ failed

``n`` counts CONSECUTIVE short-lived crashes: a child that stayed up
past ``min_uptime`` resets the streak, so a daemon that crashes once
a day never walks into the cap.  Every crash builds a
``build_process_report`` (signal name / exit status + child log
tail) and rides MMgrReport to the mgr crash module over the real
wire — the ceph-crash uploader seat.

Children are ``setsid`` process-group leaders with per-child log
capture; ``stop()`` (and the orphan reaper) kills the whole GROUP,
so a wedged daemon's own children cannot outlive the harness.
"""

from __future__ import annotations

import json
import os
import pathlib
import signal
import subprocess
import sys
import threading
import time

from ..common import crash as crash_util
from ..common.perf_counters import PerfCountersBuilder
from .spec import ClusterSpec

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
SUPERVISOR_STATE = "supervisor.json"
# crash reports ride several consecutive perf pushes (the OSD's
# CRASH_RESEND_COUNT idiom): an mgr restart racing one push must not
# lose the death
CRASH_RESEND_COUNT = 3
LOG_TAIL_LINES = 40


def build_proc_perf():
    """The supervisor counter schema (l_proc_* family) —
    module-level so tools/check_metrics.py lints it without a live
    supervisor."""
    return (
        PerfCountersBuilder("proc.supervisor")
        .add_u64_gauge(
            "l_proc_children", "supervised child processes alive"
        )
        .add_u64_counter(
            "l_proc_restarts",
            "crashed daemons respawned (after backoff)",
        )
        .add_u64_counter(
            "l_proc_crash_loops",
            "daemons abandoned after crash-looping past the cap",
        )
        .create_perf_counters()
    )


class _Child:
    """One supervised role's lifecycle record."""

    def __init__(self, role: str, argv: list[str]):
        self.role = role
        self.argv = argv
        self.proc: subprocess.Popen | None = None
        self.log_fh = None
        self.spawned_at = 0.0
        self.consecutive_crashes = 0
        self.restarts = 0
        self.state = "new"
        self.respawn_at = 0.0
        # kill-on-request: the next death is deliberate — park in
        # "held" instead of the backoff/respawn path until respawn()
        self.hold = False

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None


class Supervisor:
    """Spawn/monitor/respawn a fleet of daemon processes."""

    def __init__(
        self,
        spec: ClusterSpec,
        backoff_base: float = 0.5,
        backoff_max: float = 30.0,
        crash_loop_cap: int = 5,
        min_uptime: float = 2.0,
        poll_interval: float = 0.1,
        report_interval: float = 2.0,
        extra_env: dict | None = None,
    ):
        self.spec = spec
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.crash_loop_cap = crash_loop_cap
        self.min_uptime = min_uptime
        self.poll_interval = poll_interval
        self.report_interval = report_interval
        self.extra_env = dict(extra_env or {})
        self.perf = build_proc_perf()
        self.children: dict[str, _Child] = {}
        self._lock = threading.Lock()
        self._stopping = False
        self._monitor: threading.Thread | None = None
        self._stop = threading.Event()
        # crash-plane wire client (lazy; best-effort throughout)
        self._msgr = None
        self._monc = None
        self._mgr_state: dict = {}
        self._crash_outbox: list[tuple[dict, int]] = []
        self._outbox_lock = threading.Lock()
        self._last_report = 0.0

    # -- backoff schedule (unit-tested in isolation) ------------------------
    @staticmethod
    def backoff_delay(
        consecutive: int, base: float, cap: float
    ) -> float:
        """Exponential: base·2^(n−1), capped (systemd RestartSec +
        the ceph-run sleep ladder)."""
        return min(cap, base * (2 ** max(0, consecutive - 1)))

    # -- spawning -----------------------------------------------------------
    def _child_argv(self, role: str) -> list[str]:
        return [
            sys.executable, "-m", "ceph_tpu.proc.daemon",
            "--role", role,
            "--spec", str(self.spec.dir / "spec.json"),
        ]

    def _spawn(self, child: _Child) -> None:
        ready = self.spec.ready_path(child.role)
        try:
            ready.unlink()  # a stale file must not fake readiness
        except OSError:
            pass
        if child.log_fh is None:
            child.log_fh = open(
                self.spec.log_path(child.role), "ab", buffering=0
            )
        env = dict(os.environ)
        env["PYTHONPATH"] = (
            str(REPO_ROOT)
            + os.pathsep
            + env.get("PYTHONPATH", "")
        ).rstrip(os.pathsep)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(self.extra_env)
        # setsid: the child leads its own process group, so teardown
        # can kill the GROUP and a wedged daemon's own subprocesses
        # die with it
        child.proc = subprocess.Popen(
            child.argv,
            stdout=child.log_fh,
            stderr=child.log_fh,
            stdin=subprocess.DEVNULL,
            env=env,
            start_new_session=True,
        )
        child.spawned_at = time.monotonic()
        child.state = "running"
        self._write_state()

    def start(self, ready_timeout: float = 90.0) -> None:
        """Spawn the fleet in boot-phase order: mons (gate on quorum
        readiness), then mgrs, then OSDs (gate), then gateways."""
        self.spec.dir.mkdir(parents=True, exist_ok=True)
        self.spec.save()
        roles = self.spec.roles()
        phases = [
            [r for r in roles if r.startswith("mon.")],
            [r for r in roles if r.startswith("mgr.")],
            [r for r in roles if r.startswith("osd.")],
            [
                r for r in roles
                if r.startswith(("mds.", "rgw."))
            ],
        ]
        for phase in phases:
            for role in phase:
                child = _Child(role, self._child_argv(role))
                with self._lock:
                    self.children[role] = child
                self._spawn(child)
            self.wait_ready(phase, timeout=ready_timeout)
        self.perf.set("l_proc_children", self._alive_count())
        self._monitor = threading.Thread(
            target=self._monitor_loop,
            name="proc.supervisor",
            daemon=True,
        )
        self._monitor.start()

    def wait_ready(
        self, roles: list[str], timeout: float = 90.0
    ) -> None:
        """Block until every role's readiness file names its CURRENT
        incarnation's pid."""
        deadline = time.monotonic() + timeout
        for role in roles:
            child = self.children[role]
            path = self.spec.ready_path(role)
            while True:
                if child.proc is not None and (
                    child.proc.poll() is not None
                ):
                    raise RuntimeError(
                        f"{role} died during boot "
                        f"(rc={child.proc.returncode}); see "
                        f"{self.spec.log_path(role)}"
                    )
                try:
                    info = json.loads(path.read_text())
                    if info.get("pid") == child.pid:
                        break
                except (OSError, ValueError):
                    pass
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"{role} not ready after {timeout}s; see "
                        f"{self.spec.log_path(role)}"
                    )
                time.sleep(0.05)

    def ready_info(self, role: str) -> dict:
        return json.loads(
            self.spec.ready_path(role).read_text()
        )

    # -- monitoring / respawn ----------------------------------------------
    def _alive_count(self) -> int:
        with self._lock:
            return sum(
                1
                for c in self.children.values()
                if c.proc is not None and c.proc.poll() is None
            )

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            now = time.monotonic()
            with self._lock:
                children = list(self.children.values())
            for child in children:
                if child.state == "running":
                    rc = (
                        child.proc.poll()
                        if child.proc is not None
                        else None
                    )
                    if rc is not None:
                        self._on_death(child, rc)
                elif (
                    child.state == "backoff"
                    and now >= child.respawn_at
                    and not self._stopping
                ):
                    child.restarts += 1
                    self.perf.inc("l_proc_restarts")
                    self._spawn(child)
            self.perf.set("l_proc_children", self._alive_count())
            if now - self._last_report >= self.report_interval:
                self._last_report = now
                self._push_report()

    def _on_death(self, child: _Child, rc: int) -> None:
        if self._stopping or child.state in ("stopped", "exited"):
            return
        if rc == 0:
            # clean exit: the daemon chose to leave (Restart=
            # on-failure semantics — never respawned, never reported)
            child.state = "exited"
            self._write_state()
            return
        uptime = time.monotonic() - child.spawned_at
        if child.hold:
            # a requested kill: deliberate chaos, not a crash loop.
            # Still reported (a SIGKILL is a SIGKILL — telemetry does
            # not launder intent) but parked until respawn() instead
            # of riding the backoff path.
            child.state = "held"
            report = crash_util.build_process_report(
                child.role,
                rc,
                log_tail=self._log_tail(child.role),
                extra_meta={
                    "pid": child.pid,
                    "uptime_s": round(uptime, 3),
                    "requested": True,
                },
            )
            with self._outbox_lock:
                self._crash_outbox.append(
                    (report, CRASH_RESEND_COUNT)
                )
            self._write_state()
            self._push_report()
            return
        if uptime < self.min_uptime:
            child.consecutive_crashes += 1
        else:
            child.consecutive_crashes = 1
        report = crash_util.build_process_report(
            child.role,
            rc,
            log_tail=self._log_tail(child.role),
            extra_meta={
                "pid": child.pid,
                "uptime_s": round(uptime, 3),
                "consecutive_crashes": child.consecutive_crashes,
            },
        )
        with self._outbox_lock:
            self._crash_outbox.append((report, CRASH_RESEND_COUNT))
        if child.consecutive_crashes > self.crash_loop_cap:
            child.state = "failed"
            self.perf.inc("l_proc_crash_loops")
        else:
            child.state = "backoff"
            child.respawn_at = (
                time.monotonic()
                + self.backoff_delay(
                    child.consecutive_crashes,
                    self.backoff_base,
                    self.backoff_max,
                )
            )
        self._write_state()
        self._push_report()  # the death should raise health promptly

    def _log_tail(self, role: str) -> list[str]:
        try:
            data = self.spec.log_path(role).read_bytes()[-16384:]
            return data.decode("utf-8", "replace").splitlines()[
                -LOG_TAIL_LINES:
            ]
        except OSError:
            return []

    # -- crash/perf delivery (the RGW mgr-report wire idiom) ---------------
    def _push_report(self) -> None:
        try:
            self._push_report_inner()
        except Exception:  # noqa: BLE001 — telemetry is best-effort;
            # a monless window must not kill the monitor loop
            self._mgr_state.pop("conn", None)

    def _push_report_inner(self) -> None:
        from ..msg.message import MMgrReport

        monc = self._ensure_monc()
        if monc is None:
            return
        state = self._mgr_state
        now = time.monotonic()
        if (
            state.get("addr") is None
            or now - state.get("checked", -1e9) > 5.0
        ):
            state["checked"] = now
            reply = monc.command({"prefix": "mgr stat"})
            active = (
                json.loads(reply.outb).get("active")
                if reply.rc == 0
                else None
            )
            addr = active["addr"] if active else None
            if addr != state.get("addr"):
                state["addr"] = addr
                state["conn"] = None
        if state.get("addr") is None:
            return
        conn = state.get("conn")
        if conn is None or conn.is_closed:
            host, _, port = state["addr"].rpartition(":")
            conn = state["conn"] = self._msgr.connect(
                host, int(port), timeout=5.0
            )
        with self._outbox_lock:
            crashes = [r for r, _n in self._crash_outbox]
            self._crash_outbox = [
                (r, n - 1)
                for r, n in self._crash_outbox
                if n > 1
            ]
        conn.send(
            MMgrReport(
                daemon="supervisor",
                perf=json.dumps(self.perf.dump()),
                crashes=json.dumps(crashes),
            )
        )

    def _ensure_monc(self):
        if self._monc is not None:
            return self._monc
        try:
            from ..mon.monitor import MonClient
            from ..msg import Messenger

            self._msgr = Messenger("proc-supervisor")
            monc = MonClient(self._msgr, whoami=-1)
            monc.connect_any(self.spec.mon_addrs)
            self._monc = monc
        except Exception:  # noqa: BLE001 — no quorum yet; retried
            # on the next push
            if self._msgr is not None:
                try:
                    self._msgr.shutdown()
                except Exception:  # noqa: BLE001
                    pass
            self._msgr = None
            self._monc = None
        return self._monc

    # -- chaos / introspection ----------------------------------------------
    def kill(
        self, role: str, sig: int = signal.SIGKILL, hold: bool = False
    ) -> int:
        """Deliver a REAL signal to a child (chaos hook).  Returns
        the pid that was signalled.  ``hold=True`` is the
        kill-on-request contract: the death parks the child in
        "held" (no backoff, no auto-respawn) until ``respawn()`` —
        the thrasher owns the revive timing, not the backoff
        schedule."""
        child = self.children[role]
        pid = child.pid
        if pid is None:
            raise RuntimeError(f"{role} not running")
        child.hold = bool(hold)
        os.kill(pid, sig)
        return pid

    def respawn(self, role: str) -> int | None:
        """Bring a held (or failed/exited/backoff) child back NOW,
        clearing the hold and the crash-loop count — a requested
        revive is a fresh start, not restart N of a loop.  Returns
        the new pid (None when the child was already running)."""
        child = self.children[role]
        child.hold = False
        if child.state == "running" and child.proc is not None:
            if child.proc.poll() is None:
                return None
            # raced a death the monitor loop has not seen yet: fall
            # through and spawn over it
        child.consecutive_crashes = 0
        child.restarts += 1
        self.perf.inc("l_proc_restarts")
        self._spawn(child)
        self._write_state()
        return child.pid

    def status(self) -> dict:
        with self._lock:
            return {
                role: {
                    "state": c.state,
                    "pid": c.pid,
                    "restarts": c.restarts,
                    "consecutive_crashes": c.consecutive_crashes,
                }
                for role, c in self.children.items()
            }

    def _write_state(self) -> None:
        """Persist supervisor + child pids for the orphan reaper."""
        state = {
            "pid": os.getpid(),
            "children": {
                role: c.pid
                for role, c in self.children.items()
                if c.pid is not None
            },
        }
        path = self.spec.dir / SUPERVISOR_STATE
        try:
            tmp = path.with_suffix(".tmp")
            tmp.write_text(json.dumps(state))
            tmp.replace(path)
        except OSError:
            pass

    @staticmethod
    def reap_orphans(directory: str | pathlib.Path) -> list[int]:
        """Kill process GROUPS recorded by a dead supervisor (the
        harness-poisoning fix: a wedged daemon from a crashed run
        must not squat the ports of the next).  A LIVE supervisor's
        children are left alone.  Returns the pids signalled."""
        path = pathlib.Path(directory) / SUPERVISOR_STATE
        try:
            state = json.loads(path.read_text())
        except (OSError, ValueError):
            return []
        sup_pid = state.get("pid")
        if sup_pid is not None:
            try:
                os.kill(sup_pid, 0)
                return []  # supervisor alive: not ours to reap
            except ProcessLookupError:
                pass
            except PermissionError:
                return []
        reaped = []
        for pid in state.get("children", {}).values():
            try:
                # setsid children lead their own group: killpg takes
                # the daemon AND anything it spawned
                os.killpg(pid, signal.SIGKILL)
                reaped.append(pid)
            except (ProcessLookupError, PermissionError):
                pass
        try:
            path.unlink()
        except OSError:
            pass
        return reaped

    # -- teardown -----------------------------------------------------------
    def stop(self, timeout: float = 15.0) -> None:
        """SIGTERM every child's process group, escalate to SIGKILL
        on stragglers, stop monitoring."""
        self._stopping = True
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        with self._lock:
            children = list(self.children.values())
        for child in children:
            if child.proc is None or child.proc.poll() is not None:
                continue
            child.state = "stopped"
            try:
                os.killpg(child.proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                continue
        deadline = time.monotonic() + timeout
        for child in children:
            if child.proc is None:
                continue
            remain = max(0.1, deadline - time.monotonic())
            try:
                child.proc.wait(timeout=remain)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(child.proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                try:
                    child.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
            if child.log_fh is not None:
                try:
                    child.log_fh.close()
                except OSError:
                    pass
                child.log_fh = None
        if self._msgr is not None:
            try:
                self._msgr.shutdown()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            self._msgr = None
            self._monc = None
        try:
            (self.spec.dir / SUPERVISOR_STATE).unlink()
        except OSError:
            pass
