"""Cluster spec — the one JSON document the supervisor and every
daemon process share (the ceph.conf seat, reduced to what this
framework's daemons actually consume).

Grammar (all keys present after ``plan()``)::

    {
      "dir":       "/path/cluster",      # stores, logs, spec.json
      "mons":      3,                    # quorum trio (or 1)
      "osds":      4,
      "mgrs":      1,
      "mds":       0,
      "rgw":       0,
      "memstore":  false,                # RAM stores (no persistence)
      "wal":       false,                # WAL-front each OSD store
      "mon_addrs": [["127.0.0.1", 6789], ...],   # one per mon rank
      "rgw_ports": [8000, ...],          # one per rgw instance
      "pool_size": 2,                    # replica count for pools
    }

Ports are assigned ONCE at plan time (free-port probe) and then
pinned in the spec: a respawned mon/rgw must come back at the SAME
address or the surviving quorum and clients could never find it —
exactly why the reference pins mon addresses in the monmap.
"""

from __future__ import annotations

import json
import pathlib
import socket


SPEC_FILENAME = "spec.json"


def _free_ports(n: int) -> list[int]:
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


class ClusterSpec:
    """Planned cluster layout; serializable for child processes."""

    def __init__(self, data: dict):
        self.data = data

    # -- construction -------------------------------------------------------
    @classmethod
    def plan(
        cls,
        dir: str,
        mons: int = 3,
        osds: int = 4,
        mgrs: int = 1,
        mds: int = 0,
        rgw: int = 0,
        memstore: bool = False,
        wal: bool = False,
        mon_port: int = 0,
        rgw_port: int = 0,
    ) -> "ClusterSpec":
        """Assign mon/rgw addresses and freeze the layout.  A nonzero
        ``mon_port`` seeds consecutive ports from it (the vstart
        fixed-port mode); 0 probes free ports."""
        if mons < 1:
            raise ValueError("need at least one mon")
        if mon_port:
            mon_ports = [mon_port + r for r in range(mons)]
        else:
            mon_ports = _free_ports(mons)
        if rgw > 0:
            rgw_ports = (
                [rgw_port + i for i in range(rgw)]
                if rgw_port
                else _free_ports(rgw)
            )
        else:
            rgw_ports = []
        return cls(
            {
                "dir": str(dir),
                "mons": int(mons),
                "osds": int(osds),
                "mgrs": int(mgrs),
                "mds": int(mds),
                "rgw": int(rgw),
                "memstore": bool(memstore),
                "wal": bool(wal),
                "mon_addrs": [["127.0.0.1", p] for p in mon_ports],
                "rgw_ports": rgw_ports,
                "pool_size": min(3, max(1, int(osds))),
            }
        )

    @classmethod
    def load(cls, path: str | pathlib.Path) -> "ClusterSpec":
        return cls(json.loads(pathlib.Path(path).read_text()))

    def save(self, path: str | pathlib.Path | None = None) -> pathlib.Path:
        p = (
            pathlib.Path(path)
            if path is not None
            else self.dir / SPEC_FILENAME
        )
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_suffix(".tmp")
        tmp.write_text(json.dumps(self.data, indent=1))
        tmp.replace(p)
        return p

    # -- accessors ----------------------------------------------------------
    @property
    def dir(self) -> pathlib.Path:
        return pathlib.Path(self.data["dir"])

    @property
    def mon_addrs(self) -> list[tuple[str, int]]:
        return [(h, int(p)) for h, p in self.data["mon_addrs"]]

    def roles(self) -> list[str]:
        """Every daemon role this spec places, in boot-phase order:
        mons first (quorum), then mgrs, then OSDs, then gateways."""
        out = [f"mon.{r}" for r in range(self.data["mons"])]
        out += [f"mgr.{i}" for i in range(self.data["mgrs"])]
        out += [f"osd.{i}" for i in range(self.data["osds"])]
        out += [f"mds.{i}" for i in range(self.data["mds"])]
        out += [f"rgw.{i}" for i in range(self.data["rgw"])]
        return out

    def log_path(self, role: str) -> pathlib.Path:
        return self.dir / f"{role}.log"

    def ready_path(self, role: str) -> pathlib.Path:
        return self.dir / f"{role}.ready"
